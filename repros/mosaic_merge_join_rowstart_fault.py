"""Minimized repro: Mosaic device fault when merge-join row-start offsets
cross 2^19 under a multi-thousand-tile grid.

Gate it documents: ``ops/pallas_kernels._PALLAS_MAX_LEFT_ROWS = 393216`` —
the SINGLE-LAUNCH tiled merge-join kernel is verified stable up to that
left size; past ~2^19 compacted rows the SAME kernel raises a TPU device
fault at dispatch (v5e via the axon tunnel).  Block-index,
pipeline-lookahead and SMEM-size causes were ruled out in round-2
elimination runs (TPU_VALIDATION.md).  Since round 4, production inputs
past the gate run the chunk-level driver (bounded local windows — see
``repros/pallas_chunked_join_validation.py``), so this repro bypasses the
gate to reach the raw single-launch path and document the fault boundary
itself.

Run on real TPU:  python repros/mosaic_merge_join_rowstart_fault.py [n_left]
Default n_left = 1_048_576 (faults).  n_left = 393_216 passes.
Off-TPU this runs the interpreter and always passes (prints SKIP).
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, __file__.rsplit("/repros/", 1)[0])


def main(n_left: int) -> None:
    from kolibrie_tpu.ops import pallas_kernels as pk

    if jax.default_backend() != "tpu":
        print("SKIP: repro requires real TPU (interpret mode cannot fault)")
    # every left row matches exactly once -> compaction keeps ALL rows, so
    # row_start values reach n_left (the faulting regime is row starts
    # beyond ~2^19 with n_left/128 output tiles)
    lkey = jnp.arange(n_left, dtype=jnp.uint32)
    rkey = jnp.arange(n_left, dtype=jnp.uint32)
    lval = jnp.arange(n_left, dtype=jnp.uint32)
    rval = jnp.arange(n_left, dtype=jnp.uint32)
    # bypass the production gate to reach the kernel
    saved = pk._PALLAS_MAX_LEFT_ROWS
    pk._PALLAS_MAX_LEFT_ROWS = 1 << 30
    try:
        out = pk.merge_join(lkey, lval, rkey, rval, n_left)
        jax.block_until_ready(out)
        total = int(np.asarray(out[4]))
        print(f"OK: n_left={n_left} total={total} (no fault)")
        assert total == n_left
    finally:
        pk._PALLAS_MAX_LEFT_ROWS = saved


if __name__ == "__main__":
    import jaxlib

    # version pin: the fault boundary is empirical per toolchain — see
    # repros/OBSERVED_VERSIONS.md for the observation table
    print(f"jax {jax.__version__} / jaxlib {jaxlib.__version__}", flush=True)
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 1_048_576)
