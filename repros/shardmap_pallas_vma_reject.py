"""Repro: jax's shard_map varying-mesh-axes checker rejects pallas_call.

Why this file exists: VERDICT r3 item 3 asks to "chase removing the
``check_vma=False`` escape hatch" on the distributed Pallas join route
(``parallel/dist_join.py``).  The kernel's out_shape already propagates the
operand's vma set (``ops/pallas_kernels.py::_pallas_join_core``), but the
checker faults INSIDE pallas_call's own machinery: a ``dynamic_slice``
whose operand varies over the mesh axis while an internal index operand is
replicated.  jax's error message itself prescribes ``check_vma=False`` as
the workaround, i.e. the boundary is upstream, not in this repo.

Observed on jax 0.9.x CPU interpret mode (2026-07): ::

    ValueError: Primitive dynamic_slice requires varying manual axes to
    match, but got [frozenset({'x'}), frozenset()]. Please open an issue
    at https://github.com/jax-ml/jax/issues and as a temporary workaround
    pass the check_vma=False argument to `jax.shard_map`

Run (exits 0 when jax still rejects — the escape hatch must stay; exits 1
the day jax accepts, which is the signal to drop ``check_vma=False``):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python repros/shardmap_pallas_vma_reject.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    import jax
    import jax.numpy as jnp
    import jaxlib
    from jax.sharding import Mesh, PartitionSpec as P

    # This repro is CPU-by-design (the vma checker rejects at TRACE time;
    # no chip involved) — pin the backend so a dead TPU tunnel can never
    # hang it at device discovery (the env preloads the axon platform,
    # and jax.config is the only override that still works then).
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialized by the caller

    # version pin: upstream behavior — see repros/OBSERVED_VERSIONS.md
    print(f"jax {jax.__version__} / jaxlib {jaxlib.__version__}", flush=True)

    from kolibrie_tpu.ops.pallas_kernels import merge_join_indices

    devs = jax.devices()
    mesh = Mesh(np.array(devs[: min(8, len(devs))]), ("x",))

    def body(lk, rk):
        lk, rk = lk[0], rk[0]
        li, rpos, valid, total = merge_join_indices(lk, jnp.sort(rk), 128)
        return li[None, :128], total[None]

    f = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            check_vma=True,  # the default we would like to keep
            in_specs=(P("x", None), P("x", None)),
            out_specs=(P("x", None), P("x")),
        )
    )
    n = mesh.devices.size
    lk = np.tile(np.arange(256, dtype=np.uint32), (n, 1))
    rk = np.tile(np.arange(256, dtype=np.uint32), (n, 1))
    try:
        out = f(lk, rk)
    except ValueError as e:
        assert "check_vma=False" in str(e) or "manual axes" in str(e), e
        print("REJECTED (expected): jax still requires check_vma=False")
        print(str(e)[:300])
        return 0
    print(
        "ACCEPTED: jax now takes pallas_call under vma checking — drop the"
        " check_vma=False escape hatch in parallel/dist_join.py"
        f" (total[0]={int(out[1][0])})"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
