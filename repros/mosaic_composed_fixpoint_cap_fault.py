"""Minimized repro: TPU device fault for COMPOSED fixpoint programs whose
join buffers exceed 2^21 rows.

Gate it documents: ``reasoner/device_fixpoint.SAFE_JOIN_CAP = 2_097_152``.
Each constituent op standalone (sorts to 16M rows, join_indices at 4M cap,
gathers) passes; the fault appears only when the semi-naive round body —
scan + join + gather + sort-unique + set-difference + append — compiles as
ONE program with a join capacity past 2^21 (v5e via the axon tunnel).

Run on real TPU:  python repros/mosaic_composed_fixpoint_cap_fault.py [cap]
Default cap = 4_194_304 (faults).  cap = 2_097_152 passes.
Off-TPU this runs the XLA CPU backend and always passes (prints SKIP).
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, __file__.rsplit("/repros/", 1)[0])


def main(cap: int) -> None:
    from kolibrie_tpu.ops.device_join import (
        join_indices,
        set_difference_rows,
        sort_unique_rows,
    )

    if jax.default_backend() != "tpu":
        print("SKIP: repro requires real TPU (CPU backend does not fault)")
    n = cap // 4

    @jax.jit
    def round_body(s, p, o):
        with jax.enable_x64(True):
            li, ri, valid, _tot = join_indices(o, s, cap)  # (x p y)(y p z)
            cs, co = s[li], o[ri]
            cp = jnp.where(valid, p[0], 0)
            (us, up, uo), uv, _n1 = sort_unique_rows((cs, cp, co), valid, cap)
            (ns, np_, no), nv, n_new = set_difference_rows(
                (us, up, uo), uv, (s, p, o), jnp.ones_like(s, bool), cap
            )
            return ns, np_, no, n_new

    rng = np.random.default_rng(0)
    s = jnp.asarray(rng.integers(1, n // 2, n).astype(np.uint32))
    o = jnp.asarray(rng.integers(1, n // 2, n).astype(np.uint32))
    p = jnp.full(n, 7, dtype=jnp.uint32)
    out = round_body(s, p, o)
    jax.block_until_ready(out)
    print(f"OK: cap={cap} derived={int(np.asarray(out[3]))} (no fault)")


if __name__ == "__main__":
    import jaxlib

    # version pin: the fault boundary is empirical per toolchain — see
    # repros/OBSERVED_VERSIONS.md for the observation table
    print(f"jax {jax.__version__} / jaxlib {jaxlib.__version__}", flush=True)
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 4_194_304)
