"""Hardware validation for the chunk-level Pallas merge-join driver.

Round-4 lift of ``_PALLAS_MAX_LEFT_ROWS``: left sides past the 393,216-row
single-launch gate now run :func:`_pallas_join_core_chunked` — the same
tile kernel launched per 131,072-output chunk over a dynamic-sliced local
row window, so per-launch row-start offsets stay an order of magnitude
under the empirical 2^19 Mosaic fault boundary
(``repros/mosaic_merge_join_rowstart_fault.py``).

For each size this script runs the chunked kernel path AND the pure-XLA
formulation on the same data, checks totals + full row equality, and
prints per-path device times (one warm-up, then timed reruns).

Run on real TPU:  python repros/pallas_chunked_join_validation.py [sizes...]
Default sizes: 1048576 4194304 16777216.  Off-TPU it validates a scaled
-down size in interpret mode (full sizes are impractical interpreted).
"""
import os
import sys
import time

import jax

# A dead TPU tunnel HANGS backend init; KOLIBRIE_REPRO_CPU=1 pins the CPU
# backend before anything touches devices (env JAX_PLATFORMS is preempted
# by the preloaded plugin in this image — config.update is the override).
if os.environ.get("KOLIBRIE_REPRO_CPU") == "1":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, __file__.rsplit("/repros/", 1)[0])


def run_one(n_left: int, chunk_out=None) -> None:
    from kolibrie_tpu.ops.pallas_kernels import _xla_merge_join, merge_join

    rng = np.random.default_rng(0)
    # ~4 distinct left rows per key, ~2 right rows -> fanout ~2, total ~2n.
    lk = rng.integers(0, n_left // 4, n_left).astype(np.uint32)
    lv = rng.integers(0, 1 << 30, n_left).astype(np.uint32)
    rk = np.sort(rng.integers(0, n_left // 4, n_left // 2).astype(np.uint32))
    rv = rng.integers(0, 1 << 30, n_left // 2).astype(np.uint32)
    cap = int(n_left * 2.5)
    args = tuple(map(jnp.asarray, (lk, lv, rk, rv)))

    def timed(fn):
        out = fn()
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(3):
            out = fn()
            jax.block_until_ready(out)
        return (time.perf_counter() - t0) / 3, out

    t_xla, ref = timed(lambda: _xla_merge_join(*args, cap))
    # auto-chunks past the gate; explicit chunk_out for the interpret check
    t_pal, got = timed(lambda: merge_join(*args, cap, chunk_out=chunk_out))
    rt, gt = int(np.asarray(ref[4])), int(np.asarray(got[4]))
    assert rt == gt, (rt, gt)
    eff = min(gt, cap)
    for i in range(3):  # key, lval, rval (valid-masked, order-aligned)
        a = np.asarray(ref[i])[:eff][np.asarray(ref[3])[:eff]]
        b = np.asarray(got[i])[:eff][np.asarray(got[3])[:eff]]
        assert np.array_equal(a, b), f"column {i} mismatch at n={n_left}"
    print(
        f"OK n_left={n_left} total={gt} xla={t_xla*1e3:.2f}ms "
        f"pallas_chunked={t_pal*1e3:.2f}ms ratio={t_xla/t_pal:.2f}x"
    )


def main(sizes) -> None:
    if jax.default_backend() != "tpu":
        print("SKIP full sizes: not on TPU; full sizes are impractical "
              "interpreted — running 8K-row/1K-chunk interpret-mode check")
        run_one(8192, chunk_out=1024)
        return
    for n in sizes:
        run_one(n)


if __name__ == "__main__":
    main(
        [int(a) for a in sys.argv[1:]] or [1_048_576, 4_194_304, 16_777_216]
    )
