"""Headline benchmark: BGP 2-pattern join over employee-100K, on device.

Mirrors the reference's ``execute_query_join``/``execute_query_volcano``
criterion bench (``kolibrie/benches/my_benchmark.rs:29-100``): the query

    SELECT ?employee ?workplaceHomepage ?salary WHERE {
        ?employee foaf:workplaceHomepage ?workplaceHomepage .
        ?employee ds:annual_salary ?salary }

over 100K employee triples.  The reference repo carries the dataset only as
a git-LFS pointer, so an equivalent dataset (same shape: 4 predicates per
employee, 100K triples total) is synthesized deterministically.

Measurement notes:
- The store is PSO-sorted at build time, so each predicate is a contiguous
  slice already sorted by subject and the join is a sort-free merge
  (searchsorted ranges + static-capacity materialization) — the TPU-native
  analogue of the reference's PSO-index-driven merge join
  (``shared/src/join_algorithm.rs:19-131``).
- The shared dev TPU behind the axon tunnel has highly variable dispatch
  latency (observed 34us..90ms) and occasional contention windows, so the
  join is iterated K times inside ONE dispatch via ``lax.scan`` (with a
  loop-carried dependency XLA cannot hoist) and the minimum over several
  dispatches is taken.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
value = BGP-join throughput in input triples/sec/chip on the device path
and vs_baseline = device throughput / host-numpy throughput (the reference
is a CPU-only engine, so the in-process numpy merge join over the same
PSO slices stands in for its single-node baseline).
"""

import json
import time

import numpy as np

N_TRIPLES = 100_000
N_PRED = 4  # name, title, workplaceHomepage, annual_salary
P_WORKS = 2
P_SALARY = 3
JOIN_CAP = 1 << 15  # >= n_employees
SCAN_K = 32
N_DISPATCH = 30
DISPATCH_GAP_S = 0.2  # the shared TPU has contention windows; spread samples


def synth_employee_columns(n_triples=N_TRIPLES, seed=7):
    """u32 (s, p, o) columns shaped like synthetic_data_employee_100K."""
    rng = np.random.default_rng(seed)
    n_emp = n_triples // N_PRED
    emp = np.arange(1, n_emp + 1, dtype=np.uint32) * np.uint32(N_PRED)
    s = np.repeat(emp, N_PRED)
    p = np.tile(np.arange(N_PRED, dtype=np.uint32) + np.uint32(1), n_emp)
    base = np.uint32(n_emp * N_PRED + 10)
    o = base + rng.integers(0, 50_000, n_emp * N_PRED).astype(np.uint32)
    perm = rng.permutation(len(s))
    return s[perm], p[perm], o[perm]


def pso_slices(s, p, o):
    """Store-build step: PSO sort + predicate slicing (host, done once)."""
    order = np.lexsort((o, s, p))
    ps, pp, po = s[order], p[order], o[order]

    def sl(pred):
        lo = np.searchsorted(pp, pred, "left")
        hi = np.searchsorted(pp, pred, "right")
        return ps[lo:hi], po[lo:hi]

    return sl(P_WORKS + 1), sl(P_SALARY + 1)


def device_bench(ls, lo_, rs, ro_):
    import jax
    import jax.numpy as jnp
    from functools import partial
    from jax import lax

    @partial(jax.jit, static_argnames=("cap", "k"))
    def merge_join_k(ls, lo_, rs, ro_, cap, k):
        def body(carry, _):
            # carry >= 0 always, but XLA can't prove it: off == 0 at
            # runtime yet defeats loop-invariant hoisting of the body
            off = (carry >> 31).astype(jnp.uint32)
            lkey = ls + off
            low = jnp.searchsorted(rs, lkey, side="left")
            high = jnp.searchsorted(rs, lkey, side="right")
            counts = (high - low).astype(jnp.int32)
            cum = jnp.cumsum(counts)
            total = cum[-1]
            idx = jnp.arange(cap, dtype=jnp.int32)
            row = jnp.searchsorted(cum, idx, side="right")
            row_c = jnp.clip(row, 0, ls.shape[0] - 1)
            pos = low[row_c] + (idx - (cum[row_c] - counts[row_c]))
            jv = idx < total
            emp = jnp.where(jv, lkey[row_c], 0)
            w = jnp.where(jv, lo_[row_c], 0)
            sal = jnp.where(jv, ro_[jnp.clip(pos, 0, rs.shape[0] - 1)], 0)
            return total, (emp.sum(), w.sum(), sal.sum(), total)

        _, outs = lax.scan(body, jnp.int32(0), None, length=k)
        return outs

    args = tuple(jnp.asarray(a) for a in (ls, lo_, rs, ro_))
    out = merge_join_k(*args, JOIN_CAP, SCAN_K)
    jax.block_until_ready(out)  # compile + warm
    times = []
    for _ in range(N_DISPATCH):
        t0 = time.perf_counter()
        out = merge_join_k(*args, JOIN_CAP, SCAN_K)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
        time.sleep(DISPATCH_GAP_S)
    # Result readback AFTER all timing: through the axon tunnel, a single
    # host read of any output element degrades every subsequent dispatch of
    # the same executable from ~0.1ms to a stable ~380ms (measured), so the
    # correctness check must not precede the measurement loop.
    n_results = int(out[3][0])
    per_join = min(times) / SCAN_K
    return per_join, n_results, str(jax.devices()[0].platform)


def host_bench(ls, lo_, rs, ro_, iters=10):
    """Same merge join, numpy on host (single-node reference stand-in)."""

    def run():
        low = np.searchsorted(rs, ls, side="left")
        high = np.searchsorted(rs, ls, side="right")
        counts = high - low
        li = np.repeat(np.arange(len(ls)), counts)
        starts = np.repeat(low, counts)
        offs = np.arange(counts.sum()) - np.repeat(
            np.cumsum(counts) - counts, counts
        )
        ri = starts + offs
        return ls[li], lo_[li], ro_[ri]

    run()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        emp, w, sal = run()
        times.append(time.perf_counter() - t0)
    return min(times), len(emp)


def main():
    s, p, o = synth_employee_columns()
    (ls, lo_), (rs, ro_) = pso_slices(s, p, o)
    dev_t, n_results, platform = device_bench(ls, lo_, rs, ro_)
    host_t, host_n = host_bench(ls, lo_, rs, ro_)
    assert n_results == host_n, (n_results, host_n)
    throughput = N_TRIPLES / dev_t
    print(
        json.dumps(
            {
                "metric": f"bgp_join_employee100k_triples_per_sec_{platform}",
                "value": round(throughput, 1),
                "unit": "triples/sec/chip",
                "vs_baseline": round(host_t / dev_t, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
