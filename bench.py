"""Headline benchmark: the employee-100K BGP join through the ACTUAL engine.

Mirrors the reference's ``execute_query_join``/``execute_query_volcano``
criterion bench (``kolibrie/benches/my_benchmark.rs:29-100``): the query

    SELECT ?employee ?workplaceHomepage ?salary WHERE {
        ?employee foaf:workplaceHomepage ?workplaceHomepage .
        ?employee ds:annual_salary ?salary }

over 100K employee triples (the reference repo carries the dataset only as a
git-LFS pointer, so an equivalent dataset — 4 predicates per employee,
100K triples — is synthesized and loaded through the public N-Triples
parser).

What is measured (the framework, not an inline kernel):

- The query goes through the PUBLIC API: ``SparqlDatabase`` + SPARQL parse +
  Streamertail plan + the device execution engine
  (``kolibrie_tpu/optimizer/device_engine.py``) — the plan compiles to ONE
  jitted XLA program over the store's device-resident sorted orders.
- ``PreparedQuery`` separates prepare (parse/plan/lower, host) from execute
  (device dispatch), matching the reference bench's iteration over a loaded
  database.  Headline value = input triples/sec of the prepared device
  execution; ``vs_baseline`` = host numpy engine time / device time for the
  SAME operator pipeline (the reference is CPU-only, so the in-process numpy
  engine stands in for its single-node baseline).
- Readback discipline (shared dev TPU behind the axon tunnel): capacities
  are calibrated HOST-side, the timed executable is never read during the
  loop, and correctness (device rows == host rows) is verified afterwards.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "secondary"}.
"""

import json
import os
import subprocess
import sys
import time

N_EMPLOYEES = 25_000  # x4 predicates = 100K triples
N_TRIPLES = 4 * N_EMPLOYEES
N_DISPATCH = 30
SCAN_K = 32  # plan executions amortized into one dispatch
DISPATCH_GAP_S = 0.2  # the shared TPU has contention windows; spread samples

PREFIXES = """PREFIX ds: <https://data.example/ontology#>
PREFIX foaf: <http://xmlns.com/foaf/0.1/>
"""

JOIN_QUERY = PREFIXES + """
SELECT ?employee ?workplaceHomepage ?salary WHERE {
    ?employee foaf:workplaceHomepage ?workplaceHomepage .
    ?employee ds:annual_salary ?salary
}
"""


def build_db():
    from kolibrie_tpu.query.sparql_database import SparqlDatabase

    db = SparqlDatabase()
    lines = []
    for i in range(N_EMPLOYEES):
        e = f"<https://data.example/employee/{i}>"
        lines.append(f'{e} <http://xmlns.com/foaf/0.1/name> "Employee {i}" .')
        lines.append(f'{e} <https://data.example/ontology#title> "Engineer" .')
        lines.append(
            f"{e} <http://xmlns.com/foaf/0.1/workplaceHomepage> "
            f"<https://company{i % 500}.example/> ."
        )
        lines.append(
            f'{e} <https://data.example/ontology#annual_salary> '
            f'"{30000 + (i % 50) * 1000}" .'
        )
    t0 = time.perf_counter()
    db.parse_ntriples("\n".join(lines))
    t_load = time.perf_counter() - t0
    return db, t_load


# ---------------------------------------------------------------------------
# Replication fleet (docs/REPLICATION.md): REAL server processes — one
# primary shipping WAL segments, N followers mirroring it — measured for
# aggregate read qps vs the single process, replication lag under
# sustained ingest, and kill -9 → first-promoted-read failover time.
# Callable standalone; scripts/bench_gate.py --smoke runs the reduced
# shape (one follower, short windows) as a lint-time self-check.
# ---------------------------------------------------------------------------


def replication_fleet_bench(
    note=lambda m: None,
    fleet_sizes=(1, 2, 4),
    read_duration_s=2.0,
    n_universities=1,
    n_client_threads=2,
    lag_samples=24,
):
    import shutil
    import socket
    import tempfile
    import urllib.error
    import urllib.request

    from benches.lubm import generate_fast
    from kolibrie_tpu.query.sparql_database import SparqlDatabase
    from kolibrie_tpu.replication.router import RouterCore

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def post(base, path, payload, timeout=120):
        req = urllib.request.Request(
            base + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def get_json(base, path, timeout=30):
        with urllib.request.urlopen(base + path, timeout=timeout) as resp:
            return json.loads(resp.read())

    root = tempfile.mkdtemp(prefix="kolibrie-bench-repl-")
    procs = []

    def spawn(name, extra_env):
        port = free_port()
        env = dict(os.environ)
        # the fleet measures the host serving path on CPU: never inherit
        # the parent bench's TPU tunnel or virtual-device flags
        env.pop("XLA_FLAGS", None)
        env.pop("KOLIBRIE_BENCH_CPU", None)
        env.update(
            {
                "KOLIBRIE_DATA_DIR": os.path.join(root, name),
                "KOLIBRIE_FSYNC": "group",
                "JAX_PLATFORMS": "cpu",
            }
        )
        env.update(extra_env)
        log = open(os.path.join(root, f"{name}.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "kolibrie_tpu.frontends.http_server",
             "127.0.0.1", str(port)],
            env=env, stdout=log, stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        rec = {"name": name, "proc": proc, "log": log, "port": port,
               "base": f"http://127.0.0.1:{port}"}
        procs.append(rec)
        return rec

    def wait_ready(rec, timeout_s=240.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if rec["proc"].poll() is not None:
                with open(os.path.join(root, f"{rec['name']}.log"), "rb") as fh:
                    tail = fh.read()[-1500:].decode("utf-8", "replace")
                raise RuntimeError(f"{rec['name']} died during boot:\n{tail}")
            try:
                if get_json(rec["base"], "/healthz", 5).get("status") == "ready":
                    return
            except (urllib.error.URLError, OSError, ValueError):
                pass
            time.sleep(0.1)
        raise RuntimeError(f"{rec['name']} never became ready")

    # LUBM read-heavy mix: constant-variants of two serving templates,
    # the same worksFor/teacherOf family the sharded-serving block uses
    _ub = "PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#> "
    read_mix = [
        _ub + "SELECT ?x ?c WHERE { ?x ub:worksFor "
        f"<http://www.Department{d}.University0.edu> . "
        "?x ub:teacherOf ?c }"
        for d in range(8)
    ] + [
        _ub + "SELECT ?x ?p WHERE { ?x ub:memberOf "
        f"<http://www.Department{d}.University0.edu> . "
        "?x ub:advisor ?p }"
        for d in range(8)
    ]

    # one dedicated loadgen CHILD process per node: a single client
    # interpreter's GIL would cap the aggregate long before an N-node
    # fleet does (each child reports its own count/duration)
    _LOADGEN = r"""
import json, sys, threading, time, urllib.request
base, dur, n_threads = sys.argv[1], float(sys.argv[2]), int(sys.argv[3])
queries = json.loads(sys.argv[4])
stop_at = time.monotonic() + dur
counts = [0] * n_threads
errors = [0] * n_threads
def worker(ti):
    qi = ti
    while time.monotonic() < stop_at:
        req = urllib.request.Request(
            base + "/store/query",
            data=json.dumps({"store_id": "lubm",
                             "sparql": queries[qi % len(queries)]}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                ok = resp.status == 200
                resp.read()
        except Exception:
            ok = False
        counts[ti] += 1 if ok else 0
        errors[ti] += 0 if ok else 1
        qi += 1
t0 = time.monotonic()
ts = [threading.Thread(target=worker, args=(i,)) for i in range(n_threads)]
for t in ts: t.start()
for t in ts: t.join()
print(json.dumps({"count": sum(counts), "errors": sum(errors),
                  "dt": time.monotonic() - t0}))
"""

    def measure_qps(bases, duration_s):
        """Aggregate successful read qps: one loadgen child per node,
        ``n_client_threads`` threads each, templates striped so every
        node serves its own affinity slice of the mix (the router's
        placement — docs/REPLICATION.md)."""
        children = []
        for i, base in enumerate(bases):
            qs = read_mix[i::len(bases)] or read_mix
            children.append(subprocess.Popen(
                [sys.executable, "-c", _LOADGEN, base, str(duration_s),
                 str(n_client_threads), json.dumps(qs)],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True,
            ))
        qps = 0.0
        errors = 0
        for ch in children:
            out, _err = ch.communicate(timeout=duration_s + 120)
            rec = json.loads(out.strip().splitlines()[-1])
            qps += rec["count"] / rec["dt"]
            errors += rec["errors"]
        return qps, errors

    def pct(sorted_vals, q):
        return sorted_vals[min(len(sorted_vals) - 1,
                               int(round(q * (len(sorted_vals) - 1))))]

    try:
        # ---- boot the whole fleet at once (boots overlap) ----------------
        repl_port = free_port()
        primary = spawn("primary", {
            "KOLIBRIE_REPL_PORT": str(repl_port),
            "KOLIBRIE_REPL_SEAL_INTERVAL_S": "0.05",
        })
        followers = [
            spawn(f"follower{i}", {
                "KOLIBRIE_REPL_SOURCE": f"127.0.0.1:{repl_port}",
                "KOLIBRIE_REPL_POLL_INTERVAL_S": "0.05",
            })
            for i in range(max(fleet_sizes))
        ]
        wait_ready(primary)
        note("replication: primary up, loading LUBM")

        gen_db = SparqlDatabase()
        ls, lp, lo = generate_fast(n_universities, gen_db.dictionary)
        gen_db.store.add_batch(ls, lp, lo)
        nt = gen_db.to_ntriples()
        n_triples = len(gen_db.store)
        st, out = post(primary["base"], "/store/load",
                       {"store_id": "lubm", "rdf": nt,
                        "format": "ntriples", "mode": "host"})
        assert st == 200, out
        token = out["watermark"]

        for rec in followers:
            wait_ready(rec)
        # every follower must cover the loaded data before reads count
        for rec in followers:
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                hz = get_json(rec["base"], "/healthz", 10)
                wm = (hz.get("replication") or {}).get("watermark") or {}
                if int(wm.get("applied_segment") or 0) >= token["segment"]:
                    break
                time.sleep(0.05)
            else:
                raise RuntimeError(f"{rec['name']} never caught up")
        note("replication: fleet caught up, measuring")

        # warm each node's parse/plan caches once per template
        for rec in [primary] + followers:
            for q in read_mix:
                post(rec["base"], "/store/query",
                     {"store_id": "lubm", "sparql": q})

        block = {
            "dataset": f"lubm{n_universities}",
            "triples": n_triples,
            "read_mix_templates": len(read_mix),
            "client_threads_per_node": n_client_threads,
            "read_window_s": read_duration_s,
            "note": "followers serve the read mix while the primary owns "
            "writes; on a 1-core proxy the fleet shares the core, so the "
            "speedup lower-bounds what separate machines get",
        }
        single_qps, errs = measure_qps([primary["base"]], read_duration_s)
        block["single_read_qps"] = round(single_qps, 1)
        read_errors = errs
        for n in fleet_sizes:
            qps, errs = measure_qps(
                [rec["base"] for rec in followers[:n]], read_duration_s
            )
            block[f"fleet{n}_read_qps"] = round(qps, 1)
            read_errors += errs
        if 2 in fleet_sizes and single_qps > 0:
            block["fleet2_speedup_vs_single"] = round(
                block["fleet2_read_qps"] / single_qps, 2
            )
        block["read_errors"] = read_errors

        # ---- fleet observability: router-path overhead + /fleet scrape ---
        # The same read mix proxied through an in-process router twice:
        # spans+metrics recording on, then the obs runtime kill switch off
        # (what KOLIBRIE_OBS_DISABLED=1 sets at import) — same < 3% budget
        # as the single-process obs sweep.  Then /fleet/metrics latency
        # with the TTL cache defeated, so the number is the true N-node
        # scrape sweep and merge, not a cache hit.
        note("replication: fleet observability sweep")
        try:
            import threading

            from kolibrie_tpu.obs import runtime as obs_runtime
            from kolibrie_tpu.replication.router import make_router

            r_httpd, r_core = make_router(
                [(rec["name"], rec["base"]) for rec in [primary] + followers],
                quiet=True, probe_interval_s=3600.0, auto_promote=False,
            )
            try:
                threading.Thread(
                    target=r_httpd.serve_forever, daemon=True
                ).start()
                router_base = f"http://127.0.0.1:{r_httpd.server_address[1]}"
                r_core.probe_once()
                # warm the proxy path once per template
                for q in read_mix:
                    post(router_base, "/store/query",
                         {"store_id": "lubm", "sparql": q})
                instrumented = disabled = 0.0
                try:
                    # interleaved best-of-2 per mode: the loadgen child
                    # dominates noise at this window size
                    for _ in range(2):
                        obs_runtime.set_enabled(True)
                        q_on, _e = measure_qps([router_base],
                                               read_duration_s)
                        instrumented = max(instrumented, q_on)
                        obs_runtime.set_enabled(False)
                        q_off, _e = measure_qps([router_base],
                                                read_duration_s)
                        disabled = max(disabled, q_off)
                finally:
                    obs_runtime.set_enabled(True)
                overhead_pct = (
                    (disabled - instrumented) / disabled * 100.0
                    if disabled > 0 else 0.0
                )
                r_core.fleet_cache_ttl_s = 0.0
                scrape_ms = []
                for _ in range(8):
                    t0 = time.perf_counter()
                    r_core.fleet_metrics()
                    scrape_ms.append((time.perf_counter() - t0) * 1000.0)
                scrape_ms.sort()
                block["fleet_obs"] = {
                    "router_instrumented_read_qps": round(instrumented, 1),
                    "router_obs_disabled_read_qps": round(disabled, 1),
                    "obs_overhead_pct": round(overhead_pct, 2),
                    "budget_pct": 3.0,
                    "fleet_metrics_scrape_p50_ms": round(
                        pct(scrape_ms, 0.50), 2
                    ),
                    "fleet_metrics_scrape_p99_ms": round(
                        pct(scrape_ms, 0.99), 2
                    ),
                    # router registry + every healthy backend in the sweep
                    "fleet_metrics_nodes": 1 + len(followers) + 1,
                }
            finally:
                r_core.stop()
                r_httpd.shutdown()
                r_httpd.server_close()
        except Exception as e:  # noqa: BLE001 — bench must survive its probes
            block["fleet_obs"] = {"error": repr(e)}
        note(f"replication: fleet obs done ({block['fleet_obs']})")

        # ---- replication lag under sustained ingest ----------------------
        # each marker batch is acked by the primary, then timed until a
        # follower serves it: ack-to-visible wall time, p50/p99
        lags_ms = []
        fol0 = followers[0]
        filler = "\n".join(
            f"<http://bench/fill{j}> <http://bench/p> \"x{j}\" ."
            for j in range(64)
        )
        for j in range(lag_samples):
            marker = f"<http://bench/m{j}> <http://bench/mark> \"{j}\" ."
            st, out = post(primary["base"], "/store/load",
                           {"store_id": "lubm", "rdf": filler + "\n" + marker,
                            "format": "ntriples"})
            assert st == 200, out
            t_ack = time.monotonic()
            probe = (f"SELECT ?v WHERE {{ <http://bench/m{j}> "
                     "<http://bench/mark> ?v }")
            while True:
                st, res = post(fol0["base"], "/store/query",
                               {"store_id": "lubm", "sparql": probe})
                if st == 200 and res.get("data"):
                    lags_ms.append((time.monotonic() - t_ack) * 1000.0)
                    break
                if time.monotonic() - t_ack > 30.0:
                    lags_ms.append(30_000.0)
                    break
                time.sleep(0.01)
        lags_ms.sort()
        block["repl_lag_p50_ms"] = round(pct(lags_ms, 0.50), 1)
        block["repl_lag_p99_ms"] = round(pct(lags_ms, 0.99), 1)

        # ---- failover: kill -9 the primary mid-ingest --------------------
        # time from SIGKILL to the FIRST successful read answered by the
        # promoted follower (probe + promote + serve, the whole path)
        post(primary["base"], "/store/load",
             {"store_id": "lubm", "rdf": filler, "format": "ntriples"})
        t_kill = time.monotonic()
        primary["proc"].kill()
        core = RouterCore(
            [(rec["name"], rec["base"]) for rec in [primary] + followers],
            probe_timeout_s=2.0, evict_after=1, promote_after=1,
            promote_cooldown_s=0.0,
        )
        failover_ms = None
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            core.probe_once()
            prom = core.primary()
            if prom is not None and prom.name != "primary":
                st, _res = post(prom.url, "/store/query",
                                {"store_id": "lubm", "sparql": read_mix[0]})
                if st == 200:
                    failover_ms = (time.monotonic() - t_kill) * 1000.0
                    break
            time.sleep(0.02)
        if failover_ms is None:
            raise RuntimeError(f"failover never completed: {core.stats()}")
        block["failover_ms"] = round(failover_ms, 1)
        block["promoted"] = core.primary().name
        return block
    finally:
        for rec in procs:
            if rec["proc"].poll() is None:
                rec["proc"].kill()
                rec["proc"].wait(timeout=30)
            rec["log"].close()
        shutil.rmtree(root, ignore_errors=True)


def main():
    import jax

    if os.environ.get("KOLIBRIE_BENCH_CPU"):
        # The env preloads jax with the axon (TPU tunnel) platform via
        # sitecustomize; JAX_PLATFORMS is too late.  This is the reliable
        # CPU override (same mechanism as tests/conftest.py).
        jax.config.update("jax_platforms", "cpu")
        # 8 virtual devices so the sharded_serving sweep exercises the
        # real mesh path; XLA reads the flag at (lazy) backend init, which
        # has not happened yet in this child
        _flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _flags:
            os.environ["XLA_FLAGS"] = (
                _flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    from kolibrie_tpu.optimizer.device_engine import PreparedQuery
    from kolibrie_tpu.query.executor import execute_query_volcano

    def note(msg):
        print(f"[bench] {msg}", file=sys.stderr, flush=True)

    note("building db")
    db, t_load = build_db()
    note(f"db built in {t_load:.1f}s; querying backend")
    platform = jax.devices()[0].platform
    note(f"platform={platform}")
    # Off-TPU (CPU fallback attempt) the full dispatch protocol takes >15
    # minutes; a reduced protocol keeps the attempt inside the supervisor's
    # per-attempt timeout while still measuring the same pipeline.
    if platform == "tpu":
        n_dispatch, scan_k, gap = N_DISPATCH, SCAN_K, DISPATCH_GAP_S
    else:
        n_dispatch, scan_k, gap = 5, 4, 0.0

    # ---- host baseline: full e2e and operator-pipeline-only --------------
    db.execution_mode = "host"
    host_e2e = float("inf")
    host_e2e_cold = None
    for _ in range(4):
        t0 = time.perf_counter()
        host_rows = execute_query_volcano(JOIN_QUERY, db)
        dt = time.perf_counter() - t0
        if host_e2e_cold is None:
            host_e2e_cold = dt  # first call: parse+plan+display-cache build
        host_e2e = min(host_e2e, dt)

    note(f"host e2e done ({host_e2e:.2f}s best)")
    prep = PreparedQuery(db, JOIN_QUERY)
    prep.calibrate()  # host-side exact capacities; no device I/O
    note("calibrated")
    host_exec = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        _table, _counts = prep.lowered.host_execute()
        host_exec = min(host_exec, time.perf_counter() - t0)

    # ---- native (threaded C++) twin of the same operator pipeline --------
    # Baseline floor for what the reference's SIMD+rayon join achieves on
    # one node (shared/src/join_algorithm.rs:19-131): scans through the
    # store's sorted orders, kn_join_u32 on subject, native column gathers.
    # vs_baseline divides by the STRONGEST host engine (numpy or native).
    native_exec = None
    try:
        from kolibrie_tpu.native.join_native import (
            available as native_available,
            gather_native,
            join_indices_native,
        )

        if native_available():
            pid_w = db.dictionary.lookup(
                "http://xmlns.com/foaf/0.1/workplaceHomepage"
            )
            pid_s = db.dictionary.lookup(
                "https://data.example/ontology#annual_salary"
            )

            def native_pipeline():
                s1, _p1, o1 = db.store.match(p=pid_w)
                s2, _p2, o2 = db.store.match(p=pid_s)
                li, ri = join_indices_native(s1, s2)
                return (
                    gather_native(s1, li),
                    gather_native(o1, li),
                    gather_native(o2, ri),
                )

            e_col, _w, _v = native_pipeline()  # warm (thread pool, caches)
            assert len(e_col) == len(host_rows), (
                f"native twin rows {len(e_col)} != host {len(host_rows)}"
            )
            native_exec = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                native_pipeline()
                native_exec = min(native_exec, time.perf_counter() - t0)
    except Exception as e:  # never let the twin kill the capture
        note(f"native twin unavailable: {e}")
    host_best = min(host_exec, native_exec) if native_exec else host_exec

    # ---- device: warm, then timed dispatches (NO readback in the loop) ---
    out = prep.run()
    jax.block_until_ready(out)
    note("first device dispatch (compile) done")
    out = prep.run()
    jax.block_until_ready(out)
    times = []
    for _ in range(n_dispatch):
        t0 = time.perf_counter()
        out = prep.run()
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
        time.sleep(gap)
    dev_t = min(times)

    # ---- amortized: K plan executions per dispatch (tunnel latency is
    # ~1ms/dispatch and swamps a sub-ms plan; the scan carries a dependency
    # so XLA cannot hoist the body) -----------------------------------------
    note(f"single-dispatch loop done (best {min(times)*1e3:.2f} ms)")

    def time_amortized(n_samples):
        ok = prep.run_amortized(scan_k)
        jax.block_until_ready(ok)
        note("amortized variant compiled")
        ts = []
        for _ in range(n_samples):
            t0 = time.perf_counter()
            ok = prep.run_amortized(scan_k)
            jax.block_until_ready(ok)
            ts.append(time.perf_counter() - t0)
            time.sleep(gap)
        return ok, min(ts) / scan_k

    outk, dev_tk = time_amortized(n_dispatch)

    # ---- Pallas vs XLA join formulation on the SAME engine plan ----------
    # (the default path picked above is Pallas on TPU / XLA elsewhere; the
    # toggle is a static jit arg, so each setting compiles separately.)
    # Off-TPU the "Pallas" number runs the interpreter lowering — the same
    # code path tier-1 exercises — and is labeled as such
    # (pallas_join_timing_basis) instead of dropped to null: a change that
    # 10x-es the fallback path should show up in the capture, and on CPU
    # the interpreter costs ~0.4s/exec at this scale, not minutes.
    pallas_reps = max(5, n_dispatch // 3) if platform == "tpu" else 2
    pallas_basis = "tpu" if platform == "tpu" else "interpreter"
    os.environ["KOLIBRIE_PALLAS"] = "off"
    _, xla_tk = time_amortized(pallas_reps)
    os.environ["KOLIBRIE_PALLAS"] = "force"
    _, pallas_tk = time_amortized(pallas_reps)
    del os.environ["KOLIBRIE_PALLAS"]

    # ---- correctness AFTER timing (readback poisons later dispatches) ----
    rows = prep.fetch(out)
    assert rows == sorted(host_rows), (
        f"device rows ({len(rows)}) != host rows ({len(host_rows)})"
    )
    import numpy as np

    assert int(np.asarray(outk[1])[0]) == len(host_rows)

    # ---- plan-template cache: constant-variants share one executable -----
    # (AFTER the timing loops: the sweep reads results back per variant.)
    note("plan-template variant sweep")
    from kolibrie_tpu.optimizer.device_engine import device_compile_stats

    TPL_QUERY = (
        "PREFIX ds: <https://data.example/ontology#> "
        'SELECT ?e ?s WHERE { ?e ds:title "Engineer" . '
        "?e ds:annual_salary ?s . FILTER(?s > %d) }"
    )
    db.execution_mode = "device"
    c0 = device_compile_stats()
    t0 = time.perf_counter()
    execute_query_volcano(TPL_QUERY % 30000, db)
    tpl_cold_ms = (time.perf_counter() - t0) * 1000.0
    c1 = device_compile_stats()
    tpl_lat = []
    for k in range(1, 16):
        t0 = time.perf_counter()
        execute_query_volcano(TPL_QUERY % (30000 + k * 2500), db)
        tpl_lat.append((time.perf_counter() - t0) * 1000.0)
    c2 = device_compile_stats()
    tpl_lat.sort()
    plan_template = {
        "variants": 16,
        "compiles_first_variant": c1["run_plan"] - c0["run_plan"],
        "compiles_remaining_15": c2["run_plan"] - c1["run_plan"],
        "cold_first_variant_ms": round(tpl_cold_ms, 2),
        "warm_variant_ms_p50": round(tpl_lat[len(tpl_lat) // 2], 3),
        "warm_variant_ms_p95": round(tpl_lat[-1], 3),
    }
    note(f"plan-template sweep done ({plan_template})")

    # ---- resilience under 10% injected fault load ------------------------
    # Serving-path TemplateBatcher over the same store with a seeded fault
    # plan firing on 10% of device dispatches: failed dispatches degrade to
    # the host interpreter behind the per-template circuit breaker, so the
    # client sees rows either way.  Reports p99 request latency and the
    # shed rate (deadline/admission rejections).  Never kills the capture:
    # any failure lands as {"error": ...} in the secondary block.
    note("resilience fault-load sweep")
    resilience = None
    try:
        from kolibrie_tpu.frontends.http_server import TemplateBatcher
        from kolibrie_tpu.resilience.breaker import breaker_board
        from kolibrie_tpu.resilience.deadline import (
            Deadline,
            deadline_scope,
        )
        from kolibrie_tpu.resilience.errors import KolibrieError
        from kolibrie_tpu.resilience.faultinject import (
            FaultPlan,
            InjectedCompileError,
        )

        batcher = TemplateBatcher(db)
        fplan = FaultPlan(seed=11)
        fplan.add("device.execute", error=InjectedCompileError, rate=0.10)
        n_req, lat, served, shed = 120, [], 0, 0
        with fplan.installed():
            for k in range(n_req):
                q = TPL_QUERY % (30000 + (k % 16) * 2500)
                t0 = time.perf_counter()
                try:
                    with deadline_scope(Deadline.from_ms(5000)):
                        batcher.submit(q)
                    served += 1
                except KolibrieError:
                    shed += 1
                lat.append((time.perf_counter() - t0) * 1000.0)
        lat.sort()
        breakers = breaker_board(db).snapshot().values()
        resilience = {
            "requests": n_req,
            "injected_fault_rate": 0.10,
            "injected_fires": sum(
                r["fires"] for r in fplan.snapshot().values()
            ),
            "served": served,
            "shed": shed,
            "shed_rate": round(shed / n_req, 4),
            "latency_ms_p50": round(lat[len(lat) // 2], 3),
            "latency_ms_p99": round(
                lat[min(len(lat) - 1, int(round(0.99 * (len(lat) - 1))))], 3
            ),
            "degraded_served": sum(b["degraded_served"] for b in breakers),
            "breaker_trips": sum(b["trips"] for b in breakers),
        }
    except Exception as e:  # noqa: BLE001 — bench must survive its probes
        resilience = {"error": repr(e)}
    note(f"resilience sweep done ({resilience})")

    # ---- observability overhead: instrumented vs disabled ----------------
    # Same warm plan-template path measured twice in one process: once with
    # spans+metrics recording, once with the obs runtime kill switch off
    # (what KOLIBRIE_OBS_DISABLED=1 sets at import).  Budget: < 3% delta.
    note("observability overhead sweep")
    obs_block = None
    try:
        from kolibrie_tpu.obs import runtime as obs_runtime

        def obs_qps(n=60):
            t0 = time.perf_counter()
            for k in range(n):
                execute_query_volcano(TPL_QUERY % (30000 + (k % 16) * 2500), db)
            return n / (time.perf_counter() - t0)

        # interleaved best-of-3 per mode: a single A/B pair is dominated
        # by scheduler/frequency noise at this per-query cost (~10 ms)
        obs_qps(12)  # warm both the executor path and the metric children
        instrumented_qps = disabled_qps = 0.0
        try:
            for _ in range(3):
                obs_runtime.set_enabled(True)
                instrumented_qps = max(instrumented_qps, obs_qps())
                obs_runtime.set_enabled(False)
                disabled_qps = max(disabled_qps, obs_qps())
        finally:
            obs_runtime.set_enabled(True)
        overhead_pct = (disabled_qps - instrumented_qps) / disabled_qps * 100.0
        obs_block = {
            "instrumented_qps": round(instrumented_qps, 1),
            "disabled_qps": round(disabled_qps, 1),
            "overhead_pct": round(overhead_pct, 2),
            "budget_pct": 3.0,
            "within_budget": overhead_pct < 3.0,
        }
        # timeline ring: cost of one registry snapshot (the /debug/timeline
        # sampler pays this every interval — must stay sub-ms territory)
        from kolibrie_tpu.obs import timeseries as obs_ts

        ring = obs_ts.TimeSeriesRing(capacity=8)
        t0 = time.perf_counter()
        for _ in range(5):
            ring.record()
        obs_block["timeline_snapshot_ms"] = round(
            (time.perf_counter() - t0) / 5 * 1000.0, 3
        )
        # EXPLAIN ANALYZE: per-query cost of running under a capture
        # (stats fetch piggybacks the dispatch; this is the debug-path
        # price, not a hot-path tax)
        from kolibrie_tpu.obs import analyze as obs_analyze

        t0 = time.perf_counter()
        with obs_analyze.capture():
            execute_query_volcano(TPL_QUERY % 30000, db)
        obs_block["analyze_query_ms"] = round(
            (time.perf_counter() - t0) * 1000.0, 3
        )
    except Exception as e:  # noqa: BLE001 — bench must survive its probes
        obs_block = {"error": repr(e)}
    note(f"observability sweep done ({obs_block})")

    # ---- store_ingest: sustained interleaved insert+query throughput -----
    # The ISSUE-4 acceptance workload: small insert batches + window-evict
    # deletes over the employee store, incremental (delta segments, base
    # frozen) vs a twin forced down the pre-PR full-invalidation path
    # (every compact rebuilds all orders, re-uploads the whole store, and
    # re-keys every cached plan).  Two numbers: ``speedup`` times the
    # ingest/refresh path alone (compact + order maintenance + device
    # upload + scan-cap calibration — the costs this PR makes O(delta));
    # ``workload_speedup`` is end-to-end with a cached-template serving
    # query per batch, whose shared device dispatch+sync cost (~14 ms on
    # CPU, identical for both twins) compresses the visible ratio.
    # Results must be byte-identical per batch; h2d traffic comes from the
    # kolibrie_store_h2d_bytes_total counter split by segment.
    note("store_ingest sweep")
    store_ingest = None
    try:
        from kolibrie_tpu.obs import metrics as obs_metrics
        from kolibrie_tpu.optimizer.device_engine import template_scan_cap

        def h2d_snapshot():
            fam = obs_metrics.REGISTRY.get("kolibrie_store_h2d_bytes_total")
            if fam is None:
                return {}
            return {lv[0]: c.value for lv, c in fam.children()}

        # Bound-object point lookup: the parameterized-template serving
        # query (one cached plan, constants hoisted) fired against the
        # company streamed in the current batch.
        serve_q = (
            "PREFIX foaf: <http://xmlns.com/foaf/0.1/> "
            "PREFIX ds: <https://data.example/ontology#> "
            "SELECT ?employee ?salary WHERE { "
            "?employee foaf:workplaceHomepage <https://company%d.example/> . "
            "?employee ds:annual_salary ?salary . "
            "FILTER(?salary > 50000) }"
        )

        def ingest_loop(dbi, tag, serve, batches=24):
            """Stream 8 triples/batch with window-evict deletes two batches
            behind.  ``serve`` True runs the cached-template query each
            batch (end-to-end serving workload); False instead refreshes
            everything a serving tick depends on — compact, live order,
            device segment, scan-cap calibration — isolating the store
            maintenance path from the shared query-dispatch cost."""
            pid_w = dbi.encode_term_str(
                "<http://xmlns.com/foaf/0.1/workplaceHomepage>"
            )
            if serve:  # warm the cached template outside the timed region
                execute_query_volcano(serve_q % 0, dbi)
            else:
                dbi.store.compact()
                dbi.store.order("pos")
                dbi.store.device_segment("pos")
                template_scan_cap(dbi, "pos", 1)
            streamed = []  # per batch: [(s_id, o_id), ...] homepage rows
            per_batch_rows = []
            t0 = time.perf_counter()
            for b in range(batches):
                lines, batch_rows = [], []
                for j in range(4):
                    e = f"<https://data.example/{tag}/{b}_{j}>"
                    c = f"<https://company{(b + j) % 500}.example/>"
                    lines.append(
                        f"{e} <http://xmlns.com/foaf/0.1/workplaceHomepage> "
                        f"{c} ."
                    )
                    lines.append(
                        f"{e} <https://data.example/ontology#annual_salary> "
                        f'"{80000 + b * 10 + j}" .'
                    )
                    batch_rows.append(
                        (dbi.encode_term_str(e), dbi.encode_term_str(c))
                    )
                streamed.append(batch_rows)
                dbi.parse_ntriples("\n".join(lines))
                if b >= 2:  # window-evict the batch streamed two firings ago
                    for s, o in streamed[b - 2]:
                        dbi.store.remove(s, pid_w, o)
                if serve:
                    per_batch_rows.append(
                        sorted(map(tuple, execute_query_volcano(serve_q % (b % 500), dbi)))
                    )
                else:
                    dbi.store.compact()
                    dbi.store.order("pos")
                    dbi.store.device_segment("pos")
                    template_scan_cap(dbi, "pos", 1)
            return time.perf_counter() - t0, per_batch_rows

        db_inc, _ = build_db()
        db_inc.execution_mode = db.execution_mode
        db_oracle, _ = build_db()
        db_oracle.execution_mode = db.execution_mode
        db_oracle.store.incremental = False  # pre-PR full-invalidation twin

        # ingest path alone (what this PR optimizes), then the end-to-end
        # serving workload — same twins, disjoint entity tags so the second
        # loop's inserts are all fresh rows.
        h0 = h2d_snapshot()
        t_inc_m, _ = ingest_loop(db_inc, "stream-m", serve=False)
        h1 = h2d_snapshot()
        t_full_m, _ = ingest_loop(db_oracle, "stream-m", serve=False)
        h2 = h2d_snapshot()
        t_inc_q, rows_inc = ingest_loop(db_inc, "stream-q", serve=True)
        t_full_q, rows_full = ingest_loop(db_oracle, "stream-q", serve=True)
        identical = rows_inc == rows_full  # per-batch, already sorted
        store_ingest = {
            "batches": 24,
            "rows_per_batch": 8,
            "ingest_ms_per_batch_incremental": round(t_inc_m / 24 * 1e3, 2),
            "ingest_ms_per_batch_full": round(t_full_m / 24 * 1e3, 2),
            "speedup": round(t_full_m / t_inc_m, 2),
            "workload_s_incremental": round(t_inc_q, 3),
            "workload_s_full_invalidation": round(t_full_q, 3),
            "workload_speedup": round(t_full_q / t_inc_q, 2),
            "results_identical_to_oracle": identical,
            "h2d_delta_bytes_per_batch": round(
                (h1.get("delta", 0) - h0.get("delta", 0)) / 24, 1
            ),
            "h2d_base_bytes_per_batch_full": round(
                (h2.get("base", 0) - h1.get("base", 0)) / 24, 1
            ),
            "h2d_bytes_by_segment": {
                k: round(h2.get(k, 0) - h0.get(k, 0), 1) for k in h2
            },
        }
    except Exception as e:  # noqa: BLE001 — bench must survive its probes
        store_ingest = {"error": repr(e)}
    note(f"store_ingest sweep done ({store_ingest})")

    # ---- wcoj: worst-case-optimal vs Volcano on cyclic BGPs --------------
    # Two workloads.  (1) The AGM worst-case triangle: each relation is a
    # star-in plus star-out through a hub value (2M rows each, all equal
    # cardinality, so no scan is selective), EVERY pairwise join is M²
    # rows through the hub, yet only ~3M triangles close — WCOJ's
    # per-level intermediates must stay at the output scale.  (2) LUBM
    # Q2/Q9 (the cyclic LUBM shapes) on a miniature campus KG, Volcano vs
    # WCOJ device wall-clock.  Peak intermediate rows come from the
    # EXPLAIN host-oracle counts (matched= on binary joins, level rows=
    # on WCOJ levels).
    note("wcoj sweep")
    wcoj_block = None
    try:
        import re as _re

        from benches.lubm import LUBM_Q2, LUBM_Q9, generate_fast
        from kolibrie_tpu.query.engine import QueryEngine
        from kolibrie_tpu.query.sparql_database import SparqlDatabase

        def peak_intermediate(dbx, q):
            explain = QueryEngine(dbx).explain_device(q, exact_counts=True)
            joins = [
                int(m) for m in _re.findall(r"matched=(\d+)", explain)
            ]
            levels = [
                int(m)
                for ln in explain.splitlines()
                if ln.lstrip().startswith("level ?")
                for m in _re.findall(r"rows=(\d+)", ln)
            ]
            return max(joins + levels, default=0)

        def timed(dbx, q, n=5):
            rows = execute_query_volcano(q, dbx)  # warm: compile + caps
            best = float("inf")
            for _ in range(n):
                t0 = time.perf_counter()
                execute_query_volcano(q, dbx)
                best = min(best, time.perf_counter() - t0)
            return best * 1000.0, len(rows)

        def ab(dbx, q, n=5):
            os.environ["KOLIBRIE_WCOJ"] = "off"
            v_ms, v_rows = timed(dbx, q, n)
            v_peak = peak_intermediate(dbx, q)
            os.environ["KOLIBRIE_WCOJ"] = "auto"
            w_ms, w_rows = timed(dbx, q, n)
            w_peak = peak_intermediate(dbx, q)
            assert v_rows == w_rows, f"row mismatch {v_rows} vs {w_rows}"
            return {
                "rows": w_rows,
                "volcano_ms": round(v_ms, 3),
                "wcoj_ms": round(w_ms, 3),
                "speedup": round(v_ms / w_ms, 3) if w_ms else None,
                "volcano_peak_intermediate_rows": v_peak,
                "wcoj_peak_intermediate_rows": w_peak,
            }

        wcoj_mode_before = os.environ.get("KOLIBRIE_WCOJ")
        try:
            # AGM worst case: p1 = {x_i->y_0} ∪ {x_0->y_i} and cyclically
            # for p2 (y->z), p3 (z->x) — all relations 2M-1 rows, every
            # pairwise join M² through the hub, output 3M-2 triangles
            M = 64
            tlines = []

            def star(pred, a, b):
                for i in range(M):
                    tlines.append(
                        f"<https://t.example/{a}{i}> "
                        f"<https://t.example/{pred}> "
                        f"<https://t.example/{b}0> ."
                    )
                    tlines.append(
                        f"<https://t.example/{a}0> "
                        f"<https://t.example/{pred}> "
                        f"<https://t.example/{b}{i}> ."
                    )

            star("p1", "x", "y")
            star("p2", "y", "z")
            star("p3", "z", "x")
            tdb = SparqlDatabase()
            tdb.parse_ntriples("\n".join(tlines))
            tdb.execution_mode = db.execution_mode
            tri_q = (
                "PREFIX t: <https://t.example/> SELECT ?x ?y ?z WHERE "
                "{ ?x t:p1 ?y . ?y t:p2 ?z . ?z t:p3 ?x }"
            )

            ldb = SparqlDatabase()
            ls, lp, lo = generate_fast(30, ldb.dictionary)
            ldb.store.add_batch(ls, lp, lo)
            ldb.store.compact()
            ldb.execution_mode = db.execution_mode

            wcoj_block = {
                "triangle_agm": {"m": M, **ab(tdb, tri_q)},
                "lubm_q2": ab(ldb, LUBM_Q2),
                "lubm_q9": ab(ldb, LUBM_Q9),
            }
        finally:
            if wcoj_mode_before is None:
                os.environ.pop("KOLIBRIE_WCOJ", None)
            else:
                os.environ["KOLIBRIE_WCOJ"] = wcoj_mode_before
    except Exception as e:  # noqa: BLE001 — bench must survive its probes
        wcoj_block = {"error": repr(e)}
    note(f"wcoj sweep done ({wcoj_block})")

    # ---- pallas_probe: fused lex-probe kernels vs the XLA op chain -------
    # The WCOJ level expansion A/B (ISSUE 11): identical plan, identical
    # rows, the per-slot select/dedup/existence math either fused into the
    # Pallas lex-probe kernels (KOLIBRIE_PALLAS=force) or left as the
    # chain of separate XLA ops (off).  Two workloads: the employee-100K
    # join forced onto the WCOJ path (KOLIBRIE_WCOJ=force relaxes the
    # 3-pattern floor) and the cyclic LUBM Q2.  Off-TPU the force side
    # runs the Pallas interpreter and is labeled as such.
    note("pallas probe sweep")
    pallas_probe_block = None
    try:
        from benches.lubm import LUBM_Q2 as _PQ2, generate_fast as _pgen
        from kolibrie_tpu.query.sparql_database import (
            SparqlDatabase as _PDb,
        )

        def _probe_timed(dbx, q, n):
            rows = execute_query_volcano(q, dbx)  # warm: compile + caps
            best = float("inf")
            for _ in range(n):
                t0 = time.perf_counter()
                execute_query_volcano(q, dbx)
                best = min(best, time.perf_counter() - t0)
            return best * 1000.0, len(rows)

        def _probe_ab(dbx, q, wcoj, n):
            os.environ["KOLIBRIE_WCOJ"] = wcoj
            os.environ["KOLIBRIE_PALLAS"] = "off"
            x_ms, x_rows = _probe_timed(dbx, q, n)
            os.environ["KOLIBRIE_PALLAS"] = "force"
            p_ms, p_rows = _probe_timed(dbx, q, n)
            assert x_rows == p_rows, f"row mismatch {x_rows} vs {p_rows}"
            return {
                "rows": x_rows,
                "xla_chain_ms": round(x_ms, 3),
                "fused_probe_ms": round(p_ms, 3),
                "fused_vs_xla": round(x_ms / p_ms, 3) if p_ms else None,
            }

        probe_env_before = {
            k: os.environ.get(k) for k in ("KOLIBRIE_WCOJ", "KOLIBRIE_PALLAS")
        }
        try:
            pdb_ = _PDb()
            pls, plp, plo = _pgen(30, pdb_.dictionary)
            pdb_.store.add_batch(pls, plp, plo)
            pdb_.store.compact()
            pdb_.execution_mode = db.execution_mode
            probe_n = 5 if platform == "tpu" else 2
            pallas_probe_block = {
                "timing_basis": (
                    "tpu" if platform == "tpu" else "interpreter"
                ),
                "employee_100k": _probe_ab(
                    db, JOIN_QUERY, "force", probe_n
                ),
                "lubm_q2": _probe_ab(pdb_, _PQ2, "auto", probe_n),
            }
        finally:
            for k, v in probe_env_before.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
    except Exception as e:  # noqa: BLE001 — bench must survive its probes
        pallas_probe_block = {"error": repr(e)}
    note(f"pallas probe sweep done ({pallas_probe_block})")

    # ---- durability: WAL ingest overhead + cold-start recovery -----------
    # ISSUE-7 acceptance numbers.  (1) The same streamed ntriples ingest
    # with the WAL attached (default group-commit fsync) vs detached —
    # target < 15% overhead.  (2) Cold-start recovery of the employee
    # store: once replaying the full mutation history from the WAL, once
    # from a snapshot generation (the steady-state boot path).
    note("durability sweep")
    durability_block = None
    try:
        import shutil as _shutil
        import tempfile as _tempfile

        from kolibrie_tpu.durability.manager import DurabilityManager
        from kolibrie_tpu.query.sparql_database import SparqlDatabase

        D_BATCHES, D_ROWS, D_REPEATS = 30, 2048, 5

        def wal_ingest(dbx, tag):
            t0 = time.perf_counter()
            for b in range(D_BATCHES):
                lines = [
                    f"<https://d.example/{tag}/{b}_{j}> "
                    f"<https://d.example/p{j % 4}> "
                    f"<https://d.example/v{b}_{j}> ."
                    for j in range(D_ROWS)
                ]
                dbx.parse_ntriples("\n".join(lines))
            return time.perf_counter() - t0

        rec_dir = _tempfile.mkdtemp(prefix="kolibrie-bench-rec-")
        try:
            # best-of-N on each side: one ingest is ~0.15s, where a single
            # scheduler hiccup would swamp a 15% overhead budget
            t_wal_off = t_wal_on = float("inf")
            wal_bytes = 0
            for r in range(D_REPEATS):
                db_off = SparqlDatabase()
                t_wal_off = min(t_wal_off, wal_ingest(db_off, f"off{r}"))
                wal_dir = _tempfile.mkdtemp(prefix="kolibrie-bench-wal-")
                try:
                    mgr = DurabilityManager(wal_dir, fsync_policy="group")
                    mgr.start()
                    db_on = SparqlDatabase()
                    mgr.attach("bench", db_on)
                    t_wal_on = min(t_wal_on, wal_ingest(db_on, f"on{r}"))
                    mgr.flush()
                    wal_bytes = mgr.wal.appended_bytes
                    mgr.close()
                finally:
                    _shutil.rmtree(wal_dir, ignore_errors=True)

            # cold start: journal the employee store's full history, then
            # recover once from the WAL and once from a snapshot
            mgr = DurabilityManager(rec_dir, fsync_policy="group")
            mgr.start()
            db_emp = SparqlDatabase()
            mgr.attach("employee", db_emp)
            db_emp.parse_ntriples(db.to_ntriples())
            mgr.close()
            mgr2 = DurabilityManager(rec_dir, fsync_policy="group")
            t0 = time.perf_counter()
            rec = mgr2.recover()
            t_recover_wal = time.perf_counter() - t0
            n_recovered = len(rec.stores["employee"].store)
            assert n_recovered == len(db.store), (n_recovered, len(db.store))
            gen = mgr2.snapshot({"employee": rec.stores["employee"]})
            mgr2.close()
            mgr3 = DurabilityManager(rec_dir, fsync_policy="group")
            t0 = time.perf_counter()
            rec2 = mgr3.recover()
            t_recover_snap = time.perf_counter() - t0
            assert len(rec2.stores["employee"].store) == n_recovered
            replay_stats = dict(rec.stats)
            mgr3.close()
        finally:
            _shutil.rmtree(rec_dir, ignore_errors=True)

        durability_block = {
            "fsync_policy": "group",
            "ingest_batches": D_BATCHES,
            "rows_per_batch": D_ROWS,
            "ingest_repeats": D_REPEATS,
            "ingest_s_wal_off": round(t_wal_off, 4),
            "ingest_s_wal_on": round(t_wal_on, 4),
            "wal_overhead_pct": round(
                (t_wal_on - t_wal_off) / t_wal_off * 100.0, 1
            ),
            "wal_overhead_target_pct": 15.0,
            "wal_bytes_appended": wal_bytes,
            "recovery_triples": n_recovered,
            "recovery_from_wal_s": round(t_recover_wal, 3),
            "recovery_replayed_records": replay_stats["replayed_records"],
            "recovery_replayed_bytes": replay_stats["replayed_bytes"],
            "recovery_from_snapshot_s": round(t_recover_snap, 3),
            "recovery_snapshot_generation": gen,
        }
    except Exception as e:  # noqa: BLE001 — bench must survive its probes
        durability_block = {"error": repr(e)}
    note(f"durability sweep done ({durability_block})")

    # ---- sharded_serving: batched template groups across the mesh --------
    # ISSUE-8 acceptance: aggregate qps of the sharded front door (one
    # shard_map dispatch per same-template group, parallel/sharded_serving)
    # vs serving the same group on the same mesh one dispatch per query
    # (ShardedDatabase.execute, the documented bench/diagnostic path) —
    # i.e. what template batching buys over the mesh's per-query front
    # door.  Per-shard imbalance and fixed-cap all-to-all exchange bytes
    # ride along, plus two transparent secondary twins: a 1-device-mesh
    # ShardedDatabase driven per-query and the host volcano engine (also
    # the row oracle).  On the CPU proxy (8 virtual devices, one core)
    # the shards execute sequentially, so "sharded beats one device" is
    # unmeasurable here by construction — the speedup below isolates the
    # dispatch amortization that survives serialization; the TPU capture
    # additionally gets the 8-way data parallelism per dispatch.
    note("sharded_serving sweep")
    sharded_block = None
    try:
        from benches.lubm import generate_fast as _lubm_gen
        from kolibrie_tpu.obs import metrics as obs_metrics
        from kolibrie_tpu.parallel import make_mesh
        from kolibrie_tpu.parallel.sharded_serving import (
            ShardedDatabase,
            attach_sharded,
            detach_sharded,
        )
        from kolibrie_tpu.query.executor import execute_queries_batched
        from kolibrie_tpu.query.sparql_database import SparqlDatabase

        n_dev = jax.device_count()
        if n_dev < 2:
            raise RuntimeError(
                f"{n_dev} device(s): the mesh front door needs >= 2"
            )

        def shard_xbytes():
            fam = obs_metrics.REGISTRY.get(
                "kolibrie_shard_exchanged_bytes_total"
            )
            if fam is None:
                return 0.0
            return sum(c.value for _, c in fam.children())

        sdb = SparqlDatabase()
        ls, lp, lo = _lubm_gen(2, sdb.dictionary)
        sdb.store.add_batch(ls, lp, lo)
        sdb.execution_mode = "host"
        _ub = "PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#> "
        group = [
            _ub + "SELECT ?x ?c WHERE { ?x ub:worksFor "
            f"<http://www.Department{d}.University{u}.edu> . "
            "?x ub:teacherOf ?c . }"
            for u in range(2)
            for d in range(4)
        ]  # B=8 constant-variants of one serving template
        B, N_ROUNDS = len(group), 12

        sh = attach_sharded(sdb, make_mesh(min(8, n_dev)))
        sh.refresh()
        mesh_rows = execute_queries_batched(sdb, group)  # warm: compile
        x0 = shard_xbytes()
        t0 = time.perf_counter()
        for _ in range(N_ROUNDS):
            execute_queries_batched(sdb, group)
        t_batched = time.perf_counter() - t0
        xbytes_round = (shard_xbytes() - x0) / N_ROUNDS
        sh_stats = sh.stats()

        # twin 1: same mesh, one dispatch per query (no template batching)
        pq_rows = [sorted(sh.execute(q)) for q in group]  # warm
        t0 = time.perf_counter()
        for _ in range(N_ROUNDS):
            for q in group:
                sh.execute(q)
        t_per_query = time.perf_counter() - t0

        # twin 2: the same ShardedDatabase front door on a 1-device mesh
        sh1 = ShardedDatabase(sdb, make_mesh(1))
        sh1.refresh()
        for q in group:
            sh1.execute(q)  # warm
        t0 = time.perf_counter()
        for _ in range(N_ROUNDS):
            for q in group:
                sh1.execute(q)
        t_one_dev = time.perf_counter() - t0

        # twin 3 / row oracle: detached host volcano engine
        detach_sharded(sdb)
        solo_rows = execute_queries_batched(sdb, group)  # warm twin caches
        t0 = time.perf_counter()
        for _ in range(N_ROUNDS):
            execute_queries_batched(sdb, group)
        t_volcano = time.perf_counter() - t0
        assert mesh_rows == solo_rows, "mesh rows diverge from twin"
        assert pq_rows == [sorted(r) for r in solo_rows], (
            "per-query mesh rows diverge from twin"
        )

        qps_batched = B * N_ROUNDS / t_batched
        qps_per_query = B * N_ROUNDS / t_per_query
        sharded_block = {
            "shards": sh_stats["shards"],
            "batch": B,
            "rounds": N_ROUNDS,
            "rows_per_query": [len(r) for r in mesh_rows],
            "aggregate_qps_sharded": round(qps_batched, 1),
            "aggregate_qps_per_query_mesh": round(qps_per_query, 1),
            "speedup": round(qps_batched / qps_per_query, 2),
            "speedup_target": 4.0,
            "aggregate_qps_one_device_mesh": round(
                B * N_ROUNDS / t_one_dev, 1
            ),
            "aggregate_qps_host_volcano": round(
                B * N_ROUNDS / t_volcano, 1
            ),
            "cpu_proxy": (
                "8 virtual XLA devices share one core, so shard compute "
                "serializes; speedup is batched-vs-per-query dispatch on "
                "the same mesh, and the one-device/host twins are listed "
                "for scale — re-run on a real 8-device mesh for the "
                "parallel capture"
            ),
            "dispatch_ms_per_group": round(t_batched / N_ROUNDS * 1e3, 2),
            "shard_imbalance": round(sh_stats.get("imbalance", 1.0), 3),
            "occupancy": sh_stats.get("occupancy"),
            "exchanged_bytes_per_group": round(xbytes_round, 1),
            "cap_hits": sh_stats["cap_hits"],
            "compile_surfaces": sh_stats["compile_surfaces"],
            "results_identical_to_twin": True,
        }
    except Exception as e:  # noqa: BLE001 — bench must survive its probes
        sharded_block = {"error": repr(e)}
    note(f"sharded_serving sweep done ({sharded_block})")

    # ---- compile tail: churn cold/warm, specialized vs interp vs disk ----
    # A stream of FRESH template shapes (the serving regime the compile
    # tail hurts): per-template first-execution latency (cold) and
    # second-variant latency (warm) under (a) the specialized
    # one-compile-per-template path, (b) the plan-bytecode interpreter
    # (one executable per size class), and — CPU only, needs fresh
    # processes — (c) a restarted process over a populated persistent
    # cache, plus cold-start-to-first-result with/without that cache.
    note("compile_tail sweep")
    compile_tail = None
    try:
        import tempfile

        CHURN_N = 10

        def churn_queries(salt):
            out = []
            for i in range(CHURN_N):
                conds = " && ".join(
                    [f"?s > {28000 + 13 * i + salt}"]
                    + [
                        f"?s != {40000 + 997 * j + i}"
                        for j in range(i + 1)
                    ]
                )
                out.append(
                    "PREFIX ds: <https://data.example/ontology#> "
                    'SELECT ?e ?s WHERE { ?e ds:title "Engineer" . '
                    f"?e ds:annual_salary ?s . FILTER({conds}) }}"
                )
            return out

        def churn_lat(salt):
            cold, warm = [], []
            for q in churn_queries(salt):
                t0 = time.perf_counter()
                execute_query_volcano(q, db)
                cold.append((time.perf_counter() - t0) * 1000.0)
                t0 = time.perf_counter()
                execute_query_volcano(q, db)
                warm.append((time.perf_counter() - t0) * 1000.0)
            cold.sort()
            warm.sort()
            return {
                "cold_ms_p50": round(cold[len(cold) // 2], 3),
                "cold_ms_p99": round(cold[-1], 3),
                "warm_ms_p50": round(warm[len(warm) // 2], 3),
                "warm_ms_p99": round(warm[-1], 3),
            }

        from kolibrie_tpu.optimizer.plan_interp import override_mode

        c0 = device_compile_stats()
        with override_mode("off"):
            spec_lat = churn_lat(0)
        c1 = device_compile_stats()
        with override_mode("force"):
            interp_lat = churn_lat(1)
        c2 = device_compile_stats()
        spec_lat["compiles"] = c1["run_plan"] - c0["run_plan"]
        interp_lat["specialized_compiles"] = c2["run_plan"] - c1["run_plan"]
        interp_lat["size_class_compiles"] = c2["run_interp"] - c1["run_interp"]
        compile_tail = {
            "churn_templates": CHURN_N,
            "specialized": spec_lat,
            "interpreter": interp_lat,
        }
        if platform != "tpu":
            # restart legs: child processes sharing one cache directory
            cc_dir = tempfile.mkdtemp(prefix="kolibrie-bench-cc-")
            child = (
                "import json, os, sys, time\n"
                "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
                f"sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
                "from kolibrie_tpu.query import compile_cache\n"
                "from kolibrie_tpu.query.prewarm import replay_manifest\n"
                "from kolibrie_tpu.query.executor import execute_query_volcano\n"
                "from kolibrie_tpu.query.sparql_database import SparqlDatabase\n"
                "mode, root = sys.argv[1], sys.argv[2]\n"
                "if mode != 'nocache':\n"
                "    compile_cache.enable(explicit_dir=root)\n"
                "db = SparqlDatabase()\n"
                "rows = []\n"
                "for i in range(400):\n"
                "    e = f'<https://data.example/e{i}>'\n"
                "    rows.append(f'{e} <https://data.example/ontology#title> \"Engineer\" .')\n"
                "    rows.append(f'{e} <https://data.example/ontology#annual_salary> \"{20000 + i * 37}\" .')\n"
                "db.parse_ntriples('\\n'.join(rows))\n"
                "db.execution_mode = 'device'\n"
                "QS = json.loads(sys.argv[3])\n"
                "if mode == 'warm':\n"
                "    replay_manifest(db, root=root)\n"
                "lat = []\n"
                "for q in QS:\n"
                "    t0 = time.perf_counter()\n"
                "    execute_query_volcano(q, db)\n"
                "    lat.append((time.perf_counter() - t0) * 1000.0)\n"
                "if mode == 'seed':\n"
                "    compile_cache.save_manifest(root)\n"
                "first = lat[0]\n"
                "lat.sort()\n"
                "print(json.dumps({'first_ms': round(first, 3),\n"
                "                  'p50_ms': round(lat[len(lat) // 2], 3),\n"
                "                  'p99_ms': round(lat[-1], 3)}))\n"
            )
            qs_json = json.dumps(churn_queries(2))

            def run_child(mode):
                env = dict(os.environ)
                env.pop("KOLIBRIE_PLAN_INTERP", None)
                env.pop("KOLIBRIE_COMPILE_CACHE_DIR", None)
                env["JAX_PLATFORMS"] = "cpu"
                t0 = time.perf_counter()
                out = subprocess.run(
                    [sys.executable, "-c", child, mode, cc_dir, qs_json],
                    capture_output=True, text=True, timeout=300, env=env,
                )
                if out.returncode != 0:
                    raise RuntimeError(out.stderr[-800:])
                res = json.loads(out.stdout.splitlines()[-1])
                res["wall_s"] = round(time.perf_counter() - t0, 3)
                return res

            seed = run_child("seed")  # populates cache + manifest
            disk = run_child("warm")  # fresh process, cache + manifest hot
            no_cache = run_child("nocache")  # fresh process, no cache at all
            compile_tail["restart"] = {
                "first_process_churn": seed,
                "restarted_with_cache_churn": disk,
                "restarted_no_cache_churn": no_cache,
                "cold_start_to_first_result_ms": {
                    "with_cache": disk["first_ms"],
                    "without_cache": no_cache["first_ms"],
                },
            }
    except Exception as e:
        compile_tail = {"error": repr(e)}
    note(f"compile_tail sweep done ({compile_tail})")

    # ---- mqo: shared-prefix evaluation across a standing-query fleet -----
    # The PR-16 acceptance workload (docs/MQO.md).  (1) Fleet marginal-
    # cost curve: N standing windows share one scan/join prefix and
    # differ only in their filter; a fire round evaluates all N once,
    # shared (KOLIBRIE_MQO=force, standing scopes — the RSP fire-path
    # twin: same-content rounds are no-op mutation batches, so the
    # prefix cache key (prefix_fp, base_version, delta_epoch) holds) vs
    # independent (off).  Rows asserted identical per window; zero new
    # specialized compiles on the shared side.  Window content size is
    # seeded from the CITYBENCH_SWEEP grid (the RSP workload this fleet
    # models).  (2) Batcher mixed-template A/B: one dispatch of a mixed
    # same-prefix template group through execute_queries_batched, force
    # vs off.
    note("mqo shared-prefix fleet sweep")
    mqo_block = None
    try:
        from kolibrie_tpu.optimizer import mqo as mqo_mod
        from kolibrie_tpu.query.executor import execute_queries_batched
        from kolibrie_tpu.query.sparql_database import SparqlDatabase

        try:
            with open(
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "CITYBENCH_SWEEP.json")
            ) as f:
                _sizes = sorted({g["size"] for g in json.load(f)["grid"]})
            # the sweep's LARGEST window: prefix scan/join work must
            # dominate for the marginal-cost curve to be meaningful — at
            # toy sizes the per-query suffix overhead is the whole cost
            fleet_rows = _sizes[-1]
        except (OSError, ValueError, KeyError):
            fleet_rows = 50_000

        def fleet_db():
            dbf = SparqlDatabase()
            lines = []
            for i in range(fleet_rows):
                s = f"<http://e/s{i}>"
                lines.append(f'{s} <http://e/val> "{i % 100}" .')
                lines.append(f'{s} <http://e/kind> "k{i % 7}" .')
            dbf.parse_ntriples("\n".join(lines))
            return dbf

        def fleet_q(i):
            return (
                'SELECT ?s ?v WHERE { ?s <http://e/kind> "k3" . '
                f"?s <http://e/val> ?v . FILTER(?v > {i % 90}) }}"
            )

        def fire_round(dbf, n, owners):
            out = []
            for i in range(n):
                with mqo_mod.standing_scope(dbf, owners[i]):
                    out.append(execute_query_volcano(fleet_q(i), dbf))
            return out

        mqo_block = {"fleet_rows": fleet_rows}
        os.environ["KOLIBRIE_MQO"] = "off"
        for n in (1, 8, 64, 256):
            dbf = fleet_db()
            owners = [f"w{i}" for i in range(n)]
            for o in owners:
                mqo_mod.register_standing(dbf, o)
            os.environ["KOLIBRIE_MQO"] = "force"
            fire_round(dbf, n, owners)  # warm parse/plan caches + prefix
            comp0 = device_compile_stats()
            t0 = time.perf_counter()
            shared = fire_round(dbf, n, owners)
            t_shared = time.perf_counter() - t0
            comp1 = device_compile_stats()
            os.environ["KOLIBRIE_MQO"] = "off"
            fire_round(dbf, n, owners)  # warm the off-mode template slots
            t0 = time.perf_counter()
            indep = fire_round(dbf, n, owners)
            t_indep = time.perf_counter() - t0
            assert [sorted(map(tuple, r)) for r in shared] == [
                sorted(map(tuple, r)) for r in indep
            ], f"mqo fleet N={n}: shared rows diverge from independent"
            mqo_block[f"fleet{n}_shared_per_query_ms"] = round(
                1000 * t_shared / n, 4
            )
            mqo_block[f"fleet{n}_independent_per_query_ms"] = round(
                1000 * t_indep / n, 4
            )
            mqo_block[f"fleet{n}_marginal_ratio"] = round(
                t_shared / t_indep, 3
            )
            mqo_block[f"fleet{n}_new_compiles"] = sum(
                comp1[k] - comp0[k] for k in comp1
            )
        st = mqo_mod.stats(dbf)
        mqo_block["fleet256_cache_hits"] = sum(
            p["cache_hits"] for p in st["prefixes"].values()
        )
        # batcher mixed-template A/B: one group of same-prefix templates
        dbf = fleet_db()
        texts = [fleet_q(i) for i in range(16)]
        for mode, tag in (("force", "shared"), ("off", "independent")):
            os.environ["KOLIBRIE_MQO"] = mode
            execute_queries_batched(dbf, texts)  # warm
            t0 = time.perf_counter()
            batched = execute_queries_batched(dbf, texts)
            mqo_block[f"batched_mixed_{tag}_ms"] = round(
                1000 * (time.perf_counter() - t0), 3
            )
            if mode == "force":
                rows_shared = [sorted(map(tuple, r)) for r in batched]
            else:
                assert rows_shared == [
                    sorted(map(tuple, r)) for r in batched
                ], "mqo batched A/B rows diverge"
    except Exception as e:  # noqa: BLE001 — bench must survive its probes
        mqo_block = {"error": repr(e)}
    finally:
        os.environ.pop("KOLIBRIE_MQO", None)
    note(f"mqo sweep done ({mqo_block})")

    # ---- replication fleet: WAL-shipped read replicas + failover ---------
    # ISSUE-17 acceptance: aggregate read qps of N followers vs the single
    # process, p99 ack-to-visible replication lag under sustained ingest,
    # and kill -9 → first-promoted-read failover time.
    note("replication fleet sweep")
    try:
        replication_block = replication_fleet_bench(note=note)
    except Exception as e:  # noqa: BLE001 — bench must survive its probes
        replication_block = {"error": repr(e)}
    note(f"replication fleet done ({replication_block})")

    # ---- stats advisor: feedback-driven replanning A/B -------------------
    # ISSUE-19 acceptance: advisor-off vs advisor-on over a mixed LUBM +
    # triangle workload with identical rows on both sides, zero regression
    # on the queries the static router already gets right, and the
    # headline — the AGM-misrouted LUBM Q9 flipping from WCOJ to the
    # measured binary join after one observed execution, while the
    # triangle hub (AGM's home turf) stays on WCOJ.
    note("stats advisor sweep")
    stats_advisor_block = None
    try:
        from benches.lubm import (
            LUBM_Q2 as _SQ2,
            LUBM_Q9 as _SQ9,
            generate_fast as _sgen,
        )
        from kolibrie_tpu.optimizer.stats_advisor import stats_advisor
        from kolibrie_tpu.query.engine import QueryEngine as _SEngine
        from kolibrie_tpu.query.sparql_database import (
            SparqlDatabase as _SDb,
        )

        sa_env_before = {
            k: os.environ.get(k)
            for k in ("KOLIBRIE_STATS_ADVISOR", "KOLIBRIE_WCOJ")
        }
        try:
            os.environ["KOLIBRIE_WCOJ"] = "auto"
            adb = _SDb()
            as_, ap_, ao_ = _sgen(30, adb.dictionary)
            adb.store.add_batch(as_, ap_, ao_)
            adb.store.compact()
            adb.execution_mode = db.execution_mode
            _M = 64
            _tl = []
            for _pred, _a, _b in (
                ("p1", "x", "y"), ("p2", "y", "z"), ("p3", "z", "x")
            ):
                for _i in range(_M):
                    _tl.append(
                        f"<https://t.example/{_a}{_i}> "
                        f"<https://t.example/{_pred}> "
                        f"<https://t.example/{_b}0> ."
                    )
                    _tl.append(
                        f"<https://t.example/{_a}0> "
                        f"<https://t.example/{_pred}> "
                        f"<https://t.example/{_b}{_i}> ."
                    )
            sdb = _SDb()
            sdb.parse_ntriples("\n".join(_tl))
            sdb.execution_mode = db.execution_mode
            stri_q = (
                "PREFIX t: <https://t.example/> SELECT ?x ?y ?z WHERE "
                "{ ?x t:p1 ?y . ?y t:p2 ?z . ?z t:p3 ?x }"
            )
            workload = {
                "lubm_q2": (adb, _SQ2),
                "lubm_q9": (adb, _SQ9),
                "triangle_agm": (sdb, stri_q),
            }

            def _sa_timed(dbx, q, n=5):
                rows = execute_query_volcano(q, dbx)  # warm: learn
                execute_query_volcano(q, dbx)  # drift replan lands here
                best = float("inf")
                for _ in range(n):
                    t0 = time.perf_counter()
                    execute_query_volcano(q, dbx)
                    best = min(best, time.perf_counter() - t0)
                return best * 1000.0, sorted(map(tuple, rows))

            os.environ["KOLIBRIE_STATS_ADVISOR"] = "off"
            off_ms, off_rows = {}, {}
            for name, (dbx, q) in workload.items():
                off_ms[name], off_rows[name] = _sa_timed(dbx, q)
            os.environ["KOLIBRIE_STATS_ADVISOR"] = "auto"
            stats_advisor.reset()
            on_ms = {}
            for name, (dbx, q) in workload.items():
                ms, rows_on = _sa_timed(dbx, q)
                assert rows_on == off_rows[name], (
                    f"advisor A/B rows diverge on {name}"
                )
                on_ms[name] = ms
            q9_exp = _SEngine(adb).explain_device(_SQ9, exact_counts=False)
            tri_exp = _SEngine(sdb).explain_device(
                stri_q, exact_counts=False
            )
            off_total, on_total = sum(off_ms.values()), sum(on_ms.values())
            stats_advisor_block = {
                name: {
                    "rows": len(off_rows[name]),
                    "advisor_off_ms": round(off_ms[name], 3),
                    "advisor_on_ms": round(on_ms[name], 3),
                    "speedup": (
                        round(off_ms[name] / on_ms[name], 3)
                        if on_ms[name] else None
                    ),
                }
                for name in workload
            }
            stats_advisor_block.update(
                {
                    "q9_routing_flip": "wcoj elim=" not in q9_exp,
                    "triangle_stays_wcoj": "wcoj elim=" in tri_exp,
                    # _qps suffix = gated upward by scripts/bench_gate.py
                    "advisor_off_mixed_qps": round(
                        1000 * len(workload) / off_total, 1
                    ),
                    "advisor_on_mixed_qps": round(
                        1000 * len(workload) / on_total, 1
                    ),
                    "replans": stats_advisor.stats()["replans_total"],
                }
            )
        finally:
            for k, v in sa_env_before.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
    except Exception as e:  # noqa: BLE001 — bench must survive its probes
        stats_advisor_block = {"error": repr(e)}
    note(f"stats advisor sweep done ({stats_advisor_block})")

    # LUBM-1000 Q2/Q9 per-query wall-clock (real work per dispatch — no
    # amortization caveat): embedded from the watcher-captured artifact
    # so the headline file carries them without re-running a 4M-triple
    # build inside the bench attempt window.
    lubm = None
    try:
        with open(
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_LUBM1000.json")
        ) as f:
            lrec = json.load(f)
        lubm = {"captured": lrec.get("date")}
        for r in lrec.get("results", []):
            m = r.get("metric", "")
            if m in (
                "lubm_q2_host_wall_clock",
                "lubm_q9_host_wall_clock",
                "lubm_q2_device_wall_clock",
                "lubm_q9_device_wall_clock",
            ):
                lubm[m + "_ms"] = r.get("ms")
                if r.get("rows") is not None:
                    lubm[m + "_rows"] = r.get("rows")
    except (OSError, ValueError):
        pass

    throughput = N_TRIPLES / dev_tk
    print(
        json.dumps(
            {
                "metric": f"bgp_join_employee100k_engine_triples_per_sec_{platform}",
                "value": round(throughput, 1),
                "unit": "triples/sec/chip",
                "vs_baseline": round(host_best / dev_tk, 3),
                # first-class unamortized pair: ONE dispatch of the plan,
                # tunnel latency and all — no amortization caveat needed
                "value_single_dispatch": round(N_TRIPLES / dev_t, 1),
                "vs_baseline_single_dispatch": round(host_best / dev_t, 3),
                "secondary": {
                    "plan_exec_amortized_ms": round(1000 * dev_tk, 4),
                    "single_dispatch_ms": round(1000 * dev_t, 3),
                    "single_dispatch_triples_per_sec": round(N_TRIPLES / dev_t, 1),
                    "host_engine_exec_ms": round(1000 * host_exec, 3),
                    "host_native_engine_exec_ms": (
                        round(1000 * native_exec, 3) if native_exec else None
                    ),
                    "host_e2e_ms": round(1000 * host_e2e, 2),
                    "host_e2e_cold_ms": round(1000 * host_e2e_cold, 2),
                    "pallas_join_exec_ms": round(1000 * pallas_tk, 4),
                    "xla_join_exec_ms": round(1000 * xla_tk, 4),
                    "pallas_vs_xla_join": round(xla_tk / pallas_tk, 3),
                    # "tpu" = real Mosaic kernels; "interpreter" = the
                    # Pallas interpreter fallback (CPU), comparable only
                    # against itself, never against the TPU numbers
                    "pallas_join_timing_basis": pallas_basis,
                    "pallas_probe": pallas_probe_block,
                    "rows": len(rows),
                    "bulk_load_s": round(t_load, 3),
                    "plan_template": plan_template,
                    "resilience": resilience,
                    "obs": obs_block,
                    "store_ingest": store_ingest,
                    "wcoj": wcoj_block,
                    "durability": durability_block,
                    "sharded_serving": sharded_block,
                    "compile_tail": compile_tail,
                    "mqo": mqo_block,
                    "stats_advisor": stats_advisor_block,
                    "replication": replication_block,
                    "lubm1000": lubm,
                    "note": "public-API query: SPARQL parse + Streamertail "
                    "plan cached automatically on the database (round 5), "
                    "then the plan's single XLA program over device-resident "
                    "store orders; value = throughput amortized over "
                    f"{scan_k} executions/dispatch (materialized columns "
                    "produced every iteration), value_single_dispatch = one "
                    "plan execution per dispatch; vs_baseline divides by "
                    "the best host engine (max of numpy pipeline and the "
                    "threaded C++ native twin); rows verified equal to the "
                    "host numpy engine",
                },
            }
        )
    )


# ---------------------------------------------------------------------------
# Supervisor: the TPU behind the axon tunnel has contention windows where
# backend init / first dispatch raises UNAVAILABLE (this cost round 2 its
# only driver-captured number).  The benchmark body therefore runs in a
# child process (a failed jax backend init cannot be retried in-process),
# the supervisor retries with backoff, and the last attempt falls back to
# forced-CPU so ONE parseable JSON line is always printed.
# ---------------------------------------------------------------------------

ATTEMPT_TIMEOUT_S = 1500  # one TPU attempt ≈ 10-15 min (4 compiled
#                           variants + 3 timed dispatch loops with gaps)
PROBE_TIMEOUT_S = 150  # backend init through a healthy tunnel takes seconds
BACKOFFS_S = (5, 20, 45)  # sleeps between the TPU attempts


def _probe_backend() -> bool:
    """Quick dead-tunnel detector: backend init HANGS (no exception) when
    the axon tunnel is wedged, which would otherwise burn a full attempt
    timeout discovering nothing.  A tiny child with a short timeout tells
    us cheaply whether a real attempt is worth starting."""
    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; d = jax.devices(); print(d[0].platform)",
            ],
            capture_output=True,
            text=True,
            timeout=PROBE_TIMEOUT_S,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _run_child(env_extra):
    env = dict(os.environ)
    env["KOLIBRIE_BENCH_CHILD"] = "1"
    env.update(env_extra)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True,
            text=True,
            timeout=ATTEMPT_TIMEOUT_S,
            env=env,
        )
        out, err, rc = proc.stdout, proc.stderr, proc.returncode
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        return None, err + f"\n[supervisor] attempt timed out after {ATTEMPT_TIMEOUT_S}s"
    if rc == 0:
        for line in reversed(out.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    json.loads(line)
                    return line, None
                except ValueError:
                    continue
        return None, f"rc=0 but no JSON line in stdout:\n{out[-2000:]}\n{err[-2000:]}"
    return None, f"rc={rc}\n{err[-4000:]}"


def supervise():
    failures = []
    for i, backoff in enumerate((*BACKOFFS_S, None)):
        if not _probe_backend():
            failures.append(
                f"attempt {i + 1}: device backend init hung/failed within "
                f"{PROBE_TIMEOUT_S}s (tunnel down) — attempt skipped"
            )
            if backoff is not None:
                time.sleep(backoff)
            continue
        line, fail = _run_child({})
        if line is not None:
            try:  # checkpoint the capture for the cached-replay fallback
                rec = json.loads(line)
                if rec.get("metric", "").endswith("_tpu") and rec.get("value"):
                    rec.setdefault("secondary", {})["captured_at"] = (
                        time.strftime("%Y-%m-%dT%H:%MZ", time.gmtime())
                    )
                    with open(
                        os.path.join(
                            os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_CANDIDATE.json",
                        ),
                        "w",
                    ) as f:
                        json.dump(rec, f)
            except (OSError, ValueError):
                pass
            print(line)
            return 0
        failures.append(f"attempt {i + 1}: {fail}")
        if backoff is not None:
            time.sleep(backoff)
    # The axon tunnel answers in short bursts; a successful in-round capture
    # is checkpointed to BENCH_CANDIDATE.json the moment it happens.  If the
    # tunnel is down when the driver runs this script, replaying that capture
    # (clearly labeled, with the live failures attached) records strictly
    # more information than a degenerate CPU fallback.
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_CANDIDATE.json")) as f:
            cand = json.load(f)
        if cand.get("metric", "").endswith("_tpu") and cand.get("value"):
            sec = cand.setdefault("secondary", {})
            sec["cached_capture"] = (
                "tunnel down at bench time; this is the real-chip capture "
                "taken earlier in the round (see captured_at)"
            )
            sec["tpu_failures_live"] = failures
            # print the in-hand record IMMEDIATELY — the driver records
            # the LAST JSON line, so if anything below is cut short by an
            # external deadline this line still stands as the capture
            print(json.dumps(cand), flush=True)
            if "value_single_dispatch" not in cand:
                # the cached capture predates this round's co-reported
                # fields (unamortized pair, native twin, plan-cache e2e):
                # attach a LIVE forced-CPU run so the round still records
                # the new shape's host-side numbers honestly, re-printing
                # the augmented record as the new last line
                try:
                    line, _fail = _run_child({"KOLIBRIE_BENCH_CPU": "1"})
                except Exception:
                    line = None
                if line is not None:
                    try:
                        cpu_rec = json.loads(line)
                        sec["cpu_live"] = {
                            "metric": cpu_rec.get("metric"),
                            "value_single_dispatch": cpu_rec.get(
                                "value_single_dispatch"
                            ),
                            "secondary": cpu_rec.get("secondary"),
                        }
                        print(json.dumps(cand), flush=True)
                    except ValueError:
                        pass
            return 0
    except (OSError, ValueError):
        pass
    # Last resort: forced-CPU child so the round still records a real
    # engine-path number (metric name carries the platform).
    line, fail = _run_child({"KOLIBRIE_BENCH_CPU": "1"})
    if line is not None:
        rec = json.loads(line)
        rec.setdefault("secondary", {})["tpu_failures"] = failures
        print(json.dumps(rec))
        return 0
    failures.append(f"cpu fallback: {fail}")
    print(
        json.dumps(
            {
                "metric": "bgp_join_employee100k_engine_triples_per_sec",
                "value": None,
                "unit": "triples/sec/chip",
                "vs_baseline": None,
                "error": failures,
            }
        )
    )
    return 1


if __name__ == "__main__":
    if os.environ.get("KOLIBRIE_BENCH_CHILD"):
        main()
    else:
        sys.exit(supervise())
