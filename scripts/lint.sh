#!/usr/bin/env bash
# Repo lint gate: kolint (against the committed baseline), a compile
# sweep, and a check that no bytecode artifacts are tracked.
#
#   scripts/lint.sh            lint the package
#   scripts/lint.sh --json     machine-readable kolint output
#
# Exit nonzero on any finding not covered by kolint_baseline.json, any
# file that does not compile, or any tracked __pycache__/.pyc artifact.
set -u
cd "$(dirname "$0")/.."

rc=0

echo "== kolint =="
# --max-seconds keeps lint commit-loop fast; the .kolint_cache result
# cache this first pass warms makes the standalone passes below near-free
python -m kolibrie_tpu.analysis --max-seconds 60 "$@" kolibrie_tpu/ || rc=1

echo "== kolint cache-key versioning (KL901) =="
# the rule is in the default set above; this explicit pass keeps the
# cache-key discipline visible (and bisectable) on its own — result
# caches keyed on store identity must fold in (base_version,
# delta_epoch) or store.version_key() (docs/MQO.md)
python -m kolibrie_tpu.analysis --rules KL901 kolibrie_tpu/ || rc=1

echo "== kolint print hygiene (KL504) =="
# also in the default set; standalone pass keeps the no-bare-print
# discipline visible — library diagnostics go through obs/log.py, user
# output names its stream (docs/OBSERVABILITY.md)
python -m kolibrie_tpu.analysis --rules KL504 kolibrie_tpu/ || rc=1

echo "== kolint static races (KL311/KL312) =="
# the interprocedural race detector on its own: shared state written
# from >=2 thread roots must hold a lock at every access (docs/ANALYSIS.md)
python -m kolibrie_tpu.analysis --rules KL311,KL312 kolibrie_tpu/ || rc=1

echo "== kolint dataflow taint (KL111/KL112) =="
# def-use taint from traced params into host sinks and static/shape
# positions — the recompile-hazard class (docs/ANALYSIS.md)
python -m kolibrie_tpu.analysis --rules KL111,KL112 kolibrie_tpu/ || rc=1

echo "== lock sanitizer self-check =="
# the runtime cross-check of the static race rules: prove the
# KOLIBRIE_DEBUG_LOCKS instrumentation still catches a planted
# unguarded access before trusting its silence elsewhere
KOLIBRIE_DEBUG_LOCKS=1 python -c "
from kolibrie_tpu.analysis import lockcheck
assert lockcheck.selftest(), 'lockcheck.selftest() failed'
print('lockcheck selftest ok')
" || rc=1

echo "== compileall =="
# -q: names only on failure; PYTHONDONTWRITEBYTECODE keeps the tree clean
PYTHONDONTWRITEBYTECODE=1 python -m compileall -q kolibrie_tpu/ tests/ || rc=1

echo "== bench gate (smoke) =="
# schema + comparator + timeline-ring self-check; no live bench run
python scripts/bench_gate.py --smoke || rc=1

echo "== bytecode-free tree =="
tracked=$(git ls-files | grep -E '(__pycache__|\.pyc$)' || true)
if [ -n "$tracked" ]; then
    echo "tracked bytecode artifacts:" >&2
    echo "$tracked" >&2
    rc=1
fi
# Untracked __pycache__ dirs are build debris: a .pyc that outlives its
# deleted source keeps stale code importable by tooling that scans the
# tree. Catch them too — report and scrub so the gate leaves a clean tree.
strays=$(find kolibrie_tpu scripts tests -type d -name '__pycache__' 2>/dev/null || true)
if [ -n "$strays" ]; then
    echo "removing untracked bytecode dirs:"
    echo "$strays"
    echo "$strays" | xargs rm -rf
fi

exit $rc
