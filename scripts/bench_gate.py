#!/usr/bin/env python
"""Perf-regression sentinel: diff a fresh bench.py block against the
committed BENCH_r*.json trajectory and fail on >20% regressions.

The trajectory files each hold ``{"n", "cmd", "rc", "tail", "parsed"}``
where ``parsed`` is the bench's one-line JSON block — or ``None`` when
that round's capture failed (r02/r05 are like this); such rounds are
skipped, not fatal.  The gate compares the fresh block against the
trajectory's BEST value per metric, so a slow round in history never
lowers the bar:

- headline ``value`` and any ``secondary`` key ending in ``_qps`` /
  ``_per_sec``: higher is better, regression = fresh < best * (1 - t)
- ``secondary`` keys ending in ``_ms`` / ``_s``: lower is better,
  regression = fresh > best * (1 + t)

Only metrics present in BOTH the fresh block and the trajectory are
compared (new metrics have no bar yet; retired ones don't block), and
only trajectory rounds whose headline ``metric`` NAME matches the fresh
block's count — the trajectory mixes cpu/tpu captures and metric
renames, and a cpu run must never be gated against a tpu bar.

Usage:
    python scripts/bench_gate.py --fresh out.json   # gate a saved block
    python scripts/bench_gate.py --fresh -          # … from stdin
    python scripts/bench_gate.py                    # run bench.py live
    python scripts/bench_gate.py --smoke            # self-check, no bench

``--smoke`` runs in scripts/lint.sh: it validates the committed
trajectory's schema, proves the comparator catches an injected
regression (and ignores noise under the threshold), and exercises the
obs timeline ring end to end — all in-process, no live bench.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_THRESHOLD_PCT = 20.0

# secondary keys that are environment probes, not performance metrics
_SKIP_KEYS = ("rows", "budget_pct")


def load_trajectory(repo: str = REPO) -> List[dict]:
    """The committed bench rounds, oldest first; entries whose ``parsed``
    is None (failed captures) are dropped here."""
    out = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        with open(path) as f:
            rec = json.load(f)
        if not isinstance(rec, dict) or "parsed" not in rec:
            raise SystemExit(f"bench gate: malformed trajectory file {path}")
        if rec["parsed"] is not None:
            rec["parsed"]["_path"] = os.path.basename(path)
            out.append(rec["parsed"])
    return out


def _flatten(block: dict) -> Dict[str, float]:
    """Headline value + numeric secondary leaves, as ``key -> float``.
    Nested secondary dicts (obs, wcoj, …) flatten with a dotted prefix."""
    out: Dict[str, float] = {}
    if isinstance(block.get("value"), (int, float)):
        out["value"] = float(block["value"])

    def walk(prefix: str, d: dict):
        for k, v in d.items():
            if k in _SKIP_KEYS or k.startswith("_"):
                continue
            if isinstance(v, bool):
                continue
            if isinstance(v, (int, float)):
                out[f"{prefix}{k}"] = float(v)
            elif isinstance(v, dict):
                walk(f"{prefix}{k}.", v)

    walk("secondary.", block.get("secondary") or {})
    return out


def _direction(key: str) -> Optional[str]:
    """'up' = higher is better, 'down' = lower is better, None = not a
    gated metric (ratios, counts, timestamps…)."""
    leaf = key.rsplit(".", 1)[-1]
    if leaf == "value" or leaf.endswith(("_qps", "_per_sec")):
        return "up"
    if leaf.endswith(("_ms", "_s")):
        return "down"
    return None


def compare(
    fresh: dict,
    trajectory: List[dict],
    threshold_pct: float = DEFAULT_THRESHOLD_PCT,
) -> Tuple[List[str], List[str]]:
    """Returns (regressions, checked) message lists."""
    fresh_flat = _flatten(fresh)
    # like against like: cpu/tpu captures and metric renames make raw
    # cross-round comparison meaningless
    trajectory = [
        b for b in trajectory if b.get("metric") == fresh.get("metric")
    ]
    best: Dict[str, float] = {}
    for block in trajectory:
        for k, v in _flatten(block).items():
            d = _direction(k)
            if d is None:
                continue
            if k not in best:
                best[k] = v
            else:
                best[k] = max(best[k], v) if d == "up" else min(best[k], v)
    t = threshold_pct / 100.0
    regressions, checked = [], []
    for k, bar in sorted(best.items()):
        if k not in fresh_flat or bar <= 0:
            continue
        v, d = fresh_flat[k], _direction(k)
        if d == "up":
            worse = v < bar * (1.0 - t)
            delta = (bar - v) / bar * 100.0
        else:
            worse = v > bar * (1.0 + t)
            delta = (v - bar) / bar * 100.0
        checked.append(f"{k}: fresh={v:g} best={bar:g} ({delta:+.1f}%)")
        if worse:
            regressions.append(
                f"{k}: fresh={v:g} vs best={bar:g} — "
                f"{delta:.1f}% worse (threshold {threshold_pct:g}%)"
            )
    return regressions, checked


def _read_fresh(arg: Optional[str]) -> dict:
    if arg == "-":
        text = sys.stdin.read()
    elif arg:
        with open(arg) as f:
            text = f.read()
    else:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True,
            text=True,
            cwd=REPO,
        )
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr[-2000:])
            raise SystemExit("bench gate: live bench.py run failed")
        text = proc.stdout
    # the block is the LAST line that parses as a JSON object with "metric"
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            block = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(block, dict) and "metric" in block:
            return block
    raise SystemExit("bench gate: no bench JSON block found in input")


# ------------------------------------------------------------------ smoke


def smoke() -> None:
    trajectory = load_trajectory()
    assert trajectory, "trajectory empty — every committed round failed?"
    for block in trajectory:
        assert "metric" in block and "value" in block, block.get("_path")

    # comparator self-check against the real trajectory: the best round
    # itself must pass its own bar …
    synth = dict(trajectory[-1])
    regs, checked = compare(synth, trajectory)
    assert checked, "no comparable metrics in the trajectory"
    assert not regs, f"latest committed round fails its own gate: {regs}"
    # … an injected 2x slowdown must fail it …
    bad = json.loads(json.dumps(trajectory[-1]))
    bad["value"] = bad["value"] / 2.0
    regs, _ = compare(bad, trajectory)
    assert any(r.startswith("value:") for r in regs), "missed 50% regression"
    # … and sub-threshold noise must not
    noisy = json.loads(json.dumps(trajectory[-1]))
    noisy["value"] = noisy["value"] * 0.9
    regs, _ = compare(noisy, trajectory)
    assert not any(
        r.startswith("value:") for r in regs
    ), "10% noise tripped the 20% gate"

    # the MQO fleet headline gates downward the moment a trajectory round
    # carries it: per-window fire cost under shared-prefix evaluation is
    # a latency (docs/MQO.md), so a future round that doubles it must
    # trip the comparator exactly like any other _ms key
    assert _direction("secondary.mqo.fleet64_shared_per_query_ms") == "down"
    assert _direction("secondary.mqo.fleet64_marginal_ratio") is None
    withmqo = json.loads(json.dumps(trajectory[-1]))
    withmqo.setdefault("secondary", {})["mqo"] = {
        "fleet64_shared_per_query_ms": 1.0
    }
    base = [json.loads(json.dumps(withmqo))]
    slow = json.loads(json.dumps(withmqo))
    slow["secondary"]["mqo"]["fleet64_shared_per_query_ms"] = 2.0
    regs, _ = compare(slow, base)
    assert any(
        "mqo.fleet64_shared_per_query_ms" in r for r in regs
    ), "missed 2x MQO fleet regression"

    # the replication fleet gates like MQO: read qps gates upward, lag
    # and failover gate downward, ratios/counters are informational
    assert _direction("secondary.replication.fleet2_read_qps") == "up"
    assert _direction("secondary.replication.single_read_qps") == "up"
    assert _direction("secondary.replication.repl_lag_p99_ms") == "down"
    assert _direction("secondary.replication.failover_ms") == "down"
    assert _direction(
        "secondary.replication.fleet2_speedup_vs_single"
    ) is None
    withrepl = json.loads(json.dumps(trajectory[-1]))
    withrepl.setdefault("secondary", {})["replication"] = {
        "fleet2_read_qps": 100.0,
        "failover_ms": 500.0,
    }
    base = [json.loads(json.dumps(withrepl))]
    slow = json.loads(json.dumps(withrepl))
    slow["secondary"]["replication"]["fleet2_read_qps"] = 40.0
    slow["secondary"]["replication"]["failover_ms"] = 2000.0
    regs, _ = compare(slow, base)
    assert any("replication.fleet2_read_qps" in r for r in regs), (
        "missed 60% fleet read-qps regression"
    )
    assert any("replication.failover_ms" in r for r in regs), (
        "missed 4x failover regression"
    )

    # fleet observability gates the same way: router-path read qps must
    # not fall, the /fleet/metrics scrape sweep must not slow down, and
    # the overhead ratio / node count stay informational
    assert _direction(
        "secondary.replication.fleet_obs.router_instrumented_read_qps"
    ) == "up"
    assert _direction(
        "secondary.replication.fleet_obs.router_obs_disabled_read_qps"
    ) == "up"
    assert _direction(
        "secondary.replication.fleet_obs.fleet_metrics_scrape_p50_ms"
    ) == "down"
    assert _direction(
        "secondary.replication.fleet_obs.obs_overhead_pct"
    ) is None
    assert _direction(
        "secondary.replication.fleet_obs.fleet_metrics_nodes"
    ) is None
    withfo = json.loads(json.dumps(trajectory[-1]))
    withfo.setdefault("secondary", {})["replication"] = {
        "fleet_obs": {
            "router_instrumented_read_qps": 200.0,
            "fleet_metrics_scrape_p50_ms": 10.0,
        }
    }
    base = [json.loads(json.dumps(withfo))]
    slow = json.loads(json.dumps(withfo))
    slow["secondary"]["replication"]["fleet_obs"] = {
        "router_instrumented_read_qps": 80.0,
        "fleet_metrics_scrape_p50_ms": 40.0,
    }
    regs, _ = compare(slow, base)
    assert any(
        "fleet_obs.router_instrumented_read_qps" in r for r in regs
    ), "missed 60% router read-qps regression"
    assert any(
        "fleet_obs.fleet_metrics_scrape_p50_ms" in r for r in regs
    ), "missed 4x fleet scrape regression"

    # the stats-advisor block gates the same way: mixed-workload qps on
    # both sides gates upward, per-query _ms keys gate downward, the
    # routing-flip booleans and replan counts stay informational
    assert _direction("secondary.stats_advisor.advisor_on_mixed_qps") == "up"
    assert _direction("secondary.stats_advisor.advisor_off_mixed_qps") == "up"
    assert _direction(
        "secondary.stats_advisor.lubm_q9.advisor_on_ms"
    ) == "down"
    assert _direction("secondary.stats_advisor.replans") is None
    withsa = json.loads(json.dumps(trajectory[-1]))
    withsa.setdefault("secondary", {})["stats_advisor"] = {
        "advisor_on_mixed_qps": 50.0,
        "q9_routing_flip": True,
    }
    base = [json.loads(json.dumps(withsa))]
    slow = json.loads(json.dumps(withsa))
    slow["secondary"]["stats_advisor"]["advisor_on_mixed_qps"] = 20.0
    slow["secondary"]["stats_advisor"]["q9_routing_flip"] = False
    regs, _ = compare(slow, base)
    assert any(
        "stats_advisor.advisor_on_mixed_qps" in r for r in regs
    ), "missed 60% advisor-on qps regression"

    # timeline ring end to end, against an isolated registry
    sys.path.insert(0, REPO)
    from kolibrie_tpu.obs import metrics as m
    from kolibrie_tpu.obs.timeseries import TimeSeriesRing

    reg = m.Registry()
    c = reg.counter("smoke_total")
    ring = TimeSeriesRing(capacity=4, registry=reg)
    ring.record(now=1.0)
    c.inc(5)
    ring.record(now=2.0)
    series = ring.series()
    deltas = series["metrics"]["smoke_total"]["series"][""]["deltas"]
    assert deltas == [5.0], deltas

    # live reduced replication fleet — one primary + one follower process,
    # short windows: proves the bench block's whole path (boot, WAL ship,
    # catch-up, read qps, lag sampling, kill -9 failover) at lint time
    import bench

    repl = bench.replication_fleet_bench(
        fleet_sizes=(1,), read_duration_s=0.5, lag_samples=3,
    )
    for key in ("single_read_qps", "fleet1_read_qps",
                "repl_lag_p99_ms", "failover_ms"):
        assert repl.get(key, 0) > 0, (key, repl)
    fo = repl.get("fleet_obs") or {}
    assert "error" not in fo, fo
    for key in ("router_instrumented_read_qps", "router_obs_disabled_read_qps",
                "fleet_metrics_scrape_p50_ms"):
        assert fo.get(key, 0) > 0, (key, fo)
    assert fo.get("fleet_metrics_nodes", 0) >= 3, fo

    # live stats-advisor smoke: the q9 routing flip end to end on a
    # miniature campus KG, no device compile — EXPLAIN's host-oracle
    # calibration both feeds the advisor and renders the replanned route
    from kolibrie_tpu.optimizer import stats_advisor as sa_mod
    from kolibrie_tpu.query.engine import QueryEngine
    from kolibrie_tpu.query.sparql_database import SparqlDatabase

    sys.path.insert(0, os.path.join(REPO, "benches"))
    import lubm as _lubm

    sa_mod.stats_advisor.reset()
    adb = SparqlDatabase()
    _s, _p, _o = _lubm.generate_fast(2, adb.dictionary)
    adb.store.add_batch(_s, _p, _o)
    adb.store.compact()
    try:
        with sa_mod.override_mode("off"):
            cold = QueryEngine(adb).explain_device(_lubm.LUBM_Q9)
            assert "wcoj elim=" in cold, "static router no longer AGM-routes q9"
        with sa_mod.override_mode("auto"):
            QueryEngine(adb).explain_device(_lubm.LUBM_Q9)  # learn
            warm = QueryEngine(adb).explain_device(_lubm.LUBM_Q9)
            assert "wcoj elim=" not in warm, "advisor failed to flip q9"
        sa_stats = sa_mod.stats_advisor.stats()
        assert sa_stats["observations"] > 0, sa_stats
    finally:
        sa_mod.stats_advisor.reset()

    print(
        f"bench gate smoke OK: {len(trajectory)} trajectory rounds, "
        f"{len(checked)} gated metrics, ring deltas verified, "
        f"replication fleet smoke: single={repl['single_read_qps']}qps "
        f"fleet1={repl['fleet1_read_qps']}qps "
        f"lag_p99={repl['repl_lag_p99_ms']}ms "
        f"failover={repl['failover_ms']}ms "
        f"fleet_obs: router={fo['router_instrumented_read_qps']}qps "
        f"overhead={fo['obs_overhead_pct']}% "
        f"scrape_p50={fo['fleet_metrics_scrape_p50_ms']}ms, "
        f"stats-advisor q9 flip verified "
        f"({sa_stats['observations']} observations)"
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--fresh", help="bench JSON block file, or - for stdin")
    ap.add_argument(
        "--threshold", type=float, default=DEFAULT_THRESHOLD_PCT,
        help="regression threshold in percent (default 20)",
    )
    ap.add_argument(
        "--smoke", action="store_true",
        help="schema + comparator + ring self-check; no live bench",
    )
    args = ap.parse_args()
    if args.smoke:
        smoke()
        return 0
    trajectory = load_trajectory()
    if not trajectory:
        print("bench gate: no usable trajectory rounds; nothing to gate")
        return 0
    fresh = _read_fresh(args.fresh)
    regressions, checked = compare(fresh, trajectory, args.threshold)
    for line in checked:
        print("  " + line)
    if regressions:
        print(f"bench gate: {len(regressions)} regression(s)")
        for r in regressions:
            print("  REGRESSION " + r)
        return 1
    print(f"bench gate OK: {len(checked)} metrics within {args.threshold:g}%")
    return 0


if __name__ == "__main__":
    sys.exit(main())
