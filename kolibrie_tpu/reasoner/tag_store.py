"""Per-triple provenance tag map.

Parity: ``shared/src/tag_store.rs`` — absent triple ⇒ ``one()`` (certain),
``update_disjunction`` with saturation check (:58-67), RDF-star export
``<< s p o >> prob:value "p"^^xsd:double`` (:89-111), and proof-path
explanation export (prob:proofCount/hasProof/hasSeed/hasNegatedSeed/formula)
for DNF tags (:121-180) and SDD tags via model enumeration (:184-246).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

from kolibrie_tpu.core.triple import Triple
from kolibrie_tpu.reasoner.provenance import DnfWmcProvenance, Provenance, TopKProofs
from kolibrie_tpu.reasoner.sdd import SddProvenance

PROB_NS = "http://kolibrie.tpu/prob#"
XSD_DOUBLE = "http://www.w3.org/2001/XMLSchema#double"


class TagStore:
    """Maps triples to semiring tags; absent = one() (certain fact)."""

    def __init__(self, provenance: Provenance):
        self.provenance = provenance
        self.tags: Dict[Tuple[int, int, int], object] = {}

    def get(self, t: Triple):
        return self.tags.get(tuple(t), self.provenance.one())

    def get_opt(self, t: Triple):
        """Tag if explicitly stored, else None."""
        return self.tags.get(tuple(t))

    def set(self, t: Triple, tag) -> None:
        self.tags[tuple(t)] = tag

    def contains(self, t: Triple) -> bool:
        return tuple(t) in self.tags

    def update_disjunction(self, t: Triple, tag) -> bool:
        """⊕-merge a new derivation's tag; returns True if the stored tag
        changed.  Saturated tags short-circuit (tag_store.rs:58-67)."""
        key = tuple(t)
        old = self.tags.get(key)
        if old is None:
            self.tags[key] = self.provenance.saturate(tag)
            return True
        if self.provenance.is_saturated(old):
            return False
        new = self.provenance.saturate(self.provenance.disjunction(old, tag))
        if self.provenance.tag_eq(new, old):
            return False
        self.tags[key] = new
        return True

    def items(self) -> Iterator[Tuple[Tuple[int, int, int], object]]:
        return iter(self.tags.items())

    def __len__(self) -> int:
        return len(self.tags)

    # ------------------------------------------------------------- export

    def encode_as_rdf_star(self, db) -> List[Triple]:
        """``<< s p o >> prob:value "p"^^xsd:double`` facts
        (tag_store.rs:89-111)."""
        out: List[Triple] = []
        pv = db.dictionary.encode(PROB_NS + "value")
        for (s, p, o), tag in self.tags.items():
            prob = self.provenance.recover_probability(tag)
            qid = db.quoted.intern(s, p, o)
            lit = db.dictionary.encode(f'"{prob}"^^{XSD_DOUBLE}')
            out.append(Triple(qid, pv, lit))
        return out

    def explain_proofs(self, db, t: Triple) -> List[Triple]:
        """Proof-structure explanation triples for one fact
        (tag_store.rs:121-246).  Emits prob:proofCount plus per-proof
        prob:hasSeed / prob:hasNegatedSeed facts; SDD tags are expanded via
        model enumeration."""
        tag = self.get_opt(t)
        if tag is None:
            return []
        enc = db.dictionary.encode
        qid = db.quoted.intern(*t)
        out: List[Triple] = []
        proofs: List[List[Tuple[int, bool]]] = []
        prov = self.provenance
        if isinstance(prov, (TopKProofs, DnfWmcProvenance)):
            for proof in tag:
                proofs.append(sorted(proof))
        elif isinstance(prov, SddProvenance):
            models = prov.manager.enumerate_models(tag)
            var_to_seed = {v: s for s, v in prov.seed_vars.items()}
            for m in models:
                proofs.append(
                    sorted(
                        (var_to_seed.get(v, v), pos) for v, pos in m.items()
                    )
                )
        else:
            out.append(
                Triple(
                    qid,
                    enc(PROB_NS + "value"),
                    enc(f'"{prov.recover_probability(tag)}"^^{XSD_DOUBLE}'),
                )
            )
            return out
        out.append(
            Triple(qid, enc(PROB_NS + "proofCount"), enc(f'"{len(proofs)}"'))
        )
        for i, proof in enumerate(proofs):
            proof_node = enc(f"{PROB_NS}proof/{i}")
            out.append(Triple(qid, enc(PROB_NS + "hasProof"), proof_node))
            for sid, pos in proof:
                pred = PROB_NS + ("hasSeed" if pos else "hasNegatedSeed")
                out.append(Triple(proof_node, enc(pred), enc(f'"{sid}"')))
            formula = " & ".join(
                ("" if pos else "!") + f"s{sid}" for sid, pos in proof
            )
            out.append(
                Triple(proof_node, enc(PROB_NS + "formula"), enc(f'"{formula}"'))
            )
        return out
