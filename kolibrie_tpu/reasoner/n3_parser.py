"""N3 logic rule parser: ``{ premises } => { conclusions } .`` documents.

Parity: ``datalog/src/parser_n3_logic.rs`` — ``parse_n3_rule`` (:135),
``parse_n3_document`` multi-rule documents with a shared prefix block and
EOF validation (:227), and ``parse_n3_rules_for_sds`` (:286-360) which maps
predicate constants to their owning window IRIs (longest-prefix match) and
discovers output component IRIs for cross-window reasoning.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kolibrie_tpu.core.rule import Rule
from kolibrie_tpu.core.terms import Term, TriplePattern

_PREFIX_RE = re.compile(r"@prefix\s+([\w-]*):\s*<([^>]*)>\s*\.")
# Trailing '.' after a rule is optional, as in the reference's nom parser
# (its own benches write rules without one, parser_n3_logic.rs:135).
_RULE_RE = re.compile(r"\{(.*?)\}\s*=>\s*\{(.*?)\}\s*\.?", re.S)
_TERM_RE = re.compile(
    r"""\?(?P<var>[\w-]+)
      | <(?P<iri>[^>]*)>
      | "(?P<lit>(?:[^"\\]|\\.)*)"
      | (?P<pname>[\w-]*:[\w.-]+|a)
    """,
    re.VERBOSE,
)


class N3ParseError(ValueError):
    pass


def _strip_comments(text: str) -> str:
    """Remove ``# ...`` comments, but never a '#' inside ``<...>`` (fragment
    IRIs like rdf-syntax-ns#) or inside string literals."""
    out: List[str] = []
    in_iri = in_str = False
    skip = False
    for i, c in enumerate(text):
        if skip:
            if c == "\n":
                skip = False
                out.append(c)
            continue
        if in_str:
            out.append(c)
            if c == '"' and (i == 0 or text[i - 1] != "\\"):
                in_str = False
            continue
        if in_iri:
            out.append(c)
            if c == ">":
                in_iri = False
            continue
        if c == '"':
            in_str = True
        elif c == "<":
            in_iri = True
        elif c == "#":
            skip = True
            continue
        out.append(c)
    return "".join(out)


RDF_TYPE = "http://www.w3.org/1999/02/22-rdf-syntax-ns#type"


def _parse_term_str(text: str, prefixes: Dict[str, str]) -> Tuple[str, str]:
    """Returns (kind, value): kind 'var' or 'const' (value = full string)."""
    m = _TERM_RE.fullmatch(text.strip())
    if m is None:
        raise N3ParseError(f"bad N3 term {text!r}")
    if m.group("var") is not None:
        return "var", m.group("var")
    if m.group("iri") is not None:
        return "const", m.group("iri")
    if m.group("lit") is not None:
        return "const", f'"{m.group("lit")}"'
    pname = m.group("pname")
    if pname == "a":
        return "const", RDF_TYPE
    pfx, local = pname.split(":", 1)
    ns = prefixes.get(pfx)
    if ns is None:
        raise N3ParseError(f"undefined prefix {pfx + ':'!r}")
    return "const", ns + local


def _split_statements(block: str) -> List[str]:
    """Split on statement-terminating dots only — a '.' inside ``<...>`` or
    ``"..."`` (IRIs like foaf/0.1/, decimals) is NOT a separator; neither is
    a dot not followed by whitespace/end (prefixed-name internals)."""
    stmts: List[str] = []
    buf: List[str] = []
    in_iri = in_str = False
    n = len(block)
    for i, c in enumerate(block):
        if in_str:
            buf.append(c)
            if c == '"' and (i == 0 or block[i - 1] != "\\"):
                in_str = False
            continue
        if in_iri:
            buf.append(c)
            if c == ">":
                in_iri = False
            continue
        if c == '"':
            in_str = True
            buf.append(c)
            continue
        if c == "<":
            in_iri = True
            buf.append(c)
            continue
        if c == "." and (i + 1 >= n or block[i + 1] in " \t\r\n"):
            stmts.append("".join(buf))
            buf = []
            continue
        buf.append(c)
    if buf and "".join(buf).strip():
        stmts.append("".join(buf))
    return stmts


def _parse_patterns(
    block: str, prefixes: Dict[str, str]
) -> List[Tuple[Tuple[str, str], Tuple[str, str], Tuple[str, str]]]:
    out = []
    for stmt in _split_statements(block):
        stmt = stmt.strip()
        if not stmt:
            continue
        terms = []
        for m in _TERM_RE.finditer(stmt):
            if m.group("var") is not None:
                terms.append(("var", m.group("var")))
            elif m.group("iri") is not None:
                terms.append(("const", m.group("iri")))
            elif m.group("lit") is not None:
                terms.append(("const", f'"{m.group("lit")}"'))
            else:
                pname = m.group("pname")
                if pname == "a":
                    terms.append(("const", RDF_TYPE))
                else:
                    pfx, local = pname.split(":", 1)
                    ns = prefixes.get(pfx)
                    if ns is None:
                        raise N3ParseError(f"undefined prefix {pfx + ':'!r}")
                    terms.append(("const", ns + local))
        if len(terms) % 3 != 0:
            raise N3ParseError(f"statement {stmt!r} is not a triple")
        for i in range(0, len(terms), 3):
            out.append((terms[i], terms[i + 1], terms[i + 2]))
    return out


def _to_rule(reasoner_dict, premises, conclusions) -> Rule:
    def term(kv: Tuple[str, str]) -> Term:
        kind, val = kv
        if kind == "var":
            return Term.variable(val)
        return Term.constant(reasoner_dict.encode(val))

    def pat(t) -> TriplePattern:
        return TriplePattern(term(t[0]), term(t[1]), term(t[2]))

    return Rule(
        premise=[pat(p) for p in premises],
        conclusion=[pat(c) for c in conclusions],
    )


def parse_n3_rule(text: str, dictionary) -> Rule:
    """Parse a single ``{ ... } => { ... } .`` rule (with optional @prefix
    block) into an ID-space Rule."""
    rules = parse_n3_document(text, dictionary)
    if not rules:
        raise N3ParseError("no rule found")
    return rules[0]


def parse_n3_document(text: str, dictionary) -> List[Rule]:
    """Parse a multi-rule N3 document.  Validates that nothing but prefixes,
    comments, and rules appear (EOF validation, parser_n3_logic.rs:227)."""
    prefixes: Dict[str, str] = {}
    rest = _strip_comments(text)
    for m in _PREFIX_RE.finditer(rest):
        prefixes[m.group(1)] = m.group(2)
    rest_wo = _PREFIX_RE.sub("", rest)
    rules: List[Rule] = []
    for m in _RULE_RE.finditer(rest_wo):
        premises = _parse_patterns(m.group(1), prefixes)
        conclusions = _parse_patterns(m.group(2), prefixes)
        rules.append(_to_rule(dictionary, premises, conclusions))
    leftover = _RULE_RE.sub("", rest_wo).strip()
    if leftover:
        raise N3ParseError(f"unexpected content in N3 document: {leftover[:60]!r}")
    return rules


# --------------------------------------------------------------------------
# SDS (cross-window) variant
# --------------------------------------------------------------------------


@dataclass
class WindowContext:
    """Annotation context for cross-window reasoning: which window owns each
    predicate and which output components exist (parser_n3_logic.rs:286-360)."""

    window_iris: List[str] = field(default_factory=list)
    predicate_windows: Dict[str, str] = field(default_factory=dict)
    output_iris: List[str] = field(default_factory=list)


def parse_n3_rules_for_sds(
    text: str, dictionary, window_iris: List[str]
) -> Tuple[List[Rule], WindowContext]:
    """Parse rules whose predicate IRIs are prefixed by window IRIs; maps
    each predicate constant to its owning window (longest-prefix match) and
    collects non-window IRIs as output components."""
    prefixes: Dict[str, str] = {}
    clean = _strip_comments(text)
    for m in _PREFIX_RE.finditer(clean):
        prefixes[m.group(1)] = m.group(2)
    rest = _PREFIX_RE.sub("", clean)
    ctx = WindowContext(window_iris=list(window_iris))
    rules: List[Rule] = []
    for m in _RULE_RE.finditer(rest):
        premises = _parse_patterns(m.group(1), prefixes)
        conclusions = _parse_patterns(m.group(2), prefixes)
        rules.append(_to_rule(dictionary, premises, conclusions))
        for (sk, sv), (pk, pv), (ok_, ov) in premises + conclusions:
            if pk != "const":
                continue
            owner = None
            for w in sorted(window_iris, key=len, reverse=True):
                if pv.startswith(w):
                    owner = w
                    break
            if owner is not None:
                ctx.predicate_windows[pv] = owner
            else:
                # non-window component: candidate output IRI namespace
                base = pv.rsplit("/", 1)[0] + "/" if "/" in pv else pv
                if base not in ctx.output_iris and base not in window_iris:
                    ctx.output_iris.append(base)
    return rules, ctx
