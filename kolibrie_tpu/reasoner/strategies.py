"""Forward-chaining strategies: naive and semi-naive fixpoints over the
columnar fact store.

Parity: ``datalog/src/reasoning/materialisation/`` — the
``InferenceStrategy``/``infer_with_strategy`` generic loop
(infer_generic.rs:9-54), ``NaiveStrategy`` (my_naive.rs:16-37), semi-naive
delta seeding (semi_naive.rs:22-59), and the rayon-parallel variant
(semi_naive_parallel.rs) whose rebuild equivalent is full vectorization: each
round is a batch of columnar joins — on device, one pjit-compiled program.

Rule-body evaluation reuses the query engine's binding-table join kernels
(``kolibrie_tpu.ops.join``) — the same unification the reference routes
through ``shared::join_algorithm`` (rules.rs:167-180).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from kolibrie_tpu.core.rule import Rule
from kolibrie_tpu.core.store import ColumnarTripleStore
from kolibrie_tpu.core.terms import Term, TriplePattern
from kolibrie_tpu.ops.join import (
    BindingTable,
    anti_join_tables,
    concat_tables,
    equi_join_tables,
    table_len,
)
from kolibrie_tpu.ops.unique import unique_rows

Cols = Tuple[np.ndarray, np.ndarray, np.ndarray]


# --------------------------------------------------------------------------
# Pattern / body evaluation over columnar facts
# --------------------------------------------------------------------------


def scan_pattern_store(
    store: ColumnarTripleStore, pattern: TriplePattern, quoted=None
) -> BindingTable:
    """Match one premise against the fact store via its sorted orders."""
    consts = [
        t.value if t.is_constant else None
        for t in (pattern.subject, pattern.predicate, pattern.object)
    ]
    s, p, o = store.match(s=consts[0], p=consts[1], o=consts[2])
    return _bind_columns(pattern, s, p, o, quoted)


def scan_pattern_cols(cols: Cols, pattern: TriplePattern, quoted=None) -> BindingTable:
    """Match one premise against an explicit delta (s, p, o) column set."""
    s, p, o = cols
    mask = np.ones(len(s), dtype=bool)
    for t, c in zip((pattern.subject, pattern.predicate, pattern.object), (s, p, o)):
        if t.is_constant:
            mask &= c == t.value
    return _bind_columns(pattern, s[mask], p[mask], o[mask], quoted)


def _bind_columns(pattern: TriplePattern, s, p, o, quoted=None) -> BindingTable:
    terms = (pattern.subject, pattern.predicate, pattern.object)
    cols = [s, p, o]
    out: BindingTable = {}
    mask: Optional[np.ndarray] = None
    for t, c in zip(terms, cols):
        if t.is_variable:
            if t.value in out:  # repeated variable must agree
                m = out[t.value] == c
                mask = m if mask is None else (mask & m)
            else:
                out[t.value] = c
    if mask is not None:
        out = {k: v[mask] for k, v in out.items()}
        cols = [c[mask] for c in cols]
    # RDF-star premise positions: join against the quoted-triple store,
    # binding inner variables (mirrors engine.rs:1159 resolve_quoted_scan).
    # The qid columns ride inside the table so row alignment survives joins.
    quoted_positions = [i for i, t in enumerate(terms) if t.is_quoted]
    if quoted_positions:
        if quoted is None:
            raise ValueError("quoted premise pattern requires a quoted store")
        for pos in quoted_positions:
            out[f"__qt{pos}"] = cols[pos]
        for pos in quoted_positions:
            out = _join_quoted_position(quoted, out, f"__qt{pos}", terms[pos].value)
        for pos in quoted_positions:
            out.pop(f"__qt{pos}", None)
    if not out:
        # fully-constant pattern: presence row so the match count survives
        out["__exists"] = np.zeros(min(len(cols[0]), 1), dtype=np.uint32)
    return out


def _join_quoted_position(
    quoted, table: BindingTable, qid_col_name: str, inner: TriplePattern
) -> BindingTable:
    n = len(quoted)
    qid = np.empty(n, dtype=np.uint32)
    qcols = [np.empty(n, dtype=np.uint32) for _ in range(3)]
    for i, (q, (a, b, c)) in enumerate(quoted.items()):
        qid[i] = q
        qcols[0][i], qcols[1][i], qcols[2][i] = a, b, c
    m = np.ones(n, dtype=bool)
    qtab: BindingTable = {qid_col_name: qid}
    for part_col, t in zip(qcols, inner.terms()):
        if t.is_constant:
            m &= part_col == t.value
        elif t.is_quoted:
            raise NotImplementedError("doubly-nested quoted premise patterns")
    for part_col, t in zip(qcols, inner.terms()):
        if t.is_variable:
            if t.value in qtab:
                m &= qtab[t.value] == part_col
            else:
                qtab[t.value] = part_col
    qtab = {k: v[m] for k, v in qtab.items()}
    return equi_join_tables(table, qtab)


def _apply_rule_filters(reasoner, rule: Rule, table: BindingTable) -> BindingTable:
    """Vectorized filter pass (rules.rs:133-165 ``evaluate_filters``): each
    filter is evaluated once per DISTINCT id in its column (RDF columns are
    highly repetitive) and broadcast back with the unique-inverse map."""
    n = table_len(table)
    if n == 0 or not rule.filters:
        return table
    mask = np.ones(n, dtype=bool)
    decode = reasoner.dictionary.decode
    for f in rule.filters:
        col = table.get(f.variable)
        if col is None:
            mask[:] = False
            break
        uniq, inv = np.unique(col, return_inverse=True)
        verdicts = np.fromiter(
            (f.evaluate(int(u), decode) for u in uniq),
            dtype=bool,
            count=len(uniq),
        )
        mask &= verdicts[inv]
    return {k: v[mask] for k, v in table.items()}


def _apply_negative_premises(
    reasoner, rule: Rule, table: BindingTable, store: ColumnarTripleStore
) -> BindingTable:
    """NAF premises as anti-joins against the fact store.  A negated premise
    sharing NO variables with the bindings is an existence test: any match
    kills every row."""
    for neg in rule.negative_premise:
        neg_table = scan_pattern_store(store, neg, reasoner.quoted)
        shared = set(table) & set(neg_table) - {"__exists"}
        if not shared:
            if table_len(neg_table) > 0:
                table = {k: v[:0] for k, v in table.items()}
        else:
            table = anti_join_tables(table, neg_table)
        if table_len(table) == 0:
            break
    return table


def eval_rule_body(
    reasoner,
    rule: Rule,
    store: ColumnarTripleStore,
    delta: Optional[Cols] = None,
    old_store: Optional[ColumnarTripleStore] = None,
) -> BindingTable:
    """Bindings satisfying the rule body.

    With ``delta``: semi-naive expansion — union over premise positions i
    (semi_naive.rs:22-44).  Without ``old_store``, positions != i scan ALL
    facts (cheap, but the same derivation can appear in several expansions —
    harmless for set semantics since new facts are deduped).  With
    ``old_store`` (= facts \\ delta), positions < i scan old facts only, so
    every derivation appears EXACTLY once — required by non-idempotent
    provenance semirings where each derivation's tag is ⊕-merged.
    """
    k = len(rule.premise)
    if k == 0:
        return {}
    if delta is None or len(delta[0]) == 0:
        if delta is not None:
            return {}
        table: Optional[BindingTable] = None
        for prem in rule.premise:
            t = scan_pattern_store(store, prem, reasoner.quoted)
            table = t if table is None else equi_join_tables(table, t)
            if table_len(table) == 0:
                return table
        table = _apply_negative_premises(reasoner, rule, table, store)
        return _apply_rule_filters(reasoner, rule, table)
    parts: List[BindingTable] = []
    for i in range(k):
        table = None
        for j, prem in enumerate(rule.premise):
            if j == i:
                t = scan_pattern_cols(delta, prem, reasoner.quoted)
            elif j < i and old_store is not None:
                t = scan_pattern_store(old_store, prem, reasoner.quoted)
            else:
                t = scan_pattern_store(store, prem, reasoner.quoted)
            table = t if table is None else equi_join_tables(table, t)
            if table_len(table) == 0:
                table = None
                break
        if table is not None:
            parts.append(table)
    if not parts:
        return {}
    merged = concat_tables(parts) if len(parts) > 1 else parts[0]
    merged = _apply_negative_premises(reasoner, rule, merged, store)
    return _apply_rule_filters(reasoner, rule, merged)


def instantiate_conclusions(rule: Rule, table: BindingTable, quoted=None) -> Cols:
    """Substitute bindings into the (multi-head) conclusions → new triples."""
    n = table_len(table)

    def concl_col(t: Term):
        if t.is_variable:
            return table.get(t.value)
        if t.is_quoted:
            if quoted is None:
                return None
            inner = [concl_col(x) for x in t.value.terms()]
            if any(c is None for c in inner):
                return None
            col = np.empty(n, dtype=np.uint32)
            for i in range(n):
                col[i] = quoted.intern(
                    int(inner[0][i]), int(inner[1][i]), int(inner[2][i])
                )
            return col
        return np.full(n, t.value, dtype=np.uint32)

    out_s: List[np.ndarray] = []
    out_p: List[np.ndarray] = []
    out_o: List[np.ndarray] = []
    for concl in rule.conclusion:
        cols = []
        ok = True
        for t in (concl.subject, concl.predicate, concl.object):
            col = concl_col(t)
            if col is None:
                ok = False
                break
            cols.append(col)
        if ok:
            out_s.append(cols[0])
            out_p.append(cols[1])
            out_o.append(cols[2])
    if not out_s:
        z = np.empty(0, dtype=np.uint32)
        return z, z, z
    s = np.concatenate(out_s)
    p = np.concatenate(out_p)
    o = np.concatenate(out_o)
    (s, p, o), _ = unique_rows([s, p, o])
    return s, p, o


def subtract_existing(store: ColumnarTripleStore, cols: Cols) -> Cols:
    """Keep only rows not already in the store — vectorized membership:
    dense-rank the (s, p) pairs over both sides, pack with o into one u64
    key per row, then one sorted-membership probe (the host twin of
    ``ops.device_join._row_membership``)."""
    s, p, o = cols
    if len(s) == 0:
        return cols
    ss, sp, so = store.columns()
    if len(ss) == 0:
        return cols

    def pack2(a, b):
        return (a.astype(np.uint64) << np.uint64(32)) | b.astype(np.uint64)

    osp = pack2(s, p)
    tsp = pack2(ss, sp)
    sorted_u = np.sort(np.concatenate([osp, tsp]))
    rank_o = np.searchsorted(sorted_u, osp).astype(np.uint32)
    rank_t = np.searchsorted(sorted_u, tsp).astype(np.uint32)
    from kolibrie_tpu.ops.join import semi_join_mask

    member = semi_join_mask(pack2(rank_o, o), pack2(rank_t, so))
    keep = ~member
    return s[keep], p[keep], o[keep]


# --------------------------------------------------------------------------
# Fixpoint drivers (infer_generic.rs parity)
# --------------------------------------------------------------------------


def infer_naive(reasoner) -> int:
    """Every round joins every premise against ALL facts (my_naive.rs)."""
    total = 0
    while True:
        new_parts: List[Cols] = []
        for rule in reasoner.rules:
            table = eval_rule_body(reasoner, rule, reasoner.facts, delta=None)
            if table_len(table) == 0:
                continue
            cols = instantiate_conclusions(rule, table, reasoner.quoted)
            cols = subtract_existing(reasoner.facts, cols)
            if len(cols[0]):
                new_parts.append(cols)
        if not new_parts:
            return total
        s = np.concatenate([c[0] for c in new_parts])
        p = np.concatenate([c[1] for c in new_parts])
        o = np.concatenate([c[2] for c in new_parts])
        (s, p, o), _ = unique_rows([s, p, o])
        before = len(reasoner.facts)
        reasoner.facts.add_batch(s, p, o)
        added = len(reasoner.facts) - before
        if added == 0:
            return total
        total += added


def infer_semi_naive(reasoner) -> int:
    """Delta-driven fixpoint: round N only re-derives through facts added in
    round N-1 (semi_naive.rs:57-59 'delta = facts appended since last
    round')."""
    total = 0
    s, p, o = reasoner.facts.columns()
    delta: Cols = (s, p, o)  # first round: everything is new
    while len(delta[0]) > 0:
        new_parts: List[Cols] = []
        for rule in reasoner.rules:
            table = eval_rule_body(reasoner, rule, reasoner.facts, delta=delta)
            if table_len(table) == 0:
                continue
            cols = instantiate_conclusions(rule, table, reasoner.quoted)
            cols = subtract_existing(reasoner.facts, cols)
            if len(cols[0]):
                new_parts.append(cols)
        if not new_parts:
            break
        s = np.concatenate([c[0] for c in new_parts])
        p = np.concatenate([c[1] for c in new_parts])
        o = np.concatenate([c[2] for c in new_parts])
        (s, p, o), _ = unique_rows([s, p, o])
        before = len(reasoner.facts)
        reasoner.facts.add_batch(s, p, o)
        added = len(reasoner.facts) - before
        if added == 0:
            break
        total += added
        delta = (s, p, o)
    return total


def rule_body_matches(reasoner, rule: Rule, store: ColumnarTripleStore) -> bool:
    """True if the rule body has at least one satisfying binding (used for
    constraint violation checks)."""
    if not rule.premise:
        return False
    table = eval_rule_body(reasoner, rule, store, delta=None)
    return table_len(table) > 0
