"""Probabilistic input-fact specifications.

Parity: ``shared/src/seed_spec.rs:14-31`` — ``Independent{triple, prob,
seed_id}`` and ``ExclusiveGroup{group_id, choices}``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from kolibrie_tpu.core.triple import Triple


@dataclass
class IndependentSeed:
    triple: Triple
    prob: float
    seed_id: Optional[int] = None


@dataclass
class ExclusiveGroupSeed:
    """Annotated disjunction: exactly one of the choices holds."""

    group_id: int
    choices: List[Tuple[Triple, float, Optional[int]]] = field(default_factory=list)
    # each choice: (triple, prob, seed_id)


SeedSpec = object  # IndependentSeed | ExclusiveGroupSeed
