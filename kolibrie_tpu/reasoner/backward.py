"""Backward chaining: depth-limited SLD-style resolution with unification.

Parity: ``datalog/src/reasoning/backward_chaining.rs`` — unification incl.
quoted-triple unification (:27-55), substitution, rule-variable renaming,
MAX_DEPTH=10 goal resolution (:148-206).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kolibrie_tpu.core.rule import Rule
from kolibrie_tpu.core.terms import Term, TriplePattern
from kolibrie_tpu.core.triple import Triple

MAX_DEPTH = 10

Subst = Dict[str, object]  # var name -> int id | Term (quoted)


def _walk(term: Term, subst: Subst) -> Term:
    while term.is_variable and term.value in subst:
        v = subst[term.value]
        term = v if isinstance(v, Term) else Term.constant(v)
    return term


def unify_terms(a: Term, b: Term, subst: Subst, quoted=None) -> Optional[Subst]:
    """Unify two terms under a substitution; supports nested quoted-triple
    unification (backward_chaining.rs:27-55): a constant quoted-triple ID can
    unify against a structural quoted pattern."""
    a = _walk(a, subst)
    b = _walk(b, subst)
    if a.is_variable:
        s = dict(subst)
        s[a.value] = b if b.is_quoted else (b.value if b.is_constant else Term.variable(b.value))
        if b.is_variable and b.value == a.value:
            return subst
        return s
    if b.is_variable:
        return unify_terms(b, a, subst, quoted)
    if a.is_constant and b.is_constant:
        return subst if a.value == b.value else None
    # structural quoted unification; resolve constant ids via the quoted store
    if a.is_quoted and b.is_constant and quoted is not None:
        inner = quoted.get(b.value)
        if inner is None:
            return None
        b = Term.quoted(
            TriplePattern(
                Term.constant(inner[0]), Term.constant(inner[1]), Term.constant(inner[2])
            )
        )
    if b.is_quoted and a.is_constant and quoted is not None:
        return unify_terms(b, a, subst, quoted)
    if a.is_quoted and b.is_quoted:
        s: Optional[Subst] = subst
        for ta, tb in zip(a.value.terms(), b.value.terms()):
            s = unify_terms(ta, tb, s, quoted)
            if s is None:
                return None
        return s
    return None


def unify_pattern_triple(
    pattern: TriplePattern, triple: Triple, subst: Subst, quoted=None
) -> Optional[Subst]:
    s: Optional[Subst] = subst
    for pt, tid in zip(pattern.terms(), triple):
        s = unify_terms(pt, Term.constant(tid), s, quoted)
        if s is None:
            return None
    return s


def _rename_rule(rule: Rule, counter: int) -> Rule:
    """Fresh variable names per resolution step (standardizing apart)."""

    def rn(term: Term) -> Term:
        if term.is_variable:
            return Term.variable(f"{term.value}__r{counter}")
        if term.is_quoted:
            return Term.quoted(TriplePattern(*(rn(t) for t in term.value.terms())))
        return term

    def rp(p: TriplePattern) -> TriplePattern:
        return TriplePattern(rn(p.subject), rn(p.predicate), rn(p.object))

    return Rule(
        premise=[rp(p) for p in rule.premise],
        negative_premise=[rp(p) for p in rule.negative_premise],
        filters=rule.filters,
        conclusion=[rp(c) for c in rule.conclusion],
    )


def _apply_subst(pattern: TriplePattern, subst: Subst) -> TriplePattern:
    def ap(term: Term) -> Term:
        t = _walk(term, subst)
        if t.is_quoted:
            return Term.quoted(TriplePattern(*(ap(x) for x in t.value.terms())))
        return t

    return TriplePattern(ap(pattern.subject), ap(pattern.predicate), ap(pattern.object))


def backward_chaining(
    reasoner, goal: TriplePattern, max_depth: int = MAX_DEPTH
) -> List[Subst]:
    """All substitutions proving ``goal`` from facts and rules."""
    counter = [0]

    def solve(goals: List[TriplePattern], subst: Subst, depth: int) -> List[Subst]:
        if not goals:
            return [subst]
        if depth > max_depth:
            return []
        goal, rest = goals[0], goals[1:]
        goal = _apply_subst(goal, subst)
        results: List[Subst] = []
        # fact resolution (indexed scan on bound positions)
        consts = [
            t.value if t.is_constant else None for t in goal.terms()
        ]
        s, p, o = reasoner.facts.match(
            s=consts[0] if not goal.subject.is_quoted else None,
            p=consts[1] if not goal.predicate.is_quoted else None,
            o=consts[2] if not goal.object.is_quoted else None,
        )
        for i in range(len(s)):
            t = Triple(int(s[i]), int(p[i]), int(o[i]))
            s2 = unify_pattern_triple(goal, t, subst, reasoner.quoted)
            if s2 is not None:
                results.extend(solve(rest, s2, depth))
        # rule resolution
        for rule in reasoner.rules:
            renamed = _rename_rule(rule, counter[0])
            counter[0] += 1
            for concl in renamed.conclusion:
                s2: Optional[Subst] = dict(subst)
                ok = True
                for gt, ct in zip(goal.terms(), concl.terms()):
                    s2 = unify_terms(gt, ct, s2, reasoner.quoted)
                    if s2 is None:
                        ok = False
                        break
                if not ok:
                    continue
                results.extend(solve(renamed.premise + rest, s2, depth + 1))
        return results

    raw = solve([goal], {}, 0)
    # project to the goal's own variables, dedup
    goal_vars = goal.variables()
    out: List[Subst] = []
    seen = set()
    for s in raw:
        proj = {}
        for v in goal_vars:
            val = _walk(Term.variable(v), s)
            proj[v] = val.value if val.is_constant else None
        key = tuple(sorted(proj.items(), key=lambda kv: kv[0]))
        if key not in seen:
            seen.add(key)
            out.append(proj)
    return out
