"""Differentiable weighted model counting: ∂WMC/∂p_v per seed variable.

Parity: ``shared/src/diff_sdd.rs:15-46`` — weight-substitution method: WMC is
multilinear, so WMC = w_pos(v)·A + w_neg(v)·B for any variable v; evaluate A
(set w_pos=1, w_neg=0) and B (w_pos=0, w_neg=1) and combine per ``VarKind``:

- independent (w_neg = 1 − p):  ∂WMC/∂p = A − B
- exclusive-group (w_neg = 1):  ∂WMC/∂p = A

Validated against finite differences in tests (diff_sdd.rs:84-111 parity).
This is the bridge between the host SDD engine and the JAX training loop: the
gradients flow into jax MLP backprop as seed-probability cotangents.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

from kolibrie_tpu.reasoner.sdd import SddManager


def wmc_gradient(
    manager: SddManager, nid: int, var_indices: Optional[Iterable[int]] = None
) -> Dict[int, float]:
    """Gradient of WMC(nid) w.r.t. each variable's success probability."""
    if var_indices is None:
        var_indices = range(len(manager.vars))
    native = getattr(manager, "wmc_gradient", None)
    if native is not None:
        # native engine computes the substitution sweep in C++
        return native(nid, list(var_indices))
    grads: Dict[int, float] = {}
    for v in var_indices:
        vi = manager.vars[v]
        saved = (vi.w_pos, vi.w_neg)
        vi.w_pos, vi.w_neg = 1.0, 0.0
        a = manager.wmc(nid)
        vi.w_pos, vi.w_neg = 0.0, 1.0
        b = manager.wmc(nid)
        vi.w_pos, vi.w_neg = saved
        if vi.kind == "independent":
            grads[v] = a - b
        else:  # exclusive group: w_neg pinned at 1
            grads[v] = a
    return grads


def wmc_gradient_by_seed(
    manager: SddManager, nid: int, seed_vars: Dict[int, int]
) -> Dict[int, float]:
    """Gradient keyed by seed_id (as used by the neurosymbolic trainer)."""
    per_var = wmc_gradient(manager, nid, seed_vars.values())
    return {sid: per_var[v] for sid, v in seed_vars.items()}
