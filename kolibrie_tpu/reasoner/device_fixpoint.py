"""Single-chip device semi-naive Datalog fixpoint.

The host strategies (:mod:`kolibrie_tpu.reasoner.strategies`) evaluate rule
bodies with numpy joins round by round.  Here the ENTIRE fixpoint runs as a
single XLA dispatch: a ``lax.while_loop`` whose body is one semi-naive round
— delta-seeded premise joins (static-capacity sort joins), filter masks,
NAF anti-joins, conclusion instantiation, sort-unique dedup, set-difference
against known facts, fact append — with the loop condition fusing
"no new facts?" into the program (SURVEY §7.4: fixpoint termination without
per-round host sync).

Parity (TPU-native redesign, not a translation):
``datalog/src/reasoning/materialisation/semi_naive_parallel.rs:11-177`` —
the rayon delta fan-out becomes whole-column joins;
``semi_naive.rs:22-59`` — delta seeding per premise position.

Static-shape protocol: every buffer has a power-of-two capacity.  A round
that would overflow any capacity does NOT commit (the loop exits with the
pre-round state and an overflow code); the host driver doubles the failing
capacity and re-enters the loop from the preserved state.  Readback happens
once per ``while_loop`` exit, not per round.

GROUND quoted (RDF-star) terms lower to their qid constants — premises
against never-interned triples become never-match scans, quoted
conclusions intern eagerly at lowering.  Rules whose shapes the device
path cannot express (quoted terms with INNER VARIABLES, non-numeric
filters, cartesian premise joins) raise :class:`Unsupported`; callers
fall back to the host strategies.  3-variable
join keys ride the union dense-rank composition
(``ops/device_join.py::pack_key_multi``).  Agreement between both paths is
tested in ``tests/test_device_fixpoint.py``.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
from kolibrie_tpu.ops.jax_compat import enable_x64 as _enable_x64
import numpy as np

from kolibrie_tpu.core.rule import FilterCondition, Rule

__all__ = ["Unsupported", "DeviceFixpoint", "infer_semi_naive_device"]


class Unsupported(Exception):
    """Rule set the device fixpoint cannot express (host fallback)."""


from kolibrie_tpu.obs import metrics as _obs_metrics
from kolibrie_tpu.obs import runtime as _obs_runtime
from kolibrie_tpu.obs.spans import span as _obs_span
from kolibrie_tpu.ops import round_cap as _round_cap

_FIXPOINT_ROUNDS = _obs_metrics.histogram(
    "kolibrie_fixpoint_rounds",
    "semi-naive rounds per fixpoint run (chunked path: productive rounds)",
    buckets=_obs_metrics.DEFAULT_COUNT_BUCKETS,
)
_FIXPOINT_DERIVED = _obs_metrics.histogram(
    "kolibrie_fixpoint_derived_facts",
    "facts derived per fixpoint run",
    buckets=_obs_metrics.DEFAULT_COUNT_BUCKETS,
)
_FIXPOINT_DELTA = _obs_metrics.histogram(
    "kolibrie_fixpoint_delta_facts",
    "delta size fed to each chunked fixpoint round",
    buckets=_obs_metrics.DEFAULT_COUNT_BUCKETS,
)


# ---------------------------------------------------------------------------
# Rule lowering (host) — frozen, hashable: part of the jit static key
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LoweredPremise:
    consts: tuple  # (Optional[int], Optional[int], Optional[int])
    vars: tuple  # ((var, pos) first occurrence ...)
    eq_pairs: tuple  # ((pos, pos) ...) repeated variables


@dataclass(frozen=True)
class LoweredFilter:
    kind: str  # 'mask' (per-ID bool gather) | 'eq' | 'ne' (ID compare)
    var: str
    mask_idx: int = -1
    const_id: int = 0


@dataclass(frozen=True)
class LoweredRule:
    premises: tuple  # (LoweredPremise, ...)
    negs: tuple  # (LoweredPremise, ...)
    filters: tuple  # (LoweredFilter, ...)
    concls: tuple  # ((term, term, term), ...); term = ('var', name) | ('const', id)
    # per seed position: premise evaluation order (seed first) and the join
    # key variables for each subsequent step
    plans: tuple  # ((order: tuple[int], keys: tuple[tuple[str,...]]), ...)
    # fully-ground GUARD premises dropped from the join plan after static
    # satisfaction (see lower_rules: non-derivable + present in the initial
    # facts — facts never retract, so the gate holds for the whole closure).
    # Kept for the tagged drivers, whose ⊗ would need the guard's tag.
    guards: tuple = ()


def _ground_quoted_id(term, quoted) -> Optional[int]:
    """qid of a GROUND quoted term (recursively constant inner triple), or
    None when the triple is not interned — a premise against it can never
    match.  Raises Unsupported for quoted terms with inner variables (the
    host unification path covers those)."""
    inner = term.value.terms()
    ids = []
    for t in inner:
        if t.is_quoted:
            qid = _ground_quoted_id(t, quoted)
            if qid is None:
                return None
            ids.append(qid)
        elif t.is_constant:
            ids.append(int(t.value))
        else:
            raise Unsupported("quoted-triple pattern with inner variables")
    if quoted is None:
        raise Unsupported("quoted-triple pattern without a quoted store")
    return quoted.lookup(*ids)


# never a dictionary ID (bits 0..30 + quoted bit 31, not all-ones): a scan
# constant that matches nothing — the lowering of a ground quoted premise
# whose triple was never interned
_NEVER_MATCH = 0xFFFFFFFF


def _lower_pattern(pattern, dictionary, quoted=None) -> LoweredPremise:
    consts: List[Optional[int]] = []
    out_vars: List[tuple] = []
    eq_pairs: List[tuple] = []
    seen: Dict[str, int] = {}
    for pos, t in enumerate(pattern.terms()):
        if t.is_quoted:
            # ground quoted term → its qid constant (absent ⇒ never match);
            # inner variables stay host-side (Unsupported from the helper)
            qid = _ground_quoted_id(t, quoted)
            consts.append(_NEVER_MATCH if qid is None else int(qid))
            continue
        if t.is_constant:
            consts.append(int(t.value))
        else:
            consts.append(None)
            if t.value in seen:
                eq_pairs.append((seen[t.value], pos))
            else:
                seen[t.value] = pos
                out_vars.append((t.value, pos))
    return LoweredPremise(tuple(consts), tuple(out_vars), tuple(eq_pairs))


def _plan_rule(premises: List[LoweredPremise]) -> tuple:
    """For each seed position: greedy connected join order + key vars."""
    plans = []
    for i in range(len(premises)):
        order = [i]
        bound = {v for v, _ in premises[i].vars}
        remaining = [j for j in range(len(premises)) if j != i]
        keys: List[tuple] = []
        while remaining:
            scored = []
            for j in remaining:
                jvars = {v for v, _ in premises[j].vars}
                scored.append((len(jvars & bound), -len(jvars), j))
            scored.sort(reverse=True)
            n_shared, _, best = scored[0]
            if n_shared == 0:
                raise Unsupported("cartesian premise join")
            jvars = {v for v, _ in premises[best].vars}
            shared = tuple(sorted(jvars & bound))
            # 1-2 keys pack exactly into u64; 3 keys (a premise has only
            # three positions) ride the union dense-rank composition
            keys.append(shared)
            order.append(best)
            bound |= jvars
            remaining.remove(best)
        plans.append((tuple(order), tuple(keys)))
    return tuple(plans)


class _MaskBank:
    """Per-ID boolean masks for numeric rule filters (host-precomputed)."""

    def __init__(self, reasoner):
        self.reasoner = reasoner
        self.exprs: List[tuple] = []  # (op, float const)
        self._keys: Dict[tuple, int] = {}

    def index_for(self, op: str, const: float) -> int:
        key = (op, const)
        idx = self._keys.get(key)
        if idx is None:
            idx = len(self.exprs)
            self.exprs.append(key)
            self._keys[key] = idx
        return idx

    def materialize(self) -> List[np.ndarray]:
        if not self.exprs:
            return []
        d = self.reasoner.dictionary
        n = len(d.id_to_str)
        cached = getattr(self, "_mask_cache", None)
        if cached is not None and cached[0] == n:
            return cached[1]
        vals = np.full(n, np.nan)
        for i in range(1, n):
            v = self.reasoner.numeric_value(i)
            if v is not None:
                vals[i] = v
        out = []
        with np.errstate(invalid="ignore"):
            for op, const in self.exprs:
                if op == "=":
                    m = vals == const
                elif op == "!=":
                    m = vals != const
                elif op == "<":
                    m = vals < const
                elif op == "<=":
                    m = vals <= const
                elif op == ">":
                    m = vals > const
                else:
                    m = vals >= const
                out.append(m & ~np.isnan(vals))
        self._mask_cache = (n, out)
        return out


def _guard_derivable(guard: LoweredPremise, rules: List[Rule]) -> bool:
    """Could any rule's conclusion unify with this fully-ground premise?
    Conservative syntactic test (variables unify with anything; quoted
    conclusion terms count as wildcards)."""
    for r in rules:
        for c in r.conclusion:
            if all(
                (not t.is_constant) or int(t.value) == g
                for t, g in zip(c.terms(), guard.consts)
            ):
                return True
    return False


def lower_rules(reasoner, rules: List[Rule]) -> Tuple[tuple, _MaskBank]:
    bank = _MaskBank(reasoner)
    lowered: List[LoweredRule] = []
    for rule in rules:
        quoted = getattr(reasoner, "quoted", None)
        prems = [
            _lower_pattern(p, reasoner.dictionary, quoted)
            for p in rule.premise
        ]
        if not prems:
            raise Unsupported("rule without positive premises")
        # fully-ground GUARD premises (the RDF-star annotation-gate shape):
        # facts never retract, so a non-derivable guard's truth is CONSTANT
        # through any one closure — it drops out of the JOIN PLAN and is
        # evaluated as a whole-rule membership gate at RUN time (the same
        # lowered rules must stay correct for callers like DeviceR2R that
        # lower once and supply different fact columns per window).  A
        # derivable guard can flip mid-closure, which the delta-seeded
        # plans over the remaining premises would miss — host fallback.
        guards = [p for p in prems if not p.vars]
        if guards:
            for g in guards:
                if _guard_derivable(g, rules):
                    raise Unsupported("derivable ground guard premise")
            prems = [p for p in prems if p.vars]
            if not prems:
                raise Unsupported("fully ground rule")
        bound = {v for pr in prems for v, _ in pr.vars}
        negs = [
            _lower_pattern(p, reasoner.dictionary, quoted)
            for p in rule.negative_premise
        ]
        for neg in negs:
            # the host path anti-joins on the SHARED variables only; a
            # negated variable outside the positive premises needs that
            # looser semantics — fall back rather than trace a KeyError
            if any(v not in bound for v, _ in neg.vars):
                raise Unsupported("negated variable unbound in positive premises")
        filters: List[LoweredFilter] = []
        for f in rule.filters:
            if f.variable not in bound:
                raise Unsupported("filter variable unbound in positive premises")
            filters.append(_lower_filter(f, bank))
        concls = []
        for c in rule.conclusion:
            terms = []
            for t in c.terms():
                if t.is_quoted:
                    # a GROUND quoted conclusion is a constant qid; intern
                    # eagerly (host interns on first derivation — the only
                    # observable difference is the quoted-store entry
                    # existing before the rule fires).  Inner variables
                    # (constructing new quoted terms per binding) stay
                    # host-side.
                    inner = t.value.terms()
                    if any(not it.is_constant for it in inner):
                        raise Unsupported(
                            "quoted-triple conclusion with inner variables"
                        )
                    if quoted is None:
                        raise Unsupported("quoted conclusion without a store")
                    qid = quoted.intern(*(int(it.value) for it in inner))
                    terms.append(("const", int(qid)))
                    continue
                if t.is_constant:
                    terms.append(("const", int(t.value)))
                else:
                    if t.value not in bound:
                        raise Unsupported("head variable unbound in premises")
                    terms.append(("var", t.value))
            concls.append(tuple(terms))
        lowered.append(
            LoweredRule(
                tuple(prems),
                tuple(negs),
                tuple(filters),
                tuple(concls),
                _plan_rule(prems),
                tuple(guards),
            )
        )
    return tuple(lowered), bank


def _lower_filter(f: FilterCondition, bank: _MaskBank) -> LoweredFilter:
    if isinstance(f.value, bool):
        raise Unsupported("boolean filter value")
    if isinstance(f.value, int):
        if f.operator == "=":
            return LoweredFilter("eq", f.variable, const_id=int(f.value))
        if f.operator == "!=":
            return LoweredFilter("ne", f.variable, const_id=int(f.value))
        # ordered comparison against an ID-valued constant is numeric on the
        # DECODED literal in the host path — same here via the mask bank
        raise Unsupported("ordered comparison against term id")
    try:
        const = float(f.value)  # type: ignore[arg-type]
    except (TypeError, ValueError):
        raise Unsupported(f"non-numeric filter value {f.value!r}")
    return LoweredFilter("mask", f.variable, mask_idx=bank.index_for(f.operator, const))


# ---------------------------------------------------------------------------
# Jitted fixpoint
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class _Caps:
    fact: int
    delta: int
    join: int  # one shared capacity for all intermediate joins


def _scan_premise(prem: LoweredPremise, cols, valid):
    """Premise match against a (cols, valid) buffer → (var table, mask)."""
    import jax.numpy as jnp

    m = valid
    for c, col in zip(prem.consts, cols):
        if c is not None:
            m = m & (col == np.uint32(c))
    for a, b in prem.eq_pairs:
        m = m & (cols[a] == cols[b])
    table = {v: cols[pos] for v, pos in prem.vars}
    return table, m


def _pack(cols: List, valid, sentinel):
    import jax.numpy as jnp

    if len(cols) == 1:
        key = cols[0].astype(jnp.uint64)
    else:
        key = (cols[0].astype(jnp.uint64) << np.uint64(32)) | cols[1].astype(
            jnp.uint64
        )
    return jnp.where(valid, key, np.uint64(sentinel))


def _eval_filters(rule, table, valid, masks):
    import jax.numpy as jnp

    for f in rule.filters:
        col = table[f.var]
        if f.kind == "eq":
            valid = valid & (col == np.uint32(f.const_id))
        elif f.kind == "ne":
            valid = valid & (col != np.uint32(f.const_id))
        else:
            m = masks[f.mask_idx]
            valid = valid & m[jnp.minimum(col, m.shape[0] - 1)]
    return valid


def _eval_negs(rule, table, valid, facts):
    import jax.numpy as jnp

    from kolibrie_tpu.ops.device_join import (
        _LPAD,
        _RPAD,
        _row_membership,
        semi_join_mask,
    )

    fsx, fpx, fox, fvx = facts
    fcols = (fsx, fpx, fox)
    for neg in rule.negs:
        nm = fvx
        for c, col in zip(neg.consts, fcols):
            if c is not None:
                nm = nm & (col == np.uint32(c))
        for a, b in neg.eq_pairs:
            nm = nm & (fcols[a] == fcols[b])
        key_cols = [table[v] for v, _ in neg.vars]
        fact_cols = [fcols[pos] for _, pos in neg.vars]
        if not key_cols:
            # fully-constant negated premise: existence kills every row
            valid = valid & ~jnp.any(nm)
            continue
        if len(key_cols) <= 2:
            member = semi_join_mask(
                _pack(key_cols, valid, _LPAD), _pack(fact_cols, nm, _RPAD)
            )
        else:
            ours = [jnp.where(valid, c, np.uint32(0xFFFFFFFE)) for c in key_cols]
            theirs = [
                jnp.where(nm, c, np.uint32(0xFFFFFFFF)) for c in fact_cols
            ]
            member = _row_membership(ours, theirs)
        valid = valid & ~member
    return valid


def _gen_candidates(
    rules, fcols, fvalid, dcols, dvalid, masks, J, use_pallas=False
):
    """Candidate conclusions of one semi-naive round: delta-seeded premise
    joins + filters + NAF over a FROZEN fact snapshot, as static-cap column
    blocks.  Shared by the one-dispatch fixpoint (inside its ``while_loop``)
    and the per-round chunk program (:func:`_device_round_chunk`).

    ``use_pallas``: premise joins ride the Pallas tile kernel through the
    dense-rank prepass (the engine's production join on TPU) instead of
    the XLA searchsorted expansion.
    """
    import jax.numpy as jnp

    from kolibrie_tpu.ops.device_join import _LPAD, _RPAD, join_indices

    if use_pallas:
        from kolibrie_tpu.ops.pallas_kernels import ranked_merge_join_indices

    facts = (*fcols, fvalid)
    overflow = np.int32(0)
    cand_parts: List[tuple] = []  # (s, p, o, valid) static-cap blocks

    for rule in rules:
        # ground-guard gate: a whole-rule membership test against the fact
        # snapshot (non-derivable by the lowering gate, so its value is
        # constant through the closure — per-window callers like DeviceR2R
        # get the right value for THEIR facts)
        guard_ok = None
        for g in rule.guards:
            _t, gm = _scan_premise(g, fcols, fvalid)
            hit = jnp.any(gm)
            guard_ok = hit if guard_ok is None else (guard_ok & hit)
        for order, keys in rule.plans:
            seed = order[0]
            table, m = _scan_premise(rule.premises[seed], dcols, dvalid)
            valid = m if guard_ok is None else (m & guard_ok)
            for step, j in enumerate(order[1:]):
                ptable, pm = _scan_premise(rule.premises[j], fcols, fvalid)
                kv = keys[step]
                if len(kv) > 2:
                    from kolibrie_tpu.ops.device_join import pack_key_multi

                    lkey, rkey = pack_key_multi(
                        [table[v] for v in kv],
                        [ptable[v] for v in kv],
                        valid,
                        pm,
                    )
                else:
                    lkey = _pack([table[v] for v in kv], valid, _LPAD)
                    rkey = _pack([ptable[v] for v in kv], pm, _RPAD)
                if use_pallas:
                    li, ri, jvalid, total = ranked_merge_join_indices(
                        lkey, rkey, J
                    )
                else:
                    li, ri, jvalid, total = join_indices(lkey, rkey, J)
                overflow = overflow | jnp.where(total > J, np.int32(1), 0)
                new_table = {}
                for v, c in table.items():
                    new_table[v] = c[li]
                for v, c in ptable.items():
                    if v not in new_table:
                        new_table[v] = c[ri]
                table, valid = new_table, jvalid
            valid = _eval_filters(rule, table, valid, masks)
            valid = _eval_negs(rule, table, valid, facts)
            n = valid.shape[0]
            for concl in rule.concls:
                out = []
                for kind, v in concl:
                    if kind == "var":
                        out.append(table[v])
                    else:
                        out.append(jnp.full(n, v, dtype=jnp.uint32))
                cand_parts.append((out[0], out[1], out[2], valid))

    cs = jnp.concatenate([p[0] for p in cand_parts])
    cp = jnp.concatenate([p[1] for p in cand_parts])
    co = jnp.concatenate([p[2] for p in cand_parts])
    cv = jnp.concatenate([p[3] for p in cand_parts])
    return cs, cp, co, cv, overflow


@partial(jax.jit, static_argnames=("rules", "caps", "use_pallas"))
def _device_fixpoint(
    rules: tuple,
    caps: _Caps,
    fs,
    fp,
    fo,
    n_facts,
    masks,
    use_pallas: bool = False,
):
    """Run semi-naive rounds to fixpoint (or capacity overflow) on device.

    ``fs/fp/fo`` must be padded to ``caps.fact`` by the caller (keeps the
    jit cache keyed on capacities, not exact fact counts).  Returns
    (fs, fp, fo, n_facts, rounds, overflow_code) where overflow_code:
    a bitmask: 0 ok, bit0 join cap, bit1 delta cap, bit2 fact cap.
    """
    import jax.numpy as jnp
    from jax import lax

    from kolibrie_tpu.ops.device_join import _row_membership

    F, D, J = caps.fact, caps.delta, caps.join

    def pad_to(x, cap, fill=0):
        return jnp.concatenate(
            [x, jnp.full(cap - x.shape[0], fill, dtype=x.dtype)]
        )

    fvalid = jnp.arange(F, dtype=jnp.int32) < n_facts

    # round 0: delta = all facts
    ds = fs[:D] if D <= F else pad_to(fs, D)
    dp = fp[:D] if D <= F else pad_to(fp, D)
    do = fo[:D] if D <= F else pad_to(fo, D)
    dvalid = jnp.arange(D, dtype=jnp.int32) < jnp.minimum(n_facts, D)
    init_overflow = jnp.where(n_facts > D, np.int32(2), np.int32(0))  # bit1: delta

    def round_body(carry):
        fs, fp, fo, fvalid, n_facts, ds, dp, do, dvalid, n_new, rounds, _ovf = carry

        cs, cp, co, cv, overflow = _gen_candidates(
            rules, (fs, fp, fo), fvalid, (ds, dp, do), dvalid, masks, J,
            use_pallas,
        )

        # dedup + subtract known facts (fused membership: rank (s,p), pack o)
        ours = [
            jnp.where(cv, cs, np.uint32(0xFFFFFFFE)),
            jnp.where(cv, cp, np.uint32(0xFFFFFFFE)),
            jnp.where(cv, co, np.uint32(0xFFFFFFFE)),
        ]
        theirs = [
            jnp.where(fvalid, fs, np.uint32(0xFFFFFFFF)),
            jnp.where(fvalid, fp, np.uint32(0xFFFFFFFF)),
            jnp.where(fvalid, fo, np.uint32(0xFFFFFFFF)),
        ]
        known = _row_membership(ours, theirs)
        cv = cv & ~known

        from kolibrie_tpu.parallel.dist_fixpoint import _sort_unique3

        (us, up, uo), uvalid, n_uniq = _sort_unique3((cs, cp, co), cv, D)
        overflow = overflow | jnp.where(n_uniq > D, np.int32(2), 0)
        n_new_next = jnp.minimum(n_uniq, D).astype(jnp.int32)

        # append new facts
        dest = jnp.where(uvalid, n_facts + jnp.cumsum(uvalid) - 1, F)
        nfs = fs.at[dest].set(us, mode="drop")
        nfp = fp.at[dest].set(up, mode="drop")
        nfo = fo.at[dest].set(uo, mode="drop")
        n_facts_next = n_facts + n_new_next
        overflow = overflow | jnp.where(n_facts_next > F, np.int32(4), 0)
        nfvalid = jnp.arange(F, dtype=jnp.int32) < n_facts_next

        # commit only on success: an overflowing round must not corrupt state
        ok = overflow == 0

        def sel(new, old):
            return jnp.where(ok, new, old)

        return (
            sel(nfs, fs),
            sel(nfp, fp),
            sel(nfo, fo),
            sel(nfvalid, fvalid),
            sel(n_facts_next, n_facts),
            sel(us, ds),
            sel(up, dp),
            sel(uo, do),
            sel(uvalid, dvalid),
            sel(n_new_next, n_new),
            rounds + jnp.where(ok, 1, 0),
            overflow,
        )

    ROUND_LIMIT = 10_000  # runaway-rule backstop, far above any real closure

    def cond(carry):
        n_new, rounds, overflow = carry[9], carry[10], carry[11]
        return (n_new > 0) & (overflow == 0) & (rounds < ROUND_LIMIT)

    init = (
        fs,
        fp,
        fo,
        fvalid,
        n_facts.astype(jnp.int32),
        ds,
        dp,
        do,
        dvalid,
        jnp.minimum(n_facts, np.int32(1)).astype(jnp.int32),
        np.int32(0),
        init_overflow,
    )
    out = lax.while_loop(cond, round_body, init)
    # bit3: round limit hit with work remaining — an incomplete closure must
    # never be reported as success
    code = out[11] | jnp.where(
        (out[10] >= ROUND_LIMIT) & (out[9] > 0), np.int32(8), np.int32(0)
    )
    return out[0], out[1], out[2], out[4], out[10], code


@partial(jax.jit, static_argnames=("rules", "caps", "use_pallas"))
def _device_round_chunk(
    rules: tuple,
    caps: _Caps,
    fs,
    fp,
    fo,
    n_facts,
    ds,
    dp,
    do,
    n_delta,
    accs,
    accp,
    acco,
    n_acc,
    masks,
    use_pallas: bool = False,
):
    """One delta CHUNK of one semi-naive round as its own XLA program.

    The facts are FROZEN for the whole round — NAF and known-fact
    subtraction see the same snapshot in every chunk, so K chunked
    dispatches produce exactly the round the one-dispatch program's
    ``round_body`` would.  New facts accumulate (deduplicated) in the
    ``acc*`` buffer; the host driver merges it into the fact columns at
    round end and feeds it back as the next round's delta.

    The point of the split: each program's join capacity stays below the
    toolchain bound that faults the composed one-dispatch fixpoint
    (``SAFE_JOIN_CAP``), which is what lets LUBM-1000-scale closures run
    on-chip.  Returns ``(accs, accp, acco, n_acc, overflow)``; an
    overflowing chunk does NOT commit (bit0 join cap, bit1 accumulator
    cap), so the caller can double the failing capacity and re-run it.
    """
    import jax.numpy as jnp

    from kolibrie_tpu.ops.device_join import _row_membership

    F, D, J = caps.fact, caps.delta, caps.join
    fvalid = jnp.arange(F, dtype=jnp.int32) < n_facts
    dvalid = jnp.arange(ds.shape[0], dtype=jnp.int32) < n_delta

    cs, cp, co, cv, overflow = _gen_candidates(
        rules, (fs, fp, fo), fvalid, (ds, dp, do), dvalid, masks, J,
        use_pallas,
    )

    # subtract known facts AND rows already accumulated by earlier chunks
    ours = [jnp.where(cv, c, np.uint32(0xFFFFFFFE)) for c in (cs, cp, co)]
    known = _row_membership(
        ours,
        [jnp.where(fvalid, c, np.uint32(0xFFFFFFFF)) for c in (fs, fp, fo)],
    )
    accv = jnp.arange(D, dtype=jnp.int32) < n_acc
    in_acc = _row_membership(
        ours,
        [jnp.where(accv, c, np.uint32(0xFFFFFFFF)) for c in (accs, accp, acco)],
    )
    cv = cv & ~known & ~in_acc

    from kolibrie_tpu.parallel.dist_fixpoint import _sort_unique3

    (us, up, uo), uvalid, n_uniq = _sort_unique3((cs, cp, co), cv, D)
    n_u = jnp.minimum(n_uniq, D).astype(jnp.int32)
    overflow = overflow | jnp.where(
        (n_uniq > D) | (n_acc + n_u > D), np.int32(2), 0
    )

    dest = jnp.where(uvalid, n_acc + jnp.cumsum(uvalid) - 1, D)
    nas = accs.at[dest].set(us, mode="drop")
    nap = accp.at[dest].set(up, mode="drop")
    nao = acco.at[dest].set(uo, mode="drop")

    ok = overflow == 0

    def sel(new, old):
        return jnp.where(ok, new, old)

    return (
        sel(nas, accs),
        sel(nap, accp),
        sel(nao, acco),
        sel(n_acc + n_u, n_acc),
        overflow,
    )


# ---------------------------------------------------------------------------
# Host driver
# ---------------------------------------------------------------------------


class DeviceFixpoint:
    """Host driver: lowers the reasoner's rules, sizes capacities, runs the
    on-device fixpoint with overflow-driven capacity doubling, and writes
    derived facts back into ``reasoner.facts``."""

    def __init__(self, reasoner):
        self.reasoner = reasoner
        self.rules, self.bank = lower_rules(reasoner, reasoner.rules)
        # rounds taken by the most recent successful infer/infer_padded —
        # previously computed on device and discarded at readback
        self.last_rounds = 0

    def _caps(self, n_facts: int):
        return _Caps(
            fact=_round_cap(8 * n_facts, 2048),
            delta=_round_cap(max(2 * n_facts, 1024)),
            join=_round_cap(4 * n_facts, 1024),
        )

    def run_raw(self, caps: Optional[_Caps] = None):
        """One fixpoint dispatch with NO host readback.

        Benchmark/timing API (on the axon tunnel a single readback degrades
        later dispatches by orders of magnitude — see bench notes): returns
        the raw device outputs ``(fs, fp, fo, n_facts, rounds, code)``;
        the caller must check ``code == 0`` AFTER timing.
        """
        import jax.numpy as jnp

        s, p, o = self.reasoner.facts.columns()
        n0 = len(s)
        caps = caps if caps is not None else self._caps(n0)
        if not self.rules:
            return (
                jnp.asarray(s),
                jnp.asarray(p),
                jnp.asarray(o),
                jnp.int32(n0),
                jnp.int32(0),
                jnp.int32(0),
            )
        masks = tuple(jnp.asarray(m) for m in self.bank.materialize()) or (
            jnp.zeros(1, dtype=bool),
        )

        def pad(x):
            return jnp.concatenate(
                [
                    jnp.asarray(x, dtype=jnp.uint32),
                    jnp.zeros(caps.fact - len(x), dtype=jnp.uint32),
                ]
            )

        from kolibrie_tpu.ops.pallas_kernels import pallas_join_enabled

        with _enable_x64(True):
            return _device_fixpoint(
                self.rules, caps, pad(s), pad(p), pad(o), jnp.int32(n0), masks,
                pallas_join_enabled(),
            )

    def infer_padded(
        self,
        fs,
        fp,
        fo,
        n_facts,
        caps: _Caps,
        max_attempts: int = 12,
    ):
        """Capacity-retry fixpoint over device-resident fact columns.

        ``fs/fp/fo`` are u32 device columns holding ``n_facts`` valid rows
        (any padding beyond is ignored; columns shorter than ``caps.fact``
        are re-padded).  Returns ``(ofs, ofp, ofo, n_out, caps)`` — the raw
        padded output columns (input rows first, derived appended), the int
        fact count, and the converged capacities — WITHOUT touching
        ``reasoner.facts``.  This is the entry the device-resident RSP
        driver reuses every window firing: no host round-trip of the fact
        columns, one compiled program per capacity configuration.
        """
        import jax.numpy as jnp

        if not self.rules:
            return fs, fp, fo, int(n_facts), caps

        masks = tuple(jnp.asarray(m) for m in self.bank.materialize()) or (
            jnp.zeros(1, dtype=bool),
        )
        from kolibrie_tpu.ops.pallas_kernels import pallas_join_enabled

        use_pallas = pallas_join_enabled()
        for _attempt in range(max_attempts):

            def pad(x):
                if x.shape[0] < caps.fact:
                    return jnp.concatenate(
                        [
                            x.astype(jnp.uint32),
                            jnp.zeros(caps.fact - x.shape[0], dtype=jnp.uint32),
                        ]
                    )
                # longer columns (an oversized resident mirror) are sliced:
                # caps.fact >= 8 * n_facts, so only invalid padding drops
                return x[: caps.fact].astype(jnp.uint32)

            fs, fp, fo = pad(fs), pad(fp), pad(fo)
            with _enable_x64(True):
                ofs, ofp, ofo, on, rounds, code = _device_fixpoint(
                    self.rules, caps, fs, fp, fo, n_facts, masks, use_pallas
                )
            code = int(code)
            if code == 0:
                if _obs_runtime.enabled():
                    # one extra scalar readback, gated: the same sync the
                    # int(code) above already paid for covers its latency
                    self.last_rounds = int(rounds)
                    _FIXPOINT_ROUNDS.observe(self.last_rounds)
                return ofs, ofp, ofo, int(on), caps
            if code & 8:
                raise RuntimeError(
                    "device fixpoint hit the round limit before convergence"
                )
            # preserve progress: restart from the (committed) returned state,
            # doubling every capacity that overflowed (code is a bitmask)
            fs, fp, fo, n_facts = ofs, ofp, ofo, on
            caps = _Caps(
                caps.fact * (2 if code & 4 else 1),
                caps.delta * (2 if code & 2 else 1),
                caps.join * (2 if code & 1 else 1),
            )
            if (
                jax.default_backend() == "tpu"
                and caps.join > SAFE_JOIN_CAP
            ):
                # the doubled program would hit the toolchain fault the
                # entry gate exists to avoid — bail to the host path
                raise JoinCapExceeded(caps.join)
        raise RuntimeError("device fixpoint capacities failed to converge")

    def infer(self, max_attempts: int = 12, initial_caps: Optional[_Caps] = None) -> int:
        import jax.numpy as jnp

        r = self.reasoner
        s, p, o = r.facts.columns()
        n0 = len(s)
        if n0 == 0 or not self.rules:
            # every rule was statically dead (unsatisfiable ground guards)
            return 0
        caps = initial_caps if initial_caps is not None else self._caps(n0)
        with _obs_span("reasoner.fixpoint", facts=n0):
            ofs, ofp, ofo, n_out, caps = self.infer_padded(
                jnp.asarray(s),
                jnp.asarray(p),
                jnp.asarray(o),
                jnp.int32(n0),
                caps,
                max_attempts,
            )
        self.converged_caps = caps
        if n_out > n0:
            s_h = np.asarray(ofs[:n_out])
            p_h = np.asarray(ofp[:n_out])
            o_h = np.asarray(ofo[:n_out])
            r.facts.add_batch(s_h[n0:], p_h[n0:], o_h[n0:])
        _FIXPOINT_DERIVED.observe(n_out - n0)
        return n_out - n0


    def infer_chunked(
        self,
        chunk_rows: Optional[int] = None,
        join_cap: Optional[int] = None,
        delta_cap: Optional[int] = None,
        max_attempts: int = 64,
        writeback: bool = True,
    ) -> int:
        """Host-driven per-round fixpoint for inputs past the one-dispatch
        program's toolchain-safe join capacity.

        Each ROUND runs as one chunk program (:func:`_device_round_chunk`)
        per ``chunk_rows``-row slice of the delta, with the fact columns
        frozen for the round; the host merges the round's accumulator into
        the facts and feeds it back as the next delta.  More dispatches
        than the ``lax.while_loop`` path, but every program stays below
        ``SAFE_JOIN_CAP`` — this is the path that puts LUBM-1000-scale
        closures on the chip.  Agreement with the host reasoner is tested
        in ``tests/test_device_fixpoint.py``.
        """
        import jax.numpy as jnp
        from jax import lax

        r = self.reasoner
        s, p, o = r.facts.columns()
        n0 = len(s)
        if n0 == 0 or not self.rules:
            return 0
        masks = tuple(jnp.asarray(m) for m in self.bank.materialize()) or (
            jnp.zeros(1, dtype=bool),
        )
        def chunk_call(caps, *dyn):
            # NOTE: every scalar constant in the traced body must be a
            # numpy scalar (literal), not a jnp array — a concrete jnp
            # scalar created at trace time is lifted to a hoisted-constant
            # parameter on warm retraces, which the dispatch fast path
            # fails to feed once two capacity keys coexist (observed on
            # jax 0.9: "Executable expected parameter 0 of size 4...").
            from kolibrie_tpu.ops.pallas_kernels import pallas_join_enabled

            return _device_round_chunk(
                self.rules, caps, *dyn, use_pallas=pallas_join_enabled()
            )

        on_tpu = jax.default_backend() == "tpu"
        # all powers of two (user values rounded up), so chunk offsets stay
        # aligned across buffers: dynamic_slice never clamps a start index,
        # which would silently re-read earlier rows and skip tail rows
        Dc = _round_cap(chunk_rows, 8) if chunk_rows else min(
            _round_cap(n0, 1024), 1 << 19
        )
        J = join_cap or (
            SAFE_JOIN_CAP if on_tpu else _round_cap(4 * max(Dc, 1024), 1024)
        )
        D = _round_cap(
            max(delta_cap, Dc) if delta_cap else max(2 * Dc, 2048), Dc
        )
        F = _round_cap(n0 + D, 2048)
        attempts = 0

        with _enable_x64(True):

            def pad(x, cap):
                x = jnp.asarray(x, dtype=jnp.uint32)
                return jnp.concatenate(
                    [x, jnp.zeros(cap - x.shape[0], dtype=jnp.uint32)]
                )

            def grow(cols, old, new):
                return tuple(
                    jnp.concatenate([c, jnp.zeros(new - old, dtype=jnp.uint32)])
                    for c in cols
                )

            fs, fp, fo = pad(s, F), pad(p, F), pad(o, F)
            n_facts = n0
            # round-0 delta = all facts, in a chunk-aligned buffer
            dlen = _round_cap(n0, Dc)
            dels, delp, delo = pad(s, dlen), pad(p, dlen), pad(o, dlen)
            n_delta = n0

            for _round in range(10_000):
                _FIXPOINT_DELTA.observe(n_delta)
                # Readback discipline: chunks chain through DEVICE scalars
                # (n_acc, OR-ed overflow code) and the host syncs ONCE per
                # round attempt — on the axon tunnel a readback degrades
                # every later dispatch, and per-round is the true minimum a
                # host-driven loop needs (termination + chunk count).
                while True:
                    accs = jnp.zeros(D, dtype=jnp.uint32)
                    accp = jnp.zeros(D, dtype=jnp.uint32)
                    acco = jnp.zeros(D, dtype=jnp.uint32)
                    n_acc_dev = jnp.int32(0)
                    code_dev = jnp.int32(0)
                    for off in range(0, n_delta, Dc):
                        m = min(Dc, n_delta - off)
                        ds = lax.dynamic_slice(dels, (off,), (Dc,))
                        dpp = lax.dynamic_slice(delp, (off,), (Dc,))
                        doo = lax.dynamic_slice(delo, (off,), (Dc,))
                        accs, accp, acco, n_acc_dev, ovf = chunk_call(
                            _Caps(F, D, J),
                            fs,
                            fp,
                            fo,
                            jnp.int32(n_facts),
                            ds,
                            dpp,
                            doo,
                            jnp.int32(m),
                            accs,
                            accp,
                            acco,
                            n_acc_dev,
                            masks,
                        )
                        code_dev = code_dev | ovf
                    code = int(code_dev)  # the one sync point
                    n_acc = int(n_acc_dev)
                    if code == 0:
                        break
                    # overflow: retry the WHOLE round (facts are frozen per
                    # round, so a round restart is exact) with the failing
                    # capacities adjusted
                    attempts += 1
                    if attempts > max_attempts:
                        raise RuntimeError(
                            "chunked device fixpoint: capacities failed "
                            "to converge"
                        )
                    if code & 1:
                        if on_tpu and 2 * J > SAFE_JOIN_CAP:
                            # doubling J would enter the faulting regime the
                            # chunked path exists to avoid — shrink the
                            # chunk instead (fewer delta seeds per program
                            # → smaller join output at the same J)
                            if Dc <= 1024:
                                raise JoinCapExceeded(2 * J)
                            Dc //= 2
                        else:
                            J *= 2
                    if code & 2:
                        D *= 2
                if n_acc == 0:
                    break
                # merge the round's accumulator into the fact columns; the
                # accumulator's zero tail lands past n_facts+n_acc where
                # fvalid masks it (and later rounds overwrite it)
                if n_facts + D > F:
                    newF = _round_cap(n_facts + D, 2048)
                    fs, fp, fo = grow((fs, fp, fo), F, newF)
                    F = newF
                fs = lax.dynamic_update_slice(fs, accs, (n_facts,))
                fp = lax.dynamic_update_slice(fp, accp, (n_facts,))
                fo = lax.dynamic_update_slice(fo, acco, (n_facts,))
                n_facts += n_acc
                # next round's delta = this round's accumulator (D is a
                # power of two >= Dc, so it stays chunk-aligned)
                dels, delp, delo, n_delta = accs, accp, acco, n_acc
            else:
                raise RuntimeError(
                    "device fixpoint hit the round limit before convergence"
                )

            self.last_rounds = _round  # productive rounds (final is empty)
            _FIXPOINT_ROUNDS.observe(_round)
            self.converged_caps = _Caps(F, D, J)
            # device-resident result; ``writeback=False`` lets callers (and
            # benches) defer the bulk device→host transfer — on the axon
            # tunnel it would otherwise sit inside the timed window
            self._last_state = (fs, fp, fo, n_facts, n0)
            if writeback:
                return self.materialize_to_host()
            return n_facts - n0

    def materialize_to_host(self) -> int:
        """Copy facts derived by the last ``infer_chunked(writeback=False)``
        run into ``reasoner.facts``; returns the derived count."""
        fs, fp, fo, n_facts, n0 = self._last_state
        if n_facts > n0:
            s_h = np.asarray(fs[:n_facts])
            p_h = np.asarray(fp[:n_facts])
            o_h = np.asarray(fo[:n_facts])
            self.reasoner.facts.add_batch(s_h[n0:], p_h[n0:], o_h[n0:])
        return n_facts - n0


# Largest join capacity verified stable on the current axon/Mosaic
# toolchain: composed fixpoint programs with join buffers past 2^21 rows
# raise a TPU device fault at dispatch (the same ops standalone — sorts to
# 16M rows, join_indices at 4M cap, gathers — all pass, so this is a
# composition-specific toolchain issue, not a memory or algorithm bound).
# Past it the reasoner transparently uses the host semi-naive path.
SAFE_JOIN_CAP = 2_097_152


class JoinCapExceeded(RuntimeError):
    """Raised when capacity doubling would cross SAFE_JOIN_CAP on TPU."""


def infer_semi_naive_device(reasoner) -> Optional[int]:
    """Device fixpoint if the rule set lowers; ``None`` → host fallback.

    Small inputs take the one-dispatch ``lax.while_loop`` program; inputs
    whose capacities would cross the toolchain-safe join bound take the
    host-driven chunked per-round driver (``infer_chunked``), whose
    programs all stay below the bound — the device handles both regimes.
    """
    try:
        fx = DeviceFixpoint(reasoner)
    except Unsupported:
        return None
    import jax

    try:
        if (
            jax.default_backend() == "tpu"
            and fx._caps(len(reasoner.facts)).join > SAFE_JOIN_CAP
        ):
            # one-dispatch program would cross the toolchain bound — run
            # the round-per-dispatch chunked driver instead
            return fx.infer_chunked()
        try:
            return fx.infer()
        except JoinCapExceeded:
            return fx.infer_chunked()  # doubling crossed the bound mid-run
    except JoinCapExceeded:
        # even minimum-size chunk programs would need a join buffer past
        # the toolchain bound (pathological fan-out) — host fallback
        return None
