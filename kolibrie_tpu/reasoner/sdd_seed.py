"""SDD seed materialisation: build an SddProvenance tag store from SeedSpecs
(independent literals; exclusive groups via ``exactly_one`` ∧ literal), then
run provenance semi-naive.

Parity: ``datalog/src/reasoning/materialisation/sdd_seed_materialise.rs``
(:27-75) ``infer_new_facts_with_sdd_seed_specs``.
"""

from __future__ import annotations

from typing import List, Tuple

from kolibrie_tpu.core.triple import Triple
from kolibrie_tpu.reasoner.provenance_seminaive import infer_with_provenance
from kolibrie_tpu.reasoner.sdd import SddProvenance
from kolibrie_tpu.reasoner.seed_spec import ExclusiveGroupSeed, IndependentSeed
from kolibrie_tpu.reasoner.tag_store import TagStore


def infer_new_facts_with_sdd_seed_specs(
    reasoner, seed_specs: List[object], seeds_only_delta: bool = False,
    base_store=None,
) -> Tuple[TagStore, SddProvenance]:
    """Returns (tag store after closure, the SddProvenance used).

    ``seeds_only_delta``: the caller guarantees ``reasoner.facts`` is already
    closed under the (NAF-free) rules, so the first semi-naive round needs
    only the seed triples as its delta — every derivation not reachable from
    a seed already exists with a certain (⊤) tag.  The neurosymbolic trainer
    uses this to make the per-sample closure proportional to the seed's
    derivation cone instead of the whole database.

    ``base_store`` (with ``seeds_only_delta``): a store equal to
    ``reasoner.facts`` WITHOUT the seed triples, borrowed read-only as the
    first round's old-side — lets repeated calls share its cached sort
    orders instead of re-deriving them per call.

    Safety: the exactly-once derivation invariant needs old ∩ delta = ∅.
    If a seed triple ALREADY exists in the facts (e.g. a prior ML.PREDICT
    materialized it), both flags are dropped for this call and the closure
    runs with the full delta — same semantics as an unseeded-base run.
    """
    if seeds_only_delta:
        for spec in seed_specs:
            triples = (
                [spec.triple]
                if isinstance(spec, IndependentSeed)
                else [t for t, _p, _sid in spec.choices]
            )
            if any(
                reasoner.facts.contains(t.subject, t.predicate, t.object)
                for t in triples
            ):
                seeds_only_delta = False
                base_store = None
                break
    prov = SddProvenance()
    store = TagStore(prov)
    mgr = prov.manager
    for spec in seed_specs:
        if isinstance(spec, IndependentSeed):
            # seeds without an explicit id stay unregistered in seed_vars —
            # gradients are keyed by explicit seed ids only, and registering
            # by allocation order would collide with numbered seeds
            tag = (
                prov.tag_from_probability_with_id(spec.prob, spec.seed_id)
                if spec.seed_id is not None
                else prov.tag_from_probability(spec.prob)
            )
            store.set(spec.triple, tag)
            reasoner.facts.add_triple(spec.triple)
        elif isinstance(spec, ExclusiveGroupSeed):
            members = []
            for triple, p, seed_id in spec.choices:
                var = mgr.new_var(
                    w_pos=p, w_neg=1.0, kind="exclusive", group_id=spec.group_id,
                    seed_id=seed_id,
                )
                if seed_id is not None:
                    prov.seed_vars[seed_id] = var
                members.append((triple, var))
            constraint = mgr.exactly_one([v for _, v in members])
            for triple, var in members:
                tag = mgr.conjoin(constraint, mgr.literal(var, True))
                store.set(triple, tag)
                reasoner.facts.add_triple(triple)
        else:
            raise TypeError(f"unknown seed spec {spec!r}")
    initial_delta = None
    if seeds_only_delta:
        initial_delta = set()
        for spec in seed_specs:
            if isinstance(spec, IndependentSeed):
                t = spec.triple
                initial_delta.add((t.subject, t.predicate, t.object))
            else:
                for triple, _p, _sid in spec.choices:
                    initial_delta.add(
                        (triple.subject, triple.predicate, triple.object)
                    )
    tag_store = infer_with_provenance(
        reasoner,
        prov,
        store,
        initial_delta=initial_delta,
        round1_old_store=base_store if seeds_only_delta else None,
    )
    return tag_store, prov
