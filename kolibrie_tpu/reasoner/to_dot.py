"""Graphviz DOT export of a Reasoner's facts and rules.

Parity: ``datalog/src/reasoning/to_dot.rs:9-114`` — one node per distinct
subject/object ID (sorted, labelled with the decoded string), one ``shape=box``
node pair per rule (premise patterns / conclusion patterns), an edge per fact
labelled with its predicate, and a premise→conclusion edge per rule.
"""

from __future__ import annotations

from typing import List

from kolibrie_tpu.core.terms import Term, TriplePattern


def _term_to_string(term: Term, dictionary, quoted_store=None) -> str:
    if term.is_variable:
        return str(term.value)
    if term.is_quoted:
        inner: TriplePattern = term.value
        parts = [
            _term_to_string(t, dictionary, quoted_store) for t in inner.terms()
        ]
        return "<< {} {} {} >>".format(*parts)
    return dictionary.decode_term(int(term.value), quoted_store) or ""


def _patterns_to_dot(patterns: List[TriplePattern], reasoner) -> str:
    lines = []
    for pat in patterns:
        s, p, o = (
            _term_to_string(t, reasoner.dictionary, reasoner.quoted)
            for t in pat.terms()
        )
        lines.append(f"({s}, {p}, {o})")
    return "\n".join(lines)


def _escape(label: str) -> str:
    return label.replace("\\", "\\\\").replace('"', '\\"')


def to_dot(reasoner) -> str:
    """Render the knowledge graph as a DOT digraph string."""
    out = ["digraph {\n"]
    dict_ = reasoner.dictionary
    facts = list(reasoner.facts)

    node_ids = sorted({t.subject for t in facts} | {t.object for t in facts})
    for node_id in node_ids:
        label = dict_.decode_term(node_id, reasoner.quoted) or str(node_id)
        out.append(f'{node_id} [label="{_escape(label)}"]\n')

    for i, rule in enumerate(reasoner.rules):
        out.append(
            f'Rule{i}_premise [label="{_escape(_patterns_to_dot(rule.premise, reasoner))}", shape=box]\n'
        )
        out.append(
            f'Rule{i}_conclusion [label="{_escape(_patterns_to_dot(rule.conclusion, reasoner))}", shape=box]\n'
        )

    out.append("\n")

    for t in facts:
        label = dict_.decode_term(t.predicate, reasoner.quoted) or str(t.predicate)
        out.append(f'{t.subject} -> {t.object} [label="{_escape(label)}"]\n')
    for i in range(len(reasoner.rules)):
        out.append(f"Rule{i}_premise -> Rule{i}_conclusion\n")

    out.append("}")
    return "".join(out)
