"""Probabilistic Datalog reasoner: semi-naive fixpoint materialisation,
provenance semirings, SDD-based exact inference, stratified negation,
backward chaining, repairs, and cross-window streaming reasoning.

Parity: the reference's ``datalog/`` crate plus ``shared/src/{provenance,sdd,
diff_sdd,tag_store,seed_spec}.rs``.
"""

from kolibrie_tpu.reasoner.reasoner import Reasoner

__all__ = ["Reasoner"]
