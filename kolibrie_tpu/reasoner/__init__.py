"""Probabilistic Datalog reasoner: semi-naive fixpoint materialisation,
provenance semirings, SDD-based exact inference, stratified negation,
backward chaining, repairs, and cross-window streaming reasoning.

Parity: the reference's ``datalog/`` crate plus ``shared/src/{provenance,sdd,
diff_sdd,tag_store,seed_spec}.rs``.
"""

from kolibrie_tpu.reasoner.reasoner import Reasoner
from kolibrie_tpu.reasoner.hierarchy import (
    HierarchicalRule,
    ReasoningHierarchy,
    ReasoningLevel,
)
from kolibrie_tpu.reasoner.to_dot import to_dot

__all__ = [
    "Reasoner",
    "ReasoningHierarchy",
    "ReasoningLevel",
    "HierarchicalRule",
    "to_dot",
]
