"""Semiring-generic semi-naive materialisation with stratified negation.

Parity: ``datalog/src/reasoning/materialisation/provenance_semi_naive.rs`` —
delta also re-includes facts whose tags improved last round (:26-34,134-147),
per-derivation tag = ⊗ of premise tags merged with ⊕ (:163-193), zero-tag
pruning (:171), fixpoint = no new facts AND no tag change
(provenance_infer_generic.rs:94-97), seeding from ``probability_seeds``
sorted for deterministic seed IDs (:210-232), stratified NAF — positive
fixpoint then one negative pass where an absent fact contributes ``one()``
and a present fact contributes ``⊖(tag)`` (:235-389) — and the
explicit-delta entry for incremental SDS+
(``semi_naive_with_initial_tags_and_delta``, :271-294).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from kolibrie_tpu.core.rule import Rule
from kolibrie_tpu.core.triple import Triple
from kolibrie_tpu.reasoner.provenance import Provenance
from kolibrie_tpu.reasoner.strategies import (
    eval_rule_body,
    scan_pattern_cols,
    scan_pattern_store,
    table_len,
)
from kolibrie_tpu.reasoner.tag_store import TagStore


def _default_backend() -> str:
    import jax

    return jax.default_backend()

TripleKey = Tuple[int, int, int]


def seed_tag_store(reasoner, provenance: Provenance) -> TagStore:
    """Build the initial TagStore from ``probability_seeds`` (sorted for
    deterministic seed IDs; :210-232)."""
    store = TagStore(provenance)
    for seed_id, (key, prob) in enumerate(sorted(reasoner.probability_seeds.items())):
        tag = provenance.tag_from_probability_with_id(prob, seed_id)
        store.set(Triple(*key), tag)
    return store


def _positive_stratum_rules(rules: List[Rule]) -> Tuple[List[Rule], List[Rule]]:
    pos = [r for r in rules if not r.negative_premise]
    neg = [r for r in rules if r.negative_premise]
    return pos, neg


def _derivation_rows(
    reasoner, rule: Rule, table, row_count: int
) -> List[Dict[str, int]]:
    """Materialize binding rows as var->id dicts (host loop; tags are
    pointer-structures so this boundary is inherently host-side)."""
    keys = [k for k in table.keys() if not k.startswith("__")]
    cols = [table[k] for k in keys]
    return [
        {k: int(c[i]) for k, c in zip(keys, cols)} for i in range(row_count)
    ]


def _pattern_key_rows(
    table, pattern, n: int, quoted
) -> Optional[List[TripleKey]]:
    """Substitute a pattern under all binding rows at once: one key tuple
    per row (columnar — no per-row dicts).  None when a variable is unbound
    (the caller skips the pattern wholesale, as _subst would row-wise)."""
    cols = []
    for t in (pattern.subject, pattern.predicate, pattern.object):
        if t.is_variable:
            c = table.get(t.value)
            if c is None:
                return None
            cols.append(c.tolist())
        elif t.is_quoted:
            inner_rows = _pattern_key_rows(table, t.value, n, quoted)
            if inner_rows is None or quoted is None:
                return None
            cols.append([quoted.intern(*k) for k in inner_rows])
        else:
            cols.append([int(t.value)] * n)
    return list(zip(*cols))


def _subst(pattern, row: Dict[str, int], quoted=None) -> Optional[TripleKey]:
    def term_id(t) -> Optional[int]:
        if t.is_variable:
            return row.get(t.value)
        if t.is_quoted:
            if quoted is None:
                return None
            inner = [term_id(x) for x in t.value.terms()]
            if any(i is None for i in inner):
                return None
            return quoted.intern(*inner)
        return t.value

    ids = []
    for t in (pattern.subject, pattern.predicate, pattern.object):
        v = term_id(t)
        if v is None:
            return None
        ids.append(v)
    return tuple(ids)


def _premise_tag(provenance, tag_store: TagStore, key: TripleKey):
    t = tag_store.get_opt(Triple(*key))
    return t if t is not None else provenance.one()


def infer_with_provenance(
    reasoner,
    provenance: Provenance,
    tag_store: Optional[TagStore] = None,
    initial_delta: Optional[Set[TripleKey]] = None,
    round1_old_store=None,
) -> TagStore:
    """Provenance semi-naive fixpoint; returns the final TagStore.

    ``initial_delta`` (incremental SDS+ entry): restrict the first round's
    delta to exactly these facts instead of all facts.

    ``round1_old_store``: caller-provided store equal to
    ``reasoner.facts`` minus ``initial_delta`` (i.e. the delta facts must
    NOT be in it).  Borrowed read-only for the first round — its cached
    sort orders survive across calls, which is what makes the trainer's
    10k-per-epoch seeded closures O(cone) each.  Later rounds copy-on-write
    before the incremental old-store maintenance mutates it.
    """
    if tag_store is None:
        tag_store = seed_tag_store(reasoner, provenance)

    # idempotent scalar semirings (minmax/boolean/expiration) above the
    # size threshold run the whole tagged fixpoint on device (tags as an
    # f64 column, ⊕=max ⊗=min); None → host loop below.  Auto-routing is
    # TPU-only: the XLA CPU backend's sorts lose to the numpy host loop
    # (see benches/bench_device_provenance.py), so CPU callers must opt in
    # via infer_provenance_device directly.
    from kolibrie_tpu.reasoner import device_provenance

    if (
        device_provenance.supports(provenance)
        and len(reasoner.facts) >= device_provenance.AUTO_MIN_FACTS
        and _default_backend() == "tpu"
        and device_provenance.infer_provenance_device(
            reasoner, provenance, tag_store, initial_delta
        )
        is not None
    ):
        return tag_store

    pos_rules, neg_rules = _positive_stratum_rules(reasoner.rules)

    facts = reasoner.facts
    if initial_delta is not None:
        delta_keys: Set[TripleKey] = set(initial_delta)
    else:
        s, p, o = facts.columns()
        delta_keys = set(zip(s.tolist(), p.tolist(), o.tolist()))
    naf_seen: Set[Tuple] = set()  # processed NAF derivation signatures
    while True:
        delta_keys = _positive_fixpoint(
            reasoner,
            provenance,
            tag_store,
            pos_rules,
            facts,
            delta_keys,
            round1_old_store=round1_old_store,
        )
        round1_old_store = None  # only valid for the very first round
        naf_new = _negative_pass(
            reasoner, provenance, tag_store, neg_rules, facts, naf_seen
        )
        if not naf_new:
            break
        # NAF-derived facts feed back into the positive stratum
        delta_keys = naf_new
    return tag_store


def _sdd_batched_derive(
    mgr, tag_store, prem_rows, concl_rows, n: int
) -> Dict[TripleKey, object]:
    """One rule's derivations through the native SDD manager in BATCH:
    per-premise tag columns folded with one ``apply_batch`` per premise
    position (⊗ chain), zero-tag pruning as a mask, and one
    ``reduce_groups`` per conclusion pattern (⊕ per unique conclusion key,
    in row order — identical fold order to the per-row loop).

    SURVEY §7 "hard parts": the SDD boundary design — batch tags per
    derivation round between the device/columnar join side and the host
    SDD manager; replaces the per-row ctypes crossings that dominated
    structural-semiring closures (reasoner as of round 2:
    provenance_seminaive.py:190-326).
    """
    from kolibrie_tpu.reasoner.sdd import FALSE, TRUE

    tags = tag_store.tags
    tag_col = None
    for pr in prem_rows:
        col = np.fromiter(
            (tags.get(k, TRUE) for k in pr), dtype=np.int64, count=n
        )
        tag_col = (
            col if tag_col is None else mgr.apply_batch(tag_col, col, "and")
        )
    if tag_col is None:  # no premises: cannot happen (rules require ≥1)
        return {}
    keep = tag_col != FALSE  # zero-tag pruning (:171)
    acc: Dict[TripleKey, object] = {}
    if not keep.any():
        return acc
    kept_tags = tag_col[keep]
    for cr in concl_rows:
        if cr is None:
            continue
        arr = np.asarray(cr, dtype=np.uint32)[keep]
        uniq, inv = np.unique(arr, axis=0, return_inverse=True)
        red = mgr.reduce_groups(kept_tags, inv, len(uniq), "or")
        for row, tag in zip(uniq.tolist(), red.tolist()):
            ckey = tuple(row)
            prev = acc.get(ckey)
            acc[ckey] = int(tag) if prev is None else mgr.disjoin(prev, int(tag))
    return acc


def _positive_fixpoint(
    reasoner,
    provenance,
    tag_store,
    pos_rules,
    facts,
    delta_keys,
    round1_old_store=None,
) -> Set[TripleKey]:
    # old = facts \ delta, so each derivation is found exactly once
    # (non-idempotent ⊕ must not see duplicates).  Both the old-store and
    # the membership set are maintained INCREMENTALLY across rounds — a
    # per-round rebuild makes deep (recursive-rule) fixpoints quadratic.
    # Membership test for "conclusion already known".  Two regimes:
    # - small delta over a big base (the trainer's per-sample seeded
    #   closures): NO Python materialization of the fact set — membership is
    #   a binary-search ``facts.count`` probe, and the round-1 old-store is a
    #   vectorized clone + pending deletes.  Keeps per-closure cost
    #   proportional to the seed's derivation cone, not the database.
    # - otherwise (full closure): one memoized set (SHARED with the store —
    #   read-only here) plus a local overlay of this fixpoint's additions.
    small_delta = round1_old_store is not None or (
        delta_keys and len(delta_keys) * 16 < len(facts)
    )
    base_keys: Optional[Set[TripleKey]] = (
        None if small_delta else facts.triples_set()
    )
    new_keys: Set[TripleKey] = set()
    old_store = None
    prev_delta: Set[TripleKey] = set()
    prev_new: Set[TripleKey] = set()
    while delta_keys:
        arr = np.asarray(sorted(delta_keys), dtype=np.uint32)
        delta_cols = (arr[:, 0], arr[:, 1], arr[:, 2])
        # Invariant: old_store = committed facts \ current delta, updated in
        # O(|delta|) per round (a full rebuild per round makes deep
        # recursive fixpoints quadratic):
        #   ADD    prev_delta \ delta   (left the delta → becomes old; the
        #          previous round's new facts all re-enter the delta, so
        #          nothing else grows old)
        #   REMOVE (delta \ prev_new) \ prev_delta   (an OLD fact whose tag
        #          improved re-enters the delta → hide from old)
        if old_store is None:
            if round1_old_store is not None:
                # borrowed: already equals facts \ delta, orders pre-built
                old_store = round1_old_store
            elif small_delta:
                # COW clone + pending deletes beats rebuilding from a
                # Python set of every fact
                old_store = facts.clone()
                for k in delta_keys:
                    old_store.remove(*k)
            else:
                old_store = reasoner._store_from(base_keys - delta_keys)
        else:
            if old_store is round1_old_store:
                old_store = old_store.clone()  # COW before maintenance
            grown = prev_delta - delta_keys
            if grown:
                g = np.asarray(sorted(grown), dtype=np.uint32)
                old_store.add_batch(g[:, 0], g[:, 1], g[:, 2])
            for k in (delta_keys - prev_new) - prev_delta:
                old_store.remove(*k)
        prev_delta = set(delta_keys)
        next_delta: Set[TripleKey] = set()
        round_new: Set[TripleKey] = set()  # buffered until the round ends
        for rule in pos_rules:
            table = eval_rule_body(
                reasoner, rule, facts, delta=delta_cols, old_store=old_store
            )
            n = table_len(table)
            if n == 0:
                continue
            # Columnar substitution: per-premise/conclusion key rows built
            # once; the remaining per-row work is tag algebra only.
            prem_rows = [
                _pattern_key_rows(table, p, n, reasoner.quoted)
                for p in rule.premise
            ]
            if any(pr is None for pr in prem_rows):
                continue
            concl_rows = [
                _pattern_key_rows(table, c, n, reasoner.quoted)
                for c in rule.conclusion
            ]
            tags_get = tag_store.tags.get
            one = provenance.one()
            conj = provenance.conjunction
            disj = provenance.disjunction
            is_zero = provenance.is_zero
            # Pre-aggregate this round's derivations per conclusion key
            # (⊕ is associative and saturate() is the identity for every
            # semiring, so one final update_disjunction per key is exact).
            mgr = getattr(provenance, "manager", None)
            if (
                getattr(provenance, "name", "") == "sdd"
                and mgr is not None
                and hasattr(mgr, "apply_batch")
                and n >= 32
            ):
                # batched SDD round: whole derivation columns cross into the
                # native manager ONCE per premise (chained ⊗) and once per
                # conclusion (segment ⊕) instead of one ctypes call per row
                acc = _sdd_batched_derive(
                    mgr, tag_store, prem_rows, concl_rows, n
                )
            else:
                acc: Dict[TripleKey, object] = {}
                for i in range(n):
                    tag = one
                    for pr in prem_rows:
                        ptag = tags_get(pr[i])
                        if ptag is not None:
                            tag = conj(tag, ptag)
                    if is_zero(tag):
                        continue  # zero-tag pruning (:171)
                    for cr in concl_rows:
                        if cr is None:
                            continue
                        ckey = cr[i]
                        prev = acc.get(ckey)
                        acc[ckey] = tag if prev is None else disj(prev, tag)
            for ckey, tag in acc.items():
                if base_keys is None:
                    # committed facts (base + prior rounds) live in the store
                    existed = ckey in round_new or facts.count(*ckey) > 0
                else:
                    existed = (
                        ckey in base_keys
                        or ckey in new_keys
                        or ckey in round_new
                    )
                changed = tag_store.update_disjunction(Triple(*ckey), tag)
                if not existed:
                    round_new.add(ckey)
                    next_delta.add(ckey)
                elif changed:
                    # tag improved: re-include in delta (:26-34)
                    next_delta.add(ckey)
        # commit this round's facts only now, so the full-store scans within
        # the round never see mid-round additions (each derivation must be
        # found exactly once — non-idempotent ⊕ safety)
        if round_new:
            rn = np.asarray(sorted(round_new), dtype=np.uint32)
            facts.add_batch(rn[:, 0], rn[:, 1], rn[:, 2])
            new_keys |= round_new
        prev_new = round_new
        delta_keys = next_delta
    return set()


def _negative_pass(
    reasoner, provenance, tag_store, neg_rules, facts, naf_seen: Set[Tuple]
) -> Set[TripleKey]:
    """Stratified NAF pass (:235-389); returns NEWLY added fact keys so the
    caller can feed them back into the positive stratum.  Each derivation is
    processed at most once across passes (non-idempotent ⊕ safety)."""
    new_keys: Set[TripleKey] = set()
    for rule_idx, rule in enumerate(neg_rules):
        pos_only = Rule(
            premise=rule.premise,
            negative_premise=[],
            filters=rule.filters,
            conclusion=rule.conclusion,
        )
        table = eval_rule_body(reasoner, pos_only, facts, delta=None)
        n = table_len(table)
        rows = _derivation_rows(reasoner, rule, table, n)
        for row in rows:
            sig = (rule_idx, tuple(sorted(row.items())))
            if sig in naf_seen:
                continue
            naf_seen.add(sig)
            tag = provenance.one()
            for prem in rule.premise:
                key = _subst(prem, row, reasoner.quoted)
                if key is None:
                    tag = provenance.zero()
                    break
                tag = provenance.conjunction(
                    tag, _premise_tag(provenance, tag_store, key)
                )
            for neg in rule.negative_premise:
                key = _subst(neg, row, reasoner.quoted)
                if key is None or not facts.contains(*key):
                    # absent fact: contributes one()
                    continue
                neg_tag = provenance.negate(
                    _premise_tag(provenance, tag_store, key)
                )
                tag = provenance.conjunction(tag, neg_tag)
            if provenance.is_zero(tag):
                continue
            for concl in rule.conclusion:
                ckey = _subst(concl, row, reasoner.quoted)
                if ckey is None:
                    continue
                existed = facts.contains(*ckey)
                tag_store.update_disjunction(Triple(*ckey), tag)
                facts.add(*ckey)
                if not existed:
                    new_keys.add(ckey)
    return new_keys


def semi_naive_with_initial_tags_and_delta(
    reasoner,
    provenance: Provenance,
    tag_store: TagStore,
    delta: Set[TripleKey],
) -> TagStore:
    """Explicit-delta entry point for incremental SDS+ (:271-294)."""
    return infer_with_provenance(
        reasoner, provenance, tag_store, initial_delta=delta
    )
