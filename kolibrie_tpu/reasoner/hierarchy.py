"""Hierarchical (multi-level) reasoning — experimental, mirrors the reference.

Parity: ``datalog/src/reasoning_experimental.rs:17-306`` — four reasoning
levels (Base/Deductive/Abductive/MetaReasoning), each backed by its own
Reasoner; cross-level rules carry a priority and a list of dependency levels
whose combined fact sets seed the rule application; per-level certainty
scores for ``get_fact_certainty``.

Levels share one Dictionary so fact IDs are comparable across levels (the
reference uses per-level dictionaries and re-encodes strings on every call;
a shared dictionary is the columnar-store-friendly equivalent).
"""

from __future__ import annotations

import enum
import warnings
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kolibrie_tpu.core.dictionary import Dictionary
from kolibrie_tpu.core.rule import Rule
from kolibrie_tpu.core.terms import Term, TriplePattern
from kolibrie_tpu.core.triple import Triple
from kolibrie_tpu.reasoner.reasoner import Reasoner


class ReasoningLevel(enum.IntEnum):
    """reasoning_experimental.rs:18-23."""

    BASE = 0
    DEDUCTIVE = 1
    ABDUCTIVE = 2
    META_REASONING = 3


#: reasoning_experimental.rs:288-304 — certainty of the first (lowest) level
#: holding the fact; Base facts are most certain.
LEVEL_CERTAINTY = {
    ReasoningLevel.BASE: 1.0,
    ReasoningLevel.DEDUCTIVE: 0.9,
    ReasoningLevel.ABDUCTIVE: 0.6,
    ReasoningLevel.META_REASONING: 0.4,
}


@dataclass
class HierarchicalRule:
    """reasoning_experimental.rs:26-31."""

    rule: Rule
    level: ReasoningLevel
    priority: int = 0
    dependencies: List[ReasoningLevel] = field(default_factory=list)


class ReasoningHierarchy:
    """Four stacked knowledge graphs with cross-level rule propagation."""

    def __init__(self) -> None:
        self.dictionary = Dictionary()
        self.levels: Dict[ReasoningLevel, Reasoner] = {
            level: Reasoner(self.dictionary) for level in ReasoningLevel
        }
        self.cross_level_rules: List[HierarchicalRule] = []
        self.propagation_rules: List[HierarchicalRule] = []

    # ------------------------------------------------------------ build API

    def add_fact_at_level(
        self, level: ReasoningLevel, subject: str, predicate: str, object: str
    ) -> Triple:
        return self.levels[level].add_abox_triple(subject, predicate, object)

    def add_rule_at_level(
        self, level: ReasoningLevel, rule: Rule, priority: int = 0
    ) -> None:
        """Registers the rule both within the level's own reasoner and as a
        cross-level rule depending on Base (+ its own level)
        (reasoning_experimental.rs:61-80)."""
        self.levels[level].add_rule(rule)
        dependencies = [ReasoningLevel.BASE]
        if level != ReasoningLevel.BASE:
            dependencies.append(level)
        self.cross_level_rules.append(
            HierarchicalRule(rule, level, priority, dependencies)
        )

    def add_cross_level_rule(self, rule: HierarchicalRule) -> None:
        self.cross_level_rules.append(rule)

    # ------------------------------------------------------------ inference

    def hierarchical_inference(self) -> Dict[ReasoningLevel, List[Triple]]:
        """Per level in dependency order: in-level semi-naive closure, then
        cross-level rules targeting that level over the union of their
        dependency levels' facts (reasoning_experimental.rs:86-115)."""
        all_inferred: Dict[ReasoningLevel, List[Triple]] = {}
        for level in ReasoningLevel:
            kg = self.levels[level]
            before = kg.facts.triples_set()
            kg.infer_new_facts_semi_naive()
            inferred = [
                Triple(*t) for t in kg.facts.triples_set() - before
            ]
            inferred.extend(self._apply_cross_level_rules(level))
            all_inferred[level] = inferred
        return all_inferred

    def _apply_cross_level_rules(self, target: ReasoningLevel) -> List[Triple]:
        new_facts: List[Triple] = []
        applicable = sorted(
            (r for r in self.cross_level_rules if r.level == target),
            key=lambda r: -r.priority,
        )
        target_kg = self.levels[target]
        for hrule in applicable:
            available: List[Triple] = []
            for dep in hrule.dependencies:
                available.extend(self.levels[dep].facts)
            for fact in self._apply_rule_to_facts(hrule.rule, available):
                if not target_kg.facts.contains(*fact):
                    target_kg.insert_ground_triple(fact)
                    new_facts.append(fact)
        return new_facts

    def _apply_rule_to_facts(
        self, rule: Rule, facts: List[Triple]
    ) -> List[Triple]:
        """Direct 1- and 2-premise rule application over an explicit fact list
        (reasoning_experimental.rs:161-208), honoring NAF premises and
        filters against the same fact set."""
        out: List[Triple] = []
        seen = set()
        fact_set = {tuple(f) for f in facts}

        def emit(bindings: Dict[str, int]) -> None:
            if not self._guards_pass(rule, bindings, fact_set):
                return
            for conclusion in rule.conclusion:
                t = _construct(conclusion, bindings)
                if t is not None and tuple(t) not in seen:
                    seen.add(tuple(t))
                    out.append(t)

        if len(rule.premise) == 1:
            for fact in facts:
                bindings: Dict[str, int] = {}
                if _match_pattern(rule.premise[0], fact, bindings):
                    emit(bindings)
        elif len(rule.premise) == 2:
            for i, f1 in enumerate(facts):
                b1: Dict[str, int] = {}
                if not _match_pattern(rule.premise[0], f1, b1):
                    continue
                for j, f2 in enumerate(facts):
                    if i == j:
                        continue
                    bindings = dict(b1)
                    if _match_pattern(rule.premise[1], f2, bindings):
                        emit(bindings)
        else:
            warnings.warn(
                "cross-level rule application supports 1- and 2-premise "
                f"rules only; skipping rule with {len(rule.premise)} premises"
            )
        return out

    def _guards_pass(
        self, rule: Rule, bindings: Dict[str, int], fact_set
    ) -> bool:
        for neg in rule.negative_premise:
            t = _construct(neg, bindings)
            if t is not None and tuple(t) in fact_set:
                return False
        for f in rule.filters:
            if f.variable not in bindings:
                return False
            if not f.evaluate(bindings[f.variable], self.dictionary.decode):
                return False
        return True

    # ------------------------------------------------------------ query API

    def query_hierarchy(
        self,
        level: Optional[ReasoningLevel] = None,
        subject: Optional[str] = None,
        predicate: Optional[str] = None,
        object: Optional[str] = None,
    ) -> List[Tuple[ReasoningLevel, Triple]]:
        levels = [level] if level is not None else list(self.levels)
        results: List[Tuple[ReasoningLevel, Triple]] = []
        for lv in levels:
            for t in self.levels[lv].query_abox(subject, predicate, object):
                results.append((lv, t))
        return results

    def get_fact_certainty(self, fact: Triple) -> float:
        for level in ReasoningLevel:
            if self.levels[level].facts.contains(*fact):
                return LEVEL_CERTAINTY[level]
        return 0.0


def _match_pattern(
    pattern: TriplePattern, fact: Triple, bindings: Dict[str, int]
) -> bool:
    for term, fact_id in zip(pattern.terms(), fact):
        if term.is_variable:
            bound = bindings.get(term.value)
            if bound is None:
                bindings[term.value] = int(fact_id)
            elif bound != int(fact_id):
                return False
        elif term.is_constant:
            if int(term.value) != int(fact_id):
                return False
        else:  # quoted-triple premise terms unsupported here, as in the ref
            return False
    return True


def _construct(
    pattern: TriplePattern, bindings: Dict[str, int]
) -> Optional[Triple]:
    ids = []
    for term in pattern.terms():
        if term.is_variable:
            if term.value not in bindings:
                return None
            ids.append(bindings[term.value])
        elif term.is_constant:
            ids.append(int(term.value))
        else:
            return None
    return Triple(*ids)
