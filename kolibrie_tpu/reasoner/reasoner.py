"""The Reasoner (knowledge graph): facts + rules + constraints + seeds.

Parity: ``datalog/src/reasoning.rs:33-186`` — ``add_abox_triple`` /
``add_tagged_triple`` / ``query_abox`` (:70-129), constraint checking and
repair computation (maximal consistent subsets, :137-186),
``materialize_tags_as_rdf_star`` (:84-93) — plus the inference entry points
from ``datalog/src/reasoning/materialisation/``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from kolibrie_tpu.core.dictionary import Dictionary
from kolibrie_tpu.core.quoted import QuotedTripleStore
from kolibrie_tpu.core.rule import Rule, check_rule_safety
from kolibrie_tpu.core.rule_index import RuleIndex
from kolibrie_tpu.core.store import ColumnarTripleStore
from kolibrie_tpu.core.terms import Term, TriplePattern
from kolibrie_tpu.core.triple import Triple


class Reasoner:
    """Knowledge graph with forward/backward inference."""

    def __init__(self, dictionary: Optional[Dictionary] = None) -> None:
        self.dictionary = dictionary if dictionary is not None else Dictionary()
        self.quoted = QuotedTripleStore()
        self.facts = ColumnarTripleStore()
        self.rules: List[Rule] = []
        self.rule_index = RuleIndex()
        self.constraints: List[Rule] = []
        self.probability_seeds: Dict[Tuple[int, int, int], float] = {}
        self._numeric_cache: Dict[int, Optional[float]] = {}

    # ------------------------------------------------------------ fact API

    def add_abox_triple(self, subject: str, predicate: str, object: str) -> Triple:
        t = Triple(
            self.dictionary.encode(subject),
            self.dictionary.encode(predicate),
            self.dictionary.encode(object),
        )
        self.facts.add_triple(t)
        return t

    def add_tagged_triple(
        self, subject: str, predicate: str, object: str, probability: float
    ) -> Triple:
        """Fact with an input probability, stored for provenance seeding
        (reasoning.rs:70)."""
        t = self.add_abox_triple(subject, predicate, object)
        self.probability_seeds[tuple(t)] = probability
        return t

    def insert_ground_triple(self, t: Triple) -> None:
        self.facts.add_triple(t)

    def query_abox(
        self,
        subject: Optional[str] = None,
        predicate: Optional[str] = None,
        object: Optional[str] = None,
    ) -> List[Triple]:
        def enc(x):
            if x is None:
                return None
            return self.dictionary.lookup(x)

        ids = [enc(subject), enc(predicate), enc(object)]
        if any(x is None and orig is not None for x, orig in zip(ids, (subject, predicate, object))):
            return []
        s, p, o = self.facts.match(s=ids[0], p=ids[1], o=ids[2])
        return [Triple(int(a), int(b), int(c)) for a, b, c in zip(s, p, o)]

    def decode_triple(self, t: Triple) -> Tuple[str, str, str]:
        d = self.dictionary
        return (
            d.decode_term(t.subject, self.quoted) or "",
            d.decode_term(t.predicate, self.quoted) or "",
            d.decode_term(t.object, self.quoted) or "",
        )

    # ------------------------------------------------------------ rule API

    def add_rule(self, rule: Rule) -> None:
        """Register without safety check (legacy API)."""
        self.rules.append(rule)
        self.rule_index.add_rule(rule)

    def try_add_rule(self, rule: Rule) -> bool:
        """Safety-checked registration (rules.rs:182-205)."""
        if not check_rule_safety(rule):
            return False
        self.add_rule(rule)
        return True

    def add_constraint(self, constraint: Rule) -> None:
        self.constraints.append(constraint)

    def rule_from_strings(
        self,
        premises: List[Tuple[str, str, str]],
        conclusions: List[Tuple[str, str, str]],
        negative: Optional[List[Tuple[str, str, str]]] = None,
        filters: Optional[list] = None,
    ) -> Rule:
        """Convenience: build an ID-space rule from string patterns where
        terms starting with '?' are variables."""

        def term(x: str) -> Term:
            if x.startswith("?"):
                return Term.variable(x[1:])
            return Term.constant(self.dictionary.encode(x))

        def pat(t):
            return TriplePattern(term(t[0]), term(t[1]), term(t[2]))

        return Rule(
            premise=[pat(p) for p in premises],
            negative_premise=[pat(p) for p in (negative or [])],
            filters=list(filters or []),
            conclusion=[pat(c) for c in conclusions],
        )

    # ----------------------------------------------------------- inference

    def infer_new_facts(self) -> int:
        """Naive fixpoint (my_naive.rs:79-82 alias)."""
        from kolibrie_tpu.reasoner.strategies import infer_naive

        return infer_naive(self)

    def infer_new_facts_semi_naive(self) -> int:
        from kolibrie_tpu.reasoner.strategies import infer_semi_naive

        return infer_semi_naive(self)

    # facts below this size run the host path even in "auto" mode — a device
    # dispatch + compile outweighs a small numpy fixpoint
    _DEVICE_AUTO_MIN_FACTS = 50_000

    def infer_new_facts_semi_naive_parallel(self) -> int:
        """The vectorized/batched strategy — the rebuild's analogue of the
        rayon-parallel path (semi_naive_parallel.rs).  Above a size
        threshold the whole fixpoint runs as one device program
        (:mod:`kolibrie_tpu.reasoner.device_fixpoint`); rules the device
        path can't express fall back to the host strategy."""
        if len(self.facts) >= self._DEVICE_AUTO_MIN_FACTS:
            derived = self.infer_new_facts_device()
            if derived is not None:
                return derived
        from kolibrie_tpu.reasoner.strategies import infer_semi_naive

        return infer_semi_naive(self)

    def infer_new_facts_device(self) -> Optional[int]:
        """On-device semi-naive fixpoint (one XLA dispatch for the whole
        closure); ``None`` if the rule set can't be lowered."""
        from kolibrie_tpu.reasoner.device_fixpoint import infer_semi_naive_device

        return infer_semi_naive_device(self)

    def infer_new_facts_with_repairs(self) -> int:
        from kolibrie_tpu.reasoner.repairs import infer_semi_naive_with_repairs

        return infer_semi_naive_with_repairs(self)

    def infer_new_facts_with_provenance(self, provenance, tag_store=None):
        from kolibrie_tpu.reasoner.provenance_seminaive import (
            infer_with_provenance,
        )

        return infer_with_provenance(self, provenance, tag_store)

    def backward_chaining(self, pattern: TriplePattern, max_depth: int = 10):
        from kolibrie_tpu.reasoner.backward import backward_chaining

        return backward_chaining(self, pattern, max_depth)

    # ---------------------------------------------------------- constraints

    def violates_constraints(self, facts: Optional[Set[Tuple[int, int, int]]] = None) -> bool:
        from kolibrie_tpu.reasoner.strategies import rule_body_matches

        store = self._store_from(facts) if facts is not None else self.facts
        for c in self.constraints:
            if rule_body_matches(self, c, store):
                return True
        return False

    def _store_from(self, facts: Set[Tuple[int, int, int]]) -> ColumnarTripleStore:
        st = ColumnarTripleStore()
        if facts:
            arr = np.asarray(sorted(facts), dtype=np.uint32)
            st.add_batch(arr[:, 0], arr[:, 1], arr[:, 2])
        return st

    def compute_repairs(self) -> List[Set[Tuple[int, int, int]]]:
        """Maximal consistent subsets (reasoning.rs:137-186): BFS over fact
        removals, keeping subset-maximal consistent sets."""
        base = self.facts.triples_set()
        repairs: List[Set[Tuple[int, int, int]]] = []
        queue = [frozenset(base)]
        seen: Set[frozenset] = set()
        while queue:
            current = queue.pop()
            if current in seen:
                continue
            seen.add(current)
            if not self.violates_constraints(set(current)):
                if not any(r > set(current) for r in repairs):
                    repairs = [r for r in repairs if not (set(current) > r)]
                    repairs.append(set(current))
            else:
                for fact in current:
                    queue.append(current - {fact})
        return repairs

    def query_with_repairs(
        self,
        subject: Optional[str] = None,
        predicate: Optional[str] = None,
        object: Optional[str] = None,
    ) -> List[Triple]:
        """IAR semantics: answers present in every repair (repairs.rs:10-43)."""
        repairs = self.compute_repairs()
        if not repairs:
            return []
        answers = self.query_abox(subject, predicate, object)
        out = []
        for t in answers:
            if all(tuple(t) in r for r in repairs):
                out.append(t)
        return out

    # ------------------------------------------------------------- tag I/O

    def materialize_tags_as_rdf_star(self, tag_store, db=None) -> int:
        """Insert ``<< s p o >> prob:value "p"`` facts (reasoning.rs:84-93)."""

        class _Shim:
            pass

        shim = _Shim()
        shim.dictionary = self.dictionary
        shim.quoted = self.quoted
        triples = tag_store.encode_as_rdf_star(db or shim)
        for t in triples:
            self.facts.add_triple(t)
        return len(triples)

    # --------------------------------------------------------------- misc

    def numeric_value(self, term_id: int) -> Optional[float]:
        """Literal numeric value of a term (cached) for rule filters."""
        if term_id in self._numeric_cache:
            return self._numeric_cache[term_id]
        s = self.dictionary.decode(term_id)
        val: Optional[float] = None
        if s is not None:
            text = s
            if text.startswith('"'):
                end = text.find('"', 1)
                if end > 0:
                    text = text[1:end]
            try:
                val = float(text)
            except ValueError:
                val = None
        self._numeric_cache[term_id] = val
        return val

    def clone(self) -> "Reasoner":
        r = Reasoner(self.dictionary.clone())
        r.quoted = self.quoted.clone()
        r.facts = self.facts.clone()
        r.rules = list(self.rules)
        for rule in r.rules:
            r.rule_index.add_rule(rule)
        r.constraints = list(self.constraints)
        r.probability_seeds = dict(self.probability_seeds)
        return r

    def __len__(self) -> int:
        return len(self.facts)
