"""Provenance semirings: how derived facts combine evidence.

Parity: ``shared/src/provenance.rs`` — the ``Provenance`` trait (:18-59) and
its six implementations: MinMaxProbability (:69-104), AddMultProbability
(:111-146), BooleanProvenance (:153-188), TopKProofs (:203-320),
DnfWmcProvenance (:336-456, alias WmcProvenance), ExpirationProvenance
(:460-479).

TPU note: the four scalar semirings (MinMax, AddMult, Boolean, Expiration)
have f64/u64 tags and vectorize onto the VPU as plain columns (the
provenance semi-naive strategy batches them); TopK/DNF/SDD tags are
set/pointer structures and stay host-side.
"""

from __future__ import annotations

import itertools
from typing import FrozenSet, List, Optional, Set, Tuple


class Provenance:
    """Semiring interface.  Tags are immutable values; operations return new
    tags.  ``saturate``/``is_saturated`` short-circuit fixpoints for
    absorbing tags (e.g. probability 1.0)."""

    name = "abstract"

    def zero(self):
        raise NotImplementedError

    def one(self):
        raise NotImplementedError

    def disjunction(self, a, b):  # ⊕
        raise NotImplementedError

    def conjunction(self, a, b):  # ⊗
        raise NotImplementedError

    def negate(self, a):  # ⊖ (NAF)
        raise NotImplementedError

    def saturate(self, a):
        return a

    def is_saturated(self, a) -> bool:
        return False

    def tag_from_probability(self, p: float):
        raise NotImplementedError

    def tag_from_probability_with_id(self, p: float, seed_id: int):
        return self.tag_from_probability(p)

    def recover_probability(self, tag) -> float:
        raise NotImplementedError

    def tag_eq(self, a, b) -> bool:
        return a == b

    def is_zero(self, tag) -> bool:
        return self.tag_eq(tag, self.zero())


class MinMaxProbability(Provenance):
    """Fuzzy / possibilistic: ⊕ = max, ⊗ = min, ⊖ = 1 - p."""

    name = "minmax"

    def zero(self):
        return 0.0

    def one(self):
        return 1.0

    def disjunction(self, a, b):
        return max(a, b)

    def conjunction(self, a, b):
        return min(a, b)

    def negate(self, a):
        return 1.0 - a

    def is_saturated(self, a):
        return a >= 1.0

    def tag_from_probability(self, p):
        return float(p)

    def recover_probability(self, tag):
        return float(tag)


class AddMultProbability(Provenance):
    """Independence assumption: ⊗ = product, ⊕ = noisy-OR (a+b-ab)."""

    name = "addmult"

    def zero(self):
        return 0.0

    def one(self):
        return 1.0

    def disjunction(self, a, b):
        return a + b - a * b

    def conjunction(self, a, b):
        return a * b

    def negate(self, a):
        return 1.0 - a

    def is_saturated(self, a):
        return a >= 1.0

    def tag_from_probability(self, p):
        return float(p)

    def recover_probability(self, tag):
        return float(tag)

    def tag_eq(self, a, b):
        return abs(a - b) < 1e-12


class BooleanProvenance(Provenance):
    """Classical two-valued logic."""

    name = "boolean"

    def zero(self):
        return False

    def one(self):
        return True

    def disjunction(self, a, b):
        return a or b

    def conjunction(self, a, b):
        return a and b

    def negate(self, a):
        return not a

    def is_saturated(self, a):
        return a is True

    def tag_from_probability(self, p):
        return p > 0.0

    def recover_probability(self, tag):
        return 1.0 if tag else 0.0


class ExpirationProvenance(Provenance):
    """Tags are expiry timestamps: ⊕ = max (latest evidence wins), ⊗ = min
    (a derivation lives as long as its shortest-lived premise).  Powers
    cross-window incremental SDS+ (provenance.rs:460-479)."""

    name = "expiration"

    NEVER = 0  # zero: already expired
    FOREVER = 0xFFFF_FFFF_FFFF_FFFF  # one: static facts

    def zero(self):
        return ExpirationProvenance.NEVER

    def one(self):
        return ExpirationProvenance.FOREVER

    def disjunction(self, a, b):
        return max(a, b)

    def conjunction(self, a, b):
        return min(a, b)

    def negate(self, a):
        return ExpirationProvenance.FOREVER if a == ExpirationProvenance.NEVER else ExpirationProvenance.NEVER

    def is_saturated(self, a):
        return a == ExpirationProvenance.FOREVER

    def tag_from_probability(self, p):
        return ExpirationProvenance.FOREVER if p > 0 else ExpirationProvenance.NEVER

    def recover_probability(self, tag):
        return 1.0 if tag > 0 else 0.0


# --------------------------------------------------------------------------
# Proof-set semirings
# --------------------------------------------------------------------------

# A literal: (seed_id, polarity).  A proof (monomial): frozenset of literals.
Literal = Tuple[int, bool]
Proof = FrozenSet[Literal]


class _SeedWeighted:
    """Shared helper: seed probability registry for WMC over proof sets."""

    def __init__(self):
        self.seed_probs: dict = {}
        self._next_seed = 0

    def _alloc_seed(self, p: float, seed_id: Optional[int] = None) -> int:
        if seed_id is None:
            seed_id = self._next_seed
        self._next_seed = max(self._next_seed, seed_id + 1)
        self.seed_probs[seed_id] = p
        return seed_id


class TopKProofs(Provenance, _SeedWeighted):
    """Keep the k best proofs (by product probability); WMC by
    inclusion–exclusion over subsets of the kept proofs (k ≤ 63, ≤ 2^m
    subsets; provenance.rs:203-320).

    Tag = frozenset of proofs (each a frozenset of (seed_id, polarity)).
    """

    name = "topk"

    def __init__(self, k: int = 8):
        Provenance.__init__(self)
        _SeedWeighted.__init__(self)
        self.k = min(k, 63)

    def zero(self):
        return frozenset()

    def one(self):
        return frozenset([frozenset()])

    def _proof_prob(self, proof: Proof) -> float:
        p = 1.0
        for sid, pos in proof:
            sp = self.seed_probs.get(sid, 1.0)
            p *= sp if pos else (1.0 - sp)
        return p

    def _trim(self, proofs: Set[Proof]) -> FrozenSet[Proof]:
        # subsumption pruning: drop proofs that are supersets of another
        kept = [
            pr
            for pr in proofs
            if not any(other < pr for other in proofs)
        ]
        kept.sort(key=self._proof_prob, reverse=True)
        return frozenset(kept[: self.k])

    def disjunction(self, a, b):
        return self._trim(set(a) | set(b))

    def conjunction(self, a, b):
        out: Set[Proof] = set()
        for pa in a:
            for pb in b:
                merged = pa | pb
                # contradiction pruning: x and ¬x in one monomial
                seeds = {}
                contradict = False
                for sid, pos in merged:
                    if seeds.setdefault(sid, pos) != pos:
                        contradict = True
                        break
                if not contradict:
                    out.add(merged)
        return self._trim(out)

    def negate(self, a):
        # De Morgan over the kept proofs (bounded by k after each step)
        result = self.one()
        for proof in a:
            if not proof:
                return self.zero()
            alt = frozenset(frozenset([(sid, not pos)]) for sid, pos in proof)
            result = self.conjunction(result, self._trim(set(alt)))
        return result

    def tag_from_probability(self, p):
        sid = self._alloc_seed(p)
        return frozenset([frozenset([(sid, True)])])

    def tag_from_probability_with_id(self, p, seed_id):
        sid = self._alloc_seed(p, seed_id)
        return frozenset([frozenset([(sid, True)])])

    def recover_probability(self, tag) -> float:
        """Inclusion–exclusion over subsets of kept proofs (exact for the
        kept set).  P(∪ proofs) = Σ_{∅≠S} (-1)^{|S|+1} P(∧ S)."""
        proofs = list(tag)
        m = len(proofs)
        if m == 0:
            return 0.0
        total = 0.0
        for r in range(1, m + 1):
            for combo in itertools.combinations(range(m), r):
                merged: dict = {}
                contradict = False
                for i in combo:
                    for sid, pos in proofs[i]:
                        if merged.setdefault(sid, pos) != pos:
                            contradict = True
                            break
                    if contradict:
                        break
                if contradict:
                    continue
                p = 1.0
                for sid, pos in merged.items():
                    sp = self.seed_probs.get(sid, 1.0)
                    p *= sp if pos else (1.0 - sp)
                total += p if r % 2 == 1 else -p
        return min(max(total, 0.0), 1.0)


class DnfWmcProvenance(TopKProofs):
    """Exact DNF provenance with Shannon-expansion weighted model counting
    (provenance.rs:336-456; alias ``WmcProvenance``).  Same proof-set tag
    representation as TopK but untrimmed, with exact WMC."""

    name = "wmc"

    def __init__(self):
        super().__init__(k=10**9)
        self.k = 10**9
        self._wmc_memo: dict = {}

    def _trim(self, proofs: Set[Proof]) -> FrozenSet[Proof]:
        kept = [pr for pr in proofs if not any(o < pr for o in proofs)]
        return frozenset(kept)

    def recover_probability(self, tag) -> float:
        proofs = frozenset(tag)
        return self._wmc(proofs)

    def _wmc(self, proofs: FrozenSet[Proof]) -> float:
        """Shannon expansion on the most frequent variable, with memoization
        and subsumption/contradiction pruning."""
        if not proofs:
            return 0.0
        if frozenset() in proofs:
            return 1.0
        memo = self._wmc_memo.get(proofs)
        if memo is not None:
            return memo
        counts: dict = {}
        for pr in proofs:
            for sid, _pos in pr:
                counts[sid] = counts.get(sid, 0) + 1
        var = max(counts, key=lambda s: counts[s])
        p = self.seed_probs.get(var, 1.0)
        pos_branch: Set[Proof] = set()
        neg_branch: Set[Proof] = set()
        for pr in proofs:
            lits = dict(pr)
            if var in lits:
                rest = frozenset((s, b) for s, b in pr if s != var)
                if lits[var]:
                    pos_branch.add(rest)
                else:
                    neg_branch.add(rest)
            else:
                pos_branch.add(pr)
                neg_branch.add(pr)
        val = p * self._wmc(self._trim(pos_branch)) + (1 - p) * self._wmc(
            self._trim(neg_branch)
        )
        self._wmc_memo[frozenset(proofs)] = val
        return val

    def negate(self, a):
        """De Morgan: ¬(∨ monomials) = ∧ ¬monomial — expand to DNF."""
        result = self.one()
        for proof in a:
            if not proof:
                return self.zero()
            alt = frozenset(frozenset([(sid, not pos)]) for sid, pos in proof)
            result = self.conjunction(result, alt)
        return result


WmcProvenance = DnfWmcProvenance


def make_provenance(name: str, k: int = 8) -> Provenance:
    """Factory keyed by PROB combination names (post-normalization)."""
    if name == "minmax":
        return MinMaxProbability()
    if name == "addmult":
        return AddMultProbability()
    if name == "boolean":
        return BooleanProvenance()
    if name == "expiration":
        return ExpirationProvenance()
    if name == "topk":
        return TopKProofs(k)
    if name in ("wmc", "dnf"):
        return DnfWmcProvenance()
    if name == "sdd":
        from kolibrie_tpu.reasoner.sdd import SddProvenance

        return SddProvenance()
    raise ValueError(f"unknown provenance semiring {name!r}")
