"""Device (TPU) provenance semi-naive fixpoint for the scalar semirings.

The host provenance loop (:mod:`kolibrie_tpu.reasoner.provenance_seminaive`)
runs per-derivation tag algebra in Python.  For the three IDEMPOTENT scalar
semirings — MinMax (fuzzy), Boolean, Expiration (the cross-window SDS+
workhorse) — the whole algebra collapses onto one device form: tags are an
f64 column, ⊗ (conjunction over a derivation's premises) is ``min`` and
⊕ (disjunction over derivations of the same fact) is ``max``:

- minmax:     tags in [0,1] verbatim,     zero 0.0, one 1.0
- boolean:    False/True → 0.0/1.0,       zero 0.0, one 1.0
- expiration: expiry timestamps → f64 (exact below 2^53; FOREVER → +inf),
              zero 0.0 (expired), one +inf (static)

Because ⊕ is idempotent, duplicate discoveries of the same derivation are
harmless — the per-seed delta expansion (every premise position seeded from
the delta, remaining positions joined against ALL facts) needs no old/delta
store split.

The NON-idempotent AddMult semiring (⊕ = noisy-OR a+b−ab, ⊗ = product)
runs a separate round program (:func:`_prov_round_addmult`) with
exactly-once derivation accounting: old/delta premise decomposition, the
delta carried as fact-row indices, and per-group ⊕ as a segment noisy-OR
in log space.  Only the structural semirings (SDD/TopK/DNF), whose tags
are pointer-shaped proof objects, stay host-side.

A round is one XLA program: delta-seeded premise joins with tag ``min``
carried through the join chain, filter masks, conclusion instantiation,
4-key sort so each (s,p,o) group's first row carries its ``max`` tag,
match-against-facts index lookup, fact append + in-place tag improvement,
and the next delta = new facts ∪ tag-improved facts.  The host drives
rounds (one scalar sync per round) and doubles capacities on overflow, the
same protocol as :meth:`DeviceFixpoint.infer_chunked`.

Parity: ``datalog/.../provenance_semi_naive.rs:26-34,134-197`` (delta
re-inclusion of improved tags, per-derivation ⊗, ⊕ merge, zero-pruning) —
redesigned as whole-column device programs.  Agreement with the host path
is tested in ``tests/test_device_provenance.py``.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Set, Tuple

import jax
from kolibrie_tpu.ops.jax_compat import enable_x64 as _enable_x64
import numpy as np

from kolibrie_tpu.ops import round_cap as _round_cap
from kolibrie_tpu.reasoner.device_fixpoint import (
    Unsupported,
    _Caps,
    _eval_filters,
    _pack,
    _scan_premise,
    lower_rules,
)
__all__ = ["supports", "infer_provenance_device", "AUTO_MIN_FACTS"]

# below this many facts the host loop wins (device dispatch + compile cost)
AUTO_MIN_FACTS = 20_000

_IDEMPOTENT = ("minmax", "boolean", "expiration")

# addmult (noisy-OR/product) is NON-idempotent: it runs a separate round
# program with exactly-once derivation accounting (see _prov_round_addmult)
_DEVICE_SEMIRINGS = _IDEMPOTENT + ("addmult",)

_EXP_FOREVER = 0xFFFF_FFFF_FFFF_FFFF

# host TagStore parity: AddMultProbability.tag_eq treats |Δ| < 1e-12 as
# "unchanged", which is also what terminates cyclic noisy-OR fixpoints
_ADDMULT_TAG_EQ = 1e-12


def supports(provenance) -> bool:
    return getattr(provenance, "name", None) in _DEVICE_SEMIRINGS


def supports_idempotent(provenance) -> bool:
    """True only for the scalar-IDEMPOTENT semirings (min/max tag algebra).
    The distributed tagged round hardwires ⊗=min/⊕=max with no exactly-once
    accounting, so it must gate on THIS predicate, not :func:`supports`."""
    return getattr(provenance, "name", None) in _IDEMPOTENT


def _addmult_order_sensitive(rules) -> bool:
    """True when within-round tag updates could be VISIBLE to a later rule,
    making the non-idempotent fixpoint depend on rule evaluation order.

    The host loop (reference parity: ``provenance_semi_naive.rs:163-193``
    reads ``tag_store.get_tag`` live) lets rule j read a tag that rule i<j
    improved in the same round; the device round reads a round-start
    snapshot.  For idempotent ⊕ both converge to the same fixpoint; for
    addmult the accumulated noisy-OR values genuinely differ.  The device
    path therefore only takes rule sets where rule i's conclusion predicates
    never feed rule j>i's premises — then no mid-round improvement can be
    observed and snapshot ≡ live.  (A rule's OWN conclusions are safe: the
    host pre-aggregates per rule and writes after it.)  Variable predicates
    count as wildcards."""

    def preds(terms):
        out = set()
        for t in terms:
            p = t.predicate
            out.add(None if p.is_variable else int(p.value))
        return out

    for i, ri in enumerate(rules):
        concl = preds(ri.conclusion)
        for rj in rules[i + 1:]:
            prem = preds(rj.premise)
            if None in concl or None in prem or (concl & prem):
                return True
    return False


def _encode_tags(provenance, tags) -> np.ndarray:
    name = provenance.name
    if name == "boolean":
        return np.asarray([1.0 if t else 0.0 for t in tags], dtype=np.float64)
    if name == "expiration":
        return np.asarray(
            [np.inf if t >= _EXP_FOREVER else float(t) for t in tags],
            dtype=np.float64,
        )
    return np.asarray(tags, dtype=np.float64)


def _decode_tags(provenance, vals: np.ndarray) -> list:
    """Vectorized inverse of :func:`_encode_tags` (shared by the single-chip
    and distributed write-backs)."""
    name = provenance.name
    if name == "boolean":
        return (vals > 0.5).tolist()
    if name == "expiration":
        return [
            _EXP_FOREVER if np.isinf(v) else int(round(v))
            for v in vals.tolist()
        ]
    return vals.tolist()


def _seed_tag_arrays(provenance, tag_store, keys) -> Tuple[np.ndarray, float]:
    """(tags0, one_enc) for a fact-key list: NaN = "no explicit TagStore
    entry" (premise reads see one(); the first derivation overwrites —
    update_disjunction parity).  Shared by both device drivers."""
    tget = tag_store.tags.get  # keys are plain (s, p, o) tuples
    host_tags = [tget(k) for k in keys]
    one = provenance.one()
    tags0 = np.where(
        [t is None for t in host_tags],
        np.nan,
        _encode_tags(
            provenance, [one if t is None else t for t in host_tags]
        ),
    )
    return tags0, float(_encode_tags(provenance, [one])[0])


def _guard_tag_array(rules, provenance, tag_store) -> np.ndarray:
    """Per-rule encoded ⊗ of the rule's ground-guard tags (one() when the
    rule has no guards).  Guards are non-derivable by construction
    (lower_rules), so these values are CONSTANT through the closure —
    one dynamic operand, no recompile per tag value."""
    out = []
    for r in rules:
        t = provenance.one()
        for g in r.guards:
            gt = tag_store.tags.get(tuple(g.consts))
            if gt is not None:
                t = provenance.conjunction(t, gt)
        out.append(t)
    return _encode_tags(provenance, out) if out else np.zeros(0, np.float64)


# ---------------------------------------------------------------------------
# Jitted round
# ---------------------------------------------------------------------------


def _join_keys(table, ptable, kv, valid, pm):
    """Packed u64 join keys for a premise-join step: 1-2 shared variables
    pack exactly; 3+ ride the union dense-rank composition (the same
    ``pack_key_multi`` path as the untagged fixpoint — a plain ``_pack``
    would silently drop the third key column)."""
    from kolibrie_tpu.ops.device_join import _LPAD, _RPAD, pack_key_multi

    if len(kv) > 2:
        return pack_key_multi(
            [table[v] for v in kv], [ptable[v] for v in kv], valid, pm
        )
    return (
        _pack([table[v] for v in kv], valid, _LPAD),
        _pack([ptable[v] for v in kv], pm, _RPAD),
    )


@partial(jax.jit, static_argnames=("rules", "caps"))
def _prov_round(
    rules: tuple,
    caps: _Caps,
    fs,
    fp,
    fo,
    ftag,
    n_facts,
    ds,
    dp,
    do,
    dtag,
    n_delta,
    one_enc,
    masks,
    gtags,
):
    """One tagged semi-naive round.  Returns the updated fact columns/tags,
    the next delta (new ∪ changed facts, with their stored tags), the count
    of delta entries, and an overflow bitmask (bit0 join, bit1 delta cap,
    bit2 fact cap).  An overflowing round does not commit.

    Tag-store parity: ``ftag`` mirrors the host TagStore exactly — NaN
    means "no explicit entry" (premise reads see ``one_enc``), and a fact's
    FIRST derivation overwrites (``update_disjunction`` inserts the new tag
    when no entry exists, tag_store.py:47-49) while later derivations
    ⊕-merge with ``max``.  Delta tags (``dtag``) are effective values,
    never NaN."""
    import jax.numpy as jnp
    from jax import lax

    from kolibrie_tpu.ops.device_join import _LPAD, _RPAD, join_indices, pack2

    F, D, J = caps.fact, caps.delta, caps.join
    fvalid = jnp.arange(F, dtype=jnp.int32) < n_facts
    dvalid = jnp.arange(ds.shape[0], dtype=jnp.int32) < n_delta
    fcols = (fs, fp, fo)
    dcols = (ds, dp, do)

    overflow = np.int32(0)
    parts: List[tuple] = []  # (s, p, o, tag, valid) static-cap blocks
    for r_idx, rule in enumerate(rules):
        for order, keys in rule.plans:
            seed = order[0]
            table, m = _scan_premise(rule.premises[seed], dcols, dvalid)
            valid = m
            # statically-satisfied ground guards contribute their (closure-
            # constant) tags to every derivation's ⊗ — one() when no guards
            tag = jnp.minimum(dtag, gtags[r_idx])
            for step, j in enumerate(order[1:]):
                ptable, pm = _scan_premise(rule.premises[j], fcols, fvalid)
                kv = keys[step]
                lkey, rkey = _join_keys(table, ptable, kv, valid, pm)
                li, ri, jvalid, total = join_indices(lkey, rkey, J)
                overflow = overflow | jnp.where(total > J, np.int32(1), 0)
                new_table = {}
                for v, c in table.items():
                    new_table[v] = c[li]
                for v, c in ptable.items():
                    if v not in new_table:
                        new_table[v] = c[ri]
                # ⊗ = min: a derivation is as strong as its weakest premise;
                # an absent (NaN) entry reads as one() for premises
                ptag = ftag[ri]
                ptag = jnp.where(jnp.isnan(ptag), one_enc, ptag)
                tag = jnp.minimum(tag[li], ptag)
                table, valid = new_table, jvalid
            valid = _eval_filters(rule, table, valid, masks)
            # zero-tag pruning (provenance_semi_naive.rs:171)
            valid = valid & (tag > 0.0)
            n = valid.shape[0]
            for concl in rule.concls:
                out = []
                for kind, v in concl:
                    if kind == "var":
                        out.append(table[v])
                    else:
                        out.append(jnp.full(n, v, dtype=jnp.uint32))
                parts.append((out[0], out[1], out[2], tag, valid))

    return _commit_parts(
        parts, caps, fs, fp, fo, ftag, n_facts, ds, dp, do, dtag, overflow
    )


def _fact_lookup(qs, qp, qo, qvalid, fs, fp, fo, fvalid, F):
    """Exact ground (s,p,o) → fact-row lookup: dense-rank the (s,p) pair
    over the union, pack with o, binary-search the sorted fact keys.
    Returns ``(found, fidx)`` with ``fidx == F`` for misses.  Relies on
    dictionary IDs never reaching 0xFFFFFFFF (bits 0..30 + quoted bit 31,
    asserted in core.dictionary)."""
    import jax.numpy as jnp

    from kolibrie_tpu.ops.device_join import pack2

    sent = np.uint32(0xFFFFFFFF)
    fsp = pack2(jnp.where(fvalid, fs, sent), jnp.where(fvalid, fp, sent))
    usp = pack2(jnp.where(qvalid, qs, sent), jnp.where(qvalid, qp, sent))
    union = jnp.sort(jnp.concatenate([fsp, usp]))
    rank_f = jnp.searchsorted(union, fsp).astype(jnp.uint32)
    rank_u = jnp.searchsorted(union, usp).astype(jnp.uint32)
    fkey = pack2(rank_f, jnp.where(fvalid, fo, sent))
    ukey = pack2(rank_u, jnp.where(qvalid, qo, sent))
    forder = jnp.argsort(fkey)
    fsorted = fkey[forder]
    pos = jnp.clip(jnp.searchsorted(fsorted, ukey), 0, F - 1)
    found = qvalid & (fsorted[pos] == ukey)
    fidx = jnp.where(found, forder[pos], F)
    return found, fidx


def _commit_parts(
    parts,
    caps,
    fs,
    fp,
    fo,
    ftag,
    n_facts,
    ds,
    dp,
    do,
    dtag,
    overflow,
    fresh_delta_only=False,
):
    """Shared commit tail of the idempotent round programs: dedup candidate
    conclusions by (s,p,o) keeping each group's ⊕-max tag, look them up
    against the fact columns, append new facts / improve tags in place, and
    emit the next delta (new ∪ changed facts — or new ONLY under
    ``fresh_delta_only``, the NAF-pass contract: the host stratified loop
    feeds just ``naf_new`` KEYS back into the positive stratum, so a
    tag-improved existing fact must NOT re-fire it)."""
    import jax.numpy as jnp
    from jax import lax

    F, D = caps.fact, caps.delta
    fvalid = jnp.arange(F, dtype=jnp.int32) < n_facts

    cs = jnp.concatenate([p[0] for p in parts])
    cp = jnp.concatenate([p[1] for p in parts])
    co = jnp.concatenate([p[2] for p in parts])
    ctag = jnp.concatenate([p[3] for p in parts])
    cv = jnp.concatenate([p[4] for p in parts])

    # group candidates by (s,p,o), each group's FIRST row carrying its max
    # tag: 4-key sort with -tag as the tie-breaking key (⊕ = max)
    sent = np.uint32(0xFFFFFFFF)
    ss = jnp.where(cv, cs, sent)
    sp = jnp.where(cv, cp, sent)
    so = jnp.where(cv, co, sent)
    stag = jnp.where(cv, ctag, 0.0)
    ss, sp, so, negtag = lax.sort((ss, sp, so, -stag), num_keys=4)
    utag = -negtag
    isnew = jnp.concatenate(
        [
            jnp.ones(1, bool),
            (ss[1:] != ss[:-1]) | (sp[1:] != sp[:-1]) | (so[1:] != so[:-1]),
        ]
    )
    isnew = isnew & (ss != sent)
    n_uniq = jnp.sum(isnew)
    overflow = overflow | jnp.where(n_uniq > D, np.int32(2), 0)
    dest = jnp.where(isnew, jnp.cumsum(isnew) - 1, D)
    us = jnp.zeros(D, jnp.uint32).at[dest].set(ss, mode="drop")
    up = jnp.zeros(D, jnp.uint32).at[dest].set(sp, mode="drop")
    uo = jnp.zeros(D, jnp.uint32).at[dest].set(so, mode="drop")
    ut = jnp.zeros(D, jnp.float64).at[dest].set(utag, mode="drop")
    uvalid = jnp.arange(D) < n_uniq

    found, fidx = _fact_lookup(us, up, uo, uvalid, fs, fp, fo, fvalid, F)

    old_tag = ftag[jnp.clip(fidx, 0, F - 1)]
    # update_disjunction parity: no entry (NaN) → first derivation
    # OVERWRITES; an existing entry ⊕-merges (max), changed iff it grew
    absent = found & jnp.isnan(old_tag)
    improved = found & (ut > old_tag)  # NaN compares False
    changed = absent | improved
    fresh = uvalid & ~found

    # append new facts (tags included)
    n_new = jnp.sum(fresh)
    n_facts_next = n_facts + n_new
    overflow = overflow | jnp.where(n_facts_next > F, np.int32(4), 0)
    adest = jnp.where(fresh, n_facts + jnp.cumsum(fresh) - 1, F)
    nfs = fs.at[adest].set(us, mode="drop")
    nfp = fp.at[adest].set(up, mode="drop")
    nfo = fo.at[adest].set(uo, mode="drop")
    nftag = ftag.at[adest].set(ut, mode="drop")
    # in-place store for changed facts: overwrite when absent, else the
    # grown max (ut > old ⇒ max(old, ut) = ut in both cases)
    nftag = nftag.at[jnp.where(changed, fidx, F)].set(ut, mode="drop")

    # next delta = new ∪ changed facts, with their stored tags (NAF pass:
    # new facts only — host `naf_new` parity)
    dmask = fresh if fresh_delta_only else (fresh | changed)
    n_dnext = jnp.sum(dmask)
    ddest = jnp.where(dmask, jnp.cumsum(dmask) - 1, D)
    nds = jnp.zeros(D, jnp.uint32).at[ddest].set(us, mode="drop")
    ndp = jnp.zeros(D, jnp.uint32).at[ddest].set(up, mode="drop")
    ndo = jnp.zeros(D, jnp.uint32).at[ddest].set(uo, mode="drop")
    ndt = jnp.zeros(D, jnp.float64).at[ddest].set(ut, mode="drop")

    ok = overflow == 0

    def sel(new, old):
        return jnp.where(ok, new, old)

    # delta buffers are driver-padded to exactly D, so shapes line up
    return (
        sel(nfs, fs),
        sel(nfp, fp),
        sel(nfo, fo),
        sel(nftag, ftag),
        sel(n_facts_next, n_facts),
        sel(nds, ds),
        sel(ndp, dp),
        sel(ndo, do),
        sel(ndt, dtag),
        sel(n_dnext.astype(jnp.int32), np.int32(0)),
        overflow,
    )


# ---------------------------------------------------------------------------
# Stratified NAF pass (idempotent semirings only)
# ---------------------------------------------------------------------------


def _concl_unifies_neg(concl, neg) -> bool:
    """Conservative syntactic unification of a conclusion pattern with a
    negated premise — variables unify with anything."""
    return all(
        kind != "const" or c is None or c == v
        for (kind, v), c in zip(concl, neg.consts)
    )


def _naf_cross_blocking(naf_rules) -> bool:
    """True when some NAF rule's conclusion pattern could unify with some
    NAF rule's NEGATED premise (including its own): within one negative
    pass the host's sequential fact commits make the outcome order-
    dependent.  Since round 5 this routes to the SEQUENTIAL per-rule
    driver (host rule order reproduced dispatch-by-dispatch) instead of
    gating — only the within-rule case (:func:`_naf_self_blocking`)
    still falls back to host."""
    for ra in naf_rules:
        for concl in ra.concls:
            for rb in naf_rules:
                for neg in rb.negs:
                    if _concl_unifies_neg(concl, neg):
                        return True
    return False


def _naf_self_blocking(naf_rules) -> bool:
    """True when a NAF rule's conclusion unifies a negated premise OF THE
    SAME rule: the host commits that rule's derivations row by row, so an
    earlier row's conclusion can block a later row of the same evaluation
    — an order no snapshot pass or per-rule sequencing reproduces."""
    for r in naf_rules:
        for concl in r.concls:
            for neg in r.negs:
                if _concl_unifies_neg(concl, neg):
                    return True
    return False


def _naf_premise_drift(all_rules, naf_rules) -> bool:
    """True when a NAF pass's output can REACH a NAF rule's positive
    premise through the rule graph.  Then a premise tag read by a NAF body
    can improve BETWEEN passes, and the host's exactly-once ``naf_seen``
    skip (which freezes each derivation's first-read tags) becomes
    load-bearing — a snapshot recomputation would ⊕-merge the improved
    value.  NAF bodies over predicates that are derived but FINAL before
    the first pass (no feedback from NAF conclusions) are safe.

    Predicate-level reachability, conservative: variable predicates are
    wildcards; guard premises are excluded (non-derivable by
    construction)."""
    reach: Set[int] = set()  # predicate ids reachable from NAF conclusions
    wild = False  # a variable-predicate conclusion reaches everything

    def add_concls(r) -> bool:
        nonlocal wild
        changed = False
        for c in r.concls:
            kind, v = c[1]
            if kind == "const":
                if v not in reach:
                    reach.add(v)
                    changed = True
            elif not wild:
                wild = True
                changed = True
        return changed

    for nr in naf_rules:
        add_concls(nr)
    changed = True
    while changed:
        changed = False
        for r in all_rules:
            prem_preds = [p.consts[1] for p in r.premises]
            fires = wild or any(
                (pp is None and reach) or (pp in reach) for pp in prem_preds
            )
            if fires and add_concls(r):
                changed = True
    for nr in naf_rules:
        for p in nr.premises:
            pp = p.consts[1]
            if wild or (pp is None and reach) or pp in reach:
                return True
    return False


def _negate_enc(t, neg_kind, one_enc):
    """⊖ on the f64 tag encoding.  ``complement``: 1 − t (minmax fuzzy
    complement; boolean 0/1 flip).  ``expiration``: an expired premise
    (NEVER → 0.0) negates to FOREVER (+inf) and any live one to NEVER
    (provenance.rs negate parity)."""
    import jax.numpy as jnp

    if neg_kind == "expiration":
        return jnp.where(t == 0.0, jnp.float64(np.inf), jnp.float64(0.0))
    return 1.0 - t


@partial(jax.jit, static_argnames=("rules", "caps", "neg_kind"))
def _prov_naf_pass(
    rules: tuple,
    caps: _Caps,
    fs,
    fp,
    fo,
    ftag,
    n_facts,
    ds,
    dp,
    do,
    dtag,
    one_enc,
    masks,
    neg_kind,
    gtags,
):
    """One stratified NAF pass over the QUIESCED positive fixpoint: each
    NAF rule's positive body is evaluated against ALL facts (no delta
    decomposition — ⊕ is idempotent, so re-derivation is harmless), the
    per-row tag is the ⊗-chain of premise tags, and every negative premise
    contributes ``one()`` when its ground instantiation is absent from the
    facts and ``⊖tag`` when present (provenance_semi_naive.rs:235-389).
    Same state contract / return tuple as :func:`_prov_round`; the ``ds``
    inputs are the (drained) delta buffers, passed for the non-commit
    fallback and output shapes.

    Host-parity note: the host pass processes each derivation signature at
    most once across passes (``naf_seen``); this pass recomputes all
    derivations and ⊕-merges, which agrees because ⊕ is idempotent and a
    stratified program's premise tags are final when the stratum fires.
    Programs where one NAF rule's conclusion unifies with a NAF rule's
    negated premise are rejected at the driver (:func:`_naf_cross_blocking`)
    — there the host's sequential within-pass commits are load-bearing.
    """
    import jax.numpy as jnp

    from kolibrie_tpu.ops.device_join import join_indices

    F, D, J = caps.fact, caps.delta, caps.join
    fvalid = jnp.arange(F, dtype=jnp.int32) < n_facts
    fcols = (fs, fp, fo)
    eff = jnp.where(jnp.isnan(ftag), one_enc, ftag)

    overflow = np.int32(0)
    parts: List[tuple] = []
    for r_idx, rule in enumerate(rules):
        # one plan suffices: the body runs against the full fact store
        order, keys = rule.plans[0]
        table, valid = _scan_premise(rule.premises[order[0]], fcols, fvalid)
        tag = jnp.minimum(eff, gtags[r_idx])
        for step, j in enumerate(order[1:]):
            ptable, pm = _scan_premise(rule.premises[j], fcols, fvalid)
            kv = keys[step]
            lkey, rkey = _join_keys(table, ptable, kv, valid, pm)
            li, ri, jvalid, total = join_indices(lkey, rkey, J)
            overflow = overflow | jnp.where(total > J, np.int32(1), 0)
            new_table = {}
            for v, c in table.items():
                new_table[v] = c[li]
            for v, c in ptable.items():
                if v not in new_table:
                    new_table[v] = c[ri]
            tag = jnp.minimum(tag[li], eff[ri])
            table, valid = new_table, jvalid
        valid = _eval_filters(rule, table, valid, masks)
        n = valid.shape[0]
        for neg in rule.negs:
            # ground the negated pattern per derivation row: constants,
            # bound variables (lowering guarantees binding), repeats
            qcol: list = [None, None, None]
            for pos_i, c in enumerate(neg.consts):
                if c is not None:
                    qcol[pos_i] = jnp.full(n, c, dtype=jnp.uint32)
            for v, pos_i in neg.vars:
                qcol[pos_i] = table[v]
            for a, b in neg.eq_pairs:
                qcol[b] = qcol[a]
            found, fidx = _fact_lookup(
                qcol[0], qcol[1], qcol[2], valid, fs, fp, fo, fvalid, F
            )
            ntag = _negate_enc(
                eff[jnp.clip(fidx, 0, F - 1)], neg_kind, one_enc
            )
            tag = jnp.minimum(tag, jnp.where(found, ntag, one_enc))
        # zero-tag pruning (a certainly-blocked derivation adds nothing)
        valid = valid & (tag > 0.0)
        for concl in rule.concls:
            out = []
            for kind, v in concl:
                if kind == "var":
                    out.append(table[v])
                else:
                    out.append(jnp.full(n, v, dtype=jnp.uint32))
            parts.append((out[0], out[1], out[2], tag, valid))

    return _commit_parts(
        parts,
        caps,
        fs,
        fp,
        fo,
        ftag,
        n_facts,
        ds,
        dp,
        do,
        dtag,
        overflow,
        fresh_delta_only=True,
    )


# ---------------------------------------------------------------------------
# Non-idempotent round: AddMult (noisy-OR ⊕, product ⊗)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("rules", "caps"))
def _prov_round_addmult(
    rules: tuple,
    caps: _Caps,
    fs,
    fp,
    fo,
    ftag,
    n_facts,
    didx,
    n_delta,
    masks,
    gtags,
):
    """One EXACTLY-ONCE tagged semi-naive round for the addmult semiring.

    Non-idempotent ⊕ (a+b-ab) must see every derivation exactly once, so
    the round differs from the idempotent program in three ways:

    - **Decomposition** (host parity: ``eval_rule_body``'s old/delta split,
      ``provenance_semi_naive.rs:26-34``): for the plan seeded at premise
      position k, premise j < k scans OLD facts (facts minus delta), j > k
      scans ALL facts, so a derivation touching several delta facts is
      counted at exactly one seed position.
    - **Delta as fact-row indices** (``didx``): the delta is always a set of
      committed fact rows, so membership ("old" mask) is one scatter, and
      delta columns/tags are gathers — no separate delta buffers to keep
      consistent.
    - **⊕ within the round** is a segment noisy-OR in log space:
      group tag = 1 - ∏(1-pᵢ) = -expm1(Σ log1p(-pᵢ)) over the group's
      derivations (exactly ⊕ folded over the group, in any order).

    Merge with the stored tag matches ``TagStore.update_disjunction``:
    absent (NaN) → the group tag is inserted verbatim; saturated (≥ 1.0)
    short-circuits; otherwise new = old + g - old·g, and the fact re-enters
    the delta iff |new - old| ≥ 1e-12 (``AddMultProbability.tag_eq``) —
    the same cutoff that makes cyclic noisy-OR fixpoints terminate on the
    host.  Returns the same (state..., overflow) protocol as
    :func:`_prov_round`; an overflowing round does not commit.
    """
    import jax.numpy as jnp

    from kolibrie_tpu.ops.device_join import join_indices

    F, D, J = caps.fact, caps.delta, caps.join
    fvalid = jnp.arange(F, dtype=jnp.int32) < n_facts
    dvalid = jnp.arange(D, dtype=jnp.int32) < n_delta
    fcols = (fs, fp, fo)
    didx_c = jnp.clip(didx, 0, F - 1)
    dcols = tuple(c[didx_c] for c in fcols)
    dtag_eff = ftag[didx_c]
    dtag_eff = jnp.where(jnp.isnan(dtag_eff), 1.0, dtag_eff)  # one() = 1.0
    in_delta = (
        jnp.zeros(F, bool)
        .at[jnp.where(dvalid, didx_c, F)]
        .set(True, mode="drop")
    )
    old_valid = fvalid & ~in_delta

    overflow = np.int32(0)
    parts: List[tuple] = []  # (s, p, o, tag, valid) static-cap blocks
    for r_idx, rule in enumerate(rules):
        for order, keys in rule.plans:
            seed = order[0]
            table, m = _scan_premise(rule.premises[seed], dcols, dvalid)
            valid = m
            # statically-satisfied ground guards contribute their (closure-
            # constant) tags to every derivation's ⊗ — one() when no guards
            tag = dtag_eff * gtags[r_idx]
            for step, j in enumerate(order[1:]):
                pvalid = old_valid if j < seed else fvalid
                ptable, pm = _scan_premise(rule.premises[j], fcols, pvalid)
                kv = keys[step]
                lkey, rkey = _join_keys(table, ptable, kv, valid, pm)
                li, ri, jvalid, total = join_indices(lkey, rkey, J)
                overflow = overflow | jnp.where(total > J, np.int32(1), 0)
                new_table = {}
                for v, c in table.items():
                    new_table[v] = c[li]
                for v, c in ptable.items():
                    if v not in new_table:
                        new_table[v] = c[ri]
                # ⊗ = product; absent (NaN) entries read as one()
                ptag = ftag[ri]
                ptag = jnp.where(jnp.isnan(ptag), 1.0, ptag)
                tag = tag[li] * ptag
                table, valid = new_table, jvalid
            valid = _eval_filters(rule, table, valid, masks)
            # zero-tag pruning (provenance_semi_naive.rs:171)
            valid = valid & (tag > 0.0)
            n = valid.shape[0]
            for concl in rule.concls:
                out = []
                for kind, v in concl:
                    if kind == "var":
                        out.append(table[v])
                    else:
                        out.append(jnp.full(n, v, dtype=jnp.uint32))
                parts.append((out[0], out[1], out[2], tag, valid))

    (
        nfs,
        nfp,
        nfo,
        nftag,
        n_facts_next,
        ndidx,
        n_dnext,
        overflow,
    ) = _addmult_commit(parts, caps, fs, fp, fo, ftag, n_facts, overflow)
    ok = overflow == 0

    def sel(new, old):
        return jnp.where(ok, new, old)

    return (
        sel(nfs, fs),
        sel(nfp, fp),
        sel(nfo, fo),
        sel(nftag, ftag),
        sel(n_facts_next, n_facts),
        sel(ndidx, didx),
        sel(n_dnext.astype(jnp.int32), np.int32(0)),
        overflow,
    )


def _addmult_commit(
    parts, caps, fs, fp, fo, ftag, n_facts, overflow, fresh_delta_only=False
):
    """Shared commit tail of the addmult round AND NAF pass: group the
    candidate (s,p,o,tag,valid) blocks, ⊕ per group as a segment noisy-OR
    in log space (order-free — exactly ⊕ folded over the group), merge with
    stored tags (``TagStore.update_disjunction`` semantics incl. the 1e-12
    change cutoff), append fresh facts, and emit the next delta as fact-row
    indices.  ``fresh_delta_only`` (the NAF pass): the delta carries ONLY
    newly-appended facts — host parity with ``_negative_pass``, whose
    ``naf_new`` returns newly ADDED keys, so an improved pre-existing
    conclusion must NOT re-enter the positive stratum.  Traced inside the
    callers' jit."""
    import jax.numpy as jnp
    from jax import lax

    from kolibrie_tpu.ops.device_join import pack2

    F, D = caps.fact, caps.delta
    fvalid = jnp.arange(F, dtype=jnp.int32) < n_facts

    cs = jnp.concatenate([p[0] for p in parts])
    cp = jnp.concatenate([p[1] for p in parts])
    co = jnp.concatenate([p[2] for p in parts])
    ctag = jnp.concatenate([p[3] for p in parts])
    cv = jnp.concatenate([p[4] for p in parts])

    # group candidates by (s,p,o); ⊕ over each group = segment noisy-OR in
    # log space (order-free, unlike the idempotent max-tag sort trick)
    sent = np.uint32(0xFFFFFFFF)
    ss = jnp.where(cv, cs, sent)
    sp = jnp.where(cv, cp, sent)
    so = jnp.where(cv, co, sent)
    stag = jnp.where(cv, jnp.clip(ctag, 0.0, 1.0), 0.0)
    ss, sp, so, stag = lax.sort((ss, sp, so, stag), num_keys=3)
    isnew = jnp.concatenate(
        [
            jnp.ones(1, bool),
            (ss[1:] != ss[:-1]) | (sp[1:] != sp[:-1]) | (so[1:] != so[:-1]),
        ]
    )
    isnew = isnew & (ss != sent)
    n_uniq = jnp.sum(isnew)
    overflow = overflow | jnp.where(n_uniq > D, np.int32(2), 0)
    seg = jnp.cumsum(isnew) - 1
    segdst = jnp.where(ss != sent, seg, D)
    # log1p(-p): p=1 → -inf → group tag exactly 1.0; p∈[0,1) stays finite
    logsum = (
        jnp.zeros(D, jnp.float64)
        .at[segdst]
        .add(jnp.log1p(-stag), mode="drop")
    )
    gtag = -jnp.expm1(logsum)  # 1 - ∏(1-pᵢ)
    dest = jnp.where(isnew, seg, D)
    us = jnp.zeros(D, jnp.uint32).at[dest].set(ss, mode="drop")
    up = jnp.zeros(D, jnp.uint32).at[dest].set(sp, mode="drop")
    uo = jnp.zeros(D, jnp.uint32).at[dest].set(so, mode="drop")
    uvalid = jnp.arange(D) < n_uniq

    # exact (s,p,o) → fact-index lookup (same machinery as _prov_round)
    fsp = pack2(jnp.where(fvalid, fs, sent), jnp.where(fvalid, fp, sent))
    usp = pack2(jnp.where(uvalid, us, sent), jnp.where(uvalid, up, sent))
    union = jnp.sort(jnp.concatenate([fsp, usp]))
    rank_f = jnp.searchsorted(union, fsp).astype(jnp.uint32)
    rank_u = jnp.searchsorted(union, usp).astype(jnp.uint32)
    fkey = pack2(rank_f, jnp.where(fvalid, fo, sent))
    ukey = pack2(rank_u, jnp.where(uvalid, uo, sent))
    forder = jnp.argsort(fkey)
    fsorted = fkey[forder]
    pos = jnp.clip(jnp.searchsorted(fsorted, ukey), 0, F - 1)
    found = uvalid & (fsorted[pos] == ukey)
    fidx = jnp.where(found, forder[pos], F)

    old_tag = ftag[jnp.clip(fidx, 0, F - 1)]
    absent = found & jnp.isnan(old_tag)
    saturated = found & (old_tag >= 1.0)  # NaN compares False
    new_tag = old_tag + gtag - old_tag * gtag
    improved = (
        found
        & ~absent
        & ~saturated
        & (jnp.abs(new_tag - old_tag) >= _ADDMULT_TAG_EQ)
    )
    changed = absent | improved
    merged = jnp.where(absent, gtag, new_tag)
    fresh = uvalid & ~found

    # append new facts (tags included)
    n_new = jnp.sum(fresh)
    n_facts_next = n_facts + n_new
    overflow = overflow | jnp.where(n_facts_next > F, np.int32(4), 0)
    adest = jnp.where(fresh, n_facts + jnp.cumsum(fresh) - 1, F)
    nfs = fs.at[adest].set(us, mode="drop")
    nfp = fp.at[adest].set(up, mode="drop")
    nfo = fo.at[adest].set(uo, mode="drop")
    nftag = ftag.at[adest].set(gtag, mode="drop")
    nftag = nftag.at[jnp.where(changed, fidx, F)].set(merged, mode="drop")

    # next delta = indices of new (∪ changed, unless fresh_delta_only) rows
    dmask = fresh if fresh_delta_only else (fresh | changed)
    row_idx = jnp.where(fresh, adest, fidx).astype(jnp.int32)
    n_dnext = jnp.sum(dmask)
    ddest = jnp.where(dmask, jnp.cumsum(dmask) - 1, D)
    ndidx = jnp.zeros(D, jnp.int32).at[ddest].set(row_idx, mode="drop")
    return nfs, nfp, nfo, nftag, n_facts_next, ndidx, n_dnext, overflow


# ---------------------------------------------------------------------------
# Non-idempotent stratified NAF pass: exactly-once via a device seen-set
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("rule", "caps", "scap"))
def _prov_naf_pass_addmult(
    rule,
    caps: _Caps,
    scap: int,
    fs,
    fp,
    fo,
    ftag,
    n_facts,
    seen_cols,
    n_seen,
    masks,
    gtag,
):
    """One NAF rule's stratified pass for the NON-idempotent addmult
    semiring, with the host's exactly-once derivation accounting
    (``naf_seen``, provenance_seminaive.py::_negative_pass) ON DEVICE.

    The host processes each derivation signature — (rule, variable
    bindings) — at most once across passes, because noisy-OR ⊕ would
    double-count re-derivations.  Here the signature set is a device-
    resident SEEN relation: ``seen_cols`` is one sorted u32 column per
    rule variable (lexicographic, capacity ``scap``), partitioned per rule
    by the driver.  The pass sorts [seen rows ∥ this pass's candidate
    rows] on the binding columns with a seen-first tie-break; a candidate
    fires iff it HEADS its equal-binding group (neither a seen row nor an
    earlier duplicate candidate precedes it), and the sorted union of
    distinct bindings is the next seen relation — dedup, membership, and
    maintenance in ONE multi-operand sort.

    One rule per dispatch: the driver sequences rules in host order, so a
    rule's committed facts are visible to later rules' body joins and
    negated-premise checks exactly like the host's within-pass sequential
    commits (this also serves the idempotent cross-blocking case).
    Self-interaction (a rule's conclusion unifying its OWN negated
    premise, or reaching its own positive premises) stays host-gated —
    there the host's per-ROW commit order is load-bearing.

    Same didx delta / overflow protocol as :func:`_prov_round_addmult`;
    overflow bit 8 = seen-set capacity.
    """
    import jax.numpy as jnp
    from jax import lax

    from kolibrie_tpu.ops.device_join import join_indices

    F, D, J = caps.fact, caps.delta, caps.join
    fvalid = jnp.arange(F, dtype=jnp.int32) < n_facts
    fcols = (fs, fp, fo)
    eff = jnp.where(jnp.isnan(ftag), 1.0, ftag)

    overflow = np.int32(0)
    # body vs ALL facts (host: eval_rule_body with delta=None)
    order, keys = rule.plans[0]
    table, valid = _scan_premise(rule.premises[order[0]], fcols, fvalid)
    tag = eff * gtag
    for step, j in enumerate(order[1:]):
        ptable, pm = _scan_premise(rule.premises[j], fcols, fvalid)
        kv = keys[step]
        lkey, rkey = _join_keys(table, ptable, kv, valid, pm)
        li, ri, jvalid, total = join_indices(lkey, rkey, J)
        overflow = overflow | jnp.where(total > J, np.int32(1), 0)
        new_table = {}
        for v, c in table.items():
            new_table[v] = c[li]
        for v, c in ptable.items():
            if v not in new_table:
                new_table[v] = c[ri]
        ptag = eff[ri]
        tag = tag[li] * ptag
        table, valid = new_table, jvalid
    valid = _eval_filters(rule, table, valid, masks)

    # ---- seen-set: dedup + membership + maintenance in one sort ----------
    var_names = tuple(sorted(table))  # host sig order: sorted(row.items())
    n_cand = valid.shape[0]
    sent = np.uint32(0xFFFFFFFF)
    seen_valid = jnp.arange(scap, dtype=jnp.int32) < n_seen
    ops = []
    for k, v in enumerate(var_names):
        cand = jnp.where(valid, table[v], sent)
        seen = jnp.where(seen_valid, seen_cols[k], sent)
        ops.append(jnp.concatenate([seen, cand]))
    # flag sorts seen (0) before equal-binding candidates (1)
    flag = jnp.concatenate(
        [
            jnp.zeros(scap, dtype=jnp.uint32),
            jnp.ones(n_cand, dtype=jnp.uint32),
        ]
    )
    payload_tag = jnp.concatenate([jnp.zeros(scap, jnp.float64), tag])
    sorted_all = lax.sort(
        (*ops, flag, payload_tag), num_keys=len(var_names) + 1
    )
    scols = sorted_all[: len(var_names)]
    sflag = sorted_all[len(var_names)]
    stag = sorted_all[len(var_names) + 1]
    live = scols[0] != sent  # all-sentinel rows (invalid) sort last
    head = jnp.concatenate(
        [
            jnp.ones(1, bool),
            jnp.any(
                jnp.stack([c[1:] != c[:-1] for c in scols]), axis=0
            ),
        ]
    )
    # a candidate FIRES iff it heads its equal-binding group: no seen row
    # (flag 0 sorts first) and no duplicate candidate precedes it
    fire = live & head & (sflag == 1)
    # next seen relation = the distinct bindings of the union
    keep = live & head
    n_seen_next = jnp.sum(keep)
    overflow = overflow | jnp.where(n_seen_next > scap, np.int32(8), 0)
    kdest = jnp.where(keep, jnp.cumsum(keep) - 1, scap)
    seen_next = tuple(
        jnp.full(scap, sent, dtype=jnp.uint32).at[kdest].set(c, mode="drop")
        for c in scols
    )

    # ---- negated premises over the firing rows ---------------------------
    bind = {v: scols[k] for k, v in enumerate(var_names)}
    n_all = scap + n_cand
    tag2 = stag
    for neg in rule.negs:
        qcol: list = [None, None, None]
        for pos_i, c in enumerate(neg.consts):
            if c is not None:
                qcol[pos_i] = jnp.full(n_all, c, dtype=jnp.uint32)
        for v, pos_i in neg.vars:
            qcol[pos_i] = bind[v]
        for a, b in neg.eq_pairs:
            qcol[b] = qcol[a]
        found, fidx = _fact_lookup(
            qcol[0], qcol[1], qcol[2], fire, fs, fp, fo, fvalid, F
        )
        ntag = 1.0 - eff[jnp.clip(fidx, 0, F - 1)]  # addmult ⊖ = 1 − t
        tag2 = tag2 * jnp.where(found, ntag, 1.0)
    fire = fire & (tag2 > 0.0)  # zero-tag pruning

    parts = []
    for concl in rule.concls:
        out = []
        for kind, v in concl:
            if kind == "var":
                out.append(bind[v])
            else:
                out.append(jnp.full(n_all, v, dtype=jnp.uint32))
        parts.append((out[0], out[1], out[2], tag2, fire))

    (
        nfs,
        nfp,
        nfo,
        nftag,
        n_facts_next,
        ndidx,
        n_dnext,
        overflow,
    ) = _addmult_commit(
        parts, caps, fs, fp, fo, ftag, n_facts, overflow,
        fresh_delta_only=True,
    )
    ok = overflow == 0

    def sel(new, old):
        return jnp.where(ok, new, old)

    return (
        sel(nfs, fs),
        sel(nfp, fp),
        sel(nfo, fo),
        sel(nftag, ftag),
        sel(n_facts_next, n_facts),
        ndidx,
        sel(n_dnext.astype(jnp.int32), np.int32(0)),
        tuple(sel(ns, os_) for ns, os_ in zip(seen_next, seen_cols)),
        sel(n_seen_next.astype(jnp.int32), n_seen),
        overflow,
    )


# ---------------------------------------------------------------------------
# Host driver + integration
# ---------------------------------------------------------------------------


def infer_provenance_device(
    reasoner,
    provenance,
    tag_store,
    initial_delta: Optional[Set[Tuple[int, int, int]]] = None,
    max_attempts: int = 32,
) -> Optional[Dict[Tuple[int, int, int], float]]:
    """Run the tagged fixpoint on device; returns None for host fallback.

    On success the derived facts are appended to ``reasoner.facts`` and
    ``tag_store`` holds the final tags (exactly like the host path).
    """
    if not supports(provenance):
        return None
    if provenance.name == "addmult" and _addmult_order_sensitive(
        [r for r in reasoner.rules if not r.negative_premise]
    ):
        # order-dependent accumulation WITHIN the positive round program:
        # host semantics win.  NAF rules are excluded — the stratified
        # driver dispatches them one at a time in host order, so cross-rule
        # visibility matches the host pass by construction.
        return None
    try:
        rules, bank = lower_rules(reasoner, reasoner.rules)
    except Unsupported:
        return None
    if not rules:
        return None
    # ground-guard satisfaction at DRIVER time (this driver always lowers
    # against the real facts, unlike DeviceR2R's per-window reuse — the
    # untagged rounds evaluate guards at run time instead): facts never
    # retract and guards are non-derivable, so an absent guard makes its
    # rule dead for this whole closure
    rules = tuple(
        r
        for r in rules
        if all(reasoner.facts.contains(*g.consts) for g in r.guards)
    )
    if not rules:
        return {}  # every rule statically dead: nothing to derive
    pos_rules = tuple(r for r in rules if not r.negs)
    naf_rules = tuple(r for r in rules if r.negs)
    if naf_rules and _naf_self_blocking(naf_rules):
        # a rule whose conclusion unifies its OWN negated premise: the
        # host's per-ROW sequential commits within that rule's evaluation
        # are load-bearing (row k can block row k+1 of the same rule) —
        # no snapshot or per-rule sequencing reproduces that order
        return None
    if naf_rules and _naf_premise_drift(rules, naf_rules):
        # a NAF body reading DERIVED predicates can see its premise tags
        # improve between passes; host freezes each derivation's first
        # read (naf_seen) — keep those programs host-side
        return None
    # CROSS-rule blocking (rule A's conclusion unifying rule B's negated
    # premise) no longer gates: the drivers dispatch NAF rules one at a
    # time in host order, so each rule's commits are visible to later
    # rules' body joins and negated-premise checks exactly like the host
    # pass's sequential commits (round 5; addmult is ALWAYS sequential —
    # its per-rule seen-sets need the partition anyway)
    naf_sequential = bool(naf_rules) and (
        provenance.name == "addmult" or _naf_cross_blocking(naf_rules)
    )

    import jax.numpy as jnp

    s, p, o = reasoner.facts.columns()
    n0 = len(s)
    if n0 == 0:
        return None
    facts_keys = list(zip(s.tolist(), p.tolist(), o.tolist()))
    tags0, one_enc = _seed_tag_arrays(provenance, tag_store, facts_keys)

    masks = tuple(jnp.asarray(m) for m in bank.materialize()) or (
        jnp.zeros(1, dtype=bool),
    )

    # delta tags are EFFECTIVE values (absent resolves to one())
    eff0 = np.where(np.isnan(tags0), one_enc, tags0)
    if initial_delta is not None:
        key_to_idx = {k: i for i, k in enumerate(facts_keys)}
        didx = np.asarray(
            sorted(key_to_idx[k] for k in initial_delta if k in key_to_idx),
            dtype=np.int32,
        )
        if didx.size == 0:
            return {}
    else:
        didx = np.arange(n0, dtype=np.int32)

    if provenance.name == "addmult":
        return _drive_addmult(
            reasoner,
            provenance,
            tag_store,
            pos_rules,
            naf_rules,
            masks,
            s,
            p,
            o,
            tags0,
            didx,
            n0,
            max_attempts,
        )

    d_s = s[didx]
    d_p = p[didx]
    d_o = o[didx]
    d_t = eff0[didx]
    nd0 = len(d_s)

    with _enable_x64(True):
        st = {
            "fs": _pad_u32(s, 0),
            "fp": _pad_u32(p, 0),
            "fo": _pad_u32(o, 0),
            "ftag": _pad_f64(tags0, 0),
            "n_facts": n0,
            "ds": _pad_u32(d_s, 0),
            "dp": _pad_u32(d_p, 0),
            "do": _pad_u32(d_o, 0),
            "dt": _pad_f64(d_t, 0),
            "n_delta": nd0,
        }

        gtags_pos = jnp.asarray(
            _guard_tag_array(pos_rules, provenance, tag_store)
        )

        def round_fn(caps, st):
            out = _prov_round(
                pos_rules,
                caps,
                st["fs"],
                st["fp"],
                st["fo"],
                st["ftag"],
                jnp.int32(st["n_facts"]),
                st["ds"],
                st["dp"],
                st["do"],
                st["dt"],
                jnp.int32(st["n_delta"]),
                jnp.float64(one_enc),
                masks,
                gtags_pos,
            )
            code = int(out[10])  # one sync per round
            if code != 0:
                return None, code
            return {
                "fs": out[0],
                "fp": out[1],
                "fo": out[2],
                "ftag": out[3],
                "n_facts": int(out[4]),
                "ds": out[5],
                "dp": out[6],
                "do": out[7],
                "dt": out[8],
                "n_delta": int(out[9]),
            }, 0

        def pad_delta(st, D):
            for k in ("ds", "dp", "do"):
                st[k] = _pad_u32(st[k], D)
            st["dt"] = _pad_f64(st["dt"], D)
            return st

        if pos_rules:
            st = _run_overflow_protocol(
                round_fn, st, n0, nd0, pad_delta, max_attempts
            )
        else:
            # no positive stratum: pad buffers (the protocol's job) and
            # treat the initial delta as drained — NAF evaluates vs ALL facts
            F = _round_cap(4 * n0, 2048)
            D = _round_cap(max(2 * nd0, n0 // 2, 1024))
            for k in ("fs", "fp", "fo"):
                st[k] = _pad_u32(st[k], F)
            st["ftag"] = _pad_f64(st["ftag"], F)
            st = pad_delta(st, D)
            st["n_delta"] = 0
        if st is not None and naf_rules:
            st = _drive_naf(
                naf_rules,
                st,
                round_fn if pos_rules else None,
                pad_delta,
                provenance,
                one_enc,
                masks,
                jnp.asarray(_guard_tag_array(naf_rules, provenance, tag_store)),
                n0,
                nd0,
                max_attempts,
                sequential=naf_sequential,
            )
        if st is None:
            return None  # graceful host fallback (reasoner state untouched)
        _write_back(
            reasoner,
            provenance,
            tag_store,
            st["fs"],
            st["fp"],
            st["fo"],
            st["ftag"],
            st["n_facts"],
            n0,
            tags0,
        )
    return {}


def _pad_u32(x, cap):
    import jax.numpy as jnp

    x = jnp.asarray(x, dtype=jnp.uint32)
    pad = max(cap - x.shape[0], 0)
    return jnp.concatenate([x, jnp.zeros(pad, dtype=jnp.uint32)])


def _pad_f64(x, cap):
    import jax.numpy as jnp

    x = jnp.asarray(x, dtype=jnp.float64)
    pad = max(cap - x.shape[0], 0)
    return jnp.concatenate([x, jnp.zeros(pad, dtype=jnp.float64)])


def _pad_i32(x, cap):
    import jax.numpy as jnp

    x = jnp.asarray(x, dtype=jnp.int32)
    pad = max(cap - x.shape[0], 0)
    return jnp.concatenate([x, jnp.zeros(pad, dtype=jnp.int32)])


def _run_overflow_protocol(round_fn, st, n0, nd0, pad_delta, max_attempts):
    """THE shared static-capacity fixpoint protocol (both round programs):
    run rounds until the delta drains; an overflowing round does NOT commit
    — the failing capacity doubles (bit0 join, bit1 delta, bit2 fact) and
    the round retries from the preserved state.

    ``round_fn(caps, st) -> (next_st | None, code)``; ``st`` holds fact
    buffers under keys fs/fp/fo/ftag (+ counts n_facts/n_delta), with the
    delta representation private to the caller (re-padded by ``pad_delta``).
    Returns the final state, or None after ``max_attempts`` overflows or
    10k rounds (graceful host fallback).
    """
    # never shrink below already-padded buffers: the stratified-NAF driver
    # re-enters this protocol after a pass that may have doubled capacities
    F = max(_round_cap(4 * n0, 2048), st["fs"].shape[0])
    D = _round_cap(max(2 * nd0, n0 // 2, 1024))
    # the delta representation is caller-private: idempotent rounds carry
    # value columns ("ds"), addmult carries fact-row indices ("didx")
    _dbuf = st.get("ds", st.get("didx"))
    if _dbuf is not None:
        D = max(D, _dbuf.shape[0])
    # start TIGHT: the candidate sort scales with J × plans, and the
    # overflow protocol doubles J cheaply when a round actually needs it
    J = _round_cap(max(nd0, 1024), 1024)
    for k in ("fs", "fp", "fo"):
        st[k] = _pad_u32(st[k], F)
    st["ftag"] = _pad_f64(st["ftag"], F)
    st = pad_delta(st, D)

    attempts = 0
    for _round in range(10_000):
        new_st, code = round_fn(_Caps(F, D, J), st)
        if code != 0:
            attempts += 1
            if attempts > max_attempts:
                return None
            if code & 1:
                J *= 2
            if code & 2:
                D *= 2
                st = pad_delta(st, D)
            if code & 4:
                F *= 2
                for k in ("fs", "fp", "fo"):
                    st[k] = _pad_u32(st[k], F)
                st["ftag"] = _pad_f64(st["ftag"], F)
            continue  # retry the round (it did not commit)
        st = new_st
        if st["n_delta"] == 0:
            return st
    return None  # round limit


def _drive_naf(
    naf_rules,
    st,
    round_fn,
    pad_delta,
    provenance,
    one_enc,
    masks,
    gtags,
    n0,
    nd0,
    max_attempts,
    sequential: bool = False,
):
    """Stratified-NAF driver (host loop parity, provenance_seminaive.py):
    alternate one device NAF pass with a positive fixpoint re-run seeded by
    the pass's delta, until a pass derives nothing new.  Shares the
    doubling overflow protocol; ``round_fn is None`` means the program has
    no positive stratum.

    ``sequential`` (cross-blocking rule sets): dispatch ONE rule at a
    time in host rule order — a rule's committed facts are then visible
    to later rules' negated-premise checks and body joins within the same
    pass, exactly like the host's sequential commits; the pass delta is
    the union of the per-rule deltas."""
    import jax.numpy as jnp

    neg_kind = "expiration" if provenance.name == "expiration" else "complement"
    F = st["fs"].shape[0]
    D = st["ds"].shape[0]
    # NAF bodies join over ALL facts, not a delta — start J at fact scale
    J = _round_cap(max(st["n_facts"], 1024), 1024)
    attempts = 0
    rule_groups = (
        [((r,), gtags[i : i + 1]) for i, r in enumerate(naf_rules)]
        if sequential
        else [(naf_rules, gtags)]
    )
    for _pass in range(10_000):
        pass_start = st["n_facts"]
        committed = [False] * len(rule_groups)
        while True:  # per-pass retry loop: only NOT-yet-committed groups
            failed = False
            for gi, (grules, ggtags) in enumerate(rule_groups):
                if committed[gi]:
                    # a group that committed before an overflow keeps its
                    # commit — its appended facts are recovered from the
                    # fact buffers at pass end, so nothing is lost
                    continue
                out = _prov_naf_pass(
                    grules,
                    _Caps(F, D, J),
                    st["fs"],
                    st["fp"],
                    st["fo"],
                    st["ftag"],
                    jnp.int32(st["n_facts"]),
                    st["ds"],
                    st["dp"],
                    st["do"],
                    st["dt"],
                    jnp.float64(one_enc),
                    masks,
                    neg_kind,
                    ggtags,
                )
                code = int(out[10])  # one sync per dispatch
                if code != 0:
                    attempts += 1
                    if attempts > max_attempts:
                        return None
                    if code & 1:
                        J *= 2
                    if code & 2:
                        D *= 2
                        st = pad_delta(st, D)
                    if code & 4:
                        F *= 2
                        for k in ("fs", "fp", "fo"):
                            st[k] = _pad_u32(st[k], F)
                        st["ftag"] = _pad_f64(st["ftag"], F)
                    failed = True
                    break  # retry the remaining groups at bigger caps
                st = {
                    "fs": out[0],
                    "fp": out[1],
                    "fo": out[2],
                    "ftag": out[3],
                    "n_facts": int(out[4]),
                    "ds": out[5],
                    "dp": out[6],
                    "do": out[7],
                    "dt": out[8],
                    "n_delta": int(out[9]),
                }
                if sequential:
                    committed[gi] = True
            if not failed:
                break
        if sequential:
            # the pass delta = EXACTLY the facts appended during the pass
            # (host naf_new), read back from the fact buffers WITH their
            # current tags — a later rule may have ⊕-improved an earlier
            # rule's fresh fact, and the positive re-run must see the
            # merged value (the host reads the tag store live)
            nd = st["n_facts"] - pass_start
            if nd > D:
                D = _round_cap(nd)
            if nd:
                sl = slice(pass_start, st["n_facts"])
                dt = np.asarray(st["ftag"][sl])
                st["ds"] = _pad_u32(np.asarray(st["fs"][sl]), D)
                st["dp"] = _pad_u32(np.asarray(st["fp"][sl]), D)
                st["do"] = _pad_u32(np.asarray(st["fo"][sl]), D)
                st["dt"] = _pad_f64(
                    np.where(np.isnan(dt), one_enc, dt), D
                )
            st["n_delta"] = int(nd)
        if st["n_delta"] == 0:
            return st
        # NAF-derived facts feed back into the positive stratum
        if round_fn is not None:
            st = _run_overflow_protocol(
                round_fn, st, n0, nd0, pad_delta, max_attempts
            )
            if st is None:
                return None
        else:
            st["n_delta"] = 0
        F = st["fs"].shape[0]
        D = st["ds"].shape[0]
    return None  # pass limit


def _drive_naf_addmult(
    naf_rules,
    st,
    round_fn,
    pad_delta,
    provenance,
    tag_store,
    masks,
    n0,
    max_attempts,
):
    """Stratified-NAF driver for the NON-idempotent addmult semiring:
    one rule per dispatch in host order (sequential commits visible to
    later rules), each rule carrying its own device-resident seen-set
    (exactly-once across passes), the pass's union delta re-seeding the
    positive protocol until a pass derives nothing new."""
    import jax.numpy as jnp

    F = st["fs"].shape[0]
    D = st["didx"].shape[0]
    # NAF bodies join over ALL facts, not a delta — start J at fact scale
    J = _round_cap(max(st["n_facts"], 1024), 1024)
    gtags = np.asarray(_guard_tag_array(naf_rules, provenance, tag_store))
    scaps = [
        _round_cap(max(2 * st["n_facts"], 1024)) for _ in naf_rules
    ]
    seen: List[Optional[tuple]] = [None] * len(naf_rules)
    attempts = 0
    for _pass in range(10_000):
        pass_start = st["n_facts"]
        committed = [False] * len(naf_rules)
        while True:  # per-pass retry loop: only NOT-yet-committed rules
            failed = False
            for gi, rule in enumerate(naf_rules):
                if committed[gi]:
                    continue
                nvars = len(
                    {v for prem in rule.premises for v, _pos in prem.vars}
                )
                if seen[gi] is None:
                    cols = tuple(
                        jnp.full(scaps[gi], 0xFFFFFFFF, dtype=jnp.uint32)
                        for _ in range(nvars)
                    )
                    ns = 0
                else:
                    cols, ns = seen[gi]
                if cols and cols[0].shape[0] != scaps[gi]:
                    cols = tuple(_pad_u32(c, scaps[gi]) for c in cols)
                out = _prov_naf_pass_addmult(
                    rule,
                    _Caps(F, D, J),
                    scaps[gi],
                    st["fs"],
                    st["fp"],
                    st["fo"],
                    st["ftag"],
                    jnp.int32(st["n_facts"]),
                    cols,
                    jnp.int32(ns),
                    masks,
                    jnp.float64(gtags[gi]),
                )
                code = int(out[9])  # one sync per dispatch
                if code != 0:
                    attempts += 1
                    if attempts > max_attempts:
                        return None
                    if code & 1:
                        J *= 2
                    if code & 2:
                        D *= 2
                        st = pad_delta(st, D)
                    if code & 4:
                        F *= 2
                        for k in ("fs", "fp", "fo"):
                            st[k] = _pad_u32(st[k], F)
                        st["ftag"] = _pad_f64(st["ftag"], F)
                    if code & 8:
                        scaps[gi] *= 2
                    failed = True
                    break  # retry the remaining rules at bigger caps
                st = {
                    "fs": out[0],
                    "fp": out[1],
                    "fo": out[2],
                    "ftag": out[3],
                    "n_facts": int(out[4]),
                    "didx": out[5],
                    "n_delta": int(out[6]),
                }
                seen[gi] = (out[7], int(out[8]))
                committed[gi] = True
            if not failed:
                break
        # the pass delta = EXACTLY the facts appended during the pass
        # (host naf_new: newly ADDED keys only — an improved pre-existing
        # conclusion must not re-enter the positive stratum), as fact-row
        # indices; their tags are read from the live buffers by the round
        if st["n_facts"] == pass_start:
            return st
        didx = np.arange(pass_start, st["n_facts"], dtype=np.int32)
        if didx.size > D:
            D = _round_cap(didx.size)
        st["didx"] = _pad_i32(didx, D)
        st["n_delta"] = int(didx.size)
        if round_fn is not None:
            st = _run_overflow_protocol(
                round_fn, st, n0, st["n_delta"], pad_delta, max_attempts
            )
            if st is None:
                return None
            F = st["fs"].shape[0]
            D = st["didx"].shape[0]
        else:
            st["n_delta"] = 0
    return None  # pass limit


def _write_back(
    reasoner, provenance, tag_store, fs, fp, fo, ftag, n_facts, n0, tags0
) -> None:
    """Write back: new facts into the store; every changed-or-new tag entry
    into the tag store (vectorized — no per-fact Python loop).  Host parity:
    each derived fact gets an explicit entry (update_disjunction inserts on
    first derivation); NaN still means "no entry"."""
    fs_h = np.asarray(fs[:n_facts])
    fp_h = np.asarray(fp[:n_facts])
    fo_h = np.asarray(fo[:n_facts])
    ft_h = np.asarray(ftag[:n_facts])
    if n_facts > n0:
        reasoner.facts.add_batch(fs_h[n0:], fp_h[n0:], fo_h[n0:])
    has_entry = ~np.isnan(ft_h)
    unchanged = np.zeros(n_facts, dtype=bool)
    unchanged[:n0] = ~np.isnan(tags0) & (ft_h[:n0] == tags0)
    sel = np.flatnonzero(has_entry & ~unchanged)
    if sel.size:
        decoded = _decode_tags(provenance, ft_h[sel])
        keys = zip(
            fs_h[sel].tolist(), fp_h[sel].tolist(), fo_h[sel].tolist()
        )
        tag_store.tags.update(zip(keys, decoded))


def _drive_addmult(
    reasoner,
    provenance,
    tag_store,
    pos_rules,
    naf_rules,
    masks,
    s,
    p,
    o,
    tags0,
    didx0: np.ndarray,
    n0: int,
    max_attempts: int,
) -> Optional[Dict[Tuple[int, int, int], float]]:
    """Host driver for the exactly-once addmult rounds: the shared overflow
    protocol with the delta carried as fact-row INDICES.  NAF rules run as
    the stratified loop — positive protocol to quiescence, then ONE rule
    per dispatch in host order (:func:`_prov_naf_pass_addmult`, each rule
    carrying its own device-resident seen-set), the pass's union delta
    feeding the positive stratum again until a pass derives nothing."""
    import jax.numpy as jnp

    nd0 = int(didx0.size)

    with _enable_x64(True):
        st = {
            "fs": _pad_u32(s, 0),
            "fp": _pad_u32(p, 0),
            "fo": _pad_u32(o, 0),
            "ftag": _pad_f64(tags0, 0),
            "n_facts": n0,
            "didx": _pad_i32(didx0, 0),
            "n_delta": nd0,
        }
        gtags = jnp.asarray(
            _guard_tag_array(pos_rules, provenance, tag_store)
        )

        def round_fn(caps, st):
            out = _prov_round_addmult(
                pos_rules,
                caps,
                st["fs"],
                st["fp"],
                st["fo"],
                st["ftag"],
                jnp.int32(st["n_facts"]),
                st["didx"],
                jnp.int32(st["n_delta"]),
                masks,
                gtags,
            )
            code = int(out[7])  # one sync per round
            if code != 0:
                return None, code
            return {
                "fs": out[0],
                "fp": out[1],
                "fo": out[2],
                "ftag": out[3],
                "n_facts": int(out[4]),
                "didx": out[5],
                "n_delta": int(out[6]),
            }, 0

        def pad_delta(st, D):
            st["didx"] = _pad_i32(st["didx"], D)
            return st

        if pos_rules:
            st = _run_overflow_protocol(
                round_fn, st, n0, nd0, pad_delta, max_attempts
            )
        else:
            F = max(_round_cap(4 * n0, 2048), st["fs"].shape[0])
            D = _round_cap(max(2 * nd0, n0 // 2, 1024))
            for k in ("fs", "fp", "fo"):
                st[k] = _pad_u32(st[k], F)
            st["ftag"] = _pad_f64(st["ftag"], F)
            st = pad_delta(st, D)
            st["n_delta"] = 0
        if st is not None and naf_rules:
            st = _drive_naf_addmult(
                naf_rules,
                st,
                round_fn if pos_rules else None,
                pad_delta,
                provenance,
                tag_store,
                masks,
                n0,
                max_attempts,
            )
        if st is None:
            return None  # graceful host fallback (reasoner state untouched)
        _write_back(
            reasoner,
            provenance,
            tag_store,
            st["fs"],
            st["fp"],
            st["fo"],
            st["ftag"],
            st["n_facts"],
            n0,
            tags0,
        )
    return {}
