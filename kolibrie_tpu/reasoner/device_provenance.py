"""Device (TPU) provenance semi-naive fixpoint for idempotent scalar
semirings.

The host provenance loop (:mod:`kolibrie_tpu.reasoner.provenance_seminaive`)
runs per-derivation tag algebra in Python.  For the three IDEMPOTENT scalar
semirings — MinMax (fuzzy), Boolean, Expiration (the cross-window SDS+
workhorse) — the whole algebra collapses onto one device form: tags are an
f64 column, ⊗ (conjunction over a derivation's premises) is ``min`` and
⊕ (disjunction over derivations of the same fact) is ``max``:

- minmax:     tags in [0,1] verbatim,     zero 0.0, one 1.0
- boolean:    False/True → 0.0/1.0,       zero 0.0, one 1.0
- expiration: expiry timestamps → f64 (exact below 2^53; FOREVER → +inf),
              zero 0.0 (expired), one +inf (static)

Because ⊕ is idempotent, duplicate discoveries of the same derivation are
harmless — the per-seed delta expansion (every premise position seeded from
the delta, remaining positions joined against ALL facts) needs no old/delta
store split, unlike the non-idempotent host path (AddMult) which must count
each derivation exactly once.  AddMult and the structural semirings
(SDD/TopK/DNF) stay host-side.

A round is one XLA program: delta-seeded premise joins with tag ``min``
carried through the join chain, filter masks, conclusion instantiation,
4-key sort so each (s,p,o) group's first row carries its ``max`` tag,
match-against-facts index lookup, fact append + in-place tag improvement,
and the next delta = new facts ∪ tag-improved facts.  The host drives
rounds (one scalar sync per round) and doubles capacities on overflow, the
same protocol as :meth:`DeviceFixpoint.infer_chunked`.

Parity: ``datalog/.../provenance_semi_naive.rs:26-34,134-197`` (delta
re-inclusion of improved tags, per-derivation ⊗, ⊕ merge, zero-pruning) —
redesigned as whole-column device programs.  Agreement with the host path
is tested in ``tests/test_device_provenance.py``.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Set, Tuple

import jax
import numpy as np

from kolibrie_tpu.ops import round_cap as _round_cap
from kolibrie_tpu.reasoner.device_fixpoint import (
    Unsupported,
    _Caps,
    _eval_filters,
    _pack,
    _scan_premise,
    lower_rules,
)
__all__ = ["supports", "infer_provenance_device", "AUTO_MIN_FACTS"]

# below this many facts the host loop wins (device dispatch + compile cost)
AUTO_MIN_FACTS = 20_000

_IDEMPOTENT = ("minmax", "boolean", "expiration")

_EXP_FOREVER = 0xFFFF_FFFF_FFFF_FFFF


def supports(provenance) -> bool:
    return getattr(provenance, "name", None) in _IDEMPOTENT


def _encode_tags(provenance, tags) -> np.ndarray:
    name = provenance.name
    if name == "boolean":
        return np.asarray([1.0 if t else 0.0 for t in tags], dtype=np.float64)
    if name == "expiration":
        return np.asarray(
            [np.inf if t >= _EXP_FOREVER else float(t) for t in tags],
            dtype=np.float64,
        )
    return np.asarray(tags, dtype=np.float64)


def _decode_tags(provenance, vals: np.ndarray) -> list:
    """Vectorized inverse of :func:`_encode_tags` (shared by the single-chip
    and distributed write-backs)."""
    name = provenance.name
    if name == "boolean":
        return (vals > 0.5).tolist()
    if name == "expiration":
        return [
            _EXP_FOREVER if np.isinf(v) else int(round(v))
            for v in vals.tolist()
        ]
    return vals.tolist()


def _seed_tag_arrays(provenance, tag_store, keys) -> Tuple[np.ndarray, float]:
    """(tags0, one_enc) for a fact-key list: NaN = "no explicit TagStore
    entry" (premise reads see one(); the first derivation overwrites —
    update_disjunction parity).  Shared by both device drivers."""
    tget = tag_store.tags.get  # keys are plain (s, p, o) tuples
    host_tags = [tget(k) for k in keys]
    one = provenance.one()
    tags0 = np.where(
        [t is None for t in host_tags],
        np.nan,
        _encode_tags(
            provenance, [one if t is None else t for t in host_tags]
        ),
    )
    return tags0, float(_encode_tags(provenance, [one])[0])


# ---------------------------------------------------------------------------
# Jitted round
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("rules", "caps"))
def _prov_round(
    rules: tuple,
    caps: _Caps,
    fs,
    fp,
    fo,
    ftag,
    n_facts,
    ds,
    dp,
    do,
    dtag,
    n_delta,
    one_enc,
    masks,
):
    """One tagged semi-naive round.  Returns the updated fact columns/tags,
    the next delta (new ∪ changed facts, with their stored tags), the count
    of delta entries, and an overflow bitmask (bit0 join, bit1 delta cap,
    bit2 fact cap).  An overflowing round does not commit.

    Tag-store parity: ``ftag`` mirrors the host TagStore exactly — NaN
    means "no explicit entry" (premise reads see ``one_enc``), and a fact's
    FIRST derivation overwrites (``update_disjunction`` inserts the new tag
    when no entry exists, tag_store.py:47-49) while later derivations
    ⊕-merge with ``max``.  Delta tags (``dtag``) are effective values,
    never NaN."""
    import jax.numpy as jnp
    from jax import lax

    from kolibrie_tpu.ops.device_join import _LPAD, _RPAD, join_indices, pack2

    F, D, J = caps.fact, caps.delta, caps.join
    fvalid = jnp.arange(F, dtype=jnp.int32) < n_facts
    dvalid = jnp.arange(ds.shape[0], dtype=jnp.int32) < n_delta
    fcols = (fs, fp, fo)
    dcols = (ds, dp, do)

    overflow = np.int32(0)
    parts: List[tuple] = []  # (s, p, o, tag, valid) static-cap blocks
    for rule in rules:
        for order, keys in rule.plans:
            seed = order[0]
            table, m = _scan_premise(rule.premises[seed], dcols, dvalid)
            valid = m
            tag = dtag
            for step, j in enumerate(order[1:]):
                ptable, pm = _scan_premise(rule.premises[j], fcols, fvalid)
                kv = keys[step]
                lkey = _pack([table[v] for v in kv], valid, _LPAD)
                rkey = _pack([ptable[v] for v in kv], pm, _RPAD)
                li, ri, jvalid, total = join_indices(lkey, rkey, J)
                overflow = overflow | jnp.where(total > J, np.int32(1), 0)
                new_table = {}
                for v, c in table.items():
                    new_table[v] = c[li]
                for v, c in ptable.items():
                    if v not in new_table:
                        new_table[v] = c[ri]
                # ⊗ = min: a derivation is as strong as its weakest premise;
                # an absent (NaN) entry reads as one() for premises
                ptag = ftag[ri]
                ptag = jnp.where(jnp.isnan(ptag), one_enc, ptag)
                tag = jnp.minimum(tag[li], ptag)
                table, valid = new_table, jvalid
            valid = _eval_filters(rule, table, valid, masks)
            # zero-tag pruning (provenance_semi_naive.rs:171)
            valid = valid & (tag > 0.0)
            n = valid.shape[0]
            for concl in rule.concls:
                out = []
                for kind, v in concl:
                    if kind == "var":
                        out.append(table[v])
                    else:
                        out.append(jnp.full(n, v, dtype=jnp.uint32))
                parts.append((out[0], out[1], out[2], tag, valid))

    cs = jnp.concatenate([p[0] for p in parts])
    cp = jnp.concatenate([p[1] for p in parts])
    co = jnp.concatenate([p[2] for p in parts])
    ctag = jnp.concatenate([p[3] for p in parts])
    cv = jnp.concatenate([p[4] for p in parts])

    # group candidates by (s,p,o), each group's FIRST row carrying its max
    # tag: 4-key sort with -tag as the tie-breaking key (⊕ = max)
    sent = np.uint32(0xFFFFFFFF)
    ss = jnp.where(cv, cs, sent)
    sp = jnp.where(cv, cp, sent)
    so = jnp.where(cv, co, sent)
    stag = jnp.where(cv, ctag, 0.0)
    ss, sp, so, negtag = lax.sort((ss, sp, so, -stag), num_keys=4)
    utag = -negtag
    isnew = jnp.concatenate(
        [
            jnp.ones(1, bool),
            (ss[1:] != ss[:-1]) | (sp[1:] != sp[:-1]) | (so[1:] != so[:-1]),
        ]
    )
    isnew = isnew & (ss != sent)
    n_uniq = jnp.sum(isnew)
    overflow = overflow | jnp.where(n_uniq > D, np.int32(2), 0)
    dest = jnp.where(isnew, jnp.cumsum(isnew) - 1, D)
    us = jnp.zeros(D, jnp.uint32).at[dest].set(ss, mode="drop")
    up = jnp.zeros(D, jnp.uint32).at[dest].set(sp, mode="drop")
    uo = jnp.zeros(D, jnp.uint32).at[dest].set(so, mode="drop")
    ut = jnp.zeros(D, jnp.float64).at[dest].set(utag, mode="drop")
    uvalid = jnp.arange(D) < n_uniq

    # exact (s,p,o) → fact-index lookup: dense-rank the (s,p) pair over the
    # union, pack with o, binary-search the sorted fact keys
    fsp = pack2(jnp.where(fvalid, fs, sent), jnp.where(fvalid, fp, sent))
    usp = pack2(jnp.where(uvalid, us, sent), jnp.where(uvalid, up, sent))
    union = jnp.sort(jnp.concatenate([fsp, usp]))
    rank_f = jnp.searchsorted(union, fsp).astype(jnp.uint32)
    rank_u = jnp.searchsorted(union, usp).astype(jnp.uint32)
    fkey = pack2(rank_f, jnp.where(fvalid, fo, sent))
    ukey = pack2(rank_u, jnp.where(uvalid, uo, sent))
    forder = jnp.argsort(fkey)
    fsorted = fkey[forder]
    pos = jnp.clip(jnp.searchsorted(fsorted, ukey), 0, F - 1)
    found = uvalid & (fsorted[pos] == ukey)
    fidx = jnp.where(found, forder[pos], F)

    old_tag = ftag[jnp.clip(fidx, 0, F - 1)]
    # update_disjunction parity: no entry (NaN) → first derivation
    # OVERWRITES; an existing entry ⊕-merges (max), changed iff it grew
    absent = found & jnp.isnan(old_tag)
    improved = found & (ut > old_tag)  # NaN compares False
    changed = absent | improved
    fresh = uvalid & ~found

    # append new facts (tags included)
    n_new = jnp.sum(fresh)
    n_facts_next = n_facts + n_new
    overflow = overflow | jnp.where(n_facts_next > F, np.int32(4), 0)
    adest = jnp.where(fresh, n_facts + jnp.cumsum(fresh) - 1, F)
    nfs = fs.at[adest].set(us, mode="drop")
    nfp = fp.at[adest].set(up, mode="drop")
    nfo = fo.at[adest].set(uo, mode="drop")
    nftag = ftag.at[adest].set(ut, mode="drop")
    # in-place store for changed facts: overwrite when absent, else the
    # grown max (ut > old ⇒ max(old, ut) = ut in both cases)
    nftag = nftag.at[jnp.where(changed, fidx, F)].set(ut, mode="drop")

    # next delta = new ∪ changed facts, with their stored tags
    dmask = fresh | changed
    n_dnext = jnp.sum(dmask)
    ddest = jnp.where(dmask, jnp.cumsum(dmask) - 1, D)
    nds = jnp.zeros(D, jnp.uint32).at[ddest].set(us, mode="drop")
    ndp = jnp.zeros(D, jnp.uint32).at[ddest].set(up, mode="drop")
    ndo = jnp.zeros(D, jnp.uint32).at[ddest].set(uo, mode="drop")
    ndt = jnp.zeros(D, jnp.float64).at[ddest].set(ut, mode="drop")

    ok = overflow == 0

    def sel(new, old):
        return jnp.where(ok, new, old)

    # delta buffers are driver-padded to exactly D, so shapes line up
    return (
        sel(nfs, fs),
        sel(nfp, fp),
        sel(nfo, fo),
        sel(nftag, ftag),
        sel(n_facts_next, n_facts),
        sel(nds, ds),
        sel(ndp, dp),
        sel(ndo, do),
        sel(ndt, dtag),
        sel(n_dnext.astype(jnp.int32), np.int32(0)),
        overflow,
    )


# ---------------------------------------------------------------------------
# Host driver + integration
# ---------------------------------------------------------------------------


def infer_provenance_device(
    reasoner,
    provenance,
    tag_store,
    initial_delta: Optional[Set[Tuple[int, int, int]]] = None,
    max_attempts: int = 32,
) -> Optional[Dict[Tuple[int, int, int], float]]:
    """Run the tagged fixpoint on device; returns None for host fallback.

    On success the derived facts are appended to ``reasoner.facts`` and
    ``tag_store`` holds the final tags (exactly like the host path).
    """
    if not supports(provenance):
        return None
    if any(r.negative_premise for r in reasoner.rules):
        return None  # stratified NAF stays host-side
    try:
        rules, bank = lower_rules(reasoner, reasoner.rules)
    except Unsupported:
        return None
    if not rules:
        return None

    import jax.numpy as jnp

    s, p, o = reasoner.facts.columns()
    n0 = len(s)
    if n0 == 0:
        return None
    facts_keys = list(zip(s.tolist(), p.tolist(), o.tolist()))
    tags0, one_enc = _seed_tag_arrays(provenance, tag_store, facts_keys)

    masks = tuple(jnp.asarray(m) for m in bank.materialize()) or (
        jnp.zeros(1, dtype=bool),
    )

    # delta tags are EFFECTIVE values (absent resolves to one())
    eff0 = np.where(np.isnan(tags0), one_enc, tags0)
    if initial_delta is not None:
        key_to_idx = {k: i for i, k in enumerate(facts_keys)}
        didx = [key_to_idx[k] for k in initial_delta if k in key_to_idx]
        if not didx:
            return {}
        d_s = s[didx]
        d_p = p[didx]
        d_o = o[didx]
        d_t = eff0[didx]
    else:
        d_s, d_p, d_o, d_t = s, p, o, eff0
    nd0 = len(d_s)

    F = _round_cap(4 * n0, 2048)
    D = _round_cap(max(2 * nd0, n0 // 2, 1024))
    # start TIGHT: the candidate sort scales with J × plans, and the
    # overflow protocol doubles J cheaply when a round actually needs it
    J = _round_cap(max(nd0, 1024), 1024)

    with jax.enable_x64(True):

        def padu(x, cap):
            x = jnp.asarray(x, dtype=jnp.uint32)
            return jnp.concatenate(
                [x, jnp.zeros(cap - x.shape[0], dtype=jnp.uint32)]
            )

        def padf(x, cap):
            x = jnp.asarray(x, dtype=jnp.float64)
            return jnp.concatenate(
                [x, jnp.zeros(cap - x.shape[0], dtype=jnp.float64)]
            )

        fs, fp, fo = padu(s, F), padu(p, F), padu(o, F)
        ftag = padf(tags0, F)
        n_facts = n0
        dels, delp, delo = padu(d_s, D), padu(d_p, D), padu(d_o, D)
        delt = padf(d_t, D)
        n_delta = nd0
        attempts = 0
        for _round in range(10_000):
            out = _prov_round(
                rules,
                _Caps(F, D, J),
                fs,
                fp,
                fo,
                ftag,
                jnp.int32(n_facts),
                dels,
                delp,
                delo,
                delt,
                jnp.int32(n_delta),
                jnp.float64(one_enc),
                masks,
            )
            code = int(out[10])  # one sync per round
            if code != 0:
                attempts += 1
                if attempts > max_attempts:
                    return None  # graceful host fallback (state untouched)
                if code & 1:
                    J *= 2
                if code & 2:
                    D *= 2
                    dels, delp, delo = (
                        padu(dels, D),
                        padu(delp, D),
                        padu(delo, D),
                    )
                    delt = padf(delt, D)
                if code & 4:
                    newF = F * 2
                    fs, fp, fo = padu(fs, newF), padu(fp, newF), padu(fo, newF)
                    ftag = padf(ftag, newF)
                    F = newF
                continue  # retry the round (it did not commit)
            fs, fp, fo, ftag = out[0], out[1], out[2], out[3]
            n_facts = int(out[4])
            dels, delp, delo, delt = out[5], out[6], out[7], out[8]
            n_delta = int(out[9])
            if n_delta == 0:
                break
        else:
            return None  # round limit: graceful host fallback

        # write back: new facts into the store; every changed-or-new tag
        # entry into the tag store (vectorized — no per-fact Python loop).
        # Host parity: each derived fact gets an explicit entry
        # (update_disjunction inserts on first derivation); NaN still means
        # "no entry".
        fs_h = np.asarray(fs[:n_facts])
        fp_h = np.asarray(fp[:n_facts])
        fo_h = np.asarray(fo[:n_facts])
        ft_h = np.asarray(ftag[:n_facts])
        if n_facts > n0:
            reasoner.facts.add_batch(fs_h[n0:], fp_h[n0:], fo_h[n0:])
        has_entry = ~np.isnan(ft_h)
        unchanged = np.zeros(n_facts, dtype=bool)
        unchanged[:n0] = ~np.isnan(tags0) & (ft_h[:n0] == tags0)
        sel = np.flatnonzero(has_entry & ~unchanged)
        if sel.size:
            decoded = _decode_tags(provenance, ft_h[sel])
            keys = zip(
                fs_h[sel].tolist(), fp_h[sel].tolist(), fo_h[sel].tolist()
            )
            tag_store.tags.update(zip(keys, decoded))
    return {}
