"""SPARQL RULE integration: convert a parsed CombinedRule into an ID-space
datalog rule, run the appropriate inference, and materialize results into the
database.

Parity: ``kolibrie/src/parser.rs`` — ``convert_combined_rule`` (:2256-2436)
and ``process_rule_definition`` (:2439-2734): build a Reasoner over the
database's triples + probability seeds, run plain semi-naive for classical
rules or the PROB-selected provenance semiring (minmax/addmult/boolean/wmc/
sdd/topk) with RDF-star tag materialisation (with proof explanations for
wmc/sdd), apply the R2S stream operator, and insert derived facts.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from kolibrie_tpu.core.rule import FilterCondition, Rule
from kolibrie_tpu.core.terms import Term, TriplePattern
from kolibrie_tpu.core.triple import Triple
from kolibrie_tpu.query import ast as A
from kolibrie_tpu.reasoner.provenance import make_provenance
from kolibrie_tpu.reasoner.provenance_seminaive import infer_with_provenance
from kolibrie_tpu.reasoner.reasoner import Reasoner


def _convert_term(db, t: A.PatternTerm) -> Term:
    if t.kind == "var":
        return Term.variable(t.value)
    if t.kind == "quoted":
        s, p, o = t.value
        return Term.quoted(
            TriplePattern(_convert_term(db, s), _convert_term(db, p), _convert_term(db, o))
        )
    return Term.constant(db.dictionary.encode(db.expand_term(t.value)))


def _convert_pattern(db, p: A.PatternTriple) -> TriplePattern:
    return TriplePattern(
        _convert_term(db, p.subject),
        _convert_term(db, p.predicate),
        _convert_term(db, p.object),
    )


def _convert_filters(db, filters) -> List[FilterCondition]:
    out: List[FilterCondition] = []
    for f in filters:
        if not isinstance(f, A.Comparison):
            continue  # complex filters handled only on the query path
        if isinstance(f.left, A.Var):
            var, rhs, op = f.left.name, f.right, f.op
        elif isinstance(f.right, A.Var):
            flip = {"<": ">", ">": "<", "<=": ">=", ">=": "<="}
            var, rhs, op = f.right.name, f.left, flip.get(f.op, f.op)
        else:
            continue
        if isinstance(rhs, A.NumberLit):
            out.append(FilterCondition(var, op, float(rhs.value)))
        elif isinstance(rhs, A.IriRef):
            out.append(
                FilterCondition(var, op, db.dictionary.encode(db.expand_term(rhs.iri)))
            )
        elif isinstance(rhs, A.StringLit):
            out.append(FilterCondition(var, op, db.dictionary.encode(rhs.value)))
    return out


def convert_combined_rule(db, rule: A.CombinedRule) -> Rule:
    """AST rule -> ID-space datalog rule (parser.rs:2256 parity)."""
    premise = [_convert_pattern(db, p) for p in rule.body.patterns]
    negative = [
        _convert_pattern(db, p)
        for nb in rule.body.not_blocks
        for p in nb.patterns
    ]
    # window-block patterns are part of the body for the non-streaming path
    for wb in rule.body.window_blocks:
        premise.extend(_convert_pattern(db, p) for p in wb.patterns)
    return Rule(
        premise=premise,
        negative_premise=negative,
        filters=_convert_filters(db, rule.body.filters),
        conclusion=[_convert_pattern(db, c) for c in rule.conclusions],
    )


def build_reasoner_from_db(db) -> Reasoner:
    """Reasoner sharing the database dictionary, loaded with all triples and
    probability seeds (parser.rs:2499-2504)."""
    kg = Reasoner(db.dictionary)
    kg.quoted = db.quoted
    kg.facts = db.store.clone()
    kg.probability_seeds = dict(getattr(db, "probability_seeds", {}) or {})
    return kg


def process_combined_rule(db, rule: A.CombinedRule) -> Tuple[Rule, List[Triple]]:
    """Register + immediately apply a RULE definition
    (process_rule_definition parity)."""
    if db.neural_relations:
        # rule bodies referencing neural predicates materialize first
        # (parser.rs:2482 parity)
        from kolibrie_tpu.ml import runtime as ml_runtime
        from kolibrie_tpu.query.executor import collect_all_patterns

        ml_runtime.materialize_neural_relations_for_patterns(
            db, collect_all_patterns(rule.body)
        )
    kg = build_reasoner_from_db(db)
    dynamic_rule = convert_combined_rule(db, rule)
    db.rule_map[rule.name] = dynamic_rule

    if rule.ml_predict is not None:
        from kolibrie_tpu.ml import runtime as ml_runtime

        ml_runtime.execute_ml_predict(db, rule.ml_predict)
        kg.facts = db.store.clone()

    before = kg.facts.triples_set()

    if rule.prob is not None:
        prov = make_provenance(rule.prob.combination, rule.prob.k)
        kg.add_rule(dynamic_rule)
        tag_store = infer_with_provenance(kg, prov)
        # materialize << s p o >> prob:value tags into the database
        if rule.prob.combination in ("wmc", "sdd"):
            star: List[Triple] = []
            for (s, p, o), _tag in tag_store.items():
                star.extend(tag_store.explain_proofs(db, Triple(s, p, o)))
            star.extend(tag_store.encode_as_rdf_star(db))
        else:
            star = tag_store.encode_as_rdf_star(db)
        for t in star:
            db.store.add_triple(t)
        inferred = [
            Triple(*k) for k in kg.facts.triples_set() - before
        ]
    else:
        kg.add_rule(dynamic_rule)
        kg.infer_new_facts_semi_naive()
        inferred = [Triple(*k) for k in kg.facts.triples_set() - before]

    # R2S application (RSTREAM default emits everything; parser.rs:2577-2585)
    stream_type = rule.stream_type or A.StreamType.RSTREAM
    if stream_type == A.StreamType.RSTREAM:
        emitted = inferred
    elif stream_type == A.StreamType.ISTREAM:
        emitted = inferred  # nothing previously emitted at definition time
    else:  # DSTREAM at definition time emits nothing
        emitted = []
    for t in emitted:
        db.store.add_triple(t)
    return dynamic_rule, emitted
