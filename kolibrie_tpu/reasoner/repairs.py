"""Inconsistency-tolerant inference: repairs + constraint-guarded semi-naive.

Parity: ``datalog/src/reasoning/materialisation/semi_naive_with_repairs.rs``
(:11-73) — pre-repair the inconsistent base (largest repair wins), then run
semi-naive where each candidate inference is checked against the constraints
before commit — and ``reasoning/repairs.rs`` IAR querying (handled by
``Reasoner.query_with_repairs``).
"""

from __future__ import annotations

from typing import List

import numpy as np

from kolibrie_tpu.reasoner.strategies import (
    eval_rule_body,
    instantiate_conclusions,
    subtract_existing,
    table_len,
)


def infer_semi_naive_with_repairs(reasoner) -> int:
    # 1. pre-repair: if the base is inconsistent, replace it with the largest
    #    repair (semi_naive_with_repairs.rs:11-30)
    if reasoner.constraints and reasoner.violates_constraints():
        repairs = reasoner.compute_repairs()
        if repairs:
            best = max(repairs, key=len)
            reasoner.facts.clear()
            if best:
                arr = np.asarray(sorted(best), dtype=np.uint32)
                reasoner.facts.add_batch(arr[:, 0], arr[:, 1], arr[:, 2])
    # 2. semi-naive where each candidate batch is constraint-checked before
    #    commit; violating candidates are dropped individually
    total = 0
    s, p, o = reasoner.facts.columns()
    delta = (s, p, o)
    while len(delta[0]) > 0:
        accepted: List = []
        # one shared test set per round; accepted candidates stay in,
        # violating ones are removed again.  COPY: triples_set() returns the
        # store's per-version memo, which must stay unmutated.
        test = set(reasoner.facts.triples_set())
        for rule in reasoner.rules:
            table = eval_rule_body(reasoner, rule, reasoner.facts, delta=delta)
            if table_len(table) == 0:
                continue
            cols = instantiate_conclusions(rule, table, reasoner.quoted)
            cols = subtract_existing(reasoner.facts, cols)
            cs, cp, co = cols
            for i in range(len(cs)):
                cand = (int(cs[i]), int(cp[i]), int(co[i]))
                if cand in test:
                    continue
                test.add(cand)
                if reasoner.violates_constraints(test):
                    test.discard(cand)
                else:
                    accepted.append(cand)
        if not accepted:
            break
        arr = np.asarray(accepted, dtype=np.uint32)
        before = len(reasoner.facts)
        reasoner.facts.add_batch(arr[:, 0], arr[:, 1], arr[:, 2])
        added = len(reasoner.facts) - before
        if added == 0:
            break
        total += added
        delta = (arr[:, 0], arr[:, 1], arr[:, 2])
    return total
