"""Cross-window streaming reasoning: the RSP-QL Streaming Dataset (SDS) model
and its naive / incremental materialisation (SDS+).

Parity: ``datalog/src/cross_window_sds.rs`` — predicate annotation =
windowIRI+localName (:17-19), ``Sds{windows: WindowData{alpha, triples},
static_graphs, output_iris}`` (:45-59), ``translate_sds_to_datalog`` (alive
facts with expiry = event_time + α, static = u64::MAX, :82-122),
``translate_datalog_back`` / ``sds_with_expiry_to_external`` (:126-182) —
plus ``cross_window_naive.rs`` (full recomputation) and
``cross_window_incremental.rs`` (D_old = unexpired prior facts max-merged,
D_new = facts whose expiry improved, ExpirationProvenance TagStore, provenance
semi-naive with initial delta = D_new only).

The expiry tags are u64 columns under the Expiration semiring — the
device-friendliest semiring (min/max reductions on the VPU).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from kolibrie_tpu.core.dictionary import Dictionary
from kolibrie_tpu.core.rule import Rule
from kolibrie_tpu.core.triple import Triple
from kolibrie_tpu.reasoner.provenance import ExpirationProvenance
from kolibrie_tpu.reasoner.provenance_seminaive import (
    semi_naive_with_initial_tags_and_delta,
)
from kolibrie_tpu.reasoner.reasoner import Reasoner
from kolibrie_tpu.reasoner.tag_store import TagStore

U64_MAX = ExpirationProvenance.FOREVER

CROSS_WINDOW_STATIC_IRI = "urn:kolibrie:static:"


def annotate_predicate(window_iri: str, local_name: str) -> str:
    return window_iri + local_name


def strip_window_prefix(
    annotated: str, known_iris: List[str]
) -> Optional[Tuple[str, str]]:
    """Longest-prefix strip (caller passes IRIs sorted longest-first)."""
    for iri in known_iris:
        if annotated.startswith(iri):
            return iri, annotated[len(iri):]
    return None


@dataclass
class WindowedTriple:
    subject: str
    predicate: str  # LOCAL name under the owning window IRI
    object: str
    event_time: int


@dataclass
class WindowData:
    alpha: int  # window width
    triples: List[WindowedTriple] = field(default_factory=list)


@dataclass
class Sds:
    """An RSP-QL Streaming Dataset at a point in time."""

    windows: Dict[str, WindowData] = field(default_factory=dict)
    static_graphs: Dict[str, List[Tuple[str, str, str]]] = field(default_factory=dict)
    output_iris: Set[str] = field(default_factory=set)


def all_component_iris(sds: Sds) -> List[str]:
    iris = (
        list(sds.windows.keys())
        + list(sds.static_graphs.keys())
        + list(sds.output_iris)
    )
    iris.sort(key=len, reverse=True)
    return iris


def translate_sds_to_datalog(
    sds: Sds, dictionary: Dictionary, current_time: int
) -> List[Tuple[Triple, int]]:
    """Alive facts annotated with expiry; static facts get expiry = ∞."""
    out: List[Tuple[Triple, int]] = []
    enc = dictionary.encode
    pred_ids: Dict[Tuple[str, str], int] = {}  # (window, local) → encoded
    for window_iri, wd in sds.windows.items():
        for wt in wd.triples:
            expiry = wt.event_time + wd.alpha
            if expiry <= current_time:
                continue
            pkey = (window_iri, wt.predicate)
            pid = pred_ids.get(pkey)
            if pid is None:
                pid = enc(annotate_predicate(window_iri, wt.predicate))
                pred_ids[pkey] = pid
            out.append((Triple(enc(wt.subject), pid, enc(wt.object)), expiry))
    for graph_iri, triples in sds.static_graphs.items():
        for s, p, o in triples:
            out.append(
                (
                    Triple(enc(s), enc(annotate_predicate(graph_iri, p)), enc(o)),
                    U64_MAX,
                )
            )
    return out


def translate_datalog_back(
    facts: List[Triple], dictionary: Dictionary, sds: Sds
) -> Dict[str, List[Triple]]:
    """Strip window-IRI prefixes; route triples to component buckets.

    Distinct predicates are few; decode/strip/re-encode each once."""
    router = _PredicateRouter(dictionary, all_component_iris(sds))
    out: Dict[str, List[Triple]] = {}
    for t in facts:
        route = router.route(t.predicate)
        if route is None:
            continue
        comp, local_id = route
        out.setdefault(comp, []).append(Triple(t.subject, local_id, t.object))
    return out


_MISS = object()  # sentinel distinguishing "unseen predicate" from None


class _PredicateRouter:
    """Cached annotated-predicate-ID → (component IRI, local-name ID) map.

    The decode → longest-prefix strip → re-encode round trip runs once per
    DISTINCT predicate, not once per fact."""

    def __init__(self, dictionary: Dictionary, component_iris: List[str]):
        self._dictionary = dictionary
        self._component_iris = component_iris
        self._cache: Dict[int, Optional[Tuple[str, int]]] = {}

    def route(self, pred_id: int) -> Optional[Tuple[str, int]]:
        hit = self._cache.get(pred_id, _MISS)
        if hit is _MISS:
            pred = self._dictionary.decode(pred_id)
            stripped = (
                strip_window_prefix(pred, self._component_iris)
                if pred
                else None
            )
            hit = (
                (stripped[0], self._dictionary.encode(stripped[1]))
                if stripped is not None
                else None
            )
            self._cache[pred_id] = hit
        return hit


# Internal incremental state: component IRI -> {annotated triple -> expiry}
SdsWithExpiry = Dict[str, Dict[Tuple[int, int, int], int]]


def sds_with_expiry_to_external(
    internal: SdsWithExpiry, dictionary: Dictionary, component_iris: List[str]
) -> Dict[str, List[Triple]]:
    router = _PredicateRouter(dictionary, component_iris)
    out: Dict[str, List[Triple]] = {}
    for comp, fact_map in internal.items():
        for key in fact_map:
            hit = router.route(key[1])
            if hit is None:
                continue
            _, local_id = hit
            out.setdefault(comp, []).append(Triple(key[0], local_id, key[2]))
    return out


def naive_sds_plus(
    rules: List[Rule], sds: Sds, dictionary: Dictionary, current_time: int
) -> Dict[str, List[Triple]]:
    """Full SDS+ recomputation (cross_window_naive.rs:20-43)."""
    annotated = translate_sds_to_datalog(sds, dictionary, current_time)
    reasoner = Reasoner(dictionary)
    if annotated:
        arr = np.array([tuple(t) for t, _ in annotated], dtype=np.uint32)
        reasoner.facts.add_batch(arr[:, 0], arr[:, 1], arr[:, 2])
    for rule in rules:
        reasoner.add_rule(rule)
    reasoner.infer_new_facts_semi_naive()
    all_facts = [Triple(*k) for k in reasoner.facts.triples_set()]
    return translate_datalog_back(all_facts, dictionary, sds)


def incremental_sds_plus(
    rules: List[Rule],
    sds_current: Sds,
    sds_plus_old: SdsWithExpiry,
    dictionary: Dictionary,
    current_time: int,
) -> SdsWithExpiry:
    """Incremental SDS+ maintenance (cross_window_incremental.rs:26-110).

    D_old = unexpired prior facts (max-merged over components);
    D_new = current facts whose expiry improved on the prior state;
    run expiration-provenance semi-naive with initial delta = D_new ONLY.
    """
    d_base = translate_sds_to_datalog(sds_current, dictionary, current_time)

    d_old_map: Dict[Tuple[int, int, int], int] = {}
    for fact_map in sds_plus_old.values():
        for key, expiry in fact_map.items():
            if expiry > current_time:
                prev = d_old_map.get(key)
                if prev is None or prev < expiry:
                    d_old_map[key] = expiry

    d_new: List[Tuple[Triple, int]] = [
        (t, e)
        for t, e in d_base
        if d_old_map.get(tuple(t), -1) < e
    ]

    reasoner = Reasoner(dictionary)
    all_keys = list(d_old_map) + [tuple(t) for t, _ in d_new]
    if all_keys:
        arr = np.array(all_keys, dtype=np.uint32)
        reasoner.facts.add_batch(arr[:, 0], arr[:, 1], arr[:, 2])
    for rule in rules:
        reasoner.add_rule(rule)

    prov = ExpirationProvenance()
    initial_tags = TagStore(prov)
    tags = initial_tags.tags  # direct dict access in the per-fact loops
    for key, e in d_old_map.items():
        if e < U64_MAX:
            tags[key] = e
    for t, e in d_new:
        if e < U64_MAX:
            # a re-arrival may improve expiry over D_old
            key = tuple(t)
            old = tags.get(key)
            tags[key] = e if old is None else max(old, e)

    delta = {tuple(t) for t, _ in d_new}
    tag_store = semi_naive_with_initial_tags_and_delta(
        reasoner, prov, initial_tags, delta
    )

    router = _PredicateRouter(dictionary, all_component_iris(sds_current))
    result: SdsWithExpiry = {}
    final_tags = tag_store.tags
    for key in reasoner.facts.triples_set():
        hit = router.route(key[1])
        if hit is None:
            continue
        expiry = final_tags.get(key)
        if expiry is None:
            expiry = U64_MAX
        result.setdefault(hit[0], {})[key] = expiry
    return result
