"""Cross-window streaming reasoning: the RSP-QL Streaming Dataset (SDS) model
and its naive / incremental materialisation (SDS+).

Parity: ``datalog/src/cross_window_sds.rs`` — predicate annotation =
windowIRI+localName (:17-19), ``Sds{windows: WindowData{alpha, triples},
static_graphs, output_iris}`` (:45-59), ``translate_sds_to_datalog`` (alive
facts with expiry = event_time + α, static = u64::MAX, :82-122),
``translate_datalog_back`` / ``sds_with_expiry_to_external`` (:126-182) —
plus ``cross_window_naive.rs`` (full recomputation) and
``cross_window_incremental.rs`` (D_old = unexpired prior facts max-merged,
D_new = facts whose expiry improved, ExpirationProvenance TagStore, provenance
semi-naive with initial delta = D_new only).

The expiry tags are u64 columns under the Expiration semiring — the
device-friendliest semiring (min/max reductions on the VPU).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from kolibrie_tpu.core.dictionary import Dictionary
from kolibrie_tpu.core.rule import Rule
from kolibrie_tpu.core.triple import Triple
from kolibrie_tpu.reasoner.provenance import ExpirationProvenance
from kolibrie_tpu.reasoner.provenance_seminaive import (
    semi_naive_with_initial_tags_and_delta,
)
from kolibrie_tpu.reasoner.reasoner import Reasoner
from kolibrie_tpu.reasoner.tag_store import TagStore

U64_MAX = ExpirationProvenance.FOREVER

CROSS_WINDOW_STATIC_IRI = "urn:kolibrie:static:"


def annotate_predicate(window_iri: str, local_name: str) -> str:
    return window_iri + local_name


def strip_window_prefix(
    annotated: str, known_iris: List[str]
) -> Optional[Tuple[str, str]]:
    """Longest-prefix strip (caller passes IRIs sorted longest-first)."""
    for iri in known_iris:
        if annotated.startswith(iri):
            return iri, annotated[len(iri):]
    return None


@dataclass
class WindowedTriple:
    subject: str
    predicate: str  # LOCAL name under the owning window IRI
    object: str
    event_time: int
    # per-object encode memo: (dictionary, s_id, p_id, o_id) — re-translating
    # a long-lived window costs attribute reads, not dictionary lookups
    _enc: Optional[tuple] = field(
        default=None, repr=False, compare=False
    )


@dataclass
class WindowData:
    alpha: int  # window width
    triples: List[WindowedTriple] = field(default_factory=list)


@dataclass
class Sds:
    """An RSP-QL Streaming Dataset at a point in time."""

    windows: Dict[str, WindowData] = field(default_factory=dict)
    static_graphs: Dict[str, List[Tuple[str, str, str]]] = field(default_factory=dict)
    output_iris: Set[str] = field(default_factory=set)


def all_component_iris(sds: Sds) -> List[str]:
    iris = (
        list(sds.windows.keys())
        + list(sds.static_graphs.keys())
        + list(sds.output_iris)
    )
    iris.sort(key=len, reverse=True)
    return iris


def _window_columns(window_iri: str, wd: WindowData, dictionary: Dictionary):
    """Encoded (s, p, o, event_time) columns for one window.

    The per-triple ``_enc`` memo (keyed by dictionary AND window, since the
    annotated predicate depends on the owning window) means only new
    arrivals pay dictionary lookups; event times are read fresh each call
    so in-place time updates are always honored."""
    triples = wd.triples
    n = len(triples)
    enc = dictionary.encode
    pred_ids: Dict[str, int] = {}
    s = np.empty(n, dtype=np.uint32)
    p = np.empty(n, dtype=np.uint32)
    o = np.empty(n, dtype=np.uint32)
    et = np.empty(n, dtype=np.int64)
    for i, wt in enumerate(triples):
        e = wt._enc
        if e is None or e[0] is not dictionary or e[1] != window_iri:
            pid = pred_ids.get(wt.predicate)
            if pid is None:
                pid = enc(annotate_predicate(window_iri, wt.predicate))
                pred_ids[wt.predicate] = pid
            e = (dictionary, window_iri, enc(wt.subject), pid, enc(wt.object))
            wt._enc = e
        s[i], p[i], o[i] = e[2], e[3], e[4]
        et[i] = wt.event_time
    return s, p, o, et


def translate_sds_to_arrays(
    sds: Sds, dictionary: Dictionary, current_time: int
):
    """Vectorized SDS translation: alive facts as (s, p, o, expiry) u32/u64
    columns (the columnar twin of :func:`translate_sds_to_datalog`)."""
    parts = []
    for window_iri, wd in sds.windows.items():
        s, p, o, et = _window_columns(window_iri, wd, dictionary)
        alpha = int(wd.alpha)
        if alpha >= 1 << 62:
            # "forever" window (u64::MAX-style alpha): saturate instead of
            # overflowing int64 arithmetic
            expiry = np.full(len(et), U64_MAX, dtype=np.uint64)
            alive = np.ones(len(et), dtype=bool)
        else:
            exp64 = et + np.int64(alpha)  # event times are < 2^62
            alive = exp64 > current_time
            expiry = exp64.astype(np.uint64)
        parts.append((s[alive], p[alive], o[alive], expiry[alive]))
    enc = dictionary.encode
    for graph_iri, triples in sds.static_graphs.items():
        if not triples:
            continue
        n = len(triples)
        gs = np.fromiter((enc(t[0]) for t in triples), np.uint32, count=n)
        gp = np.fromiter(
            (enc(annotate_predicate(graph_iri, t[1])) for t in triples),
            np.uint32,
            count=n,
        )
        go = np.fromiter((enc(t[2]) for t in triples), np.uint32, count=n)
        parts.append((gs, gp, go, np.full(n, U64_MAX, dtype=np.uint64)))
    if not parts:
        z = np.empty(0, dtype=np.uint32)
        return z, z, z, np.empty(0, dtype=np.uint64)
    return (
        np.concatenate([x[0] for x in parts]),
        np.concatenate([x[1] for x in parts]),
        np.concatenate([x[2] for x in parts]),
        np.concatenate([x[3] for x in parts]),
    )


def translate_sds_to_datalog(
    sds: Sds, dictionary: Dictionary, current_time: int
) -> List[Tuple[Triple, int]]:
    """Alive facts annotated with expiry; static facts get expiry = ∞."""
    out: List[Tuple[Triple, int]] = []
    enc = dictionary.encode
    pred_ids: Dict[Tuple[str, str], int] = {}  # (window, local) → encoded
    for window_iri, wd in sds.windows.items():
        for wt in wd.triples:
            expiry = wt.event_time + wd.alpha
            if expiry <= current_time:
                continue
            pkey = (window_iri, wt.predicate)
            pid = pred_ids.get(pkey)
            if pid is None:
                pid = enc(annotate_predicate(window_iri, wt.predicate))
                pred_ids[pkey] = pid
            out.append((Triple(enc(wt.subject), pid, enc(wt.object)), expiry))
    for graph_iri, triples in sds.static_graphs.items():
        for s, p, o in triples:
            out.append(
                (
                    Triple(enc(s), enc(annotate_predicate(graph_iri, p)), enc(o)),
                    U64_MAX,
                )
            )
    return out


def translate_datalog_back(
    facts: List[Triple], dictionary: Dictionary, sds: Sds
) -> Dict[str, List[Triple]]:
    """Strip window-IRI prefixes; route triples to component buckets.

    Distinct predicates are few; decode/strip/re-encode each once."""
    router = _PredicateRouter(dictionary, all_component_iris(sds))
    out: Dict[str, List[Triple]] = {}
    for t in facts:
        route = router.route(t.predicate)
        if route is None:
            continue
        comp, local_id = route
        out.setdefault(comp, []).append(Triple(t.subject, local_id, t.object))
    return out


_MISS = object()  # sentinel distinguishing "unseen predicate" from None


class _PredicateRouter:
    """Cached annotated-predicate-ID → (component IRI, local-name ID) map.

    The decode → longest-prefix strip → re-encode round trip runs once per
    DISTINCT predicate, not once per fact."""

    def __init__(self, dictionary: Dictionary, component_iris: List[str]):
        self._dictionary = dictionary
        self._component_iris = component_iris
        self._cache: Dict[int, Optional[Tuple[str, int]]] = {}

    def route(self, pred_id: int) -> Optional[Tuple[str, int]]:
        hit = self._cache.get(pred_id, _MISS)
        if hit is _MISS:
            pred = self._dictionary.decode(pred_id)
            stripped = (
                strip_window_prefix(pred, self._component_iris)
                if pred
                else None
            )
            hit = (
                (stripped[0], self._dictionary.encode(stripped[1]))
                if stripped is not None
                else None
            )
            self._cache[pred_id] = hit
        return hit


# Internal incremental state: component IRI -> {annotated triple -> expiry}
SdsWithExpiry = Dict[str, Dict[Tuple[int, int, int], int]]


def sds_with_expiry_to_external(
    internal: SdsWithExpiry, dictionary: Dictionary, component_iris: List[str]
) -> Dict[str, List[Triple]]:
    router = _PredicateRouter(dictionary, component_iris)
    out: Dict[str, List[Triple]] = {}
    for comp, fact_map in internal.items():
        for key in fact_map:
            hit = router.route(key[1])
            if hit is None:
                continue
            _, local_id = hit
            out.setdefault(comp, []).append(Triple(key[0], local_id, key[2]))
    return out


def naive_sds_plus(
    rules: List[Rule], sds: Sds, dictionary: Dictionary, current_time: int
) -> Dict[str, List[Triple]]:
    """Full SDS+ recomputation (cross_window_naive.rs:20-43)."""
    s, p, o, _exp = translate_sds_to_arrays(sds, dictionary, current_time)
    reasoner = Reasoner(dictionary)
    if len(s):
        reasoner.facts.add_batch(s, p, o)
    for rule in rules:
        reasoner.add_rule(rule)
    reasoner.infer_new_facts_semi_naive()
    all_facts = [Triple(*k) for k in reasoner.facts.triples_set()]
    return translate_datalog_back(all_facts, dictionary, sds)


class SdsPlusState(dict):
    """An ``SdsWithExpiry`` result that carries its own columnar mirror
    ``(s, p, o, expiry)`` so the NEXT incremental call's D_old handling is
    vectorized instead of re-walking the dicts."""

    arrays = None  # (s u32, p u32, o u32, expiry u64)


class _OverlayTags(dict):
    """Tag map whose misses fall back to the prior state's component maps
    (max-merged D_old semantics).  The fixpoint reads via ``.get`` and
    writes normal items, so this dict's OWN entries are exactly the facts
    whose tags were seeded or changed this cycle — the incremental result
    update set."""

    def __init__(self, prior_maps):
        super().__init__()
        self._prior = prior_maps

    def get(self, key, default=None):
        if dict.__contains__(self, key):
            return dict.__getitem__(self, key)
        best = None
        for m in self._prior:
            e = m.get(key)
            if e is not None and (best is None or e > best):
                best = e
        return default if best is None else best


def _state_arrays(sds_plus_old: SdsWithExpiry):
    """(s, p, o, expiry) columns of a prior state (cached on SdsPlusState)."""
    arrays = getattr(sds_plus_old, "arrays", None)
    if arrays is not None:
        return arrays
    n = sum(len(m) for m in sds_plus_old.values())
    s = np.empty(n, dtype=np.uint32)
    p = np.empty(n, dtype=np.uint32)
    o = np.empty(n, dtype=np.uint32)
    exp = np.empty(n, dtype=np.uint64)
    i = 0
    for fact_map in sds_plus_old.values():
        for (ks, kp, ko), e in fact_map.items():
            s[i], p[i], o[i], exp[i] = ks, kp, ko, e
            i += 1
    return _dedup_max_expiry(s, p, o, exp)


def _pack3(s, p, o):
    """Exact two-u64 lex key for (s, p, o) u32 rows."""
    return (s.astype(np.uint64) << np.uint64(32)) | p.astype(np.uint64), o


def _dedup_max_expiry(s, p, o, exp):
    """Sort rows by (s, p, o) keeping the MAX expiry per distinct triple."""
    if len(s) == 0:
        return s, p, o, exp
    order = np.lexsort((exp, o, p, s))
    s, p, o, exp = s[order], p[order], o[order], exp[order]
    # groups are contiguous; last of each group has the max expiry
    last = np.ones(len(s), dtype=bool)
    last[:-1] = (s[1:] != s[:-1]) | (p[1:] != p[:-1]) | (o[1:] != o[:-1])
    return s[last], p[last], o[last], exp[last]


def _lookup_expiry(os_, op_, oo_, oexp, cs, cp, co):
    """Vectorized per-row lookup of current rows in the (sorted, deduped)
    old columns; returns (found mask, old expiry where found else 0)."""
    if len(os_) == 0 or len(cs) == 0:
        z = np.zeros(len(cs), dtype=np.uint64)
        return np.zeros(len(cs), dtype=bool), z
    k1o, k2o = _pack3(os_, op_, oo_)
    k1c, k2c = _pack3(cs, cp, co)
    lo = np.searchsorted(k1o, k1c, side="left")
    hi = np.searchsorted(k1o, k1c, side="right")
    # refine on o within each (s, p) run: runs are sorted by o
    found = np.zeros(len(cs), dtype=bool)
    old_e = np.zeros(len(cs), dtype=np.uint64)
    narrow = hi - lo
    # common case: unique (s, p) per row -> fully vectorized equality
    one = narrow == 1
    if one.any():
        pos = lo[one]
        eq = k2o[pos] == k2c[one]
        found_idx = np.flatnonzero(one)
        found[found_idx[eq]] = True
        old_e[found_idx[eq]] = oexp[pos[eq]]
    multi = np.flatnonzero(narrow > 1)
    for i in multi:
        sub = k2o[lo[i] : hi[i]]
        j = int(np.searchsorted(sub, k2c[i]))
        if j < len(sub) and sub[j] == k2c[i]:
            found[i] = True
            old_e[i] = oexp[lo[i] + j]
    return found, old_e


def incremental_sds_plus(
    rules: List[Rule],
    sds_current: Sds,
    sds_plus_old: SdsWithExpiry,
    dictionary: Dictionary,
    current_time: int,
) -> SdsWithExpiry:
    """Incremental SDS+ maintenance (cross_window_incremental.rs:26-110).

    D_old = unexpired prior facts (max-merged over components);
    D_new = current facts whose expiry improved on the prior state;
    run expiration-provenance semi-naive with initial delta = D_new ONLY.

    All O(state) bookkeeping is vectorized (columnar D_old carried on
    :class:`SdsPlusState`, membership via packed-key binary search, tag
    fallback instead of tag pre-seeding), so the per-cycle cost tracks the
    UPDATE volume plus one C-speed state carry — the asymmetry that makes
    incremental beat naive at low update ratios.
    """
    t = np.uint64(current_time)
    cs, cp, co, cexp = translate_sds_to_arrays(
        sds_current, dictionary, current_time
    )
    os_, op_, oo_, oexp = _state_arrays(sds_plus_old)
    alive = oexp > t
    os_, op_, oo_, oexp = os_[alive], op_[alive], oo_[alive], oexp[alive]

    # D_new: current facts absent from D_old or with improved expiry
    found, old_e = _lookup_expiry(os_, op_, oo_, oexp, cs, cp, co)
    is_new = ~found | (cexp > old_e)
    ds, dp, do_, dexp = cs[is_new], cp[is_new], co[is_new], cexp[is_new]

    reasoner = Reasoner(dictionary)
    if len(os_) or len(ds):
        reasoner.facts.add_batch(
            np.concatenate([os_, ds]),
            np.concatenate([op_, dp]),
            np.concatenate([oo_, do_]),
        )
    for rule in rules:
        reasoner.add_rule(rule)

    prov = ExpirationProvenance()
    prior_maps = list(sds_plus_old.values())
    overlay = _OverlayTags(prior_maps)
    initial_tags = TagStore(prov)
    initial_tags.tags = overlay
    # seed ONLY the update set (D_old reads go through the fallback)
    for ks, kp, ko, e in zip(
        ds.tolist(), dp.tolist(), do_.tolist(), dexp.tolist()
    ):
        key = (ks, kp, ko)
        old = overlay.get(key)
        overlay[key] = e if old is None else max(old, e)

    delta = set(zip(ds.tolist(), dp.tolist(), do_.tolist()))
    semi_naive_with_initial_tags_and_delta(
        reasoner, prov, initial_tags, delta
    )  # effects land in `overlay` (initial_tags.tags)

    # result = carried prior state (expired pruned) + this cycle's overlay
    router = _PredicateRouter(dictionary, all_component_iris(sds_current))
    result = SdsPlusState()
    for comp, fact_map in sds_plus_old.items():
        carried = {k: e for k, e in fact_map.items() if e > current_time}
        if carried:
            result[comp] = carried
    # ROUTED overlay entries only, so the columnar mirror stays an exact
    # mirror of the dict state (unroutable intermediates are dropped from
    # both, as in the reference)
    routed: List[Tuple[Tuple[int, int, int], int]] = []
    for key, expiry in overlay.items():
        hit = router.route(key[1])
        if hit is not None:
            result.setdefault(hit[0], {})[key] = expiry
            routed.append((key, expiry))
    touched_s = np.empty(len(routed), dtype=np.uint32)
    touched_p = np.empty(len(routed), dtype=np.uint32)
    touched_o = np.empty(len(routed), dtype=np.uint32)
    touched_e = np.empty(len(routed), dtype=np.uint64)
    for i, (key, expiry) in enumerate(routed):
        touched_s[i], touched_p[i], touched_o[i] = key
        touched_e[i] = expiry
    # columnar mirror for the NEXT cycle: old-alive rows superseded by the
    # overlay where both exist (overlay expiries are >= by construction)
    result.arrays = _dedup_max_expiry(
        np.concatenate([os_, touched_s]),
        np.concatenate([op_, touched_p]),
        np.concatenate([oo_, touched_o]),
        np.concatenate([oexp, touched_e]),
    )
    return result
