"""Sentential Decision Diagram engine with a right-linear vtree.

Parity: ``shared/src/sdd.rs`` — arena ``SddManager`` with unique table +
apply/negate caches (:85-167), compression (:276-352), ``apply`` (:390-500),
``negate`` (:598-620), ``wmc`` (:623-655), ``enumerate_models`` (:661-692),
``exactly_one`` annotated-disjunction encoding (:175-193), ``VarKind``
Independent/ExclusiveGroup with separate pos/neg literal weights (:75-79,
125-167), and ``SddProvenance`` (tags = node IDs, :705-777).

An SDD over a right-linear vtree is structurally an ordered decision diagram,
so the manager is implemented as a reduced OBDD arena: decision nodes
``(var, hi, lo)`` hash-consed in a unique table.  WMC applies the
(w_pos + w_neg) correction for variables skipped between decision levels so
ExclusiveGroup weights (pos=p_i, neg=1) count correctly.

This pointer-chasing structure is inherently host-side (SURVEY §7 "hard
parts"); the TPU sees only the resulting probabilities/gradients.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

FALSE = 0
TRUE = 1


@dataclass
class VarInfo:
    """Weight + grouping info for one SDD variable."""

    index: int  # decision order
    w_pos: float
    w_neg: float
    kind: str = "independent"  # "independent" | "exclusive"
    group_id: Optional[int] = None
    seed_id: Optional[int] = None


class SddManager:
    """Hash-consed decision-diagram arena."""

    def __init__(self) -> None:
        # nodes[i] = (var, hi, lo); ids 0/1 reserved for terminals
        self.nodes: List[Tuple[int, int, int]] = [(-1, 0, 0), (-1, 1, 1)]
        self.unique: Dict[Tuple[int, int, int], int] = {}
        self.apply_cache: Dict[Tuple[int, int, str], int] = {}
        self.negate_cache: Dict[int, int] = {}
        self.vars: List[VarInfo] = []
        self._group_members: Dict[int, List[int]] = {}

    # ------------------------------------------------------------ variables

    def new_var(
        self,
        w_pos: float = 0.5,
        w_neg: Optional[float] = None,
        kind: str = "independent",
        group_id: Optional[int] = None,
        seed_id: Optional[int] = None,
    ) -> int:
        """Allocate a variable; returns its var index (decision order)."""
        idx = len(self.vars)
        if w_neg is None:
            w_neg = 1.0 - w_pos if kind == "independent" else 1.0
        self.vars.append(VarInfo(idx, w_pos, w_neg, kind, group_id, seed_id))
        if group_id is not None:
            self._group_members.setdefault(group_id, []).append(idx)
        return idx

    def literal(self, var: int, positive: bool = True) -> int:
        if positive:
            return self._mk(var, TRUE, FALSE)
        return self._mk(var, FALSE, TRUE)

    # ---------------------------------------------------------- construction

    def _mk(self, var: int, hi: int, lo: int) -> int:
        if hi == lo:  # trimming rule
            return hi
        key = (var, hi, lo)
        nid = self.unique.get(key)
        if nid is None:
            nid = len(self.nodes)
            self.nodes.append(key)
            self.unique[key] = nid
        return nid

    def _var_of(self, nid: int) -> int:
        return self.nodes[nid][0]

    def apply(self, a: int, b: int, op: str) -> int:
        """op in {"and", "or"} — O(|a||b|) with memoization (sdd.rs:390)."""
        if op == "and":
            if a == FALSE or b == FALSE:
                return FALSE
            if a == TRUE:
                return b
            if b == TRUE:
                return a
        else:
            if a == TRUE or b == TRUE:
                return TRUE
            if a == FALSE:
                return b
            if b == FALSE:
                return a
        if a == b:
            return a
        if a > b:
            a, b = b, a
        key = (a, b, op)
        hit = self.apply_cache.get(key)
        if hit is not None:
            return hit
        va, vb = self._var_of(a), self._var_of(b)
        if va == vb:
            _, ahi, alo = self.nodes[a]
            _, bhi, blo = self.nodes[b]
            res = self._mk(va, self.apply(ahi, bhi, op), self.apply(alo, blo, op))
        elif va < vb:
            _, ahi, alo = self.nodes[a]
            res = self._mk(va, self.apply(ahi, b, op), self.apply(alo, b, op))
        else:
            _, bhi, blo = self.nodes[b]
            res = self._mk(vb, self.apply(a, bhi, op), self.apply(a, blo, op))
        self.apply_cache[key] = res
        return res

    def conjoin(self, a: int, b: int) -> int:
        return self.apply(a, b, "and")

    def disjoin(self, a: int, b: int) -> int:
        return self.apply(a, b, "or")

    def negate(self, a: int) -> int:
        """O(|SDD|) with caching (sdd.rs:598)."""
        if a == FALSE:
            return TRUE
        if a == TRUE:
            return FALSE
        hit = self.negate_cache.get(a)
        if hit is not None:
            return hit
        var, hi, lo = self.nodes[a]
        res = self._mk(var, self.negate(hi), self.negate(lo))
        self.negate_cache[a] = res
        self.negate_cache[res] = a
        return res

    def exactly_one(self, var_indices: List[int]) -> int:
        """Annotated-disjunction constraint: exactly one of the variables is
        true (sdd.rs:175-193)."""
        result = FALSE
        for chosen in var_indices:
            term = TRUE
            for v in var_indices:
                term = self.conjoin(term, self.literal(v, v == chosen))
            result = self.disjoin(result, term)
        return result

    # ------------------------------------------------------------------ WMC

    def wmc(self, nid: int) -> float:
        """Weighted model count over ALL allocated variables (sdd.rs:623).

        Skipped decision levels contribute (w_pos + w_neg) each; for
        independent vars that is 1 so only exclusive-group weights need it.
        """
        n_vars = len(self.vars)
        memo: Dict[int, float] = {}

        def level_weight(lo_level: int, hi_level: int) -> float:
            w = 1.0
            for v in range(lo_level, hi_level):
                vi = self.vars[v]
                w *= vi.w_pos + vi.w_neg
            return w

        def rec(node: int) -> Tuple[float, int]:
            """Returns (wmc below this node incl. its level, node's level)."""
            if node == TRUE:
                return 1.0, n_vars
            if node == FALSE:
                return 0.0, n_vars
            if node in memo:
                return memo[node], self._var_of(node)
            var, hi, lo = self.nodes[node]
            vi = self.vars[var]
            whi, lhi = rec(hi)
            wlo, llo = rec(lo)
            val = vi.w_pos * whi * level_weight(var + 1, lhi) + vi.w_neg * wlo * level_weight(var + 1, llo)
            memo[node] = val
            return val, var
        val, lvl = rec(nid)
        return val * level_weight(0, lvl)

    def set_weight(self, var: int, w_pos: float, w_neg: Optional[float] = None):
        vi = self.vars[var]
        vi.w_pos = w_pos
        if w_neg is not None:
            vi.w_neg = w_neg
        elif vi.kind == "independent":
            vi.w_neg = 1.0 - w_pos

    # ----------------------------------------------------- model enumeration

    def enumerate_models(self, nid: int, limit: int = 1000) -> List[Dict[int, bool]]:
        """Paths to TRUE as partial assignments var->bool (sdd.rs:661) —
        used for proof-path explanations."""
        out: List[Dict[int, bool]] = []

        def walk(node: int, assignment: Dict[int, bool]):
            if len(out) >= limit:
                return
            if node == FALSE:
                return
            if node == TRUE:
                out.append(dict(assignment))
                return
            var, hi, lo = self.nodes[node]
            assignment[var] = True
            walk(hi, assignment)
            assignment[var] = False
            walk(lo, assignment)
            del assignment[var]

        walk(nid, {})
        return out

    def size(self, nid: int) -> int:
        seen = set()

        def walk(n):
            if n in (TRUE, FALSE) or n in seen:
                return
            seen.add(n)
            _, hi, lo = self.nodes[n]
            walk(hi)
            walk(lo)

        walk(nid)
        return len(seen)


def make_sdd_manager():
    """SddManager factory: native C++ engine when available (the
    neurosymbolic training hot path), pure-Python otherwise.  Both expose
    the identical interface and node semantics (tests/test_native.py)."""
    try:
        from kolibrie_tpu.native.sdd_native import NativeSddManager

        return NativeSddManager()
    except (ImportError, RuntimeError):
        return SddManager()


class SddProvenance:
    """Provenance semiring with SDD-node tags (sdd.rs:705-777)."""

    name = "sdd"

    def __init__(self, manager: Optional[SddManager] = None):
        self.manager = manager if manager is not None else make_sdd_manager()
        self.seed_vars: Dict[int, int] = {}  # seed_id -> var index

    def zero(self):
        return FALSE

    def one(self):
        return TRUE

    def disjunction(self, a, b):
        return self.manager.disjoin(a, b)

    def conjunction(self, a, b):
        return self.manager.conjoin(a, b)

    def negate(self, a):
        return self.manager.negate(a)

    def saturate(self, a):
        return a

    def is_saturated(self, a):
        return a == TRUE

    def tag_from_probability(self, p: float):
        var = self.manager.new_var(w_pos=p)
        return self.manager.literal(var, True)

    def tag_from_probability_with_id(self, p: float, seed_id: int):
        var = self.seed_vars.get(seed_id)
        if var is None:
            var = self.manager.new_var(w_pos=p, seed_id=seed_id)
            self.seed_vars[seed_id] = var
        else:
            self.manager.set_weight(var, p)
        return self.manager.literal(var, True)

    def recover_probability(self, tag) -> float:
        return self.manager.wmc(tag)

    def tag_eq(self, a, b) -> bool:
        return a == b

    def is_zero(self, tag) -> bool:
        return tag == FALSE
