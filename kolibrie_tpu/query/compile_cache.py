"""Persistent XLA compilation cache management — kill the restart tail.

Every new template shape pays one XLA compile (PERF_r06: 567 ms cold vs
3.8 ms warm on CPU; far worse on real chips).  Within a process the jit
entry points (``_run_plan`` & friends) memoize by ``PlanSpec``, but a
restarted replica — or a fresh member of a replica fleet sharing a data
volume — used to recompile every template from scratch.  This module
turns on JAX's persistent compilation cache and scopes it so the disk
artifacts are shared exactly as widely as they are valid:

- **Location**: ``$KOLIBRIE_COMPILE_CACHE_DIR``, else
  ``<data_dir>/compile_cache`` where ``data_dir`` is the durability root
  (``$KOLIBRIE_DATA_DIR`` for the HTTP server).  No directory → cache
  stays off (library embedders opt in explicitly).
- **Keying**: entries are namespaced under
  ``<root>/<jax-version>-<backend>/`` so a jax upgrade or a backend
  switch (cpu ↔ tpu) never replays a stale binary.  *Within* the
  namespace the key is XLA's own hash of the lowered HLO — and because
  the engine's jit entry points take the constant-free ``PlanSpec`` as
  their static argument (the parameter-vector ABI), that HLO is a pure
  function of (template fingerprint, mesh signature, store shape
  buckets).  Two replicas that ever lower the same template shape hash
  to the same entry; constants never leak into the key.
- **Thresholds**: min-compile-time and min-entry-size are zeroed — the
  serving tail this kills is made of exactly the small-but-many
  template compiles the defaults would skip.

Hit/miss traffic is observed through jax's monitoring events and
re-exported as ``kolibrie_compile_cache_{hits,misses}_total`` so /stats
and the bench can attribute a cold query to "disk hit" vs "real
compile".

The module also owns the **pre-warm manifest**: a small JSON file next
to the cache recording, per template fingerprint, one representative
query text and its cumulative hit count.  On startup the warmer
(:mod:`kolibrie_tpu.query.prewarm`) replays the top-N entries so the
first *foreground* query finds both the in-process jit cache and the
disk cache hot — zero compiles, zero disk misses.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

from kolibrie_tpu.obs import metrics as _metrics

__all__ = [
    "enable",
    "enabled_dir",
    "cache_namespace",
    "stats",
    "counters",
    "manifest_path",
    "load_manifest",
    "save_manifest",
    "record_template",
    "manifest_snapshot",
    "suppress_recording",
]

_HITS = _metrics.counter(
    "kolibrie_compile_cache_hits_total",
    "persistent compilation cache hits (executable loaded from disk)",
)
_MISSES = _metrics.counter(
    "kolibrie_compile_cache_misses_total",
    "persistent compilation cache misses (real XLA compile + write)",
)

_lock = threading.Lock()
_active_dir: Optional[str] = None
_listener_installed = False
# raw event tallies, independent of the obs registry being enabled —
# the restart regression test asserts on these
_event_counts = {"hits": 0, "misses": 0}


def cache_namespace() -> str:
    """Version/backend namespace segment: artifacts are valid exactly as
    long as (jax version, backend kind) both match."""
    import jax

    try:
        backend = jax.default_backend()
    # kolint: ignore[KL601] backend init failure: namespace stays well-formed
    except Exception:
        backend = "unknown"
    return f"jax{jax.__version__}-{backend}"


def _on_event(event: str, **kwargs) -> None:
    if event == "/jax/compilation_cache/cache_hits":
        _event_counts["hits"] += 1
        _HITS.inc()
    elif event == "/jax/compilation_cache/cache_misses":
        _event_counts["misses"] += 1
        _MISSES.inc()


def _install_listener() -> None:
    global _listener_installed
    if _listener_installed:
        return
    try:
        from jax._src import monitoring

        monitoring.register_event_listener(_on_event)
        _listener_installed = True
    # kolint: ignore[KL601] private-API drift: cache still works, only the counters go dark
    except Exception:
        pass


def enable(
    data_dir: Optional[str] = None, explicit_dir: Optional[str] = None
) -> Optional[str]:
    """Idempotently enable the persistent compilation cache.

    Resolution order: ``explicit_dir`` argument →
    ``$KOLIBRIE_COMPILE_CACHE_DIR`` → ``<data_dir>/compile_cache``.
    Returns the active namespaced directory, or ``None`` when no
    location is configured (cache left untouched).  Must run before the
    first lowering it should capture; durability recovery calls it
    before WAL replay so even the replay's own dispatches hit disk.
    """
    global _active_dir
    root = explicit_dir or os.environ.get("KOLIBRIE_COMPILE_CACHE_DIR")
    if not root and data_dir:
        root = os.path.join(data_dir, "compile_cache")
    if not root:
        return None
    target = os.path.join(os.path.abspath(root), cache_namespace())
    with _lock:
        if _active_dir == target:
            return _active_dir
        import jax

        os.makedirs(target, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", target)
        # the tail is many SMALL compiles: cache all of them
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            jax.config.update("jax_enable_compilation_cache", True)
        # kolint: ignore[KL601] older jax: the cache-dir config alone enables it
        except Exception:
            pass
        _install_listener()
        _active_dir = target
    return target


def enabled_dir() -> Optional[str]:
    return _active_dir


def counters() -> Dict[str, int]:
    """Raw (registry-independent) hit/miss event tallies since process
    start — snapshot/delta these around a dispatch to classify its
    source as disk-hit vs real compile."""
    return dict(_event_counts)


def stats() -> dict:
    """Inspection block for /stats: location, entry count, bytes, and
    the hit/miss tallies."""
    out: dict = {
        "enabled": _active_dir is not None,
        "dir": _active_dir,
        "hits": _event_counts["hits"],
        "misses": _event_counts["misses"],
    }
    if _active_dir and os.path.isdir(_active_dir):
        entries = 0
        size = 0
        try:
            for name in os.listdir(_active_dir):
                p = os.path.join(_active_dir, name)
                if os.path.isfile(p):
                    entries += 1
                    size += os.path.getsize(p)
        except OSError:
            pass
        out["entries"] = entries
        out["bytes"] = size
    return out


# ---------------------------------------------------------------------------
# Pre-warm manifest: fingerprint -> representative query + hit count
# ---------------------------------------------------------------------------

_MANIFEST_NAME = "prewarm_manifest.json"
_MANIFEST_MAX = 256  # top-N by hits kept on disk

# in-memory accumulation: fp -> {"query": str, "hits": int}
_templates: Dict[str, Dict] = {}
_templates_lock = threading.Lock()
_suppress = threading.local()


class suppress_recording:
    """Context manager: executions inside do not feed the manifest.
    The warmer wraps its replays in this so warming the top-N does not
    inflate the very popularity ranking it replays."""

    def __enter__(self):
        self._prev = getattr(_suppress, "on", False)
        _suppress.on = True
        return self

    def __exit__(self, *exc):
        _suppress.on = self._prev
        return False


def manifest_path(root: Optional[str] = None) -> Optional[str]:
    """The manifest lives at the cache ROOT (not the versioned
    namespace): query texts replay across jax upgrades just fine."""
    base = root or _active_dir
    if base is None:
        return None
    if base == _active_dir:
        base = os.path.dirname(base)  # strip the namespace segment
    return os.path.join(base, _MANIFEST_NAME)


def record_template(fp: str, query: str) -> None:
    """Account one execution of template ``fp``; keeps the first-seen
    query text as the replayable representative.  Called from the
    executor's plan-cache bookkeeping — must stay O(1)."""
    if getattr(_suppress, "on", False):
        return
    with _templates_lock:
        ent = _templates.get(fp)
        if ent is None:
            if len(_templates) >= 4 * _MANIFEST_MAX:
                # bound the accumulator; the save path re-ranks anyway
                drop = min(_templates, key=lambda k: _templates[k]["hits"])
                _templates.pop(drop)
            _templates[fp] = {"query": query, "hits": 1}
        else:
            ent["hits"] += 1


def manifest_snapshot() -> List[dict]:
    """Current top-N, hottest first."""
    with _templates_lock:
        items = [
            {"fp": fp, "query": e["query"], "hits": e["hits"]}
            for fp, e in _templates.items()
        ]
    items.sort(key=lambda e: (-e["hits"], e["fp"]))
    return items[:_MANIFEST_MAX]


def save_manifest(root: Optional[str] = None) -> Optional[str]:
    """Atomically persist the ranked manifest (tmp + rename, same
    discipline as the durability snapshots)."""
    path = manifest_path(root)
    if path is None:
        return None
    merged: Dict[str, dict] = {
        e["fp"]: e for e in load_manifest(root)
    }
    for e in manifest_snapshot():
        old = merged.get(e["fp"])
        if old is None or e["hits"] >= old.get("hits", 0):
            merged[e["fp"]] = e
    ranked = sorted(
        merged.values(), key=lambda e: (-e.get("hits", 0), e["fp"])
    )[:_MANIFEST_MAX]
    from kolibrie_tpu.optimizer.stats_advisor import stats_advisor

    payload = json.dumps(
        {
            "version": 1,
            "templates": ranked,
            # learned per-template cardinalities ride the same manifest:
            # a restarted replica (or a follower bootstrapping from
            # snapshot) starts with tuned routing instead of re-learning
            "stats_advisor": stats_advisor.export_state(),
        }
    ).encode()
    try:
        from kolibrie_tpu.durability.fsio import atomic_write_bytes

        os.makedirs(os.path.dirname(path), exist_ok=True)
        atomic_write_bytes(path, payload)
    # kolint: ignore[KL601] manifest persistence is advisory: a failed save only costs the next boot warmth
    except Exception:
        return None
    return path


def load_manifest(root: Optional[str] = None) -> List[dict]:
    path = manifest_path(root)
    if path is None or not os.path.isfile(path):
        return []
    try:
        with open(path, "rb") as f:
            doc = json.loads(f.read().decode("utf-8"))
    except (OSError, ValueError):
        return []  # torn/corrupt manifest only costs warmth
    out = []
    for e in doc.get("templates", []):
        if isinstance(e, dict) and isinstance(e.get("query"), str):
            out.append(e)
    return out


def load_advisor_state(root: Optional[str] = None) -> int:
    """Import the manifest's ``stats_advisor`` section into the
    process-wide advisor; returns templates imported.  Corruption at any
    level (file, JSON, section, entry) degrades to the static AGM model
    — the section is advisory, exactly like the template list."""
    path = manifest_path(root)
    if path is None or not os.path.isfile(path):
        return 0
    try:
        with open(path, "rb") as f:
            doc = json.loads(f.read().decode("utf-8"))
    except (OSError, ValueError):
        return 0
    if not isinstance(doc, dict):
        return 0
    from kolibrie_tpu.optimizer.stats_advisor import stats_advisor

    return stats_advisor.import_state(doc.get("stats_advisor"))
