"""Query → (template fingerprint, parameter tuple) canonicalization.

A *template* is the parsed AST with every constant leaf (IRIs, string and
numeric literals, pattern-position terms, VALUES cells) replaced by a typed
placeholder.  Two queries that differ only in those constants share one
fingerprint, and therefore one plan-cache entry and — because the lowered
plan carries the constants in a traced parameter vector
(:mod:`kolibrie_tpu.optimizer.device_engine`) — one device executable.

The constants themselves come back as an ordered tuple of ``params``; the
order is the deterministic AST traversal order, which is also the order the
lowering pass consumes them in, so equal fingerprints imply positionally
comparable parameter tuples.

Structure-relevant scalars stay in the fingerprint:

* variable / alias names, operators, DISTINCT, GROUP BY keys;
* whether a string literal parses as a number (the lowering pass branches
  on that when it sits on one side of a comparison);
* for ordered+limited queries, the power-of-two bucket of
  ``offset + limit`` (the top-k ``k`` is a static jit argument, quantized
  exactly like :func:`try_device_execute_ordered` quantizes it);
* the VALUES row/column shape and its UNDEF mask (the device VALUES table
  is shape-static; only the cell contents are parameters).
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
from typing import Any, List, Tuple

from kolibrie_tpu.query.ast import (
    CombinedQuery,
    IriRef,
    NumberLit,
    PatternTerm,
    SelectQuery,
    StringLit,
    ValuesClause,
)

__all__ = ["fingerprint_query", "template_key"]


def _as_number(text: str) -> bool:
    try:
        float(text.strip('"'))
        return True
    except (ValueError, AttributeError):
        return False


def _k_bucket(n: int, lo: int = 8) -> int:
    c = lo
    while c < n:
        c <<= 1
    return c


def _ser(node: Any, params: List[Any]) -> Any:
    """Serialize ``node`` into a hashable structure, appending constant
    leaves to ``params`` and emitting typed placeholders in their place."""
    if isinstance(node, NumberLit):
        params.append(node.value)
        return ("#num",)
    if isinstance(node, StringLit):
        params.append(node.value)
        # lowering treats numeric-looking strings as numeric comparands
        return ("#str", _as_number(node.value))
    if isinstance(node, IriRef):
        params.append(node.iri)
        return ("#iri",)
    if isinstance(node, PatternTerm):
        if node.kind == "var":
            return ("pv", node.value)
        if node.kind == "quoted":
            s, p, o = node.value  # type: ignore[misc]
            return ("pq", _ser(s, params), _ser(p, params), _ser(o, params))
        params.append(node.value)
        return ("#pt",)
    if isinstance(node, ValuesClause):
        rows = tuple(
            tuple("U" if c is None else "#vc" for c in row) for row in node.rows
        )
        for row in node.rows:
            for c in row:
                if c is not None:
                    params.append(c)
        return ("values", tuple(node.variables), rows)
    if isinstance(node, SelectQuery):
        body = tuple(
            (f.name, _ser(getattr(node, f.name), params))
            for f in dataclasses.fields(node)
            if f.name not in ("prefixes", "limit", "offset")
        )
        if node.order_by and node.limit is not None:
            # static top-k bucket: same quantization as the ordered device path
            lim = ("kbucket", _k_bucket((node.offset or 0) + node.limit))
        else:
            lim = ("lim", node.limit is None, node.offset is None)
        params.append(node.limit)
        params.append(node.offset)
        return ("SelectQuery", body, lim)
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        return (
            type(node).__name__,
            tuple(
                (f.name, _ser(getattr(node, f.name), params))
                for f in dataclasses.fields(node)
                if f.name != "prefixes"
            ),
        )
    if isinstance(node, enum.Enum):
        return ("enum", type(node).__name__, node.value)
    if isinstance(node, dict):
        return (
            "dict",
            tuple(sorted((str(k), _ser(v, params)) for k, v in node.items())),
        )
    if isinstance(node, (list, tuple)):
        return tuple(_ser(x, params) for x in node)
    if node is None or isinstance(node, (bool, int, float, str)):
        return node
    return ("repr", repr(node))  # unknown node kinds stay fully structural


def template_key(cq: CombinedQuery) -> Tuple[Any, Tuple[Any, ...]]:
    """Return ``(structure, params)`` for a parsed query: the hashable
    template skeleton and the ordered tuple of extracted constants.

    The join-strategy routing mode (``KOLIBRIE_WCOJ``) is folded into the
    skeleton: strategy selection happens at PLAN time, so a plan cached
    under one mode must never replay after the mode flips — distinct
    fingerprints give each strategy its own slot (and device executable).
    ``KOLIBRIE_PLAN_INTERP`` joins it for the same reason: the interpreter
    routing decision is sticky per cached slot (its source state, its
    learned caps), so a mode flip must land in a fresh fingerprint."""
    from kolibrie_tpu.optimizer.planner import wcoj_mode  # lazy: avoids cycle
    from kolibrie_tpu.optimizer.plan_interp import plan_interp_mode

    params: List[Any] = []
    structure = (
        "interp",
        plan_interp_mode(),
        ("wcoj", wcoj_mode(), _ser(cq, params)),
    )
    return structure, tuple(params)


def fingerprint_query(cq: CombinedQuery) -> Tuple[str, Tuple[Any, ...]]:
    """Return ``(fingerprint, params)``: a stable hex digest of the query's
    template skeleton plus the constants stripped from it."""
    structure, params = template_key(cq)
    digest = hashlib.sha1(repr(structure).encode("utf-8")).hexdigest()
    return digest, params
