"""Query → (template fingerprint, parameter tuple) canonicalization.

A *template* is the parsed AST with every constant leaf (IRIs, string and
numeric literals, pattern-position terms, VALUES cells) replaced by a typed
placeholder.  Two queries that differ only in those constants share one
fingerprint, and therefore one plan-cache entry and — because the lowered
plan carries the constants in a traced parameter vector
(:mod:`kolibrie_tpu.optimizer.device_engine`) — one device executable.

The constants themselves come back as an ordered tuple of ``params``; the
order is the deterministic AST traversal order, which is also the order the
lowering pass consumes them in, so equal fingerprints imply positionally
comparable parameter tuples.

Structure-relevant scalars stay in the fingerprint:

* variable / alias names, operators, DISTINCT, GROUP BY keys;
* whether a string literal parses as a number (the lowering pass branches
  on that when it sits on one side of a comparison);
* for ordered+limited queries, the power-of-two bucket of
  ``offset + limit`` (the top-k ``k`` is a static jit argument, quantized
  exactly like :func:`try_device_execute_ordered` quantizes it);
* the VALUES row/column shape and its UNDEF mask (the device VALUES table
  is shape-static; only the cell contents are parameters).
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from kolibrie_tpu.obs import metrics
from kolibrie_tpu.query.ast import (
    CombinedQuery,
    IriRef,
    NumberLit,
    PatternTerm,
    SelectQuery,
    StringLit,
    ValuesClause,
)

__all__ = [
    "fingerprint_query",
    "template_key",
    "CapAdvisor",
    "cap_advisor",
    "cap_advisor_enabled",
    "occupancy_pct",
]


def occupancy_pct(rows: int, cap: int) -> float:
    """How full a template-cap slot ran: ``rows / cap`` as a percentage.
    The EXPLAIN ANALYZE renderer and the cap advisor's telemetry share
    this so 'occupancy' means one thing everywhere.  A non-positive cap
    (degenerate/elided slot) reads as 0 rather than dividing by zero."""
    if cap <= 0:
        return 0.0
    return 100.0 * rows / cap


def _as_number(text: str) -> bool:
    try:
        float(text.strip('"'))
        return True
    except (ValueError, AttributeError):
        return False


def _k_bucket(n: int, lo: int = 8) -> int:
    c = lo
    while c < n:
        c <<= 1
    return c


def _ser(node: Any, params: List[Any]) -> Any:
    """Serialize ``node`` into a hashable structure, appending constant
    leaves to ``params`` and emitting typed placeholders in their place."""
    if isinstance(node, NumberLit):
        params.append(node.value)
        return ("#num",)
    if isinstance(node, StringLit):
        params.append(node.value)
        # lowering treats numeric-looking strings as numeric comparands
        return ("#str", _as_number(node.value))
    if isinstance(node, IriRef):
        params.append(node.iri)
        return ("#iri",)
    if isinstance(node, PatternTerm):
        if node.kind == "var":
            return ("pv", node.value)
        if node.kind == "quoted":
            s, p, o = node.value  # type: ignore[misc]
            return ("pq", _ser(s, params), _ser(p, params), _ser(o, params))
        params.append(node.value)
        return ("#pt",)
    if isinstance(node, ValuesClause):
        rows = tuple(
            tuple("U" if c is None else "#vc" for c in row) for row in node.rows
        )
        for row in node.rows:
            for c in row:
                if c is not None:
                    params.append(c)
        return ("values", tuple(node.variables), rows)
    if isinstance(node, SelectQuery):
        body = tuple(
            (f.name, _ser(getattr(node, f.name), params))
            for f in dataclasses.fields(node)
            if f.name not in ("prefixes", "limit", "offset")
        )
        if node.order_by and node.limit is not None:
            # static top-k bucket: same quantization as the ordered device path
            lim = ("kbucket", _k_bucket((node.offset or 0) + node.limit))
        else:
            lim = ("lim", node.limit is None, node.offset is None)
        params.append(node.limit)
        params.append(node.offset)
        return ("SelectQuery", body, lim)
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        return (
            type(node).__name__,
            tuple(
                (f.name, _ser(getattr(node, f.name), params))
                for f in dataclasses.fields(node)
                if f.name != "prefixes"
            ),
        )
    if isinstance(node, enum.Enum):
        return ("enum", type(node).__name__, node.value)
    if isinstance(node, dict):
        return (
            "dict",
            tuple(sorted((str(k), _ser(v, params)) for k, v in node.items())),
        )
    if isinstance(node, (list, tuple)):
        return tuple(_ser(x, params) for x in node)
    if node is None or isinstance(node, (bool, int, float, str)):
        return node
    return ("repr", repr(node))  # unknown node kinds stay fully structural


def template_key(cq: CombinedQuery) -> Tuple[Any, Tuple[Any, ...]]:
    """Return ``(structure, params)`` for a parsed query: the hashable
    template skeleton and the ordered tuple of extracted constants.

    The join-strategy routing mode (``KOLIBRIE_WCOJ``) is folded into the
    skeleton: strategy selection happens at PLAN time, so a plan cached
    under one mode must never replay after the mode flips — distinct
    fingerprints give each strategy its own slot (and device executable).
    ``KOLIBRIE_PLAN_INTERP`` joins it for the same reason: the interpreter
    routing decision is sticky per cached slot (its source state, its
    learned caps), so a mode flip must land in a fresh fingerprint.
    ``KOLIBRIE_PALLAS`` is the third member: the kernel-vs-XLA routing is
    a static argument of the compiled plan body, and the cap advisor keys
    its high-water marks on the fingerprint — a mode flip must replan AND
    re-learn in a fresh slot, never replay a stale one.  ``KOLIBRIE_MQO``
    is the fourth: shared-prefix routing changes which engine produces a
    template's rows, so a mode flip must land in a fresh fingerprint
    (``off`` reproduces pre-MQO behavior bit-for-bit, docs/MQO.md).
    ``KOLIBRIE_STATS_ADVISOR`` is the fifth: the feedback optimizer keys
    its learned cardinalities (and its plan-generation counter) on the
    fingerprint, so a mode flip must replan in a fresh slot where ``off``
    is bitwise-inert and ``auto`` re-learns from scratch
    (docs/OPTIMIZER.md)."""
    from kolibrie_tpu.optimizer.planner import wcoj_mode  # lazy: avoids cycle
    from kolibrie_tpu.optimizer.mqo import mqo_mode
    from kolibrie_tpu.optimizer.plan_interp import plan_interp_mode
    from kolibrie_tpu.optimizer.stats_advisor import stats_advisor_mode
    from kolibrie_tpu.ops.pallas_kernels import pallas_mode

    params: List[Any] = []
    structure = (
        "stats",
        stats_advisor_mode(),
        (
            "mqo",
            mqo_mode(),
            (
                "interp",
                plan_interp_mode(),
                (
                    "pallas",
                    pallas_mode(),
                    ("wcoj", wcoj_mode(), _ser(cq, params)),
                ),
            ),
        ),
    )
    return structure, tuple(params)


def fingerprint_query(cq: CombinedQuery) -> Tuple[str, Tuple[Any, ...]]:
    """Return ``(fingerprint, params)``: a stable hex digest of the query's
    template skeleton plus the constants stripped from it."""
    structure, params = template_key(cq)
    digest = hashlib.sha1(repr(structure).encode("utf-8")).hexdigest()
    return digest, params


# ---------------------------------------------------------------------------
# capacity advisor
# ---------------------------------------------------------------------------

_CAP_RETRIES = metrics.counter(
    "kolibrie_cap_retries_total",
    "doubled-capacity retried dispatches (overflow → re-run); the cap "
    "advisor exists to hold this at zero in steady state",
    labels=("engine",),
)
# pre-create both engine series so a zero-retry steady state is visible
# in /metrics as an explicit 0, not an absent family
_CAP_RETRIES.labels("device")
_CAP_RETRIES.labels("sharded")


def cap_advisor_enabled() -> bool:
    """``KOLIBRIE_CAP_ADVISOR=off`` (or ``0``) disables advice — retries
    fall back to the pre-advisor heuristics.  Observation continues either
    way, so flipping the flag on after a warm-up period works."""
    return os.environ.get("KOLIBRIE_CAP_ADVISOR", "").strip().lower() not in (
        "off",
        "0",
        "false",
    )


class CapAdvisor:
    """Process-wide per-``(engine, template-fingerprint)`` capacity
    advisor: the feedback loop between the overflow-retry protocols and
    initial capacity choice.

    The engines' own capacity caches are deliberately narrow — the device
    engine's ``_device_cap_cache`` lives on one db object and its
    ``cap_key`` embeds scan-cap buckets that MOVE when store growth
    crosses a power-of-two key-group boundary, and the sharded server
    pins caps per ``(fingerprint, base_version)``, dropping them on every
    mutation.  Each of those invalidations used to restart the
    double-and-retry ladder from the static defaults.  This advisor keys
    only on the template fingerprint (which already folds the
    WCOJ/interp/Pallas routing modes), merges observations as a monotonic
    elementwise maximum, and survives db churn and base-version bumps —
    so a warm process re-dispatches at the high-water mark and retries
    stay at zero.

    ``caps`` tuples are engine-opaque: the device engine stores its
    per-join capacity vector, the sharded server ``(join_cap,
    bucket_cap)``.  Entries whose tuple length changes (a replan under a
    different mode lands on a different fingerprint, so this is
    defensive) are replaced rather than merged.  Thread-safe; bounded by
    the upstream plan-template caches (~64 fingerprints per engine).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str], Dict[str, Any]] = {}

    def advise(self, engine: str, fp: str) -> Optional[Tuple[int, ...]]:
        """High-water-mark caps for a template, or ``None`` when cold or
        disabled (callers keep their heuristic defaults)."""
        if not cap_advisor_enabled():
            return None
        with self._lock:
            rec = self._entries.get((engine, fp))
            return None if rec is None else rec["caps"]

    def observe(
        self,
        engine: str,
        fp: str,
        caps: Tuple[int, ...],
        base_version: Optional[int] = None,
    ) -> None:
        """Record a successfully converged capacity vector (monotonic
        elementwise max merge)."""
        caps = tuple(int(c) for c in caps)
        with self._lock:
            rec = self._entries.get((engine, fp))
            if rec is None:
                rec = {"caps": caps, "retries": 0, "base_version": None}
                self._entries[(engine, fp)] = rec
            elif len(rec["caps"]) == len(caps):
                rec["caps"] = tuple(
                    max(a, b) for a, b in zip(rec["caps"], caps)
                )
            else:
                rec["caps"] = caps
            if base_version is not None:
                rec["base_version"] = int(base_version)

    def observe_retry(self, engine: str, fp: str, n: int = 1) -> None:
        """Count an overflow-driven doubled-cap re-dispatch (the waste the
        advisor is eliminating)."""
        _CAP_RETRIES.labels(engine).inc(n)
        with self._lock:
            rec = self._entries.setdefault(
                (engine, fp),
                {"caps": (), "retries": 0, "base_version": None},
            )
            rec["retries"] += n

    def retries(self, engine: Optional[str] = None) -> int:
        """Total observed retries (optionally for one engine) — the
        steady-state-zero signal the chaos suite asserts on."""
        with self._lock:
            return sum(
                rec["retries"]
                for (eng, _fp), rec in self._entries.items()
                if engine is None or eng == engine
            )

    def stats(self) -> dict:
        """The ``/stats`` block: per-template current caps, high-water
        mark and retry counts (bounded by the plan-template caches, so
        per-template detail belongs here, not in /metrics labels)."""
        with self._lock:
            return {
                "enabled": cap_advisor_enabled(),
                "templates": {
                    f"{eng}:{fp}": {
                        "caps": list(rec["caps"]),
                        "hwm": max(rec["caps"]) if rec["caps"] else 0,
                        "retries": rec["retries"],
                        "base_version": rec["base_version"],
                    }
                    for (eng, fp), rec in self._entries.items()
                },
                "retries_total": sum(
                    rec["retries"] for rec in self._entries.values()
                ),
            }

    def reset(self) -> None:
        """Drop all learned state (test isolation)."""
        with self._lock:
            self._entries.clear()


#: the process-wide singleton every engine feeds and consults
cap_advisor = CapAdvisor()
