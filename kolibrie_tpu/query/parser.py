"""SPARQL++ parser: standard SPARQL SELECT/INSERT/DELETE plus the reference's
extensions — RULE (CONSTRUCT/WHERE), PROB annotations, RSP-QL REGISTER with
named windows and sync policies, WINDOW blocks, NOT blocks (NAF), RDF-star
quoted patterns and annotation syntax, MODEL / NEURAL RELATION / TRAIN
declarations, ML.PREDICT, and RETRIEVE.

Parity: ``kolibrie/src/parser.rs`` (nom combinators, 2.8k LoC) — rebuilt as a
tokenizer + recursive-descent parser.  Dispatcher parity:
``parse_combined_query`` (parser.rs:2146-2223).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from kolibrie_tpu.query.ast import (
    Aggregate,
    ArithOp,
    BindClause,
    CombinedQuery,
    CombinedRule,
    Comparison,
    DeleteClause,
    FuncExpr,
    FunctionCall,
    InsertClause,
    IriRef,
    LogicalAnd,
    LogicalNot,
    LogicalOr,
    LossFn,
    MLPredictClause,
    ModelArch,
    ModelDecl,
    NeuralOutputKind,
    NeuralRelationDecl,
    NotBlock,
    NumberLit,
    OptimizerKind,
    OrderCondition,
    PatternTerm,
    PatternTriple,
    ProbAnnotation,
    QuotedPattern,
    RegisterClause,
    RetrieveClause,
    SelectItem,
    SelectQuery,
    StreamType,
    StringLit,
    SubQuery,
    SyncPolicy,
    SyncPolicyKind,
    TimeoutFallback,
    TrainNeuralRelationDecl,
    ValuesClause,
    Var,
    WhereClause,
    WindowBlock,
    WindowClause,
    WindowSpec,
    WindowType,
)

RDF_TYPE = "http://www.w3.org/1999/02/22-rdf-syntax-ns#type"
XSD = "http://www.w3.org/2001/XMLSchema#"


class SparqlParseError(ValueError):
    """Parse failure with position info (rendered by
    :mod:`kolibrie_tpu.query.error_handler`)."""

    def __init__(self, message: str, line: int = 0, col: int = 0, hint: str = ""):
        loc = f" at line {line}:{col}" if line else ""
        super().__init__(f"{message}{loc}" + (f"  hint: {hint}" if hint else ""))
        self.message = message
        self.line = line
        self.col = col
        self.hint = hint


# --------------------------------------------------------------------------
# Tokenizer
# --------------------------------------------------------------------------

_TOK_RE = re.compile(
    r"""
      (?P<comment>\#[^\n]*)
    | (?P<qt_open><<)
    | (?P<qt_close>>>)
    | (?P<iri><[^<>\s{}|^`\\]*>)
    | (?P<literal>"(?:[^"\\]|\\.)*"(?:@[A-Za-z][A-Za-z0-9-]*|\^\^(?:<[^<>\s]*>|[A-Za-z_][\w.-]*:[\w.-]*))?)
    | (?P<var>[?$][A-Za-z_][\w]*)
    | (?P<blank>_:[\w-]+)
    | (?P<op>&&|\|\||!=|<=|>=|:-|[=<>!+\-*/])
    | (?P<punct>[{}()\[\],;.])
    | (?P<num>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
    | (?P<pname>[A-Za-z_][\w.-]*:(?:[\w%-](?:[\w.%-]*[\w%-])?)?|:[\w%-](?:[\w.%-]*[\w%-])?|[A-Za-z_]\w*(?:\.[A-Za-z_]\w*)*|:)
    """,
    re.VERBOSE,
)


class Token:
    __slots__ = ("kind", "text", "line", "col")

    def __init__(self, kind: str, text: str, line: int, col: int):
        self.kind = kind
        self.text = text
        self.line = line
        self.col = col

    def __repr__(self):
        return f"Token({self.kind},{self.text!r})"


def tokenize(text: str) -> List[Token]:
    tokens: List[Token] = []
    line, col = 1, 1
    pos, n = 0, len(text)
    while pos < n:
        ch = text[pos]
        if ch == "\n":
            line += 1
            col = 1
            pos += 1
            continue
        if ch in " \t\r":
            pos += 1
            col += 1
            continue
        m = _TOK_RE.match(text, pos)
        if m is None:
            raise SparqlParseError(f"unexpected character {ch!r}", line, col)
        kind = m.lastgroup or ""
        tok = m.group()
        if kind != "comment":
            tokens.append(Token(kind, tok, line, col))
        nl = tok.count("\n")
        if nl:
            line += nl
            col = len(tok) - tok.rfind("\n")
        else:
            col += len(tok)
        pos = m.end()
    return tokens


_KEYWORDS = {
    "select", "where", "prefix", "base", "filter", "bind", "values", "as",
    "group", "order", "by", "asc", "desc", "limit", "offset", "distinct",
    "insert", "delete", "data", "union", "optional", "minus", "not",
    "register", "from", "named", "window", "on", "range", "step", "sliding",
    "slide", "tumbling", "report", "tick", "with", "policy", "rule",
    "construct", "prob", "model", "neural", "relation", "using", "train",
    "retrieve", "some", "every", "active", "latent", "stream", "a",
    "rstream", "istream", "dstream", "arch", "mlp", "hidden", "output",
    "binary", "exclusive", "input", "features", "label", "target", "loss",
    "optimizer", "learning_rate", "epochs", "batch_size", "save_to", "query",
    "undef", "in",
}


class TokenStream:
    def __init__(self, tokens: List[Token], prefixes: Optional[Dict[str, str]] = None):
        self.tokens = tokens
        self.i = 0
        self.prefixes: Dict[str, str] = dict(prefixes or {})
        self.base = ""

    # -- primitives

    def peek(self, offset: int = 0) -> Optional[Token]:
        j = self.i + offset
        return self.tokens[j] if j < len(self.tokens) else None

    def next(self) -> Token:
        tok = self.peek()
        if tok is None:
            last = self.tokens[-1] if self.tokens else None
            raise SparqlParseError(
                "unexpected end of input",
                last.line if last else 0,
                last.col if last else 0,
            )
        self.i += 1
        return tok

    def at_end(self) -> bool:
        return self.i >= len(self.tokens)

    def error(self, message: str, hint: str = "") -> SparqlParseError:
        tok = self.peek() or (self.tokens[-1] if self.tokens else None)
        return SparqlParseError(
            message, tok.line if tok else 0, tok.col if tok else 0, hint
        )

    # -- keyword/punct helpers (keywords are case-insensitive)

    def is_kw(self, *kws: str, offset: int = 0) -> bool:
        tok = self.peek(offset)
        return (
            tok is not None
            and tok.kind == "pname"
            and ":" not in tok.text
            and tok.text.lower() in kws
        )

    def take_kw(self, *kws: str) -> bool:
        if self.is_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str):
        if not self.take_kw(kw):
            raise self.error(f"expected {kw.upper()}")

    def is_punct(self, p: str, offset: int = 0) -> bool:
        tok = self.peek(offset)
        return tok is not None and tok.kind == "punct" and tok.text == p

    def take_punct(self, p: str) -> bool:
        if self.is_punct(p):
            self.next()
            return True
        return False

    def expect_punct(self, p: str):
        if not self.take_punct(p):
            raise self.error(f"expected {p!r}")

    def is_op(self, o: str, offset: int = 0) -> bool:
        tok = self.peek(offset)
        return tok is not None and tok.kind == "op" and tok.text == o

    def take_op(self, o: str) -> bool:
        if self.is_op(o):
            self.next()
            return True
        return False

    # -- term helpers

    def expand_pname(self, text: str) -> str:
        pfx, local = text.split(":", 1)
        ns = self.prefixes.get(pfx)
        if ns is None:
            # leave unexpanded — databases may expand later with their prefixes
            return text
        return ns + local

    def literal_store_form(self, text: str) -> str:
        """Normalize a literal token to the stored-term form (datatype IRIs
        expanded, unbracketed)."""
        m = re.match(r'^("(?:[^"\\]|\\.)*")(.*)$', text, re.S)
        assert m
        lex, suffix = m.group(1), m.group(2)
        lex = '"' + _unescape(lex[1:-1]) + '"'
        if suffix.startswith("^^"):
            dt = suffix[2:]
            if dt.startswith("<"):
                dt = dt[1:-1]
            else:
                dt = self.expand_pname(dt)
            return f"{lex}^^{dt}"
        return lex + suffix


_ESCAPES = {"t": "\t", "n": "\n", "r": "\r", '"': '"', "'": "'", "\\": "\\"}


def _unescape(s: str) -> str:
    if "\\" not in s:
        return s
    out, i = [], 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            out.append(_ESCAPES.get(s[i + 1], s[i + 1]))
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


# --------------------------------------------------------------------------
# Parser
# --------------------------------------------------------------------------


class SparqlParser:
    def __init__(self, text: str, prefixes: Optional[Dict[str, str]] = None):
        self.ts = TokenStream(tokenize(text), prefixes)

    # ---------------------------------------------------------- prefix decls

    def parse_prologue(self):
        while True:
            if self.ts.is_kw("prefix"):
                self.ts.next()
                tok = self.ts.next()
                if tok.kind != "pname" or not tok.text.endswith(":"):
                    if tok.kind == "pname" and ":" in tok.text and tok.text.split(":", 1)[1] == "":
                        pass
                    else:
                        raise self.ts.error("expected prefix name in PREFIX")
                pfx = tok.text[:-1]
                iri_tok = self.ts.next()
                if iri_tok.kind != "iri":
                    raise self.ts.error("expected IRI in PREFIX")
                self.ts.prefixes[pfx] = iri_tok.text[1:-1]
            elif self.ts.is_kw("base"):
                self.ts.next()
                iri_tok = self.ts.next()
                if iri_tok.kind != "iri":
                    raise self.ts.error("expected IRI in BASE")
                self.ts.base = iri_tok.text[1:-1]
            else:
                return

    # ---------------------------------------------------------------- terms

    def parse_pattern_term(self, position: str = "any") -> PatternTerm:
        ts = self.ts
        tok = ts.peek()
        if tok is None:
            raise ts.error("expected term")
        if tok.kind == "var":
            ts.next()
            return PatternTerm.var(tok.text[1:])
        if tok.kind == "iri":
            ts.next()
            iri = tok.text[1:-1]
            if ts.base and not re.match(r"^[A-Za-z][\w+.-]*:", iri):
                iri = ts.base + iri
            return PatternTerm.term(iri)
        if tok.kind == "literal":
            ts.next()
            return PatternTerm.term(ts.literal_store_form(tok.text))
        if tok.kind == "num":
            ts.next()
            dt = "integer" if re.fullmatch(r"\d+", tok.text) else "decimal"
            if "e" in tok.text.lower():
                dt = "double"
            return PatternTerm.term(f'"{tok.text}"^^{XSD}{dt}')
        if tok.kind == "blank":
            ts.next()
            return PatternTerm.term(tok.text)
        if tok.kind == "qt_open":
            ts.next()
            s = self.parse_pattern_term("subject")
            p = self.parse_pattern_term("predicate")
            o = self.parse_pattern_term("object")
            if ts.peek() is None or ts.next().kind != "qt_close":
                raise ts.error("expected '>>' closing quoted triple")
            return PatternTerm("quoted", (s, p, o))
        if tok.kind == "pname":
            if tok.text.lower() == "a" and position == "predicate":
                ts.next()
                return PatternTerm.term(RDF_TYPE)
            if tok.text.lower() in ("true", "false"):
                ts.next()
                return PatternTerm.term(f'"{tok.text.lower()}"^^{XSD}boolean')
            if ":" in tok.text:
                ts.next()
                return PatternTerm.term(ts.expand_pname(tok.text))
        raise ts.error(f"unexpected token {tok.text!r} in triple {position}")

    # ------------------------------------------------------- triple patterns

    def parse_triple_block(self, patterns: List[PatternTriple]):
        """One subject with ``;``/``,`` predicate-object lists.  RDF-star
        annotation syntax ``{| p v |}`` is not in the reference; quoted
        subjects/objects are."""
        ts = self.ts
        subject = self.parse_pattern_term("subject")
        while True:
            pred = self.parse_pattern_term("predicate")
            while True:
                obj = self.parse_pattern_term("object")
                patterns.append(PatternTriple(subject, pred, obj))
                if ts.take_punct(","):
                    continue
                break
            if ts.take_punct(";"):
                nxt = ts.peek()
                if nxt is not None and (
                    nxt.kind in ("var", "iri", "literal", "qt_open")
                    or (nxt.kind == "pname" and (":" in nxt.text or nxt.text.lower() == "a"))
                ):
                    continue
            break

    # ----------------------------------------------------------- arithmetic

    def parse_arith_expr(self):
        left = self.parse_arith_term()
        while self.ts.is_op("+") or self.ts.is_op("-"):
            op = self.ts.next().text
            right = self.parse_arith_term()
            left = ArithOp(left, op, right)
        return left

    def parse_arith_term(self):
        left = self.parse_arith_factor()
        while self.ts.is_op("*") or self.ts.is_op("/"):
            op = self.ts.next().text
            right = self.parse_arith_factor()
            left = ArithOp(left, op, right)
        return left

    def parse_arith_factor(self):
        ts = self.ts
        tok = ts.peek()
        if tok is None:
            raise ts.error("expected expression")
        if tok.kind == "punct" and tok.text == "(":
            ts.next()
            e = self.parse_arith_expr()
            ts.expect_punct(")")
            return e
        if tok.kind == "var":
            ts.next()
            return Var(tok.text[1:])
        if tok.kind == "num":
            ts.next()
            return NumberLit(float(tok.text))
        if tok.kind == "op" and tok.text == "-":
            ts.next()
            inner = self.parse_arith_factor()
            return ArithOp(NumberLit(0.0), "-", inner)
        if tok.kind == "literal":
            ts.next()
            return StringLit(ts.literal_store_form(tok.text))
        if tok.kind == "iri":
            ts.next()
            return IriRef(tok.text[1:-1])
        if tok.kind == "qt_open":
            ts.next()
            s = self.parse_arith_factor()
            p = self.parse_arith_factor()
            o = self.parse_arith_factor()
            if ts.next().kind != "qt_close":
                raise ts.error("expected '>>'")
            return QuotedPattern(s, p, o)
        if tok.kind == "pname":
            # function call or pname constant
            if ts.is_punct("(", offset=1) and ":" not in tok.text:
                name = ts.next().text
                ts.expect_punct("(")
                args = []
                if not ts.is_punct(")"):
                    args.append(self.parse_arith_expr())
                    while ts.take_punct(","):
                        args.append(self.parse_arith_expr())
                ts.expect_punct(")")
                return FuncExpr(name.upper(), args)
            if ":" in tok.text:
                ts.next()
                return IriRef(ts.expand_pname(tok.text))
            if tok.text.lower() in ("true", "false"):
                ts.next()
                return StringLit(f'"{tok.text.lower()}"^^{XSD}boolean')
        raise ts.error(f"unexpected token {tok.text!r} in expression")

    # -------------------------------------------------------------- filters

    def parse_filter_expr(self):
        """Full precedence: OR < AND < NOT < comparison."""
        left = self.parse_filter_and()
        while self.ts.take_op("||"):
            right = self.parse_filter_and()
            left = LogicalOr(left, right)
        return left

    def parse_filter_and(self):
        left = self.parse_filter_not()
        while self.ts.take_op("&&"):
            right = self.parse_filter_not()
            left = LogicalAnd(left, right)
        return left

    def parse_filter_not(self):
        if self.ts.take_op("!"):
            return LogicalNot(self.parse_filter_not())
        return self.parse_filter_atom()

    def parse_filter_atom(self):
        ts = self.ts
        # parenthesized sub-expression — but "(expr) > 5" is a comparison whose
        # left side is parenthesized arithmetic; try filter first, backtrack.
        if ts.is_punct("("):
            save = ts.i
            ts.next()
            try:
                inner = self.parse_filter_expr()
                ts.expect_punct(")")
                # if a comparison operator follows, re-parse as arithmetic
                if not (ts.peek() is not None and ts.peek().kind == "op" and ts.peek().text in ("=", "!=", "<", "<=", ">", ">=")):
                    return inner
            except SparqlParseError:
                pass
            ts.i = save
        left = self.parse_arith_expr()
        tok = ts.peek()
        if tok is not None and tok.kind == "op" and tok.text in ("=", "!=", "<", "<=", ">", ">="):
            op = ts.next().text
            right = self.parse_arith_expr()
            return Comparison(left, op, right)
        if isinstance(left, FuncExpr):
            return FunctionCall(left.name, left.args)
        raise ts.error("expected comparison or boolean function in FILTER")

    # ------------------------------------------------------------ WHERE body

    def parse_group_graph_pattern(self, allow_windows: bool = True) -> WhereClause:
        ts = self.ts
        ts.expect_punct("{")
        wc = WhereClause()
        while not ts.is_punct("}"):
            if ts.at_end():
                raise ts.error("unterminated group pattern, expected '}'")
            if ts.is_kw("filter"):
                ts.next()
                paren = ts.take_punct("(")
                wc.filters.append(self.parse_filter_expr())
                if paren:
                    ts.expect_punct(")")
            elif ts.is_kw("bind"):
                ts.next()
                ts.expect_punct("(")
                expr = self.parse_arith_expr()
                ts.expect_kw("as")
                var_tok = ts.next()
                if var_tok.kind != "var":
                    raise ts.error("expected variable after AS")
                ts.expect_punct(")")
                wc.binds.append(BindClause(expr, var_tok.text[1:]))
            elif ts.is_kw("values"):
                ts.next()
                wc.values = self.parse_values_body()
            elif ts.is_kw("optional"):
                ts.next()
                wc.optionals.append(self.parse_group_graph_pattern(allow_windows))
            elif ts.is_kw("minus"):
                ts.next()
                wc.minus.append(self.parse_group_graph_pattern(allow_windows))
            elif ts.is_kw("not") and not ts.is_punct("(", offset=1):
                ts.next()
                inner: List[PatternTriple] = []
                ts.expect_punct("{")
                while not ts.is_punct("}"):
                    self.parse_triple_block(inner)
                    ts.take_punct(".")
                ts.expect_punct("}")
                wc.not_blocks.append(NotBlock(inner))
            elif allow_windows and ts.is_kw("window"):
                ts.next()
                wtok = ts.next()
                if wtok.kind == "iri":
                    wiri = wtok.text[1:-1]
                elif wtok.kind == "pname":
                    wiri = ts.expand_pname(wtok.text)
                else:
                    raise ts.error("expected window IRI after WINDOW")
                inner_wc = self.parse_group_graph_pattern(allow_windows=False)
                wc.window_blocks.append(
                    WindowBlock(wiri, inner_wc.patterns, inner_wc.filters)
                )
            elif ts.is_punct("{"):
                # subquery or nested group
                save = ts.i
                ts.next()
                if ts.is_kw("select"):
                    sub = self.parse_select_query(already_prologued=True)
                    ts.expect_punct("}")
                    wc.subqueries.append(SubQuery(sub))
                else:
                    ts.i = save
                    groups = [self.parse_group_graph_pattern(allow_windows)]
                    while ts.is_kw("union"):
                        ts.next()
                        groups.append(self.parse_group_graph_pattern(allow_windows))
                    if len(groups) == 1:
                        g = groups[0]
                        wc.patterns.extend(g.patterns)
                        wc.filters.extend(g.filters)
                        wc.binds.extend(g.binds)
                        wc.not_blocks.extend(g.not_blocks)
                        wc.subqueries.extend(g.subqueries)
                        wc.optionals.extend(g.optionals)
                        wc.minus.extend(g.minus)
                        wc.window_blocks.extend(g.window_blocks)
                        if g.values is not None:
                            wc.values = g.values
                    else:
                        wc.unions.append(groups)
            else:
                self.parse_triple_block(wc.patterns)
            ts.take_punct(".")
        ts.expect_punct("}")
        return wc

    def parse_values_body(self) -> ValuesClause:
        ts = self.ts
        variables: List[str] = []
        if ts.is_punct("("):
            ts.next()
            while not ts.is_punct(")"):
                vt = ts.next()
                if vt.kind != "var":
                    raise ts.error("expected variable in VALUES")
                variables.append(vt.text[1:])
            ts.next()
            ts.expect_punct("{")
            rows: List[List[Optional[str]]] = []
            while not ts.is_punct("}"):
                ts.expect_punct("(")
                row: List[Optional[str]] = []
                while not ts.is_punct(")"):
                    row.append(self._values_term())
                ts.next()
                rows.append(row)
            ts.next()
            return ValuesClause(variables, rows)
        vt = ts.next()
        if vt.kind != "var":
            raise ts.error("expected variable in VALUES")
        variables.append(vt.text[1:])
        ts.expect_punct("{")
        rows = []
        while not ts.is_punct("}"):
            rows.append([self._values_term()])
        ts.next()
        return ValuesClause(variables, rows)

    def _values_term(self) -> Optional[str]:
        ts = self.ts
        if ts.is_kw("undef"):
            ts.next()
            return None
        t = self.parse_pattern_term("object")
        if t.kind == "var":
            raise ts.error("variables not allowed in VALUES data")
        return t.value  # type: ignore[return-value]

    # ---------------------------------------------------------------- SELECT

    def parse_select_query(self, already_prologued: bool = False) -> SelectQuery:
        ts = self.ts
        if not already_prologued:
            self.parse_prologue()
        ts.expect_kw("select")
        distinct = ts.take_kw("distinct")
        items: List[SelectItem] = []
        while True:
            tok = ts.peek()
            if tok is None:
                break
            if tok.kind == "op" and tok.text == "*":
                ts.next()
                items.append(SelectItem("var", var="*"))
                continue
            if tok.kind == "var":
                ts.next()
                items.append(SelectItem("var", var=tok.text[1:]))
                continue
            if tok.kind == "punct" and tok.text == "(":
                ts.next()
                agg = self._try_parse_aggregate()
                if agg is not None:
                    items.append(SelectItem("agg", agg=agg))
                else:
                    expr = self.parse_arith_expr()
                    ts.expect_kw("as")
                    vt = ts.next()
                    if vt.kind != "var":
                        raise ts.error("expected variable after AS")
                    items.append(SelectItem("expr", expr=expr, alias=vt.text[1:]))
                ts.expect_punct(")")
                continue
            if tok.kind == "pname" and tok.text.upper() in (
                "COUNT", "SUM", "AVG", "MIN", "MAX", "GROUP_CONCAT", "SAMPLE",
            ):
                agg = self._try_parse_aggregate()
                if agg is not None:
                    items.append(SelectItem("agg", agg=agg))
                    continue
            break
        if not items:
            raise ts.error("SELECT requires at least one projection")
        # FROM NAMED WINDOW clauses (RSP-QL) are parsed by the caller when in
        # REGISTER context; plain FROM <g> is accepted and ignored (single graph).
        windows: List[WindowClause] = []
        while ts.is_kw("from"):
            ts.next()
            if ts.is_kw("named"):
                ts.next()
                ts.expect_kw("window")
                windows.append(self.parse_window_clause_body())
            else:
                ts.next()  # graph IRI — single-graph store, ignored
        where = None
        if ts.is_kw("where"):
            ts.next()
            where = self.parse_group_graph_pattern()
        else:
            where = WhereClause()
        q = SelectQuery(
            select=items, where=where, distinct=distinct, prefixes=dict(ts.prefixes)
        )
        q.window_clauses = windows  # type: ignore[attr-defined]
        while True:
            if ts.is_kw("group"):
                ts.next()
                ts.expect_kw("by")
                while ts.peek() is not None and ts.peek().kind == "var":
                    q.group_by.append(ts.next().text[1:])
            elif ts.is_kw("order"):
                ts.next()
                ts.expect_kw("by")
                while True:
                    if ts.is_kw("asc") or ts.is_kw("desc"):
                        desc = ts.next().text.lower() == "desc"
                        ts.expect_punct("(")
                        expr = self.parse_arith_expr()
                        ts.expect_punct(")")
                        q.order_by.append(OrderCondition(expr, desc))
                    elif ts.peek() is not None and ts.peek().kind == "var":
                        q.order_by.append(OrderCondition(Var(ts.next().text[1:]), False))
                    else:
                        break
            elif ts.is_kw("limit"):
                ts.next()
                q.limit = int(ts.next().text)
            elif ts.is_kw("offset"):
                ts.next()
                q.offset = int(ts.next().text)
            else:
                break
        return q

    def _try_parse_aggregate(self) -> Optional[Aggregate]:
        """Parse ``COUNT(?x) [AS ?alias]`` etc.  The caller may already have
        consumed an outer '(' (``(COUNT(?x) AS ?n)`` form); either way the
        next token here must be the aggregate function name."""
        ts = self.ts
        save = ts.i
        name_tok = ts.peek()
        if name_tok is None or name_tok.kind != "pname":
            return None
        fname = name_tok.text.upper()
        if fname not in ("COUNT", "SUM", "AVG", "MIN", "MAX", "GROUP_CONCAT", "SAMPLE"):
            return None
        ts.next()  # consume function name
        ts.expect_punct("(")
        distinct = ts.take_kw("distinct")
        if ts.is_op("*"):
            ts.next()
            arg = None
        else:
            vt = ts.next()
            if vt.kind != "var":
                ts.i = save
                return None
            arg = vt.text[1:]
        ts.expect_punct(")")
        if ts.take_kw("as"):
            vt = ts.next()
            alias = vt.text[1:]
        else:
            alias = f"{fname.lower()}_{arg or 'all'}"
        return Aggregate(fname, arg, alias, distinct)

    # ----------------------------------------------------- INSERT / DELETE

    def parse_insert(self) -> InsertClause:
        ts = self.ts
        ts.expect_kw("insert")
        ts.take_kw("data")
        ts.expect_punct("{")
        triples: List[PatternTriple] = []
        while not ts.is_punct("}"):
            self.parse_triple_block(triples)
            ts.take_punct(".")
        ts.next()
        return InsertClause(triples)

    def parse_delete(self) -> DeleteClause:
        ts = self.ts
        ts.expect_kw("delete")
        ts.take_kw("data")
        ts.expect_punct("{")
        triples: List[PatternTriple] = []
        while not ts.is_punct("}"):
            self.parse_triple_block(triples)
            ts.take_punct(".")
        ts.next()
        where = None
        if ts.is_kw("where"):
            ts.next()
            where = self.parse_group_graph_pattern()
        return DeleteClause(triples, where)

    # ------------------------------------------------------------- windows

    def parse_window_clause_body(self) -> WindowClause:
        """After ``FROM NAMED WINDOW``: ``:w ON :stream [SPEC] [WITH POLICY p]``."""
        ts = self.ts
        wiri = self._iri_or_pname("window IRI")
        ts.expect_kw("on")
        tok = ts.peek()
        if tok is not None and tok.kind == "var":
            ts.next()
            stream = "?" + tok.text[1:]
        else:
            stream = self._iri_or_pname("stream IRI")
        ts.expect_punct("[")
        spec = self._parse_window_spec()
        ts.expect_punct("]")
        policy = None
        if ts.is_kw("with"):
            ts.next()
            ts.expect_kw("policy")
            policy = self._parse_sync_policy()
        return WindowClause(wiri, stream, spec, policy)

    def _iri_or_pname(self, what: str) -> str:
        ts = self.ts
        tok = ts.next()
        if tok.kind == "iri":
            return tok.text[1:-1]
        if tok.kind == "pname":
            return ts.expand_pname(tok.text) if ":" in tok.text else tok.text
        raise ts.error(f"expected {what}")

    def _parse_duration(self) -> int:
        """Window size: bare int, ``PT10M``-style ISO-8601, ``5s``/``500ms``."""
        ts = self.ts
        tok = ts.next()
        if tok.kind == "num":
            val = int(float(tok.text))
            nxt = ts.peek()
            if nxt is not None and nxt.kind == "pname" and nxt.text in ("s", "ms"):
                ts.next()
                return val if nxt.text == "s" else max(1, val // 1000)
            return val
        if tok.kind == "pname":
            m = re.fullmatch(r"(?i)PT(\d+)([SMH])", tok.text)
            if m:
                n = int(m.group(1))
                unit = m.group(2).upper()
                return n * {"S": 1, "M": 60, "H": 3600}[unit]
            m = re.fullmatch(r"(\d+)(s|ms)", tok.text)
            if m:
                n = int(m.group(1))
                return n if m.group(2) == "s" else max(1, n // 1000)
        raise ts.error("expected window duration")

    def _parse_window_spec(self) -> WindowSpec:
        ts = self.ts
        if ts.take_kw("range"):
            width = self._parse_duration()
            slide = width
            wtype = WindowType.SLIDING
            if ts.take_kw("step"):
                slide = self._parse_duration()
        elif ts.take_kw("sliding"):
            width = self._parse_duration()
            slide = 1
            wtype = WindowType.SLIDING
            if ts.take_kw("slide"):
                slide = self._parse_duration()
        elif ts.take_kw("tumbling"):
            width = self._parse_duration()
            slide = width
            wtype = WindowType.TUMBLING
        else:
            raise ts.error("expected RANGE / SLIDING / TUMBLING")
        spec = WindowSpec(width, slide, wtype)
        while True:
            if ts.take_kw("report"):
                spec.report = ts.next().text.upper()
            elif ts.take_kw("tick"):
                spec.tick = ts.next().text.upper()
            else:
                break
        return spec

    def _parse_sync_policy(self) -> SyncPolicy:
        ts = self.ts
        if ts.take_kw("steal"):
            return SyncPolicy(SyncPolicyKind.STEAL)
        if ts.take_kw("wait"):
            return SyncPolicy(SyncPolicyKind.WAIT)
        ts.expect_punct("(")
        ts.expect_kw("timeout")
        if not ts.take_op("="):
            raise ts.error("expected '=' after timeout")
        dur_s = self._parse_policy_duration_ms()
        ts.expect_punct(",")
        ts.expect_kw("fallback")
        if not ts.take_op("="):
            raise ts.error("expected '=' after fallback")
        fb = ts.next().text.lower()
        ts.expect_punct(")")
        return SyncPolicy(
            SyncPolicyKind.TIMEOUT,
            timeout_ms=dur_s,
            fallback=TimeoutFallback.DROP if fb == "drop" else TimeoutFallback.STEAL,
        )

    def _parse_policy_duration_ms(self) -> int:
        ts = self.ts
        tok = ts.next()
        if tok.kind == "num":
            val = int(float(tok.text))
            nxt = ts.peek()
            if nxt is not None and nxt.kind == "pname" and nxt.text in ("s", "ms"):
                ts.next()
                return val * 1000 if nxt.text == "s" else val
            return val * 1000  # bare integer = seconds
        if tok.kind == "pname":
            m = re.fullmatch(r"(?i)PT(\d+)([SMH])", tok.text)
            if m:
                n = int(m.group(1))
                return n * {"S": 1, "M": 60, "H": 3600}[m.group(2).upper()] * 1000
            m = re.fullmatch(r"(\d+)(s|ms)", tok.text)
            if m:
                return int(m.group(1)) * (1000 if m.group(2) == "s" else 1)
        raise ts.error("expected duration")

    # ------------------------------------------------------------- REGISTER

    def parse_register(self) -> RegisterClause:
        ts = self.ts
        ts.expect_kw("register")
        st_tok = ts.next()
        st = st_tok.text.upper()
        if st not in ("RSTREAM", "ISTREAM", "DSTREAM"):
            raise ts.error("expected RSTREAM/ISTREAM/DSTREAM after REGISTER")
        out_iri = self._iri_or_pname("output stream IRI")
        ts.expect_kw("as")
        select = self.parse_select_query(already_prologued=True)
        windows = getattr(select, "window_clauses", [])
        return RegisterClause(StreamType[st], out_iri, select, windows)

    # ----------------------------------------------------------------- RULE

    def parse_rule(self) -> CombinedRule:
        """``RULE :Name [PROB(...)] :- CONSTRUCT { ... } WHERE { ... }``."""
        ts = self.ts
        ts.expect_kw("rule")
        name = self._iri_or_pname("rule name")
        params: List[str] = []
        if ts.take_punct("("):
            while not ts.is_punct(")"):
                vt = ts.next()
                if vt.kind == "var":
                    params.append(vt.text[1:])
                ts.take_punct(",")
            ts.next()
        prob = None
        if ts.is_kw("prob"):
            prob = self._parse_prob_annotation()
        if not ts.take_op(":-"):
            raise ts.error("expected ':-' after rule head")
        ml_predict = None
        if ts.is_kw("construct"):
            ts.next()
        conclusions: List[PatternTriple] = []
        ts.expect_punct("{")
        while not ts.is_punct("}"):
            self.parse_triple_block(conclusions)
            ts.take_punct(".")
        ts.next()
        body = WhereClause()
        if ts.is_kw("where"):
            ts.next()
            body = self.parse_group_graph_pattern()
        # trailing ML.PREDICT attached to the rule
        if ts.is_kw("ml") or (
            ts.peek() is not None and ts.peek().kind == "pname" and ts.peek().text.upper().startswith("ML.")
        ):
            ml_predict = self.parse_ml_predict()
        rule = CombinedRule(
            name=name,
            params=params,
            body=body,
            conclusions=conclusions,
            prob=prob,
            ml_predict=ml_predict,
        )
        return rule

    def _parse_prob_annotation(self) -> ProbAnnotation:
        ts = self.ts
        ts.expect_kw("prob")
        ts.expect_punct("(")
        ann = ProbAnnotation()
        explicit_k = None
        while not ts.is_punct(")"):
            key = ts.next().text.lower()
            if not ts.take_op("="):
                raise ts.error("expected '=' in PROB annotation")
            val_tok = ts.next()
            val = val_tok.text.strip('"')
            if key in ("combination", "provenance"):
                ann.combination = _normalize_combination(val)
            elif key == "threshold":
                ann.threshold = float(val)
            elif key == "confidence":
                ann.confidence = float(val)
            elif key == "k":
                explicit_k = int(float(val))
            ts.take_punct(",")
        ts.next()
        # topk reads k from the threshold field at use time, key-order
        # independent; default 5 (parser.rs:2679 unwrap_or(5))
        if explicit_k is not None:
            ann.k = explicit_k
        elif ann.combination == "topk":
            ann.k = int(ann.threshold) if ann.threshold is not None else 5
        return ann

    # ----------------------------------------------------- ML declarations

    def parse_ml_predict(self) -> MLPredictClause:
        """``ML.PREDICT(MODEL "m", INPUT { SELECT ... }, OUTPUT ?v)``."""
        ts = self.ts
        tok = ts.next()
        if tok.text.upper() not in ("ML.PREDICT", "ML"):
            raise ts.error("expected ML.PREDICT")
        if tok.text.upper() == "ML":
            # tokenized as ML . PREDICT
            ts.expect_punct(".")
            nt = ts.next()
            if nt.text.upper() != "PREDICT":
                raise ts.error("expected PREDICT after ML.")
        ts.expect_punct("(")
        ts.expect_kw("model")
        model_tok = ts.next()
        model = model_tok.text.strip('"') if model_tok.kind == "literal" else self.ts.expand_pname(model_tok.text) if ":" in model_tok.text else model_tok.text
        ts.expect_punct(",")
        ts.expect_kw("input")
        ts.expect_punct("{")
        select = self.parse_select_query(already_prologued=True)
        ts.expect_punct("}")
        ts.expect_punct(",")
        ts.expect_kw("output")
        vt = ts.next()
        if vt.kind != "var":
            raise ts.error("expected output variable")
        ts.expect_punct(")")
        return MLPredictClause(model, select, vt.text[1:])

    def parse_model_decl(self) -> ModelDecl:
        ts = self.ts
        ts.expect_kw("model")
        name = ts.next().text.strip('"')
        ts.expect_punct("{")
        arch = ModelArch()
        output = NeuralOutputKind("binary")
        while not ts.is_punct("}"):
            if ts.take_kw("arch"):
                ts.expect_kw("mlp")
                ts.expect_punct("{")
                ts.expect_kw("hidden")
                ts.expect_punct("[")
                hidden: List[int] = []
                while not ts.is_punct("]"):
                    hidden.append(int(ts.next().text))
                    ts.take_punct(",")
                ts.next()
                ts.expect_punct("}")
                arch = ModelArch(hidden)
            elif ts.take_kw("output"):
                if ts.take_kw("binary"):
                    output = NeuralOutputKind("binary")
                elif ts.take_kw("exclusive"):
                    ts.expect_punct("{")
                    labels: List[str] = []
                    while not ts.is_punct("}"):
                        labels.append(ts.next().text.strip('"'))
                        ts.take_punct(",")
                    ts.next()
                    output = NeuralOutputKind("exclusive", labels)
                else:
                    raise ts.error("expected BINARY or EXCLUSIVE")
            else:
                raise ts.error("unexpected token in MODEL declaration")
        ts.next()
        return ModelDecl(name, arch, output)

    def parse_neural_relation_decl(self) -> NeuralRelationDecl:
        ts = self.ts
        ts.expect_kw("neural")
        ts.expect_kw("relation")
        pred_tok = ts.next()
        predicate = (
            ts.expand_pname(pred_tok.text) if pred_tok.kind == "pname" and ":" in pred_tok.text
            else pred_tok.text[1:-1] if pred_tok.kind == "iri"
            else pred_tok.text
        )
        ts.expect_kw("using")
        ts.expect_kw("model")
        model = ts.next().text.strip('"')
        ts.expect_punct("{")
        patterns: List[PatternTriple] = []
        features: List[str] = []
        while not ts.is_punct("}"):
            if ts.take_kw("input"):
                ts.expect_punct("{")
                while not ts.is_punct("}"):
                    self.parse_triple_block(patterns)
                    ts.take_punct(".")
                ts.next()
            elif ts.take_kw("features"):
                ts.expect_punct("{")
                while not ts.is_punct("}"):
                    vt = ts.next()
                    if vt.kind == "var":
                        features.append(vt.text[1:])
                    ts.take_punct(",")
                ts.next()
            else:
                raise ts.error("expected INPUT or FEATURES")
        ts.next()
        anchor = ""
        if patterns and patterns[0].subject.is_var:
            anchor = patterns[0].subject.value  # type: ignore[assignment]
        return NeuralRelationDecl(predicate, model, patterns, anchor, features)

    def parse_train_decl(self) -> TrainNeuralRelationDecl:
        ts = self.ts
        ts.expect_kw("train")
        ts.expect_kw("neural")
        ts.expect_kw("relation")
        rel_tok = ts.next()
        relation = (
            ts.expand_pname(rel_tok.text) if rel_tok.kind == "pname" and ":" in rel_tok.text
            else rel_tok.text[1:-1] if rel_tok.kind == "iri"
            else rel_tok.text
        )
        decl = TrainNeuralRelationDecl(relation)
        ts.expect_punct("{")
        while not ts.is_punct("}"):
            if ts.take_kw("data"):
                ts.expect_punct("{")
                while not ts.is_punct("}"):
                    self.parse_triple_block(decl.data_patterns)
                    ts.take_punct(".")
                ts.next()
            elif ts.take_kw("query"):
                ts.expect_punct("{")
                sub = self.parse_select_query(already_prologued=True)
                decl.data_query = sub  # keep parsed form
                ts.expect_punct("}")
            elif ts.take_kw("label"):
                vt = ts.next()
                decl.label_var = vt.text[1:] if vt.kind == "var" else vt.text
            elif ts.take_kw("target"):
                ts.expect_punct("{")
                tgt: List[PatternTriple] = []
                self.parse_triple_block(tgt)
                ts.take_punct(".")
                ts.expect_punct("}")
                decl.target = tgt[0]
            elif ts.take_kw("loss"):
                name = ts.next().text.lower()
                decl.loss = {
                    "cross_entropy": LossFn.CROSS_ENTROPY,
                    "nll": LossFn.NLL,
                    "mse": LossFn.MSE,
                    "bce": LossFn.BCE,
                }.get(name, LossFn.BCE)
            elif ts.take_kw("optimizer"):
                decl.optimizer = (
                    OptimizerKind.SGD if ts.next().text.lower() == "sgd" else OptimizerKind.ADAM
                )
            elif ts.take_kw("learning_rate"):
                decl.learning_rate = float(ts.next().text)
            elif ts.take_kw("epochs"):
                decl.epochs = int(ts.next().text)
            elif ts.take_kw("batch_size"):
                decl.batch_size = int(ts.next().text)
            elif ts.take_kw("save_to"):
                decl.save_path = ts.next().text.strip('"')
            else:
                raise ts.error("unexpected token in TRAIN NEURAL RELATION")
        ts.next()
        return decl

    # ------------------------------------------------------------- RETRIEVE

    def parse_retrieve(self) -> RetrieveClause:
        ts = self.ts
        ts.expect_kw("retrieve")
        mode = "SOME" if ts.take_kw("some") else ("EVERY" if ts.take_kw("every") else None)
        if mode is None:
            raise ts.error("expected SOME or EVERY after RETRIEVE")
        state = "ACTIVE" if ts.take_kw("active") else ("LATENT" if ts.take_kw("latent") else None)
        if state is None:
            raise ts.error("expected ACTIVE or LATENT")
        ts.expect_kw("stream")
        vt = ts.next()
        if vt.kind != "var":
            raise ts.error("expected stream variable")
        ts.expect_kw("from")
        from_iri = self._iri_or_pname("catalog IRI")
        patterns: List[PatternTriple] = []
        if ts.take_kw("with"):
            ts.expect_punct("{")
            while not ts.is_punct("}"):
                self.parse_triple_block(patterns)
                ts.take_punct(".")
            ts.next()
        return RetrieveClause(mode, state, vt.text[1:], from_iri, patterns)

    # ------------------------------------------------------- combined query

    def parse_combined(self) -> CombinedQuery:
        """Top-level dispatcher. Parity: parser.rs:2146-2223."""
        ts = self.ts
        cq = CombinedQuery()
        self.parse_prologue()
        while not ts.at_end():
            if ts.is_kw("prefix") or ts.is_kw("base"):
                self.parse_prologue()
            elif ts.is_kw("model") and ts.peek(1) is not None and ts.peek(1).kind == "literal":
                cq.models.append(self.parse_model_decl())
            elif ts.is_kw("neural"):
                cq.neural_relations.append(self.parse_neural_relation_decl())
            elif ts.is_kw("train"):
                cq.train_decls.append(self.parse_train_decl())
            elif ts.is_kw("rule"):
                cq.rules.append(self.parse_rule())
            elif ts.is_kw("retrieve"):
                cq.retrieve = self.parse_retrieve()
            elif ts.is_kw("register"):
                cq.register = self.parse_register()
            elif ts.is_kw("select"):
                cq.select = self.parse_select_query(already_prologued=True)
            elif ts.is_kw("insert"):
                cq.insert = self.parse_insert()
            elif ts.is_kw("delete"):
                cq.delete = self.parse_delete()
            elif ts.peek() is not None and ts.peek().kind == "pname" and ts.peek().text.upper() in ("ML.PREDICT",):
                cq.ml_predict = self.parse_ml_predict()
            elif ts.is_kw("ml"):
                cq.ml_predict = self.parse_ml_predict()
            else:
                raise ts.error(
                    f"unexpected token {ts.peek().text!r} at top level",
                    hint="expected SELECT, INSERT, DELETE, RULE, REGISTER, MODEL, "
                    "NEURAL RELATION, TRAIN, ML.PREDICT, or RETRIEVE",
                )
        cq.prefixes = dict(ts.prefixes)
        return cq


def _normalize_combination(val: str) -> str:
    """PROB combination aliases (parser_test.rs cases): independent→addmult,
    min/minmax→minmax, plus topk / wmc / sdd / boolean."""
    v = val.lower()
    return {
        "independent": "addmult",
        "addmult": "addmult",
        "noisyor": "addmult",
        "min": "minmax",
        "minmax": "minmax",
        "fuzzy": "minmax",
        "boolean": "boolean",
        "topk": "topk",
        "wmc": "wmc",
        "dnf": "wmc",
        "sdd": "sdd",
    }.get(v, v)


# --------------------------------------------------------------------------
# Public entry points (parity: parse_sparql_query parser.rs:1036,
# parse_combined_query parser.rs:2146)
# --------------------------------------------------------------------------


def parse_sparql_query(text: str, prefixes: Optional[Dict[str, str]] = None) -> SelectQuery:
    p = SparqlParser(text, prefixes)
    q = p.parse_select_query()
    return q


def parse_combined_query(text: str, prefixes: Optional[Dict[str, str]] = None) -> CombinedQuery:
    p = SparqlParser(text, prefixes)
    return p.parse_combined()


def parse_rule_definition(text: str, prefixes: Optional[Dict[str, str]] = None) -> CombinedRule:
    p = SparqlParser(text, prefixes)
    p.parse_prologue()
    return p.parse_rule()
