"""SparqlDatabase — the store facade: columnar triples + dictionary +
parsers + prefixes + UDF/neural registries + probability seeds.

Parity: ``kolibrie/src/sparql_database.rs:44-60`` (struct) and its parse/
serialize/prefix/UDF surface.  The SIMD join/filter members of the reference
live in :mod:`kolibrie_tpu.ops` instead; the six-permutation index is the
columnar store's sorted orders.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from kolibrie_tpu.core.dictionary import Dictionary, QUOTED_BIT
from kolibrie_tpu.core.quoted import QuotedTripleStore
from kolibrie_tpu.core.store import ColumnarTripleStore
from kolibrie_tpu.core.triple import Triple
from kolibrie_tpu.query import rdf_parsers
from kolibrie_tpu.query.rdf_parsers import ParsedTerm, format_term_nt

_NUM_RE = re.compile(r'^"([+-]?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)"')

DEFAULT_PREFIXES = {
    "rdf": "http://www.w3.org/1999/02/22-rdf-syntax-ns#",
    "rdfs": "http://www.w3.org/2000/01/rdf-schema#",
    "xsd": "http://www.w3.org/2001/XMLSchema#",
}


class SparqlDatabase:
    """In-memory RDF(-star) store with dictionary-encoded columnar triples."""

    def __init__(self) -> None:
        self.store = ColumnarTripleStore()
        self.dictionary = Dictionary()
        self.quoted = QuotedTripleStore()
        self.prefixes: Dict[str, str] = dict(DEFAULT_PREFIXES)
        self.udfs: Dict[str, Callable] = {}
        self.rule_map: Dict[str, object] = {}
        self.model_registry: Dict[str, object] = {}
        self.neural_relations: Dict[str, object] = {}
        self.trained_models: Dict[str, object] = {}
        self.probability_seeds: Dict[Tuple[int, int, int], float] = {}
        # query execution: "auto" = device engine above a size threshold with
        # host fallback; "device" forces the TPU path; "host" forces numpy
        self.execution_mode: str = "auto"
        self._stats = None
        self._stats_version = -1
        self._numeric_cache: Optional[np.ndarray] = None
        self._numeric_cache_len = 0

    # ------------------------------------------------------------- encoding

    def encode_parsed_term(self, term: ParsedTerm) -> int:
        """Encode a parser-produced term (string or nested ('qt', s, p, o))."""
        if isinstance(term, tuple):
            _, s, p, o = term
            return self.quoted.intern(
                self.encode_parsed_term(s),
                self.encode_parsed_term(p),
                self.encode_parsed_term(o),
            )
        return self.dictionary.encode(term)

    def encode_term_str(self, term: str) -> int:
        """Encode a term given in text syntax, supporting ``<< s p o >>``.

        Parity: ``sparql_database.rs:87`` ``encode_term_star``.
        """
        term = term.strip()
        if term.startswith("<<") and term.endswith(">>"):
            parts = split_quoted_triple_content(term[2:-2].strip())
            ids = [self.encode_term_str(p) for p in parts]
            if len(ids) != 3:
                raise ValueError(f"malformed quoted triple: {term!r}")
            return self.quoted.intern(*ids)
        if term.startswith("<") and term.endswith(">"):
            return self.dictionary.encode(term[1:-1])
        return self.dictionary.encode(term)

    def lookup_term_str(self, term: str) -> Optional[int]:
        """Non-interning counterpart of :meth:`encode_term_str` — same
        normalization (``<iri>`` brackets, ``<< s p o >>`` quoted triples),
        but returns ``None`` for unknown terms instead of allocating IDs."""
        term = term.strip()
        if term.startswith("<<") and term.endswith(">>"):
            parts = split_quoted_triple_content(term[2:-2].strip())
            if len(parts) != 3:
                return None
            ids = [self.lookup_term_str(p) for p in parts]
            if any(i is None for i in ids):
                return None
            return self.quoted.lookup(*ids)
        if term.startswith("<") and term.endswith(">"):
            term = term[1:-1]
        return self.dictionary.lookup(term)

    def decode_term(self, term_id: int) -> Optional[str]:
        return self.dictionary.decode_term(term_id, self.quoted)

    # ------------------------------------------------------------- mutation

    def add_triple_parts(self, s: str, p: str, o: str) -> Triple:
        t = Triple(
            self.encode_term_str(s), self.encode_term_str(p), self.encode_term_str(o)
        )
        self.store.add_triple(t)
        return t

    def add_triple(self, t: Triple) -> None:
        self.store.add_triple(t)

    def delete_triple(self, t: Triple) -> None:
        self.store.remove(t.subject, t.predicate, t.object)

    def __len__(self) -> int:
        return len(self.store)

    # -------------------------------------------------------------- parsing

    def _ingest(self, parsed: List[Tuple[ParsedTerm, ParsedTerm, ParsedTerm]]) -> int:
        if not parsed:
            return 0
        n = len(parsed)
        s = np.empty(n, dtype=np.uint32)
        p = np.empty(n, dtype=np.uint32)
        o = np.empty(n, dtype=np.uint32)
        enc = self.encode_parsed_term
        for i, (ts, tp, to) in enumerate(parsed):
            s[i] = enc(ts)
            p[i] = enc(tp)
            o[i] = enc(to)
        self.store.add_batch(s, p, o)
        return n

    def parse_turtle(self, data: str) -> int:
        native = self._parse_turtle_native(data)
        if native is not None:
            return native
        triples, prefixes = rdf_parsers.parse_turtle(data, self.prefixes)
        self.prefixes.update(prefixes)
        return self._ingest(triples)

    def _parse_turtle_native(self, data: str) -> Optional[int]:
        """Bulk fast path: chunk-parallel C++ Turtle tokenizer + unique-term
        interning (see :mod:`kolibrie_tpu.native.ttl_native`).  Returns None
        (fall back) for Turtle-star / ``[]`` / ``()`` / multiline strings /
        ``@base`` or if native is off."""
        try:
            from kolibrie_tpu.native.ttl_native import bulk_parse_turtle
        except ImportError:
            return None
        result = bulk_parse_turtle(data, self.prefixes)
        if result is None:
            return None
        ids, terms, prefixes_out = result
        self.prefixes.update(prefixes_out)
        return self._ingest_native_session(ids, terms)

    def parse_n3(self, data: str) -> int:
        triples, prefixes = rdf_parsers.parse_n3(data, self.prefixes)
        self.prefixes.update(prefixes)
        return self._ingest(triples)

    def parse_ntriples(self, data: str) -> int:
        native = self._parse_ntriples_native(data)
        if native is not None:
            return native
        return self._ingest(rdf_parsers.parse_ntriples(data))

    def _ingest_native_session(self, ids: np.ndarray, terms) -> int:
        """Shared tail of every native bulk parse: intern the session's
        UNIQUE terms once (``encode_batch``), then remap the (n, 3)
        1-based id matrix with one vectorized gather into the store.
        ``remap[0]`` is intentionally never read (ids are 1-based)."""
        remap = np.empty(len(terms) + 1, dtype=np.uint32)
        remap[1:] = self.dictionary.encode_batch(terms)
        cols = remap[ids]
        self.store.add_batch(cols[:, 0], cols[:, 1], cols[:, 2])
        return int(ids.shape[0])

    def _parse_ntriples_native(self, data: str) -> Optional[int]:
        """Bulk fast path: C++ tokenizer + unique-term interning; Python
        interns only unique terms, then one vectorized remap.  Returns None
        (fall back) for RDF-star / Turtle constructs or if native is off."""
        try:
            from kolibrie_tpu.native.nt_native import bulk_parse_ntriples
        except ImportError:
            return None
        result = bulk_parse_ntriples(data)
        if result is None:
            return None
        return self._ingest_native_session(*result)

    # ------------------------------------------------- preemption/restart

    def checkpoint(self, path: str) -> None:
        """One-file durable snapshot of the DATA state (docs/PREEMPTION.md):
        triple columns, dictionary, quoted-triple table, prefixes, and
        probability seeds.  Rules, UDFs, neural registries, and device
        residency are CONFIGURATION/derived state — re-registered by the
        application and lazily rebuilt from the restored columns.  The
        reference keeps everything in memory with no snapshot at all
        (SURVEY §5 "checkpoint/resume: none")."""
        # kolint: durable-path — checkpoints must survive a crash mid-write
        from kolibrie_tpu.durability.fsio import atomic_write

        s, p, o = self.store.columns()
        seeds = self.probability_seeds
        # write through a file object: np.savez_compressed appends ".npz"
        # to bare string paths, which would break same-path restore.
        # temp → fsync → rename: a kill -9 mid-checkpoint leaves the
        # previous checkpoint intact, never a torn half-file (KL701)
        with atomic_write(path) as fh:
            self._checkpoint_to(fh, s, p, o, seeds)

    def _checkpoint_to(self, fh, s, p, o, seeds) -> None:
        import pickle

        np.savez_compressed(
            fh,
            s=s,
            p=p,
            o=o,
            terms=np.frombuffer(
                pickle.dumps(self.dictionary.id_to_str), dtype=np.uint8
            ),
            quoted=np.asarray(
                [
                    (qid, t[0], t[1], t[2])
                    for qid, t in sorted(self.quoted.items())
                ],
                dtype=np.uint64,
            ).reshape(-1, 4),
            prefixes=np.frombuffer(pickle.dumps(self.prefixes), dtype=np.uint8),
            seeds=np.asarray(
                [(k[0], k[1], k[2], v) for k, v in sorted(seeds.items())],
                dtype=np.float64,
            ).reshape(-1, 4),
        )

    @classmethod
    def from_checkpoint(cls, path: str) -> "SparqlDatabase":
        """Rebuild a database from :meth:`checkpoint` output; indexes and
        device copies are rebuilt lazily on first use."""
        import pickle

        data = np.load(path, allow_pickle=False)
        db = cls()
        db.store.add_batch(
            data["s"].astype(np.uint32),
            data["p"].astype(np.uint32),
            data["o"].astype(np.uint32),
        )
        id_to_str = pickle.loads(data["terms"].tobytes())
        db.dictionary.id_to_str = id_to_str
        db.dictionary.str_to_id = {
            t: i for i, t in enumerate(id_to_str) if t is not None
        }
        # display is a POSITION-aligned cache of id_to_str; replacing the
        # term list wholesale requires rebuilding it, or later appends
        # would extend a misaligned prefix (wrong decoded rows)
        from kolibrie_tpu.core.dictionary import display_form

        db.dictionary.display = [display_form(t) for t in id_to_str]
        db.dictionary._next_id = len(id_to_str)
        for qid, s_, p_, o_ in data["quoted"].astype(np.uint64).tolist():
            key = (int(s_), int(p_), int(o_))
            db.quoted.triple_to_id[key] = int(qid)
            db.quoted.id_to_triple[int(qid)] = key
        db.prefixes = pickle.loads(data["prefixes"].tobytes())
        for s_, p_, o_, prob in data["seeds"].tolist():
            db.probability_seeds[(int(s_), int(p_), int(o_))] = float(prob)
        return db

    # --------------------------------------------------- whole-database ops

    def _remap_from(self, other: "SparqlDatabase"):
        """Id remap other→self: ``(remap, qremap)`` where ``remap`` is a
        vectorized per-plain-id array (other's terms bulk-interned into
        self's dictionary) and ``qremap`` maps other's quoted-triple ids
        after a store merge (None when other has no quoted triples)."""
        from kolibrie_tpu.core.dictionary import QUOTED_BIT

        its = other.dictionary.id_to_str
        n_plain = len(its)
        remap = np.zeros(n_plain, dtype=np.uint32)
        if n_plain > 1:
            remap[1:] = self.dictionary.encode_batch(its[1:])
        if len(other.quoted) == 0:
            return remap, None
        # only the plain ids actually referenced inside quoted triples need
        # dict entries (not the whole id space)
        refs = set()
        for _qid, (qs, qp, qo) in other.quoted.items():
            for t in (qs, qp, qo):
                if not (t & QUOTED_BIT):
                    refs.add(t)
        term_remap = {i: int(remap[i]) for i in refs}
        qremap = self.quoted.merge(other.quoted, term_remap)
        return remap, qremap

    @staticmethod
    def _apply_remap(col: np.ndarray, remap: np.ndarray, qremap) -> np.ndarray:
        from kolibrie_tpu.core.dictionary import QUOTED_BIT

        if qremap is None:
            return remap[col]
        quoted = (col & QUOTED_BIT) != 0
        out = remap[np.where(quoted, 0, col)]
        if quoted.any():
            out[quoted] = [qremap[int(q)] for q in col[quoted]]
        return out

    def union(self, other: "SparqlDatabase") -> "SparqlDatabase":
        """New database holding both stores' triples: other's ids re-encoded
        through a merged dictionary, probability seeds merged, prefixes/
        UDFs/registries/execution mode from self.  Parity: the reference's
        whole-DB ``union`` (``sparql_database.rs:1990-2041``) — vectorized
        remap instead of a per-triple decode/encode loop."""
        out = self.clone()
        remap, qremap = out._remap_from(other)
        s, p, o = other.store.columns()
        out.store.add_batch(
            *(self._apply_remap(c, remap, qremap) for c in (s, p, o))
        )

        def map_id(i: int) -> int:
            from kolibrie_tpu.core.dictionary import QUOTED_BIT

            if qremap is not None and (i & QUOTED_BIT):
                return qremap[i]
            return int(remap[i])

        for (ts, tp, to), prob in other.probability_seeds.items():
            out.probability_seeds[
                (map_id(ts), map_id(tp), map_id(to))
            ] = prob
        return out

    def par_join(
        self, other: "SparqlDatabase", predicate: str
    ) -> "SparqlDatabase":
        """New database with the join of the two stores along ``predicate``:
        for self ``(a, p, b)`` and other ``(b, p, c)``, emit ``(a, p, c)``.
        Shares self's dictionary (ids remain valid); other's ids are
        remapped first, so the databases need not share an id space.
        Parity: ``sparql_database.rs:2042-2117`` ``par_join`` — one
        vectorized sort join instead of a rayon fold."""
        from kolibrie_tpu.ops.join import join_indices

        out = SparqlDatabase()
        out.dictionary = self.dictionary  # shared, like the reference
        out.quoted = self.quoted
        out.prefixes = dict(self.prefixes)
        # normalized non-interning lookup (<iri> brackets accepted); an
        # unknown predicate joins nothing and must not pollute the SHARED
        # dictionary with a garbage term
        pid = self.lookup_term_str(predicate)
        if pid is None:
            return out
        remap, qremap = self._remap_from(other)
        os_, op, oo = (
            self._apply_remap(c, remap, qremap)
            for c in other.store.columns()
        )
        s, p, o = self.store.columns()
        lmask = p == pid
        rmask = op == pid
        li, ri = join_indices(
            o[lmask].astype(np.uint64), os_[rmask].astype(np.uint64)
        )
        ls = s[lmask][li]
        ro = oo[rmask][ri]
        out.store.add_batch(
            ls, np.full(len(ls), pid, dtype=np.uint32), ro
        )
        return out

    def parse_rdf(self, data: str) -> int:
        """RDF/XML. Parity: ``sparql_database.rs:401`` ``parse_rdf``."""
        native = self._parse_rdf_native(data)
        if native is not None:
            return native
        return self._ingest(rdf_parsers.parse_rdf_xml(data))

    def _parse_rdf_native(self, data: str) -> Optional[int]:
        """Bulk fast path: streaming C++ RDF/XML parser + unique-term
        interning.  None (fall back to ElementTree) for shapes outside the
        common bulk subset — see ``bulk_parse_rdf_xml``."""
        try:
            from kolibrie_tpu.native.nt_native import bulk_parse_rdf_xml
        except ImportError:
            return None
        result = bulk_parse_rdf_xml(data)
        if result is None:
            return None
        return self._ingest_native_session(*result)

    def parse_rdf_from_file(self, path: str) -> int:
        with open(path, "r", encoding="utf-8") as f:
            return self.parse_rdf(f.read())

    def load_file(self, path: str, fmt: Optional[str] = None) -> int:
        if fmt is None:
            for ext, f in (
                (".ttl", "turtle"),
                (".nt", "ntriples"),
                (".n3", "n3"),
                (".rdf", "rdfxml"),
                (".xml", "rdfxml"),
                (".owl", "rdfxml"),
            ):
                if path.endswith(ext):
                    fmt = f
                    break
            else:
                fmt = "turtle"
        with open(path, "r", encoding="utf-8") as fh:
            data = fh.read()
        if fmt in ("rdfxml", "rdf/xml", "xml"):
            return self.parse_rdf(data)
        if fmt in ("nt", "ntriples"):
            return self.parse_ntriples(data)
        if fmt == "n3":
            return self.parse_n3(data)
        return self.parse_turtle(data)

    # ---------------------------------------------------------- serialization

    def iter_decoded(self):
        for t in self.store:
            yield (
                self.decode_term(t.subject),
                self.decode_term(t.predicate),
                self.decode_term(t.object),
            )

    def to_ntriples(self) -> str:
        out = []
        for s, p, o in self.iter_decoded():
            out.append(f"{format_term_nt(s)} {format_term_nt(p)} {format_term_nt(o)} .")
        return "\n".join(out) + ("\n" if out else "")

    def to_turtle(self) -> str:
        """Subject/predicate-grouped Turtle-star with prefix compaction
        (``generate_turtle``, sparql_database.rs:343-400)."""
        from kolibrie_tpu.query.rdf_parsers import serialize_turtle

        return serialize_turtle(self.iter_decoded(), self.prefixes)

    def to_rdfxml(self) -> str:
        """RDF/XML export (``generate_rdf_xml``, sparql_database.rs:277-317).
        Quoted-triple (RDF-star) facts are omitted — RDF/XML cannot express
        them; use :meth:`to_ntriples`/:meth:`to_turtle`.  Raises
        ``ValueError`` if a predicate IRI cannot form an XML QName."""
        from kolibrie_tpu.query.rdf_parsers import serialize_rdfxml

        return serialize_rdfxml(self.iter_decoded(), self.prefixes)

    # -------------------------------------------------------------- prefixes

    def register_prefix(self, prefix: str, iri: str) -> None:
        self.prefixes[prefix.rstrip(":")] = iri

    def register_prefixes_from_query(self, query: str) -> None:
        """Parity: ``sparql_database.rs:1442``."""
        for m in re.finditer(
            r"(?i)\bPREFIX\s+([\w-]*):\s*<([^>]*)>", query
        ):
            self.prefixes[m.group(1)] = m.group(2)

    def expand_term(self, term: str) -> str:
        """Expand a prefixed name using registered prefixes; pass through IRIs
        and literals."""
        if term.startswith("<") and term.endswith(">"):
            return term[1:-1]
        if term.startswith('"') or term.startswith("_:") or term.startswith("?"):
            return term
        if ":" in term:
            pfx, local = term.split(":", 1)
            if not local.startswith("//"):
                ns = self.prefixes.get(pfx)
                if ns is not None:
                    return ns + local
        return term

    # ------------------------------------------------------------------ UDFs

    def register_udf(self, name: str, fn: Callable) -> None:
        """Parity: ``sparql_database.rs:3164`` UDF registry."""
        self.udfs[name.upper()] = fn
        # re-registering a name can change semantics of an already-cached
        # plan whose filters bound the old function: bump the cache state
        self._udf_version = self.__dict__.get("_udf_version", 0) + 1

    # --------------------------------------------------------- numeric cache

    def numeric_values(self) -> np.ndarray:
        """f64 array aligned to dictionary IDs: literal numeric value or NaN.

        This is the VPU-friendly replacement for the reference's SIMD numeric
        filter path (``apply_filters_simd``, ``sparql_database.rs:1497``):
        numeric comparison over ID columns becomes one vectorized gather +
        compare over this table.
        """
        d = self.dictionary
        n = len(d.id_to_str)
        if self._numeric_cache is None or self._numeric_cache_len < n:
            vals = np.full(n, np.nan)
            if self._numeric_cache is not None:
                vals[: self._numeric_cache_len] = self._numeric_cache
                start = self._numeric_cache_len
            else:
                start = 1
            for i in range(start, n):
                s = d.id_to_str[i]
                if s is None:
                    continue
                m = _NUM_RE.match(s) if s.startswith('"') else None
                if m:
                    vals[i] = float(m.group(1))
                elif not s.startswith('"'):
                    try:
                        vals[i] = float(s)
                    except ValueError:
                        pass
            self._numeric_cache = vals
            self._numeric_cache_len = n
        return self._numeric_cache

    # ----------------------------------------------------------------- stats

    def get_or_build_stats(self):
        """Sampled cardinality stats for the optimizer (built lazily, cached
        per store BASE version — stats guide plan choice, so small delta
        drift is tolerable and re-sampling per mutation batch is not).
        Parity: ``sparql_database.rs:202`` →
        ``stats/database_stats.rs:43``."""
        from kolibrie_tpu.optimizer.stats import DatabaseStats

        v = self.store.base_version
        if self._stats is None or self._stats_version != v:
            self._stats = DatabaseStats.gather_stats_fast(self)
            self._stats_version = v
        return self._stats

    def query(self):
        """Fluent builder entry point (python/src/py_query_builder.rs surface)."""
        from kolibrie_tpu.query.builder import QueryBuilder

        return QueryBuilder(self)

    def clone(self) -> "SparqlDatabase":
        db = SparqlDatabase()
        db.store = self.store.clone()
        db.dictionary = self.dictionary.clone()
        db.quoted = self.quoted.clone()
        db.prefixes = dict(self.prefixes)
        db.udfs = dict(self.udfs)
        db.rule_map = dict(self.rule_map)
        db.model_registry = dict(self.model_registry)
        db.neural_relations = dict(self.neural_relations)
        db.trained_models = dict(self.trained_models)
        db.probability_seeds = dict(self.probability_seeds)
        db.execution_mode = self.execution_mode
        return db


def split_quoted_triple_content(content: str) -> List[str]:
    """Split ``s p o`` inside ``<< ... >>`` respecting nested ``<< >>``,
    ``<...>`` IRIs and quoted literals.

    Parity: ``sparql_database.rs:130`` ``split_quoted_triple_content``.
    """
    parts: List[str] = []
    buf: List[str] = []
    depth = 0
    in_str = False
    i = 0
    n = len(content)
    while i < n:
        c = content[i]
        if in_str:
            buf.append(c)
            if c == "\\" and i + 1 < n:
                buf.append(content[i + 1])
                i += 2
                continue
            if c == '"':
                in_str = False
            i += 1
            continue
        if c == '"':
            in_str = True
            buf.append(c)
            i += 1
            continue
        if content.startswith("<<", i):
            depth += 1
            buf.append("<<")
            i += 2
            continue
        if content.startswith(">>", i):
            depth -= 1
            buf.append(">>")
            i += 2
            continue
        if c.isspace() and depth == 0:
            if buf:
                parts.append("".join(buf))
                buf = []
            i += 1
            continue
        buf.append(c)
        i += 1
    if buf:
        parts.append("".join(buf))
    return parts
