"""Inline eligible sub-SELECTs into the enclosing group graph pattern.

A sub-SELECT with no aggregation, no solution modifiers (DISTINCT / ORDER
BY / LIMIT / OFFSET), and a plain patterns+filters body is bag-equivalent
to joining its WHERE patterns directly into the outer group, provided the
variables NOT carried by its projection are first renamed to fresh names
(SPARQL scopes them to the subquery, so an outer variable with the same
name must not unify with them).  Rewriting before planning lets the
Streamertail optimizer order joins globally and — the point on TPU — lets
the device engine compile outer patterns and subquery patterns into ONE
XLA program.  The previous strategy (still used for non-inlinable
subqueries) evaluates the subquery as a separate program and equi-joins
the two materialized tables on host.

Parity: the reference materializes every nested select and hash-joins it
into the outer solution (``kolibrie/src/sparql_database.rs`` nested-select
handling); its criterion "COMPLEX QUERY" benchmark
(``kolibrie/benches/my_benchmark.rs:55-113``) is exactly an inlinable
shape.  Multiplicity is preserved: projection without DISTINCT keeps one
row per inner solution, so the join of the projected table equals the
projection of the inlined join.
"""

from __future__ import annotations

from typing import Dict, List, Set

from kolibrie_tpu.query.ast import (
    ArithOp,
    Comparison,
    FuncExpr,
    FunctionCall,
    LogicalAnd,
    LogicalNot,
    LogicalOr,
    PatternTerm,
    PatternTriple,
    QuotedPattern,
    SelectQuery,
    SubQuery,
    Var,
    WhereClause,
)

__all__ = ["inline_subqueries"]


# ----------------------------------------------------------------- renaming


def _rename_term(t: PatternTerm, ren: Dict[str, str]) -> PatternTerm:
    if t.kind == "var":
        new = ren.get(t.value)  # type: ignore[arg-type]
        return PatternTerm("var", new) if new is not None else t
    if t.kind == "quoted":
        s, p, o = t.value  # type: ignore[misc]
        return PatternTerm(
            "quoted",
            (_rename_term(s, ren), _rename_term(p, ren), _rename_term(o, ren)),
        )
    return t


def _rename_pattern(p: PatternTriple, ren: Dict[str, str]) -> PatternTriple:
    return PatternTriple(
        _rename_term(p.subject, ren),
        _rename_term(p.predicate, ren),
        _rename_term(p.object, ren),
    )


def _rename_arith(e, ren: Dict[str, str]):
    if isinstance(e, Var):
        new = ren.get(e.name)
        return Var(new) if new is not None else e
    if isinstance(e, ArithOp):
        return ArithOp(_rename_arith(e.left, ren), e.op, _rename_arith(e.right, ren))
    if isinstance(e, FuncExpr):
        return FuncExpr(e.name, [_rename_arith(a, ren) for a in e.args])
    if isinstance(e, QuotedPattern):
        return QuotedPattern(
            _rename_arith(e.subject, ren),
            _rename_arith(e.predicate, ren),
            _rename_arith(e.object, ren),
        )
    return e  # literals / IRIs


def _rename_filter(e, ren: Dict[str, str]):
    if isinstance(e, Comparison):
        return Comparison(_rename_arith(e.left, ren), e.op, _rename_arith(e.right, ren))
    if isinstance(e, LogicalAnd):
        return LogicalAnd(_rename_filter(e.left, ren), _rename_filter(e.right, ren))
    if isinstance(e, LogicalOr):
        return LogicalOr(_rename_filter(e.left, ren), _rename_filter(e.right, ren))
    if isinstance(e, LogicalNot):
        return LogicalNot(_rename_filter(e.inner, ren))
    if isinstance(e, FunctionCall):
        return FunctionCall(e.name, [_rename_arith(a, ren) for a in e.args])
    return e


# ------------------------------------------------------------- var harvest


def _arith_vars(e, out: Set[str]) -> None:
    if isinstance(e, Var):
        out.add(e.name)
    elif isinstance(e, (ArithOp, Comparison)):
        _arith_vars(e.left, out)
        _arith_vars(e.right, out)
    elif isinstance(e, (FuncExpr, FunctionCall)):
        for a in e.args:
            _arith_vars(a, out)
    elif isinstance(e, QuotedPattern):
        _arith_vars(e.subject, out)
        _arith_vars(e.predicate, out)
        _arith_vars(e.object, out)
    elif isinstance(e, (LogicalAnd, LogicalOr)):
        _arith_vars(e.left, out)
        _arith_vars(e.right, out)
    elif isinstance(e, LogicalNot):
        _arith_vars(e.inner, out)


def _where_vars(w: WhereClause, out: Set[str]) -> None:
    """Every variable name textually visible anywhere under ``w`` (used to
    keep generated names fresh; over-collecting is safe)."""
    for p in w.patterns:
        out.update(p.variables())
    for f in w.filters:
        _arith_vars(f, out)
    for b in w.binds:
        out.add(b.var)
        _arith_vars(b.expr, out)
    if w.values is not None:
        out.update(w.values.variables)
    for sq in w.subqueries:
        for item in sq.query.select:
            if item.var:
                out.add(item.var)
            if item.alias:
                out.add(item.alias)
        _where_vars(sq.query.where, out)
    for nb in w.not_blocks:
        for p in nb.patterns:
            out.update(p.variables())
    for wb in w.window_blocks:
        for p in wb.patterns:
            out.update(p.variables())
        for f in wb.filters:
            _arith_vars(f, out)
    for opt in w.optionals:
        _where_vars(opt, out)
    for groups in w.unions:
        for g in groups:
            _where_vars(g, out)
    for m in w.minus:
        _where_vars(m, out)


# ------------------------------------------------------------- eligibility


def _inlinable(q: SelectQuery) -> bool:
    if q.distinct or q.group_by or q.order_by:
        return False
    if q.limit is not None or q.offset is not None:
        return False
    if not q.select_all() and any(i.kind != "var" for i in q.select):
        return False  # aggregates / expression projections
    w = q.where
    if not w.patterns:
        return False
    return not (
        w.binds
        or w.values is not None
        or w.subqueries
        or w.not_blocks
        or w.window_blocks
        or w.optionals
        or w.unions
        or w.minus
    )


# ----------------------------------------------------------------- rewrite


def inline_subqueries(where: WhereClause) -> WhereClause:
    """Return ``where`` with every eligible sub-SELECT folded into the
    outer patterns+filters (fresh names for subquery-scoped variables);
    non-inlinable subqueries stay in ``.subqueries`` for the
    materialize-then-join path.  Input is never mutated; returns the input
    object unchanged when there is nothing to do."""
    if not where.subqueries:
        return where

    used: Set[str] = set()
    _where_vars(where, used)

    patterns: List[PatternTriple] = list(where.patterns)
    filters = list(where.filters)
    remaining: List[SubQuery] = []
    changed = False

    for sq in where.subqueries:
        q = sq.query
        # fold the subquery's own nested subqueries first (depth-first), so
        # a nest of plain selects flattens completely
        inner_where = inline_subqueries(q.where)
        if inner_where is not q.where:
            q = SelectQuery(
                select=q.select,
                where=inner_where,
                distinct=q.distinct,
                group_by=q.group_by,
                order_by=q.order_by,
                limit=q.limit,
                offset=q.offset,
                prefixes=q.prefixes,
            )
        if not _inlinable(q):
            remaining.append(SubQuery(q) if q is not sq.query else sq)
            continue

        inner_vars: Set[str] = set()
        for p in q.where.patterns:
            inner_vars.update(p.variables())
        for f in q.where.filters:
            _arith_vars(f, inner_vars)
        if q.select_all():
            projected = set(inner_vars)
        else:
            projected = {i.var for i in q.select if i.var}
        ren: Dict[str, str] = {}
        for name in sorted(inner_vars - projected):
            n = 0
            fresh = f"__sq{n}_{name}"
            while fresh in used:
                n += 1
                fresh = f"__sq{n}_{name}"
            used.add(fresh)
            ren[name] = fresh
        patterns.extend(_rename_pattern(p, ren) for p in q.where.patterns)
        filters.extend(_rename_filter(f, ren) for f in q.where.filters)
        changed = True

    if not changed:
        return where
    return WhereClause(
        patterns=patterns,
        filters=filters,
        binds=where.binds,
        values=where.values,
        subqueries=remaining,
        not_blocks=where.not_blocks,
        window_blocks=where.window_blocks,
        optionals=where.optionals,
        unions=where.unions,
        minus=where.minus,
    )
