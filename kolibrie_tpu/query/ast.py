"""The extended-SPARQL AST shared by the parser, executors, and RSP builder.

Parity: ``shared/src/query.rs`` (346 LoC of enums/structs): filter
expressions with full precedence, arithmetic, VALUES, INSERT/DELETE,
subqueries, ML.PREDICT, model/neural-relation/train declarations, windowing
(RSP-QL), sync policies, stream types, PROB annotations, combined rules,
RETRIEVE, and the top-level CombinedQuery.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional, Tuple, Union


# --------------------------------------------------------------------------
# Filter / arithmetic expressions  (query.rs:15-57)
# --------------------------------------------------------------------------


@dataclass
class Comparison:
    """``left <op> right`` where sides are ArithmeticExpression."""

    left: "ArithExpr"
    op: str  # = != < <= > >=
    right: "ArithExpr"


@dataclass
class LogicalAnd:
    left: "FilterExpression"
    right: "FilterExpression"


@dataclass
class LogicalOr:
    left: "FilterExpression"
    right: "FilterExpression"


@dataclass
class LogicalNot:
    inner: "FilterExpression"


@dataclass
class FunctionCall:
    """Builtin or UDF call in filter context, e.g. ``BOUND(?x)``,
    ``isTRIPLE(?t)``, ``REGEX(?s, "pat")``."""

    name: str
    args: List["ArithExpr"]


FilterExpression = Union[Comparison, LogicalAnd, LogicalOr, LogicalNot, FunctionCall]


@dataclass
class Var:
    name: str


@dataclass
class NumberLit:
    value: float


@dataclass
class StringLit:
    value: str  # stored-term form (quoted lexical)


@dataclass
class IriRef:
    iri: str  # expanded


@dataclass
class ArithOp:
    left: "ArithExpr"
    op: str  # + - * /
    right: "ArithExpr"


@dataclass
class FuncExpr:
    name: str
    args: List["ArithExpr"]


@dataclass
class QuotedPattern:
    """RDF-star quoted triple in expression/pattern position."""

    subject: "ArithExpr"
    predicate: "ArithExpr"
    object: "ArithExpr"


ArithExpr = Union[Var, NumberLit, StringLit, IriRef, ArithOp, FuncExpr, QuotedPattern]


# --------------------------------------------------------------------------
# Patterns and clauses
# --------------------------------------------------------------------------


@dataclass
class PatternTerm:
    """Unresolved pattern position: variable, term string, or quoted pattern."""

    kind: str  # "var" | "term" | "quoted"
    value: Union[str, Tuple["PatternTerm", "PatternTerm", "PatternTerm"]]

    @staticmethod
    def var(name: str) -> "PatternTerm":
        return PatternTerm("var", name)

    @staticmethod
    def term(text: str) -> "PatternTerm":
        return PatternTerm("term", text)

    @property
    def is_var(self) -> bool:
        return self.kind == "var"


@dataclass
class PatternTriple:
    subject: PatternTerm
    predicate: PatternTerm
    object: PatternTerm

    def variables(self) -> List[str]:
        out = []
        for t in (self.subject, self.predicate, self.object):
            if t.kind == "var":
                out.append(t.value)  # type: ignore[arg-type]
            elif t.kind == "quoted":
                s, p, o = t.value  # type: ignore[misc]
                out.extend(PatternTriple(s, p, o).variables())
        return out


@dataclass
class BindClause:
    expr: ArithExpr
    var: str


@dataclass
class ValuesClause:
    variables: List[str]
    rows: List[List[Optional[str]]]  # term strings; None = UNDEF


@dataclass
class Aggregate:
    func: str  # COUNT SUM AVG MIN MAX GROUP_CONCAT SAMPLE
    var: Optional[str]  # argument variable; None = * (COUNT only)
    alias: str
    distinct: bool = False


@dataclass
class SelectItem:
    """Projection item: plain variable, aggregate, or expression AS alias."""

    kind: str  # "var" | "agg" | "expr"
    var: Optional[str] = None
    agg: Optional[Aggregate] = None
    expr: Optional[ArithExpr] = None
    alias: Optional[str] = None


@dataclass
class OrderCondition:
    expr: ArithExpr
    descending: bool = False


@dataclass
class InsertClause:
    triples: List[PatternTriple]


@dataclass
class DeleteClause:
    triples: List[PatternTriple]
    where: Optional["WhereClause"] = None


@dataclass
class SubQuery:
    query: "SelectQuery"


@dataclass
class NotBlock:
    """NAF block in rule bodies: ``NOT { patterns }`` (parser.rs:699)."""

    patterns: List[PatternTriple]


@dataclass
class WindowBlock:
    """``WINDOW :w { patterns }`` inside WHERE (parser.rs:664)."""

    window_iri: str
    patterns: List[PatternTriple]
    filters: List[FilterExpression] = field(default_factory=list)


@dataclass
class WhereClause:
    patterns: List[PatternTriple] = field(default_factory=list)
    filters: List[FilterExpression] = field(default_factory=list)
    binds: List[BindClause] = field(default_factory=list)
    values: Optional[ValuesClause] = None
    subqueries: List[SubQuery] = field(default_factory=list)
    not_blocks: List[NotBlock] = field(default_factory=list)
    window_blocks: List[WindowBlock] = field(default_factory=list)
    optionals: List["WhereClause"] = field(default_factory=list)
    unions: List[List["WhereClause"]] = field(default_factory=list)
    minus: List["WhereClause"] = field(default_factory=list)


# --------------------------------------------------------------------------
# Windowing / RSP-QL  (query.rs:172-252)
# --------------------------------------------------------------------------


class WindowType(Enum):
    SLIDING = "sliding"
    TUMBLING = "tumbling"


@dataclass
class WindowSpec:
    """``[RANGE n STEP m]`` / ``[SLIDING n SLIDE m]`` / ``[TUMBLING n]`` with
    optional ``REPORT <strategy>`` and ``TICK <strategy>``."""

    width: int  # RANGE (time units / item count)
    slide: int  # STEP
    window_type: WindowType = WindowType.SLIDING
    report: str = "ON_WINDOW_CLOSE"  # NON_EMPTY_CONTENT|ON_CONTENT_CHANGE|ON_WINDOW_CLOSE|PERIODIC
    tick: str = "TIME_DRIVEN"  # TIME_DRIVEN | TUPLE_DRIVEN


class SyncPolicyKind(Enum):
    STEAL = "steal"
    WAIT = "wait"
    TIMEOUT = "timeout"


class TimeoutFallback(Enum):
    STEAL = "steal"
    DROP = "drop"


@dataclass
class SyncPolicy:
    """Multi-window coordination policy (query.rs:203-217)."""

    kind: SyncPolicyKind = SyncPolicyKind.STEAL
    timeout_ms: int = 0
    fallback: TimeoutFallback = TimeoutFallback.STEAL


class StreamType(Enum):
    RSTREAM = "RSTREAM"
    ISTREAM = "ISTREAM"
    DSTREAM = "DSTREAM"


@dataclass
class WindowClause:
    """``FROM NAMED WINDOW :w ON :stream [RANGE n STEP m]``."""

    window_iri: str
    stream_iri: str
    spec: WindowSpec
    policy: Optional[SyncPolicy] = None


@dataclass
class RegisterClause:
    """``REGISTER RSTREAM :out AS SELECT ...`` (query.rs:228-252)."""

    stream_type: StreamType
    output_iri: str
    select: "SelectQuery"
    windows: List[WindowClause] = field(default_factory=list)


# --------------------------------------------------------------------------
# ML / neurosymbolic declarations  (query.rs:101-169)
# --------------------------------------------------------------------------


class LossFn(Enum):
    CROSS_ENTROPY = "cross_entropy"
    NLL = "nll"
    MSE = "mse"
    BCE = "bce"


class OptimizerKind(Enum):
    ADAM = "adam"
    SGD = "sgd"


@dataclass
class ModelArch:
    """MLP architecture: hidden layer sizes."""

    hidden: List[int] = field(default_factory=lambda: [16])


@dataclass
class NeuralOutputKind:
    """``OUTPUT BINARY`` or ``OUTPUT EXCLUSIVE { "l0", "l1", ... }``."""

    kind: str  # "binary" | "exclusive"
    labels: List[str] = field(default_factory=list)


@dataclass
class ModelDecl:
    """``MODEL "name" { ARCH MLP { HIDDEN [64, 32] } OUTPUT ... }``."""

    name: str
    arch: ModelArch
    output: NeuralOutputKind = field(
        default_factory=lambda: NeuralOutputKind("binary")
    )
    options: Dict[str, str] = field(default_factory=dict)


@dataclass
class NeuralRelationDecl:
    """``NEURAL RELATION pred USING MODEL "m" { INPUT {...} FEATURES {...} }``."""

    predicate: str
    model_name: str
    input_patterns: List[PatternTriple] = field(default_factory=list)
    anchor_var: str = ""  # subject variable of the first input pattern
    feature_vars: List[str] = field(default_factory=list)


@dataclass
class TrainNeuralRelationDecl:
    """``TRAIN NEURAL RELATION pred { DATA{...}|QUERY{...} LABEL ?l
    TARGET {...} LOSS .. OPTIMIZER .. ... }``."""

    relation: str
    data_patterns: List[PatternTriple] = field(default_factory=list)
    data_query: Optional[str] = None
    label_var: str = ""
    target: Optional[PatternTriple] = None
    loss: LossFn = LossFn.BCE
    optimizer: OptimizerKind = OptimizerKind.ADAM
    epochs: int = 10
    batch_size: int = 32
    learning_rate: float = 0.01
    save_path: Optional[str] = None


@dataclass
class MLPredictClause:
    """``ML.PREDICT(MODEL :m, INPUT { SELECT ... }, OUTPUT ?var)``
    (query.rs:101-108)."""

    model: str
    input_select: "SelectQuery"
    output_var: str


# --------------------------------------------------------------------------
# Probabilistic annotation + rules  (query.rs:257-306)
# --------------------------------------------------------------------------


@dataclass
class ProbAnnotation:
    combination: str = "minmax"  # minmax | addmult | boolean | topk | wmc | sdd
    threshold: Optional[float] = None
    confidence: Optional[float] = None
    k: int = 5  # topk proof budget (reference default, parser.rs:2679)


@dataclass
class CombinedRule:
    """``RULE :Name(?a, ?b) :- body => { conclusions }`` (query.rs:265-284)."""

    name: str
    params: List[str]
    body: WhereClause
    conclusions: List[PatternTriple]
    prob: Optional[ProbAnnotation] = None
    windows: List[WindowClause] = field(default_factory=list)
    ml_predict: Optional[MLPredictClause] = None
    stream_type: Optional[StreamType] = None


@dataclass
class RetrieveClause:
    """``RETRIEVE SOME|EVERY ACTIVE|LATENT STREAM ?s FROM <catalog> WITH
    { patterns }`` (query.rs:299-306, parser.rs:2067-2144)."""

    mode: str  # SOME | EVERY
    state: str  # ACTIVE | LATENT
    variable: str  # stream variable, e.g. "s"
    from_iri: str  # catalog IRI
    with_patterns: List[PatternTriple] = field(default_factory=list)


# --------------------------------------------------------------------------
# Queries
# --------------------------------------------------------------------------


@dataclass
class SelectQuery:
    select: List[SelectItem]
    where: WhereClause
    distinct: bool = False
    group_by: List[str] = field(default_factory=list)
    order_by: List[OrderCondition] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    prefixes: Dict[str, str] = field(default_factory=dict)

    def select_all(self) -> bool:
        return len(self.select) == 1 and self.select[0].kind == "var" and self.select[0].var == "*"


@dataclass
class CombinedQuery:
    """Top-level parse result (query.rs:320-345): any combination of
    declarations, rules, a select/register query, and updates."""

    select: Optional[SelectQuery] = None
    register: Optional[RegisterClause] = None
    rules: List[CombinedRule] = field(default_factory=list)
    insert: Optional[InsertClause] = None
    delete: Optional[DeleteClause] = None
    models: List[ModelDecl] = field(default_factory=list)
    neural_relations: List[NeuralRelationDecl] = field(default_factory=list)
    train_decls: List[TrainNeuralRelationDecl] = field(default_factory=list)
    ml_predict: Optional[MLPredictClause] = None
    retrieve: Optional[RetrieveClause] = None
    prefixes: Dict[str, str] = field(default_factory=dict)
