"""Annotated rendering of SPARQL parse errors.

Parity: ``kolibrie/src/error_handler.rs:14-259`` — converts a parse failure
into a compiler-style annotated snippet with line/column, a caret marking the
failing position, and a HELP footer when a common SPARQL mistake is detected:
SELECT without WHERE, unbalanced braces, unterminated string literal,
undefined prefix, and missing `.`/`;` separators between triple patterns.
"""

from __future__ import annotations

from typing import Optional, Tuple

from kolibrie_tpu.query.parser import SparqlParseError

#: prefixes the reference treats as well-known (error_handler.rs:188)
_WELL_KNOWN_PREFIXES = {"rdf", "rdfs", "owl", "xsd", "foaf", "dc"}


def format_parse_error(source: str, err: SparqlParseError) -> str:
    """Render ``err`` (raised while parsing ``source``) as an annotated,
    multi-line message. Mirrors ``format_parse_error`` (error_handler.rs:14)."""
    line_no = max(err.line, 1)
    col_no = max(err.col, 1)
    lines = source.split("\n")
    error_line = (
        lines[line_no - 1] if line_no <= len(lines) else "[end of input]"
    )
    offset = sum(len(l) + 1 for l in lines[: line_no - 1]) + (col_no - 1)
    offset = min(offset, len(source))

    title = f"{err.message} at line {line_no}, column {col_no}"
    label = err.message
    footer = err.hint or None

    specific = detect_specific_sparql_error(source, offset)
    if specific is not None:
        title, label, footer = specific

    gutter = len(str(line_no))
    pad = " " * gutter
    caret_col = min(col_no, len(error_line) + 1)
    out = [
        f"error: {title}",
        f"{pad}--> query:{line_no}:{col_no}",
        f"{pad} |",
        f"{line_no} | {error_line}",
        f"{pad} | {' ' * (caret_col - 1)}^ {label}",
    ]
    if footer:
        out.append(f"{pad} = help: {footer}")
    return "\n".join(out)


def detect_specific_sparql_error(
    source: str, offset: int
) -> Optional[Tuple[str, str, str]]:
    """Heuristic detection of common SPARQL mistakes
    (error_handler.rs:135-180). Returns (title, label, help) or None."""
    lower = source.lower()

    if (
        "select" in lower
        and "where" not in lower
        and "insert" not in lower
    ):
        return (
            "SELECT query missing WHERE clause",
            "SELECT statement found but no WHERE clause",
            "SPARQL SELECT queries typically require a WHERE clause. "
            "Example: SELECT ?var WHERE { ?var ?pred ?obj }",
        )

    open_braces = source.count("{")
    close_braces = source.count("}")
    if open_braces != close_braces:
        return (
            "Unclosed brace in SPARQL query",
            "missing closing '}'" if open_braces > close_braces else "extra '}'",
            f"Found {open_braces} opening '{{' but {close_braces} "
            "closing '}' in the query",
        )

    before = source[:offset]
    if before.count('"') % 2 != 0:
        return (
            "Unterminated string literal",
            "string not closed with matching quote",
            "Make sure all string literals are properly closed with "
            "matching double quotes",
        )

    prefix_error = _check_missing_prefix(source, offset)
    if prefix_error is not None:
        return prefix_error

    return _check_missing_triple_separator(source, offset)


def _check_missing_prefix(
    source: str, offset: int
) -> Optional[Tuple[str, str, str]]:
    """error_handler.rs:183-216 — last token before the error uses an
    undeclared prefix."""
    declared = set(_WELL_KNOWN_PREFIXES)
    for line in source.split("\n"):
        stripped = line.strip()
        if stripped.upper().startswith("PREFIX "):
            parts = stripped.split()
            if len(parts) >= 2 and ":" in parts[1]:
                declared.add(parts[1][: parts[1].index(":")])

    words = source[:offset].split()
    if words:
        last = words[-1]
        if ":" in last and not last.startswith("<") and not last.startswith('"'):
            potential = last.split(":", 1)[0]
            if potential and not potential.startswith("?") and potential not in declared:
                return (
                    f"Undefined prefix '{potential}'",
                    f"prefix '{potential}' is not declared",
                    f"Add a PREFIX declaration like: PREFIX {potential}: "
                    "<http://example.org/>",
                )
    return None


def _check_missing_triple_separator(
    source: str, offset: int
) -> Optional[Tuple[str, str, str]]:
    """error_handler.rs:219-247 — two variables in a row with no `.`/`;`
    between pattern boundaries."""
    trimmed = source[:offset].rstrip()
    if "?" not in trimmed or not trimmed:
        return None
    last_char = trimmed[-1]
    if not (last_char.isalnum() or last_char == "_"):
        return None
    tail = trimmed[-10:]
    if "?" in tail and not any(c in tail for c in ".;{"):
        return (
            "Missing separator between triple patterns",
            "expected '.' or ';' to separate triple patterns",
            "Triple patterns in SPARQL should be separated by '.' or ';'",
        )
    return None
