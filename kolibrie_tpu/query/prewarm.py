"""Background pre-warm: compile the hot templates before clients do.

The persistent compilation cache (:mod:`kolibrie_tpu.query.compile_cache`)
turns a restart's compile tail from "recompile everything" into "reload
from disk" — but a disk load is still milliseconds of deserialization
per template, paid by the first unlucky foreground query.  The warmer
moves even that off the request path:

- at startup (once recovery opens the gate) it replays the top-N
  templates from the persisted manifest against every registered store,
  so the first foreground query finds the in-process jit cache hot;
- it is *admission-aware*: before each compile it checks the server's
  inflight count and backs off while real traffic is being served — the
  warmer must never add latency to the tail it exists to remove;
- warm executions run with the plan interpreter forced OFF
  (:func:`~kolibrie_tpu.optimizer.plan_interp.override_mode`), so they
  produce the *specialized* executable and flip auto-mode routing for
  that template shape from the interpreter to the compiled fast path
  (``mark_compiled``);
- it periodically persists the manifest so the next restart knows this
  process's hot set.

The module is deliberately server-agnostic: targets are ``(label, db,
lock)`` triples and idleness is a callable, so tests (and the restart
regression test) drive it directly against a bare database.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

from kolibrie_tpu.obs import metrics as _metrics
from kolibrie_tpu.query import compile_cache

__all__ = ["PrewarmManager", "replay_manifest", "warm_one"]

_COMPILED = _metrics.counter(
    "kolibrie_prewarm_compiled_total",
    "templates warmed (specialized plan compiled or disk-loaded)",
)
_SKIPPED = _metrics.counter(
    "kolibrie_prewarm_skipped_total",
    "warm attempts skipped (admission pressure or unknown template)",
)
_ERRORS = _metrics.counter(
    "kolibrie_prewarm_errors_total", "warm attempts that raised"
)
_WARM_LAT = _metrics.histogram(
    "kolibrie_prewarm_seconds", "per-template warm wall time"
)

# targets: (label, database, lock-or-None); the lock is the store's
# dispatch serialization (TemplateBatcher.dispatch_lock on the server)
Target = Tuple[str, object, Optional[threading.Lock]]

DEFAULT_TOP_N = 32
_IDLE_WAIT_S = 0.05
_IDLE_RETRIES = 40  # ~2s of admission pressure before skipping a template


def warm_one(db, query: str, lock: Optional[threading.Lock] = None) -> dict:
    """Execute ``query`` against ``db`` with interpreter routing forced
    off, returning ``{ms, source, rows}``.  The execution IS the warm:
    it lowers the specialized plan, compiles (or disk-loads) the jit
    executable, and marks the shape compiled for auto-mode routing."""
    from kolibrie_tpu.optimizer.plan_interp import override_mode
    from kolibrie_tpu.query.executor import execute_query_volcano, plan_cache_info
    from kolibrie_tpu.query.template import fingerprint_query
    from kolibrie_tpu.query.parser import parse_combined_query

    t0 = time.perf_counter()
    with compile_cache.suppress_recording(), override_mode("off"):
        if lock is not None:
            with lock:
                rows = execute_query_volcano(query, db)
        else:
            rows = execute_query_volcano(query, db)
        # mesh-attached store: the serving path dispatches template
        # groups through the sharded program — warm that executable too
        # (its compile is the biggest single tail item on real meshes)
        sharded = db.__dict__.get("_sharded_serving")
        mesh_warmed = sharded.warm(query) if sharded is not None else None
        ms = (time.perf_counter() - t0) * 1000.0
        # source of the executable this warm produced (interp is
        # impossible here — routing was forced off)
        fp, _ = fingerprint_query(parse_combined_query(query, db.prefixes))
    per = plan_cache_info(db)["per_template"].get(fp, {})
    out = {"ms": round(ms, 3), "source": per.get("source"), "rows": len(rows)}
    if mesh_warmed is not None:
        out["mesh"] = mesh_warmed
    return out


def replay_manifest(
    db,
    root: Optional[str] = None,
    top_n: int = DEFAULT_TOP_N,
    lock: Optional[threading.Lock] = None,
    is_idle: Optional[Callable[[], bool]] = None,
) -> List[dict]:
    """Warm ``db`` from the persisted manifest (hottest first).  The
    restart regression test calls this directly: after it returns, the
    first real query must trigger zero XLA compiles and zero disk
    misses."""
    # tuned routing rides the same manifest: import the advisor section
    # BEFORE replaying, so even the warm executions plan from the
    # previous process's learned cardinalities
    compile_cache.load_advisor_state(root)
    results: List[dict] = []
    for ent in compile_cache.load_manifest(root)[:top_n]:
        results.append(
            _warm_entry(ent, [("db", db, lock)], is_idle or (lambda: True))
        )
    return results


def _warm_entry(
    ent: dict, targets: List[Target], is_idle: Callable[[], bool]
) -> dict:
    out = {"fp": ent.get("fp"), "hits": ent.get("hits", 0), "targets": {}}
    query = ent.get("query")
    if not query:
        _SKIPPED.inc()
        out["skipped"] = "no representative query"
        return out
    for label, db, lock in targets:
        for _ in range(_IDLE_RETRIES):
            if is_idle():
                break
            time.sleep(_IDLE_WAIT_S)
        else:
            _SKIPPED.inc()
            out["targets"][label] = {"skipped": "admission pressure"}
            continue
        try:
            t0 = time.perf_counter()
            res = warm_one(db, query, lock)
            _COMPILED.inc()
            _WARM_LAT.observe(time.perf_counter() - t0)
            out["targets"][label] = res
        except Exception as e:  # a poisoned template must not stop the sweep
            _ERRORS.inc()
            out["targets"][label] = {"error": repr(e)}
    return out


class PrewarmManager:
    """Owns the warmer thread: startup replay, periodic manifest saves,
    and the on-demand sweep behind ``POST /debug/prewarm``."""

    def __init__(
        self,
        get_targets: Callable[[], List[Target]],
        is_idle: Callable[[], bool] = lambda: True,
        is_ready: Callable[[], bool] = lambda: True,
        root: Optional[str] = None,
        top_n: int = DEFAULT_TOP_N,
        save_interval_s: float = 30.0,
    ):
        self.get_targets = get_targets
        self.is_idle = is_idle
        self.is_ready = is_ready
        self.root = root
        self.top_n = top_n
        self.save_interval_s = save_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()  # serializes run_once vs the thread
        self.startup_replayed = 0  # guarded by: _lock
        self.last_results: List[dict] = []  # guarded by: _lock

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="kolibrie-prewarm"
        )
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
        compile_cache.save_manifest(self.root)

    def _run(self) -> None:
        from kolibrie_tpu.obs.spans import trace_scope

        # fresh trace: warm sweeps land under one queryable trace id
        # (thread-locals do not cross the make_server -> warmer hop)
        with trace_scope(None):
            # gate on readiness: recovery replay owns the device until
            # the server opens; the warmer is strictly lower priority
            while not self._stop.is_set() and not self.is_ready():
                time.sleep(_IDLE_WAIT_S)
            if not self._stop.is_set():
                replayed = len(self.run_once())
                with self._lock:
                    self.startup_replayed = replayed
            while not self._stop.wait(self.save_interval_s):
                compile_cache.save_manifest(self.root)

    # ------------------------------------------------------------------ work

    def run_once(self, top_n: Optional[int] = None) -> List[dict]:
        """One warm sweep: manifest entries (disk ∪ in-memory, hottest
        first) against every current target.  Serialized against the
        background thread's own sweep."""
        n = top_n or self.top_n
        compile_cache.load_advisor_state(self.root)
        merged = {e["fp"]: e for e in compile_cache.load_manifest(self.root)}
        for e in compile_cache.manifest_snapshot():
            old = merged.get(e["fp"])
            if old is None or e.get("hits", 0) >= old.get("hits", 0):
                merged[e["fp"]] = e
        ranked = sorted(
            merged.values(), key=lambda e: (-e.get("hits", 0), e["fp"])
        )[:n]
        results: List[dict] = []
        with self._lock:
            targets = list(self.get_targets())
            for ent in ranked:
                if self._stop.is_set():
                    break
                results.append(_warm_entry(ent, targets, self.is_idle))
            self.last_results = results
        return results

    def stats(self) -> dict:
        with self._lock:
            return {
                "startup_replayed": self.startup_replayed,
                "top_n": self.top_n,
                "last_sweep": len(self.last_results),
            }


# Debug-build runtime check of the # guarded by: annotations above
# (no-op unless KOLIBRIE_DEBUG_LOCKS=1 — see analysis/lockcheck.py)
from kolibrie_tpu.analysis import lockcheck as _lockcheck

_lockcheck.auto_instrument(globals())
