"""QueryEngine facade — load + query + explain + stats.

Parity: ``kolibrie/src/query_engine.rs:17-158`` — ``QueryEngine`` (new /
load_ntriples_to_memory / add_triple / query via the Volcano path),
``explain`` with ``StorageMode`` Static/Streaming/Hybrid decided by keyword
detection (:117-156), and ``QueryEngineStats`` (:114-116).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List

from kolibrie_tpu.query.executor import execute_query_volcano
from kolibrie_tpu.query.sparql_database import SparqlDatabase


class StorageMode:
    STATIC = "Static"
    STREAMING = "Streaming"
    HYBRID = "Hybrid"


@dataclass
class QueryExplanation:
    storage_mode: str
    will_use_volcano: bool
    has_windowing: bool
    window_clauses: List[str] = field(default_factory=list)


@dataclass
class QueryEngineStats:
    memory_triple_count: int


_WINDOWING_KEYWORDS = (
    "WINDOW", "FROM NAMED WINDOW", "SLIDING", "TUMBLING", "RANGE",
    "RSTREAM", "ISTREAM", "DSTREAM", "SLIDE",
)

# Keywords must be standalone tokens: not part of a larger word, not the
# local part of a prefixed name (ex:range), not a variable (?range) — and
# IRIs, string literals, and # comments are scrubbed before matching.
_WINDOWING_RE = re.compile(
    r"(?<![\w:?$])("
    + "|".join(re.escape(k) for k in _WINDOWING_KEYWORDS)
    + r")(?![\w:])"
)
_SCRUB_RE = re.compile(
    r"""<[^>\s]*>              # IRIs
      | "(?:[^"\\]|\\.)*"      # double-quoted literals
      | '(?:[^'\\]|\\.)*'      # single-quoted literals
      | \#[^\n]*               # comments
    """,
    re.VERBOSE,
)


def _scrub(query: str) -> str:
    """Blank out IRIs, literals and comments so keyword detection only sees
    real syntax."""
    return _SCRUB_RE.sub(" ", query)


def has_windowing_operations(query: str) -> bool:
    return _WINDOWING_RE.search(_scrub(query).upper()) is not None


_RSPQL_RE = re.compile(
    r"(?<![\w:?$])REGISTER\s+(R|I|D)STREAM(?![\w:])", re.IGNORECASE
)


def is_rspql_query(query: str) -> bool:
    return _RSPQL_RE.search(_scrub(query)) is not None


def extract_window_clauses(query: str) -> List[str]:
    clauses = []
    start = query.upper().find("FROM NAMED WINDOW")
    if start >= 0:
        remaining = query[start:]
        end = remaining.upper().find("WHERE")
        clauses.append((remaining[:end] if end >= 0 else remaining).strip())
    return clauses


class QueryEngine:
    """Simple facade: an in-memory database plus the Volcano query path."""

    def __init__(self, db: SparqlDatabase | None = None) -> None:
        self.db = db if db is not None else SparqlDatabase()

    def load_ntriples_to_memory(self, data: str) -> int:
        return self.db.parse_ntriples(data)

    def load_turtle_to_memory(self, data: str) -> int:
        return self.db.parse_turtle(data)

    def add_triple(self, subject: str, predicate: str, obj: str) -> None:
        self.db.add_triple_parts(subject, predicate, obj)

    def query(self, sparql: str) -> List[List[str]]:
        return execute_query_volcano(sparql, self.db)

    def explain(self, sparql: str) -> QueryExplanation:
        windowing = has_windowing_operations(sparql)
        rspql = is_rspql_query(sparql)
        if rspql:
            mode = StorageMode.STREAMING
        elif windowing:
            mode = StorageMode.HYBRID
        else:
            mode = StorageMode.STATIC
        return QueryExplanation(
            storage_mode=mode,
            will_use_volcano=not rspql,
            has_windowing=windowing,
            window_clauses=extract_window_clauses(sparql),
        )

    def stats(self) -> QueryEngineStats:
        return QueryEngineStats(memory_triple_count=len(self.db))

    def explain_device(self, sparql: str, exact_counts: bool = True,
                       analyze: bool = False) -> str:
        """Physical-plan EXPLAIN for the device engine: the Streamertail
        plan lowered to its device IR, rendered as a tree with scan orders
        + live range sizes, join keys + capacities, filters, quoted
        expansions and the final projection.  ``exact_counts`` also runs
        the host-oracle pass to annotate each join with its true match
        count (no device I/O).  Returns a 'host path: <reason>' line when
        the plan is not device-expressible.

        ``analyze=True`` is EXPLAIN ANALYZE: the lowered plan actually
        executes once under an analyze capture, and the tree is annotated
        with per-operator actual row counts, cap occupancy percentages,
        and the per-stage device time from the dispatch's spans —
        estimated vs actual, PostgreSQL style."""
        from kolibrie_tpu.optimizer.device_engine import (
            Unsupported,
            lower_plan,
        )
        from kolibrie_tpu.optimizer.engine import resolve_pattern
        from kolibrie_tpu.optimizer.planner import (
            Streamertail,
            build_logical_plan,
        )
        from kolibrie_tpu.query.parser import parse_sparql_query

        self.db.register_prefixes_from_query(sparql)
        # plan under the SAME template fingerprint the executor would
        # use, so the Streamertail pass consults (and the analyze
        # execution feeds) the stats advisor's learned cardinalities for
        # this template — EXPLAIN shows the plan clients actually get
        from kolibrie_tpu.optimizer import stats_advisor as _sa
        from kolibrie_tpu.query.parser import parse_combined_query
        from kolibrie_tpu.query.template import fingerprint_query

        try:
            fp, _ = fingerprint_query(
                parse_combined_query(sparql, self.db.prefixes)
            )
        # kolint: ignore[KL601] EXPLAIN renders even for unparseable fp
        except Exception:
            fp = None
        _sa.set_current_fp(fp)
        q = parse_sparql_query(sparql, self.db.prefixes)
        from kolibrie_tpu.query.executor import _branch_plan
        from kolibrie_tpu.query.subquery_inline import inline_subqueries
        from kolibrie_tpu.query.ast import WhereClause

        w = inline_subqueries(q.where)
        resolved = [resolve_pattern(self.db, p) for p in w.patterns]
        logical = build_logical_plan(
            resolved, list(w.filters), [], w.values
        )
        planner = Streamertail(self.db.get_or_build_stats())
        plan = planner.find_best_plan(logical)
        union_groups, optional_plans, anti_plans = [], [], []
        fusable = not w.subqueries
        for groups in w.unions if fusable else ():
            g = [_branch_plan(self.db, planner, bw) for bw in groups]
            if any(bp is None for bp in g):
                fusable = False
                break
            union_groups.append(tuple(g))
        for ow in w.optionals if fusable else ():
            bp = _branch_plan(self.db, planner, ow)
            if bp is None:
                fusable = False
                break
            optional_plans.append(bp)
        branches = list(w.minus) + [
            WhereClause(patterns=nb.patterns) for nb in w.not_blocks
        ]
        for bw in branches if fusable else ():
            bp = _branch_plan(self.db, planner, bw)
            if bp is None:
                fusable = False
                break
            anti_plans.append(bp)
        if not fusable:
            union_groups, optional_plans, anti_plans = [], [], []
        try:
            lowered = lower_plan(
                self.db,
                plan,
                tuple(anti_plans),
                tuple(union_groups),
                tuple(optional_plans),
            )
        except Unsupported as e:
            return f"host path: {e}"
        counts = (
            lowered.calibrate_host() if exact_counts or analyze else None
        )
        from kolibrie_tpu.optimizer import mqo

        mqo_line = mqo.describe_shared(self.db, lowered)
        if not analyze:
            out = lowered.describe(counts)
            return out + "\n" + mqo_line if mqo_line else out
        from kolibrie_tpu.obs import analyze as obs_analyze
        from kolibrie_tpu.obs.spans import spans_snapshot, trace_scope

        with obs_analyze.capture() as cap, trace_scope() as tid:
            lowered.execute()
        rec = cap.last("device") or {}
        rep = _sa.stats_advisor.report(fp)
        drift = rep["ops"] if rep else None
        lines = [lowered.describe(counts, analyze=rec, drift=drift)]
        if mqo_line:
            lines.append(mqo_line)
        if _sa.stats_advisor_mode() == "off":
            lines.append("advisor: off")
        elif rep is None:
            lines.append("advisor: source=agm replans=0 drift=cold")
        else:
            lines.append(
                f"advisor: source={rep['source']}"
                f" replans={rep['replans']} drift={rep['drift']}"
            )
        if rec:
            lines.append(f"source: {rec.get('source', '?')}")
            lines.append(f"rows: {rec.get('rows', '?')}")
        stage_spans = [
            s for s in spans_snapshot(tid)
            if s["name"].startswith(("device.", "interp."))
        ]
        if stage_spans:
            lines.append("device time:")
            for s in stage_spans:
                lines.append(f"  {s['name']}: {s['dur_ms']:.3f} ms")
        return "\n".join(lines)
