"""Query driver: parse → (updates | plan → execute) → post-process → format.

Parity: ``kolibrie/src/execute_query.rs`` — the Volcano path
``execute_query_rayon_parallel2_volcano`` (:356): TRAIN decls, DELETE (re-issue
SELECT + substitute + delete), INSERT, logical plan build, memoized
``Streamertail::find_best_plan``, execution, then the post-pass (subqueries,
GROUP BY/aggregate, ORDER BY, LIMIT, formatting :607-650).  The legacy
sequential join path ``execute_query`` (:156) is kept as the naive reference
implementation for agreement testing (the reference's own most valuable test
pattern, SURVEY §4).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kolibrie_tpu.core.dictionary import QUOTED_BIT, display_form
from kolibrie_tpu.core.triple import Triple
from kolibrie_tpu.optimizer.engine import UNBOUND, ExecutionEngine, resolve_pattern
from kolibrie_tpu.optimizer.planner import Streamertail, build_logical_plan
from kolibrie_tpu.ops.join import (
    BindingTable,
    anti_join_tables,
    concat_tables,
    equi_join_tables,
    left_outer_join_tables,
    table_len,
)
from kolibrie_tpu.ops.unique import unique_rows, unique_table
from kolibrie_tpu.query.ast import (
    Aggregate,
    CombinedQuery,
    DeleteClause,
    InsertClause,
    OrderCondition,
    PatternTerm,
    PatternTriple,
    SelectItem,
    SelectQuery,
    SubQuery,
    Var,
    WhereClause,
)
from kolibrie_tpu.obs import metrics as obs_metrics
from kolibrie_tpu.obs.spans import set_baggage, span
from kolibrie_tpu.optimizer.stats_advisor import (
    set_current_fp as _sa_set_current_fp,
)
from kolibrie_tpu.query.parser import parse_combined_query
from kolibrie_tpu.resilience.breaker import breaker_board
from kolibrie_tpu.resilience.deadline import check_deadline
from kolibrie_tpu.resilience.errors import DeadlineExceeded, is_device_fault

Rows = List[List[str]]

_PARSE_LAT = obs_metrics.histogram(
    "kolibrie_query_parse_seconds", "SPARQL parse + template fingerprint time"
)
_PLAN_LAT = obs_metrics.histogram(
    "kolibrie_query_plan_seconds",
    "Streamertail planning time (plan-cache misses only)",
)
_QUERY_LAT = obs_metrics.histogram(
    "kolibrie_query_seconds",
    "end-to-end executor time by path (device/host/degraded)",
    labels=("path",),
)
_PLAN_CACHE_EVENTS = obs_metrics.counter(
    "kolibrie_plan_cache_events_total",
    "plan cache events (hit/miss/param_rebind/eviction)",
    labels=("event",),
)
_BATCHED_QUERIES = obs_metrics.counter(
    "kolibrie_query_batched_total",
    "queries served by a stacked-parameter batch dispatch",
)
# fixed-label children hoisted out of the per-query hot path
_QUERY_LAT_DEVICE = _QUERY_LAT.labels("device")
_QUERY_LAT_HOST = _QUERY_LAT.labels("host")
_QUERY_LAT_DEGRADED = _QUERY_LAT.labels("degraded")
_PLAN_CACHE_HIT = _PLAN_CACHE_EVENTS.labels("hit")
_PLAN_CACHE_MISS = _PLAN_CACHE_EVENTS.labels("miss")
_PLAN_CACHE_REBIND = _PLAN_CACHE_EVENTS.labels("param_rebind")
_PLAN_CACHE_EVICTION = _PLAN_CACHE_EVENTS.labels("eviction")

# "auto" execution mode switches to the device engine at this store size;
# db.execution_mode = "device" / "host" forces either path.
_DEVICE_AUTO_MIN = 100_000


# --------------------------------------------------------------------------
# WHERE evaluation (shared by volcano executor, rules, RSP, ML input queries)
# --------------------------------------------------------------------------


def _interp_mode() -> str:
    """Current ``KOLIBRIE_PLAN_INTERP`` routing mode (lazy import: the
    interpreter module pulls in the device engine)."""
    from kolibrie_tpu.optimizer.plan_interp import plan_interp_mode

    return plan_interp_mode()


def _device_routed(db) -> bool:
    """THE routing rule for "does this query run on the device engine":
    explicit ``execution_mode == "device"``, or auto mode over a store big
    enough that device dispatch beats the host numpy engine."""
    mode = getattr(db, "execution_mode", "auto")
    return mode == "device" or (
        mode == "auto" and len(db.store) >= _DEVICE_AUTO_MIN
    )


def eval_where(
    db,
    where: WhereClause,
    use_optimizer: bool = True,
    prebuilt_plan=None,
    prebuilt_lowered=None,
    capture=None,
) -> BindingTable:
    """Evaluate a group graph pattern to a binding table (IDs).

    ``prebuilt_plan``: physical plan already produced for this WHERE (the
    device-aggregation attempt plans first; on fallback the plan is reused
    here instead of running the optimizer twice).  ``prebuilt_lowered``:
    the matching device-lowered plan — an object to execute directly,
    ``False`` if lowering already failed (skip the device path), None if
    no lowering was attempted yet.  ``capture``: plan-cache entry dict —
    the plan and the lowered program (or ``False`` for a failed lowering)
    are recorded into it for reuse by the next identical query."""
    from kolibrie_tpu.query.subquery_inline import inline_subqueries

    # Fold plain sub-SELECTs into the group before planning: one plan (and
    # on TPU one device program) instead of materialize-then-join-on-host.
    # Non-inlinable subqueries stay in where.subqueries for the post-pass.
    where = inline_subqueries(where)
    engine = ExecutionEngine(db, subquery_eval=lambda sq: eval_select_to_table(db, sq.query))
    resolved = [resolve_pattern(db, p) for p in where.patterns]
    # filters referencing BIND outputs can only run after the binds
    bind_vars = {b.var for b in where.binds}
    plan_filters = [
        f for f in where.filters if not (set(_filter_vars(f)) & bind_vars)
    ]
    post_bind_filters = [
        f for f in where.filters if set(_filter_vars(f)) & bind_vars
    ]
    fused_clauses = False
    if use_optimizer:
        planner = Streamertail(db.get_or_build_stats())
        if prebuilt_plan is not None:
            plan = prebuilt_plan
        else:
            logical = build_logical_plan(resolved, plan_filters, [], where.values)
            with span("query.plan"):
                t0 = time.perf_counter()
                plan = planner.find_best_plan(logical)
                _PLAN_LAT.observe(time.perf_counter() - t0)
        if capture is not None:
            capture["plan"] = plan
        table = None
        if prebuilt_lowered is not None and prebuilt_lowered is not False:
            table = prebuilt_lowered.execute()
            fused_clauses = getattr(prebuilt_lowered, "fused_clauses", False)
        elif prebuilt_lowered is None and _device_routed(db):
            from kolibrie_tpu.optimizer.device_engine import try_device_execute

            # UNION / OPTIONAL / MINUS / NOT clauses fuse into the device
            # program (union concat, left-outer join, anti-join) in the
            # same order the host post-passes apply them.  All-or-nothing:
            # a single non-BGP branch keeps everything on the post-pass
            # path so clause ordering semantics never split across engines.
            union_groups: List[tuple] = []
            optional_plans: List[object] = []
            anti_plans: List[object] = []
            fusable = not where.subqueries and (
                where.minus
                or where.not_blocks
                or where.unions
                or where.optionals
            )
            if fusable:
                for groups in where.unions:
                    g = [_branch_plan(db, planner, bw) for bw in groups]
                    if any(bp is None for bp in g):
                        fusable = False
                        break
                    union_groups.append(tuple(g))
                for ow in where.optionals if fusable else ():
                    bp = _branch_plan(db, planner, ow)
                    if bp is None:
                        fusable = False
                        break
                    optional_plans.append(bp)
                branches = list(where.minus) + [
                    WhereClause(patterns=nb.patterns)
                    for nb in where.not_blocks
                ]
                for bw in branches if fusable else ():
                    bp = _branch_plan(db, planner, bw)
                    if bp is None:
                        fusable = False
                        break
                    anti_plans.append(bp)
            if fusable:
                main_plan = plan
                if not where.patterns and where.values is None:
                    # clause-only group: the first union/optional stands
                    # alone (plan=None).  Filters attached to an empty
                    # plan never see clause columns on the host path, so
                    # only a filter-free group keeps exact parity.
                    if where.filters or not (union_groups or optional_plans):
                        main_plan = False  # shape host handles better
                    else:
                        main_plan = None
                if main_plan is not False:
                    table = try_device_execute(
                        db,
                        main_plan,
                        tuple(anti_plans),
                        tuple(union_groups),
                        tuple(optional_plans),
                        capture=capture,
                    )
                    fused_clauses = table is not None
            if table is None:
                table = try_device_execute(db, plan, capture=capture)
        if table is None and not _device_routed(db):
            # host-routed stores (RSP window stores live far below the
            # device-routing floor) reach the MQO layer here: the shared
            # prefix evaluates through the numpy twin and only the filter
            # suffix runs per query (optimizer/mqo.py, docs/MQO.md)
            from kolibrie_tpu.optimizer import mqo as _mqo

            table = _mqo.try_shared_host(db, plan)
        if table is None:
            from kolibrie_tpu.obs import analyze as _obs_analyze

            cap_rec = _obs_analyze.active()
            if cap_rec is not None:
                # EXPLAIN ANALYZE honesty: say WHICH engine ran when the
                # query never reached a device program
                cap_rec.record(
                    "host",
                    reason=(
                        "device lowering unavailable"
                        if _device_routed(db)
                        else "host-routed store"
                    ),
                )
            table = engine.execute_with_ids(plan)
    else:
        table = _naive_eval(engine, resolved, where, plan_filters)
    # subqueries join in
    for sq in where.subqueries:
        sub = eval_select_to_table(db, sq.query)
        table = equi_join_tables(table, sub)
    # UNION groups
    for groups in () if fused_clauses else where.unions:
        parts = [eval_where(db, g, use_optimizer) for g in groups]
        keys = set()
        for t in parts:
            keys |= set(t)
        norm = []
        for t in parts:
            nt = dict(t)
            n = table_len(t)
            for k in keys:
                if k not in nt:
                    nt[k] = np.full(n, UNBOUND, dtype=np.uint32)
            norm.append(nt)
        union_table = concat_tables(norm) if norm else {}
        table = equi_join_tables(table, union_table) if table_len(table) or where.patterns else union_table
    # OPTIONAL — over the unit table (no preceding clauses produced columns)
    # join(unit, optional) keeps the optional's solutions
    for opt in () if fused_clauses else where.optionals:
        opt_table = eval_where(db, opt, use_optimizer)
        if (
            not table
            and not where.patterns
            and where.values is None
            and not where.subqueries
            and not where.unions
        ):
            table = opt_table
        else:
            table = left_outer_join_tables(table, opt_table)
    # MINUS
    if not fused_clauses:
        for m in where.minus:
            table = anti_join_tables(table, eval_where(db, m, use_optimizer))
        # NOT blocks (NAF)
        for nb in where.not_blocks:
            neg_where = WhereClause(patterns=nb.patterns)
            table = anti_join_tables(
                table, eval_where(db, neg_where, use_optimizer)
            )
    # BINDs after joins (may reference any bound variable)
    for b in where.binds:
        col = engine.eval_arith_to_ids(b.expr, table)
        table = dict(table)
        table[b.var] = col
    # filters that reference BIND outputs run now
    for f in post_bind_filters:
        mask = engine.eval_filter(f, table)
        table = {k: v[mask] for k, v in table.items()}
    return table


def _branch_plan(db, planner, bw: WhereClause):
    """Physical plan for a clause branch (UNION / OPTIONAL / MINUS / NOT
    block) eligible to fuse into the device program; ``None`` when the
    branch needs the host post-pass (non-BGP content)."""
    from kolibrie_tpu.query.subquery_inline import inline_subqueries

    bw = inline_subqueries(bw)
    if (
        not bw.patterns
        or bw.binds
        or bw.values is not None
        or bw.subqueries
        or bw.not_blocks
        or bw.window_blocks
        or bw.optionals
        or bw.unions
        or bw.minus
    ):
        return None
    bres = [resolve_pattern(db, p) for p in bw.patterns]
    blogical = build_logical_plan(bres, list(bw.filters), [], None)
    return planner.find_best_plan(blogical)


def _filter_vars(expr) -> List[str]:
    from kolibrie_tpu.query import ast as A

    out: List[str] = []

    def walk(e):
        if isinstance(e, A.Var):
            out.append(e.name)
        elif isinstance(e, A.Comparison):
            walk(e.left)
            walk(e.right)
        elif isinstance(e, (A.LogicalAnd, A.LogicalOr)):
            walk(e.left)
            walk(e.right)
        elif isinstance(e, A.LogicalNot):
            walk(e.inner)
        elif isinstance(e, (A.FunctionCall, A.FuncExpr)):
            for a in e.args:
                walk(a)
        elif isinstance(e, A.ArithOp):
            walk(e.left)
            walk(e.right)

    walk(expr)
    return out


def _naive_eval(
    engine: ExecutionEngine, patterns, where: WhereClause, filters
) -> BindingTable:
    """Legacy sequential join path (execute_query.rs:156): patterns joined in
    textual order, filters applied at the end."""
    table: Optional[BindingTable] = None
    for pat in patterns:
        t = engine._scan(pat)
        table = t if table is None else equi_join_tables(table, t)
    if table is None:
        table = {}
        if where.values is not None:
            table = engine._values_table(where.values)
    elif where.values is not None:
        table = equi_join_tables(table, engine._values_table(where.values))
    for f in filters:
        mask = engine.eval_filter(f, table)
        table = {k: v[mask] for k, v in table.items()}
    return table


# --------------------------------------------------------------------------
# SELECT execution
# --------------------------------------------------------------------------


def eval_select_to_table(
    db, q: SelectQuery, use_optimizer: bool = True, cache_entry=None
) -> BindingTable:
    """Run a SELECT down to a binding table projected to its variables
    (aggregates resolved).  Used for subqueries and ML input queries.

    ``cache_entry``: automatic plan-cache slot (see ``_plan_cache_entry``)
    — a populated entry's plan/lowered program short-circuit the planner
    and device lowering; a fresh one captures them for the next call."""
    prebuilt_plan = None
    prebuilt_lowered = None
    if q.group_by or any(i.kind == "agg" for i in q.select):
        table, prebuilt_plan, prebuilt_lowered = _try_device_aggregate(
            db, q, use_optimizer, cache_entry=cache_entry
        )
        if table is not None:
            if q.distinct:
                table = unique_table(table)
            return table
        cache_entry = None  # aggregate fallback: prebuilts already in hand
    if cache_entry is not None:
        if cache_entry["plan"] is not None:
            prebuilt_plan = cache_entry["plan"]
        if cache_entry["lowered"] is not None:
            prebuilt_lowered = cache_entry["lowered"]
    table = eval_where(
        db,
        q.where,
        use_optimizer,
        prebuilt_plan=prebuilt_plan,
        prebuilt_lowered=prebuilt_lowered,
        capture=cache_entry,
    )
    if q.group_by or any(i.kind == "agg" for i in q.select):
        table = _group_and_aggregate_table(db, table, q)
    else:
        if not q.select_all():
            keep = [i.var for i in q.select if i.kind == "var" and i.var in table]
            engine = ExecutionEngine(db)
            out: BindingTable = {v: table[v] for v in keep}
            for item in q.select:
                if item.kind == "expr":
                    out[item.alias] = engine.eval_arith_to_ids(item.expr, table)
            table = out
        elif any(k.startswith("__") for k in table):
            # internal columns (e.g. inlined subqueries' scoped variables)
            # are not part of ``*`` — drop them BEFORE DISTINCT so dedup
            # runs over the visible projection only
            table = {k: v for k, v in table.items() if not k.startswith("__")}
    if q.distinct:
        table = unique_table(table)
    return table


def _try_device_aggregate(
    db, q: SelectQuery, use_optimizer: bool, cache_entry=None
) -> Tuple[Optional[BindingTable], Optional[object], Optional[object]]:
    """Aggregate query fused ON DEVICE (plan + GROUP BY segment-reduce in
    one device pipeline; readback is one row per group).  Returns
    ``(table, plan, lowered)``: table None → the normal eval_where + host
    aggregation path, which reuses the returned plan AND device-lowered
    plan when present (neither the optimizer nor plan lowering runs
    twice on fallback; lowered False = lowering failed, don't retry).

    ``cache_entry``: plan-cache slot — a populated slot replays the
    cached plan + lowered program (repeat aggregate queries skip the
    optimizer and lowering entirely); a fresh one captures them."""
    if not use_optimizer or not _device_routed(db):
        return None, None, None
    from kolibrie_tpu.query.subquery_inline import inline_subqueries

    w = inline_subqueries(q.where)  # same fold eval_where applies (it is
    #                                 deterministic, so the plan built here
    #                                 matches the where eval_where sees)
    if w.subqueries or w.binds or w.window_blocks or not w.patterns:
        return None, None, None
    from kolibrie_tpu.optimizer.device_engine import (
        Unsupported,
        clause_replayable,
        lower_plan,
        try_device_execute_aggregated,
    )

    if cache_entry is not None and cache_entry["lowered"] is False:
        # lowering known-failed for this template+state.  The sentinel is
        # sticky across parameter rebinds (the slot's plan is dropped when
        # the constants change, but lowerability is a property of the
        # template, so the False survives and no retry happens here).
        return None, cache_entry["plan"], False
    if cache_entry is not None and cache_entry["plan"] is not None:
        cplan, clow = cache_entry["plan"], cache_entry["lowered"]
        if clow is not None:
            if not clause_replayable(clow, w):
                # plain-BGP lowering for a clause-carrying WHERE: its
                # UNION/OPTIONAL/MINUS/NOT ran as host post-passes on the
                # first call — hand it back as prebuilts so eval_where
                # replays exactly that route (device BGP + host clauses +
                # host aggregation), never the fused aggregate pipeline
                return None, cplan, clow
            table = try_device_execute_aggregated(db, cplan, q, lowered=clow)
            # table None here means the AGGREGATE stage declined (shape);
            # the caller's host fallback still reuses plan+lowered
            return table, cplan, clow

    resolved = [resolve_pattern(db, p) for p in w.patterns]
    logical = build_logical_plan(resolved, list(w.filters), [], w.values)
    planner = Streamertail(db.get_or_build_stats())
    plan = planner.find_best_plan(logical)
    # UNION/OPTIONAL/MINUS/NOT fuse under the aggregation exactly as on
    # the plain path (all-or-nothing; ineligible branch → host post-pass,
    # which also means host aggregation over the post-passed table)
    union_groups, optional_plans, anti_plans = [], [], []
    fusable = True
    for groups in w.unions:
        g = [_branch_plan(db, planner, bw) for bw in groups]
        if any(bp is None for bp in g):
            fusable = False
            break
        union_groups.append(tuple(g))
    for ow in w.optionals if fusable else ():
        bp = _branch_plan(db, planner, ow)
        if bp is None:
            fusable = False
            break
        optional_plans.append(bp)
    for bw in (
        list(w.minus) + [WhereClause(patterns=nb.patterns) for nb in w.not_blocks]
        if fusable
        else ()
    ):
        bp = _branch_plan(db, planner, bw)
        if bp is None:
            fusable = False
            break
        anti_plans.append(bp)
    def _capture(p, low):
        if cache_entry is not None:
            cache_entry["plan"] = p
            cache_entry["lowered"] = low

    if not fusable and (w.unions or w.optionals or w.minus or w.not_blocks):
        # branches un-fusable: eval_where will run the plain device BGP
        # with host clause post-passes + host aggregation — lower and
        # cache that program HERE so repeats (and this call's fallback)
        # skip the second optimizer pass and the re-lowering
        try:
            plain = lower_plan(db, plan)
            _capture(plan, plain)
            return None, plan, plain
        except Unsupported:
            _capture(plan, False)
            return None, plan, False

    try:
        lowered = lower_plan(
            db, plan, tuple(anti_plans), tuple(union_groups), tuple(optional_plans)
        )
    except Unsupported:
        if anti_plans or union_groups or optional_plans:
            try:  # the plain BGP may still lower even if a branch cannot
                plain = lower_plan(db, plan)
                _capture(plan, plain)
                return None, plan, plain
            except Unsupported:
                pass
        _capture(plan, False)
        return None, plan, False
    _capture(plan, lowered)
    return (
        try_device_execute_aggregated(db, plan, q, lowered=lowered),
        plan,
        lowered,
    )


def _group_key_cols(table: BindingTable, group_by: List[str]):
    cols = [table[g] for g in group_by if g in table]
    return cols


def _group_and_aggregate_table(db, table: BindingTable, q: SelectQuery) -> BindingTable:
    """GROUP BY + aggregates via np.unique segment ids (segment-reduce —
    device-friendly).  Parity: ``group_and_aggregate_results`` in
    execute_query.rs."""
    n = table_len(table)
    group_by = [g for g in q.group_by if g in table]
    if group_by:
        cols = _group_key_cols(table, group_by)
        stacked = np.stack(cols, axis=1) if cols else np.zeros((n, 0), dtype=np.uint32)
        uniq, inverse = np.unique(stacked, axis=0, return_inverse=True)
        n_groups = len(uniq)
    else:
        # aggregate without GROUP BY: exactly one group (SPARQL semantics)
        uniq = None
        inverse = np.zeros(n, dtype=np.int64)
        n_groups = 1
    out: BindingTable = {}
    for j, g in enumerate(group_by):
        out[g] = uniq[:, j].astype(np.uint32) if uniq is not None else np.empty(0, dtype=np.uint32)
    numeric = db.numeric_values()
    enc = db.dictionary.encode
    for item in q.select:
        if item.kind != "agg":
            continue
        agg = item.agg
        vals_col: Optional[np.ndarray] = None
        if agg.var is not None and agg.var in table:
            vals_col = table[agg.var]
        if agg.func == "COUNT":
            if vals_col is None:
                counts = np.bincount(inverse, minlength=n_groups) if n else np.zeros(n_groups, dtype=np.int64)
            elif agg.distinct:
                counts = np.zeros(n_groups, dtype=np.int64)
                for g in range(n_groups):
                    seg = vals_col[inverse == g]
                    counts[g] = len(np.unique(seg[seg != UNBOUND]))
            else:
                counts = np.bincount(inverse, weights=(vals_col != UNBOUND).astype(float), minlength=n_groups).astype(np.int64) if n else np.zeros(n_groups, dtype=np.int64)
            out[agg.alias] = _encode_numbers(enc, counts.astype(np.float64))
            continue
        if vals_col is None:
            out[agg.alias] = np.full(n_groups, UNBOUND, dtype=np.uint32)
            continue
        nums = numeric[np.minimum(vals_col, len(numeric) - 1)] if n else np.empty(0)
        if agg.func in ("SUM", "AVG", "MIN", "MAX"):
            res = np.zeros(n_groups, dtype=np.float64)
            for g in range(n_groups):
                seg = nums[inverse == g]
                seg = seg[~np.isnan(seg)]
                if len(seg) == 0:
                    res[g] = np.nan
                elif agg.func == "SUM":
                    res[g] = seg.sum()
                elif agg.func == "AVG":
                    res[g] = seg.mean()
                elif agg.func == "MIN":
                    res[g] = seg.min()
                else:
                    res[g] = seg.max()
            out[agg.alias] = _encode_numbers(enc, res)
        elif agg.func == "SAMPLE":
            res_ids = np.zeros(n_groups, dtype=np.uint32)
            for g in range(n_groups):
                seg = vals_col[inverse == g]
                res_ids[g] = seg[0] if len(seg) else UNBOUND
            out[agg.alias] = res_ids
        elif agg.func == "GROUP_CONCAT":
            dec = db.decode_term
            res_ids = np.zeros(n_groups, dtype=np.uint32)
            for g in range(n_groups):
                seg = vals_col[inverse == g]
                parts = [_format_value(dec(int(i))) for i in seg]
                res_ids[g] = enc('"' + ", ".join(x or "" for x in parts) + '"')
            out[agg.alias] = res_ids
        else:
            raise ValueError(f"unsupported aggregate {agg.func}")
    return out


def _encode_numbers(enc, values: np.ndarray) -> np.ndarray:
    out = np.empty(len(values), dtype=np.uint32)
    for i, v in enumerate(values):
        if np.isnan(v):
            out[i] = UNBOUND
        else:
            # non-finite stays float-formatted ("inf"/"-inf"); int(inf) raises
            isint = np.isfinite(v) and float(v) == int(v)
            sv = str(int(v)) if isint else f"{v:g}"
            out[i] = enc(f'"{sv}"')
    return out


# --------------------------------------------------------------------------
# Ordering / formatting
# --------------------------------------------------------------------------


def _order_table(db, table: BindingTable, order_by: List[OrderCondition]) -> BindingTable:
    n = table_len(table)
    if n == 0 or not order_by:
        return table
    numeric = db.numeric_values()
    keys = []
    for cond in reversed(order_by):
        if isinstance(cond.expr, Var) and cond.expr.name in table:
            col = table[cond.expr.name]
            nums = numeric[np.minimum(col, len(numeric) - 1)]
            if np.isnan(nums).any():
                # non-numeric: rank the decoded strings so DESC can negate
                dec = db.decode_term
                strs = np.array([dec(int(i)) or "" for i in col])
                _, order_key = np.unique(strs, return_inverse=True)
                order_key = order_key.astype(np.float64)
            else:
                order_key = nums
        else:
            engine = ExecutionEngine(db)
            nums = engine._try_numeric(cond.expr, table)
            order_key = nums if nums is not None else np.zeros(n)
        if cond.descending:
            order_key = -order_key
        keys.append(order_key)
    # stable lexsort over keys (last key = primary)
    idx = np.lexsort(tuple(keys))
    return {k: v[idx] for k, v in table.items()}


def _format_value(term: Optional[str]) -> str:
    """Human-facing form: strip literal quotes and datatype suffix.

    THE display rule — delegates to :func:`core.dictionary.display_form`,
    which the dictionary also applies incrementally at intern time, so the
    per-ID display cache and this per-term path can never diverge."""
    return display_form(term)


def table_header(table: BindingTable, q: SelectQuery) -> List[str]:
    """Output column names for a SELECT over a binding table (internal
    ``__``-prefixed columns excluded)."""
    if q.select_all():
        return sorted(k for k in table.keys() if not k.startswith("__"))
    header = []
    for item in q.select:
        if item.kind == "var":
            header.append(item.var)
        elif item.kind == "agg":
            header.append(item.agg.alias)
        else:
            header.append(item.alias)
    return header


_GLOBAL_RANK_MAX = 1 << 19  # dict sizes past this use per-column ranks


def _display_array(db):
    """(dict_len, display): ``display[id]`` is the human-facing form of
    every plain dictionary term (object array; ``display[0] == ""`` for
    UNBOUND).  Maintained INCREMENTALLY: the dictionary appends display
    forms at intern time, and growth here is one ``np.concatenate`` of the
    new tail — no full rebuild.  This converts the per-query decode of
    :func:`format_results` into one fancy index — the decode analogue of
    the reference's deferred final rayon pass (engine.rs:34-50)."""
    d = db.dictionary
    n = d._next_id
    cache = db.__dict__.get("_display_cache")
    if cache is not None and cache[0] == n:
        return cache
    forms = d.display_forms()
    if cache is not None and cache[0] < n:
        disp = np.concatenate(
            [cache[1], np.array(forms[cache[0]:], dtype=object)]
        )
    else:
        disp = np.array(forms, dtype=object)
    cache = (n, disp)
    db.__dict__["_display_cache"] = cache
    return cache


def _display_ranks(db, disp, result_rows: int = 1 << 62):
    """``ranks[id]`` = dense rank of ``display[id]`` in lexicographic
    order, or None when a dictionary-wide sort would not amortize (callers
    rank per column instead).  Built only when a canonical row sort
    actually needs it, once per dictionary size.

    Under mutation the dictionary grows every batch; rebuilding the global
    ranks then costs O(dict log dict) per batch no matter how small the
    result.  A stale cache is therefore only refreshed when the result is
    large enough for the rebuild to amortize — small results on a grown
    dictionary take the per-column path, which scales with the result."""
    n = len(disp)
    if n > _GLOBAL_RANK_MAX:
        return None
    cached = db.__dict__.get("_display_ranks")
    if (cached is None or cached[0] != n) and result_rows * 8 < n:
        return None
    cache = db.__dict__.get("_display_ranks")
    if cache is not None and cache[0] == n:
        return cache[1]
    if n:
        _, ranks = np.unique(disp, return_inverse=True)
        ranks = ranks.astype(np.uint32)
    else:
        ranks = np.empty(0, dtype=np.uint32)
    db.__dict__["_display_ranks"] = (n, ranks)
    return ranks


def format_results(
    db, table: BindingTable, q: SelectQuery, sort_rows: bool = False
) -> Rows:
    """Final ID→string decode (engine.rs:34-50 parity).

    Plain-term columns decode by fancy-indexing the db-level display cache;
    ``sort_rows=True`` additionally applies the engine's canonical
    no-ORDER-BY row order (lexicographic by display string) via
    ``np.lexsort`` over per-ID display ranks — exactly ``rows.sort()``,
    without materializing rows first.  Columns containing quoted-triple IDs
    (RDF-star) take the per-unique decode path instead."""
    header = table_header(table, q)
    n = table_len(table)
    if n == 0 or not header:
        return []
    id_cols = []
    any_quoted = False
    for h in header:
        col = table.get(h)
        if col is None:
            id_cols.append(None)
            continue
        ids = np.asarray(col)
        if (ids & QUOTED_BIT).any():
            any_quoted = True
        id_cols.append(ids)
    if any_quoted:
        # rare path: per-unique recursive decode (<< s p o >> rendering)
        dec = db.decode_term
        cols = []
        for ids in id_cols:
            if ids is None:
                cols.append([""] * n)
                continue
            uniq, inv = np.unique(ids, return_inverse=True)
            decoded = [
                _format_value(dec(int(i))) if i != UNBOUND else ""
                for i in uniq
            ]
            cols.append([decoded[j] for j in inv.tolist()])
        rows = [list(row) for row in zip(*cols)]
        if sort_rows:
            rows.sort()
        return rows
    dict_len, disp = _display_array(db)
    safe_cols = [
        None if ids is None else np.where(ids < dict_len, ids, 0)
        for ids in id_cols
    ]
    if sort_rows:
        ranks = _display_ranks(db, disp, result_rows=n)
        keys = []
        for ids in safe_cols:
            if ids is None:
                keys.append(np.zeros(n, dtype=np.uint32))
            elif ranks is not None:
                keys.append(ranks[ids])
            else:
                # dictionary too large for global ranks: dense ranks over
                # just this column's distinct display strings
                u_ids, inv = np.unique(ids, return_inverse=True)
                _, u_rank = np.unique(disp[u_ids], return_inverse=True)
                keys.append(u_rank.astype(np.uint32)[inv])
        idx = np.lexsort(tuple(reversed(keys)))
        safe_cols = [None if c is None else c[idx] for c in safe_cols]
    out = np.empty((n, len(header)), dtype=object)
    for j, ids in enumerate(safe_cols):
        out[:, j] = "" if ids is None else disp[ids]
    return out.tolist()


# --------------------------------------------------------------------------
# Top-level entry points
# --------------------------------------------------------------------------


def _apply_limit_offset(rows: Rows, q: SelectQuery) -> Rows:
    start = q.offset or 0
    end = start + q.limit if q.limit is not None else None
    return rows[start:end]


def execute_select(
    db, q: SelectQuery, use_optimizer: bool = True, cache_entry=None
) -> Rows:
    if (
        use_optimizer
        and q.order_by
        and q.limit is not None
        and not (cache_entry is not None and cache_entry.get("ordered_failed"))
    ):
        # ORDER BY + LIMIT fused on device: top-k sort, O(limit) readback.
        # ``ordered_failed`` is the sticky per-template negative: once the
        # fused lowering raised Unsupported for this template+state, repeat
        # calls (any constants) skip the doomed plan+lower attempt.
        from kolibrie_tpu.optimizer.device_engine import (
            try_device_execute_ordered,
        )

        rows = try_device_execute_ordered(db, q, cache_entry=cache_entry)
        if rows is not None:
            return rows
    table = eval_select_to_table(db, q, use_optimizer, cache_entry=cache_entry)
    table = _order_table(db, table, q.order_by)
    rows = format_results(db, table, q, sort_rows=not q.order_by)
    return _apply_limit_offset(rows, q)


def process_insert_clause(db, insert: InsertClause) -> int:
    count = 0
    for pat in insert.triples:
        ids = []
        for t in (pat.subject, pat.predicate, pat.object):
            if t.is_var:
                raise ValueError("INSERT DATA cannot contain variables")
            ids.append(_encode_pattern_term(db, t))
        db.add_triple(Triple(*ids))
        count += 1
    return count


def _encode_pattern_term(db, t: PatternTerm) -> int:
    if t.kind == "quoted":
        s, p, o = t.value
        return db.quoted.intern(
            _encode_pattern_term(db, s),
            _encode_pattern_term(db, p),
            _encode_pattern_term(db, o),
        )
    return db.dictionary.encode(db.expand_term(t.value))


def process_delete_clause(db, delete: DeleteClause) -> int:
    """DELETE [WHERE]: bind variables from WHERE, substitute into the delete
    templates, remove (execute_query.rs:395-468)."""
    count = 0
    if delete.where is None:
        for pat in delete.triples:
            ids = [_encode_pattern_term(db, t) for t in (pat.subject, pat.predicate, pat.object)]
            db.delete_triple(Triple(*ids))
            count += 1
        return count
    table = eval_where(db, delete.where)
    n = table_len(table)
    for pat in delete.triples:
        cols = []
        for t in (pat.subject, pat.predicate, pat.object):
            if t.is_var:
                col = table.get(t.value)
                if col is None:
                    col = np.full(n, UNBOUND, dtype=np.uint32)
                cols.append(col)
            else:
                cols.append(np.full(n, _encode_pattern_term(db, t), dtype=np.uint32))
        for i in range(n):
            db.delete_triple(Triple(int(cols[0][i]), int(cols[1][i]), int(cols[2][i])))
            count += 1
    return count


_PLAN_CACHE_MAX = 128  # parsed-AST entries (query text → template key)


_TEMPLATE_CACHE_MAX = 64  # plan templates (fingerprint → per-state slots)


_PLAN_STATES_MAX = 4  # per-template (store version, udfs, mode) slots kept


def _plan_caches(db):
    """The two cache levels + counters, lazily attached to the database."""
    from collections import OrderedDict

    parse = db.__dict__.get("_plan_cache")
    if parse is None:
        parse = OrderedDict()
        db.__dict__["_plan_cache"] = parse
    templates = db.__dict__.get("_template_cache")
    if templates is None:
        templates = OrderedDict()
        db.__dict__["_template_cache"] = templates
    stats = db.__dict__.get("_plan_cache_stats")
    if stats is None:
        stats = {
            "hits": 0,
            "misses": 0,
            "param_rebinds": 0,
            "evictions": 0,
            "batched": 0,
            "batch_groups": 0,
        }
        db.__dict__["_plan_cache_stats"] = stats
    return parse, templates, stats


def _unresolved_params(db, params) -> tuple:
    """The string constants among ``params`` with no dictionary id yet.
    A plan built while any of these were unknown embeds a can-never-match
    sentinel for them, so it must be rebuilt (host-side; the device
    executable is keyed on the constant-free spec and is NOT recompiled)
    once the term gets interned — mutation batches under the delta
    threshold no longer move ``base_version``, so the slot key alone
    can't notice."""
    dic = db.dictionary
    return tuple(
        p
        for p in params
        if isinstance(p, str) and dic.lookup(db.expand_term(p)) is None
    )


def _plan_cache_entry(db, sparql: str):
    """Automatic plan cache on the database.  Three granularities:

    - the parsed AST is keyed by (query text, prefix map) — it survives
      store mutations, so INSERT/SELECT workloads never re-parse; parsing
      also canonicalizes the query into a constant-free *template*
      fingerprint plus its parameter tuple
      (:func:`kolibrie_tpu.query.template.fingerprint_query`);
    - plan slots are keyed by the TEMPLATE fingerprint, not the query
      text: the thousand constant-variants of one query shape share a
      single cache entry (and, downstream, a single jit executable —
      the lowered program carries its constants in a traced parameter
      vector);
    - within a template, the physical plan + device-lowered program live
      in per-state slots keyed by (store BASE version, UDF registry,
      execution mode), so e.g. host/device alternation keeps BOTH
      compiled programs warm instead of evicting on every flip — and
      because mutation batches under the store's delta threshold advance
      only ``delta_epoch`` (never ``base_version``), prepared plans
      survive sustained insert/delete traffic; per-execution scan ranges
      and the small device delta segment carry the fresh state.

    A slot replays its plan/lowered program only when the stored
    parameter binding matches the incoming one; on mismatch the plan is
    rebuilt (host-side, cheap) while the device executable — keyed on
    the constant-free ``PlanSpec`` — is reused without recompiling.
    Known-failure sentinels (``lowered is False``, ``ordered_failed``)
    are properties of the template and survive parameter rebinds.

    Both levels are LRU-bounded (``_PLAN_CACHE_MAX`` parse entries,
    ``_TEMPLATE_CACHE_MAX`` templates); ``plan_cache_info`` reports
    occupancy and hit/miss/eviction counters.  Returns ``(entry, slot)``;
    ``entry`` carries the parsed ``cq``, ``slot`` has the
    ``plan``/``lowered`` keys ``eval_select_to_table`` consumes."""
    from kolibrie_tpu.optimizer.mqo import mqo_mode
    from kolibrie_tpu.optimizer.planner import wcoj_mode
    from kolibrie_tpu.optimizer.stats_advisor import (
        stats_advisor,
        stats_advisor_mode,
    )
    from kolibrie_tpu.ops.pallas_kernels import pallas_mode
    from kolibrie_tpu.query.compile_cache import record_template
    from kolibrie_tpu.query.template import fingerprint_query

    parse, templates, stats = _plan_caches(db)
    prefix_sig = tuple(sorted(db.prefixes.items()))
    # the join-strategy, interpreter-routing, Pallas kernel, MQO sharing
    # and stats-advisor modes are part of the template fingerprint; a
    # mode flip after parse must refingerprint (not replay the old-mode
    # plan)
    env_sig = (
        wcoj_mode(),
        _interp_mode(),
        pallas_mode(),
        mqo_mode(),
        stats_advisor_mode(),
    )
    ent = parse.get(sparql)
    if ent is None or ent["prefix_sig"] != prefix_sig or ent["env_sig"] != env_sig:
        ent = {
            "prefix_sig": prefix_sig,
            "env_sig": env_sig,
            "cq": None,
            "fp": None,
            "params": (),
        }
        parse[sparql] = ent
    parse.move_to_end(sparql)
    while len(parse) > _PLAN_CACHE_MAX:
        parse.popitem(last=False)
    if ent["cq"] is None:
        with span("query.parse"):
            t0 = time.perf_counter()
            ent["cq"] = parse_combined_query(sparql, db.prefixes)
            ent["fp"], ent["params"] = fingerprint_query(ent["cq"])
            _PARSE_LAT.observe(time.perf_counter() - t0)
    fp, params = ent["fp"], ent["params"]
    # feed the pre-warm manifest: per-template popularity + one
    # representative query text the warmer can replay after a restart
    record_template(fp, sparql)
    tent = templates.get(fp)
    if tent is None:
        tent = {"by_state": {}, "hits": 0, "misses": 0}
        templates[fp] = tent
    templates.move_to_end(fp)
    while len(templates) > _TEMPLATE_CACHE_MAX:
        templates.popitem(last=False)
        stats["evictions"] += 1
        _PLAN_CACHE_EVICTION.inc()
    version = db.store.base_version
    # the mesh signature joins the state key: attaching/detaching the
    # sharded serving layer (or resizing its mesh) must never replay a
    # plan lowered for the other topology (docs/SHARDING.md)
    _sh = db.__dict__.get("_sharded_serving")
    state = (
        version,
        db.__dict__.get("_udf_version", 0),
        db.execution_mode,
        None if _sh is None else _sh.signature,
    )
    slot = tent["by_state"].get(state)
    if slot is not None and slot["lowered"] is False:
        # sticky-failure expiry: a ``False`` sentinel from a TRANSIENT
        # device fault should not outlive the fault.  The template's
        # circuit breaker bumps ``close_epoch`` on every open→closed
        # recovery; when the epoch has advanced past the one captured
        # with the sentinel, the fault demonstrably healed — clear the
        # sentinel so the next execution retries device lowering.
        # Shape-level failures (Unsupported) stay sticky: their host
        # fallback records success on an always-closed breaker, which
        # never bumps the epoch.
        epoch = breaker_board(db).close_epoch(fp)
        if slot.get("breaker_epoch") is None:
            slot["breaker_epoch"] = epoch
        elif slot["breaker_epoch"] != epoch:
            slot["plan"] = None
            slot["lowered"] = None
            slot["ordered_failed"] = False
            slot["breaker_epoch"] = epoch
            stats["sentinel_expiries"] = stats.get("sentinel_expiries", 0) + 1
    if slot is None:
        # stale-base-version slots pin device-resident copies of OLD store
        # orders (a LoweredPlan holds full sorted-store copies): drop
        # them, keeping only the live base's udf/mode variants (same
        # policy as dist_query's _dist_cap_cache)
        for k in [k for k in tent["by_state"] if k[0] != version]:
            tent["by_state"].pop(k)
        slot = {
            "plan": None,
            "lowered": None,
            "params": params,
            "ordered_failed": False,
            "unresolved": _unresolved_params(db, params),
            "quoted_n": len(db.quoted),
        }
        tent["by_state"][state] = slot
        while len(tent["by_state"]) > _PLAN_STATES_MAX:
            # dicts iterate in insertion order: drop the oldest state
            tent["by_state"].pop(next(iter(tent["by_state"])))
        stats["misses"] += 1
        tent["misses"] += 1
        _PLAN_CACHE_MISS.inc()
    elif slot["params"] != params:
        # same template, new constants: the cached plan/lowered program
        # embed the OLD parameter binding, so they cannot replay — drop
        # them and rebind.  The jit executable is keyed on the
        # constant-free PlanSpec, so the re-lowering triggered downstream
        # rebinds the parameter vector WITHOUT a device recompile.  The
        # known-failure sentinels stay: lowerability is decided by the
        # template's shape, never by the constant values.
        failed = slot["lowered"] is False
        slot["plan"] = None
        slot["lowered"] = False if failed else None
        slot["params"] = params
        slot["unresolved"] = _unresolved_params(db, params)
        slot["quoted_n"] = len(db.quoted)
        stats["param_rebinds"] += 1
        tent["misses"] += 1
        _PLAN_CACHE_REBIND.inc()
    else:
        # same binding — but a constant that was UNKNOWN when the slot's
        # plan was built may have been interned by an insert since (only
        # delta_epoch moved, so the state key didn't): the embedded
        # can-never-match sentinel is now wrong.  Rebind exactly like a
        # parameter change: host-side rebuild, no device recompile.
        rebind = False
        unres = slot.get("unresolved", ())
        if unres:
            still = _unresolved_params(db, unres)
            if len(still) != len(unres):
                slot["unresolved"] = still
                rebind = True
        if not rebind and slot.get("quoted_n") != len(db.quoted):
            # unknown quoted-triple ids resolve through db.quoted, not the
            # dictionary; only plans that actually embed one need a rebuild
            low = slot["lowered"]
            if low is not None and low is not False:
                checks = getattr(low, "const_checks", ()) or ()
                scans = getattr(low, "scan_descs", ()) or ()
                if any(t is None for cc in checks for t in cc) or any(
                    c is not None and c < 0 for _n, cs in scans for c in cs
                ):
                    rebind = True
            slot["quoted_n"] = len(db.quoted)
        if rebind:
            failed = slot["lowered"] is False
            slot["plan"] = None
            slot["lowered"] = False if failed else None
            stats["param_rebinds"] += 1
            tent["misses"] += 1
            _PLAN_CACHE_REBIND.inc()
        else:
            stats["hits"] += 1
            tent["hits"] += 1
            _PLAN_CACHE_HIT.inc()
    # drift-triggered replan: the stats advisor bumps a template's plan
    # generation when observed cardinalities drift past the estimates the
    # cached plan was built from (mutation churn moving selectivities, or
    # the cold→learned transition).  A stale stamp drops the plan AND the
    # lowered program — the rebuild replans with the tuned stats; the jit
    # executable for an unchanged plan shape replays from its spec-keyed
    # cache without recompiling.  Same slot-expiry discipline as the
    # breaker epoch above; the MODE itself already rode in via env_sig.
    gen = stats_advisor.plan_gen(fp)
    if slot.get("advisor_gen") is None:
        slot["advisor_gen"] = gen
    elif slot["advisor_gen"] != gen:
        slot["plan"] = None
        slot["lowered"] = None
        slot["ordered_failed"] = False
        slot["advisor_gen"] = gen
        stats["advisor_replans"] = stats.get("advisor_replans", 0) + 1
        stats_advisor.note_replan(fp)
    return ent, slot


def plan_cache_info(db) -> dict:
    """Inspection snapshot of the two-level plan cache: occupancy,
    hit/miss/eviction/rebind counters, sticky-failure counts, and a
    per-template breakdown (keyed by fingerprint)."""
    parse, templates, stats = _plan_caches(db)
    per = {}
    sticky = 0
    for fp, tent in templates.items():
        failed = sum(
            1 for s in tent["by_state"].values() if s["lowered"] is False
        )
        sticky += failed
        # where the template's most recent device dispatch came from:
        # "interp" (bytecode interpreter), "compiled" (real XLA compile
        # or warm jit replay), "disk" (persistent-cache hit) — None when
        # nothing device-lowered has run yet
        source = None
        for s in tent["by_state"].values():
            low = s.get("lowered")
            if low is not None and low is not False:
                source = getattr(low, "last_source", None) or source
        per[fp] = {
            "states": len(tent["by_state"]),
            "hits": tent["hits"],
            "misses": tent["misses"],
            "failed_states": failed,
            "source": source,
        }
    return {
        "parse_entries": len(parse),
        "templates": len(templates),
        "hits": stats["hits"],
        "misses": stats["misses"],
        "param_rebinds": stats["param_rebinds"],
        "evictions": stats["evictions"],
        "batched": stats["batched"],
        "batch_groups": stats["batch_groups"],
        "sticky_failures": sticky,
        "sentinel_expiries": stats.get("sentinel_expiries", 0),
        "advisor_replans": stats.get("advisor_replans", 0),
        "per_template": per,
        "limits": {
            "parse": _PLAN_CACHE_MAX,
            "templates": _TEMPLATE_CACHE_MAX,
            "states": _PLAN_STATES_MAX,
        },
    }


def _execute_degraded(db, sparql: str) -> Rows:
    """Degraded mode: run on the CPU interpreter path by forcing host
    execution for this call.  The plan-cache state key includes
    ``execution_mode``, so the host plan gets (and keeps) its own warm
    slot — repeat degraded queries don't re-plan.

    The mode flip is a plain attribute swap: callers that share a
    database across threads (the serving layer's TemplateBatcher) already
    serialize all database access on ``dispatch_lock``."""
    check_deadline("executor.degraded")
    prev = db.execution_mode
    db.execution_mode = "host"
    t0 = time.perf_counter()
    try:
        with span("query.degraded"):
            ent, slot = _plan_cache_entry(db, sparql)
            rows = execute_combined(db, ent["cq"], cache_entry=slot)
        _QUERY_LAT_DEGRADED.observe(time.perf_counter() - t0)
        return rows
    finally:
        db.execution_mode = prev


def execute_query_volcano(sparql: str, db) -> Rows:
    """The main query path (execute_query.rs:356 parity).

    Device-routed queries run behind the template's circuit breaker
    (:mod:`kolibrie_tpu.resilience.breaker`): transient device faults
    (injected or real compile failures, device OOM) and deadline blowups
    count against the breaker; a device fault degrades THIS call to the
    CPU interpreter path and, once the breaker trips, the whole template
    is served degraded until a half-open probe succeeds.  ``Unsupported``
    is not a fault — the sticky lowering sentinel already handles it."""
    check_deadline("executor.enter")
    db.register_prefixes_from_query(sparql)
    ent, slot = _plan_cache_entry(db, sparql)
    fp = ent["fp"]
    # baggage lets device_engine label its lower/dispatch timings with
    # the template fingerprint without threading it through eval_where
    set_baggage("template", fp)
    # the stats advisor's own channel: planning (Streamertail) and the
    # observation hooks key learned cardinalities on the fingerprint —
    # routing state must not ride the observability baggage, which dies
    # with the obs kill switch
    _sa_set_current_fp(fp)
    if not _device_routed(db):
        t0 = time.perf_counter()
        with span("query.execute", template=fp, path="host"):
            rows = execute_combined(db, ent["cq"], cache_entry=slot)
        _QUERY_LAT_HOST.observe(time.perf_counter() - t0)
        return rows
    board = breaker_board(db)
    if not board.allow(fp):
        return _execute_degraded(db, sparql)
    t0 = time.perf_counter()
    try:
        with span("query.execute", template=fp, path="device"):
            rows = execute_combined(db, ent["cq"], cache_entry=slot)
    except DeadlineExceeded:
        # still shed (the client's budget is gone either way), but a
        # template that repeatedly blows deadlines on the device trips
        # its breaker and future calls go straight to the host path
        board.record_failure(fp)
        raise
    except Exception as e:
        if not is_device_fault(e):
            raise
        board.record_failure(fp)
        return _execute_degraded(db, sparql)
    board.record_success(fp)
    _QUERY_LAT_DEVICE.observe(time.perf_counter() - t0)
    return rows


def _batchable_select(db, cq):
    """Return ``(q, folded_where)`` when the query is a plain SELECT the
    batched device dispatch can run — single BGP + filters, projection of
    variables only, all post-processing (DISTINCT, LIMIT/OFFSET,
    formatting) host-side per member.  ``None`` → run it solo."""
    from kolibrie_tpu.query.subquery_inline import inline_subqueries

    if (
        cq.select is None
        or cq.register is not None
        or cq.rules
        or cq.insert is not None
        or cq.delete is not None
        or cq.models
        or cq.neural_relations
        or cq.train_decls
        or cq.ml_predict is not None
        or cq.retrieve is not None
    ):
        return None
    if db.neural_relations:
        return None
    q = cq.select
    if q.group_by or q.order_by or any(i.kind != "var" for i in q.select):
        return None
    w = inline_subqueries(q.where)
    if (
        w.subqueries
        or w.binds
        or w.window_blocks
        or w.unions
        or w.optionals
        or w.minus
        or w.not_blocks
        or w.values is not None
        or not w.patterns
    ):
        return None
    return q, w


def _finish_select_table(db, q: SelectQuery, table: BindingTable) -> Rows:
    """The host tail of a plain SELECT (projection → DISTINCT → format →
    LIMIT/OFFSET), mirroring eval_select_to_table + execute_select."""
    if not q.select_all():
        keep = [i.var for i in q.select if i.kind == "var" and i.var in table]
        table = {v: table[v] for v in keep}
    elif any(k.startswith("__") for k in table):
        table = {k: v for k, v in table.items() if not k.startswith("__")}
    if q.distinct:
        table = unique_table(table)
    rows = format_results(db, table, q, sort_rows=True)
    return _apply_limit_offset(rows, q)


def execute_queries_batched(db, queries: List[str]) -> List[Rows]:
    """Execute a batch of queries, dispatching same-template plain SELECTs
    as ONE stacked-parameter vmap program (``execute_plan_batch``): the
    device runs every member of a template group in a single jit call
    instead of one dispatch per query.  Everything else — singleton
    templates, aggregates, ordered queries, updates — falls back to
    ``execute_query_volcano`` per query.  Results come back in input
    order; per-query host post-processing (DISTINCT, LIMIT/OFFSET,
    formatting) is identical to the solo path."""
    from kolibrie_tpu.optimizer.device_engine import (
        Unsupported,
        execute_plan_batch,
        lower_plan,
    )

    check_deadline("executor.batch")
    results: List[Optional[Rows]] = [None] * len(queries)
    for text in queries:
        db.register_prefixes_from_query(text)
    groups: Dict[str, List[int]] = {}
    members: List[Optional[tuple]] = [None] * len(queries)
    board = breaker_board(db)
    sharded = db.__dict__.get("_sharded_serving")
    if _device_routed(db) or sharded is not None:
        for i, text in enumerate(queries):
            ent, slot = _plan_cache_entry(db, text)
            if slot["lowered"] is False:
                continue  # template known un-lowerable: solo (host) path
            eligible = _batchable_select(db, ent["cq"])
            if eligible is None:
                continue
            q, w = eligible
            members[i] = (ent, slot, q, w)
            groups.setdefault(ent["fp"], []).append(i)
    _, _, stats = _plan_caches(db)
    for fp, idxs in groups.items():
        if len(idxs) < 2:
            continue  # solo dispatch is already optimal for singletons
        if not board.allow(fp):
            continue  # breaker open: members fall to the solo degraded path
        if _interp_mode() == "force":
            # forced interpreter routing: the mesh shard_map program and
            # the stacked-batch jit are exactly the per-template compiles
            # the mode exists to avoid — members run solo through the
            # single-device interpreter instead (docs/COMPILE_CACHE.md)
            continue
        set_baggage("template", fp)
        _sa_set_current_fp(fp)
        if sharded is not None:
            # mesh-first: the whole template group rides one shard_map
            # dispatch (parallel/sharded_serving.py); on Unsupported or a
            # device fault the group degrades to the single-device paths
            # below, with the breaker counting mesh trips
            from kolibrie_tpu.parallel.sharded_serving import (
                Unsupported as _MeshUnsupported,
            )

            try:
                with span("executor.sharded", template=fp, batch=len(idxs)):
                    got = sharded.execute_batch(
                        fp, [(i, queries[i]) for i in idxs]
                    )
            except _MeshUnsupported:
                pass  # group shape stays single-device: fall through
            except DeadlineExceeded:
                board.record_failure(fp)
                raise
            except Exception as e:
                if not is_device_fault(e):
                    raise
                board.record_failure(fp)
            else:
                board.record_success(fp)
                stats["batched"] += len(idxs)
                stats["batch_groups"] += 1
                _BATCHED_QUERIES.inc(len(idxs))
                for i in idxs:
                    results[i] = got[i]
                continue
        if not _device_routed(db):
            continue  # mesh declined and no single-device jit routing
        lowereds, ok = [], True
        for i in idxs:
            ent, slot, q, w = members[i]
            try:
                resolved = [resolve_pattern(db, p) for p in w.patterns]
                logical = build_logical_plan(resolved, list(w.filters), [], None)
                planner = Streamertail(db.get_or_build_stats())
                plan = planner.find_best_plan(logical)
                lowered = lower_plan(db, plan)
            except Unsupported:
                ok = False
                break
            except DeadlineExceeded:
                board.record_failure(fp)
                raise
            except Exception as e:
                if not is_device_fault(e):
                    raise
                # transient compile fault: count it, hand the whole group
                # to the solo path (which degrades per the breaker)
                board.record_failure(fp)
                ok = False
                break
            lowereds.append((i, q, plan, lowered))
        if not ok:
            continue
        try:
            tables = execute_plan_batch([low for _, _, _, low in lowereds])
        except Unsupported:
            continue  # shape/plan divergence inside the group: solo path
        except DeadlineExceeded:
            board.record_failure(fp)
            raise
        except Exception as e:
            if not is_device_fault(e):
                raise
            board.record_failure(fp)
            continue
        board.record_success(fp)
        stats["batched"] += len(idxs)
        stats["batch_groups"] += 1
        _BATCHED_QUERIES.inc(len(idxs))
        for (i, q, plan, lowered), table in zip(lowereds, tables):
            ent, slot, _, _ = members[i]
            if slot["params"] == ent["params"] and slot["lowered"] is None:
                slot["plan"], slot["lowered"] = plan, lowered
            results[i] = _finish_select_table(db, q, table)
    # multi-query sharing for the solo tail: register every still-pending
    # member's prefix fingerprint as a transient beneficiary, so the MQO
    # layer sees the dispatch's full fan-out before the first member runs
    # (optimizer/mqo.py; fingerprints memoize per store version)
    from kolibrie_tpu.optimizer import mqo as _mqo

    transient_fps: List[str] = []
    pending = [i for i in range(len(queries)) if results[i] is None]
    if len(pending) >= 2 and _mqo.mqo_mode() != "off":
        for i in pending:
            fp = _solo_prefix_fp(db, queries[i])
            if fp is not None:
                transient_fps.append(fp)
    with _mqo.transient_scope(db, transient_fps):
        for i, text in enumerate(queries):
            if results[i] is None:
                results[i] = execute_query_volcano(text, db)
    return results


def _solo_prefix_fp(db, text: str) -> Optional[str]:
    """MQO prefix fingerprint for one batch member, or None when the
    query is outside the batchable/shareable shape.  Never raises: a
    member that fails here simply isn't registered as a beneficiary, and
    the solo loop reports its real error in input order."""
    from kolibrie_tpu.optimizer import mqo as _mqo
    from kolibrie_tpu.optimizer.device_engine import Unsupported, lower_plan

    try:
        ent, _slot = _plan_cache_entry(db, text)
        eligible = _batchable_select(db, ent["cq"])
        if eligible is None:
            return None
        _q, w = eligible

        def _lower():
            try:
                resolved = [resolve_pattern(db, p) for p in w.patterns]
                logical = build_logical_plan(
                    resolved, list(w.filters), [], None
                )
                planner = Streamertail(db.get_or_build_stats())
                return lower_plan(db, planner.find_best_plan(logical))
            except Unsupported:
                return None

        return _mqo.prefix_fp_for(db, ent["fp"], _lower)
    except Exception:
        # registration is best-effort routing state; the member's actual
        # evaluation surfaces any real error — but the miss is counted so
        # a systematically failing registration path stays visible
        _mqo._DECLINED.labels("fp_error").inc()
        return None


def collect_all_patterns(where: WhereClause) -> List[PatternTriple]:
    """Every triple pattern reachable from a group pattern — including
    OPTIONAL/UNION/MINUS branches, NOT blocks, subqueries, and WINDOW
    blocks (used for neural-relation materialization coverage)."""
    out: List[PatternTriple] = list(where.patterns)
    for nb in where.not_blocks:
        out.extend(nb.patterns)
    for wb in where.window_blocks:
        out.extend(wb.patterns)
    for opt in where.optionals:
        out.extend(collect_all_patterns(opt))
    for groups in where.unions:
        for g in groups:
            out.extend(collect_all_patterns(g))
    for m in where.minus:
        out.extend(collect_all_patterns(m))
    for sq in where.subqueries:
        out.extend(collect_all_patterns(sq.query.where))
    return out


def _materialize_neural_for_select(db, select: SelectQuery) -> None:
    if not db.neural_relations:
        return
    from kolibrie_tpu.ml import runtime as ml_runtime

    ml_runtime.materialize_neural_relations_for_patterns(
        db, collect_all_patterns(select.where)
    )


def execute_combined(db, cq: CombinedQuery, cache_entry=None) -> Rows:
    db.prefixes.update(cq.prefixes)
    if cache_entry is not None and (
        cq.register is not None
        or cq.rules
        or cq.insert is not None
        or cq.delete is not None
        or cq.models
        or cq.neural_relations
        or cq.train_decls
        or cq.ml_predict is not None
    ):
        # updates / declarations mutate the database (or registries the
        # cache state key doesn't cover): only plain SELECTs reuse plans
        cache_entry = None
    if cache_entry is not None and db.neural_relations:
        # neural-predicate materialization inserts triples MID-execution,
        # so the slot's store-version key would not describe the program
        # captured under it
        cache_entry = None
    # neural/train declarations
    if cq.models or cq.neural_relations or cq.train_decls or cq.ml_predict:
        from kolibrie_tpu.ml import runtime as ml_runtime

        ml_runtime.register_declarations(db, cq)
        for train in cq.train_decls:
            ml_runtime.execute_train_decl(db, train)
        if cq.ml_predict is not None:
            ml_runtime.execute_ml_predict(db, cq.ml_predict)
    for rule in cq.rules:
        from kolibrie_tpu.reasoner import rule_runtime

        rule_runtime.process_combined_rule(db, rule)
    if cq.delete is not None:
        process_delete_clause(db, cq.delete)
    if cq.insert is not None:
        process_insert_clause(db, cq.insert)
    if cq.select is not None:
        # neural predicates referenced anywhere in the query materialize as
        # ordinary triples first (neural_relations.rs parity)
        _materialize_neural_for_select(db, cq.select)
        return execute_select(db, cq.select, cache_entry=cache_entry)
    return []


def execute_query(sparql: str, db) -> Rows:
    """Legacy sequential path (execute_query.rs:156 parity): same semantics,
    naive join order, no cost-based planning.  Kept for agreement tests."""
    db.register_prefixes_from_query(sparql)
    cq = parse_combined_query(sparql, db.prefixes)
    if cq.select is None:
        return execute_combined(db, cq)
    # same pre-pass as the volcano path, so both agree on neural queries
    _materialize_neural_for_select(db, cq.select)
    return execute_select(db, cq.select, use_optimizer=False)
