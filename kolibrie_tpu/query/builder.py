"""Fluent native query API over :class:`SparqlDatabase`.

Parity: ``kolibrie/src/query_builder.rs`` — subject/predicate/object filters
including like/starting/ending and custom closures (:180-259), joins on
s/p/o or a custom condition against a second database (:261-292), distinct /
order_by / desc / asc / limit / offset / count / group_by (:294-331,:442-470),
and streaming mode ``.window(width, slide).with_report_strategy(...)
.with_tick_strategy(...).with_stream_operator(...).as_stream()`` with
``add_stream_triple`` / ``get_stream_results`` (:624-751).

Rebuild notes (TPU-first, not a port): exact s/p/o filters are evaluated as
ID-compares over the columnar store (one ``Dictionary.lookup`` then a numpy
mask over the u32 columns — the device-friendly path); pattern filters
(contains/starts/ends) decode each column's *unique* IDs once and map the
string predicate over those, so string work is O(distinct terms) instead of
O(triples).  Joins hash the right side by key once instead of the reference's
nested loop.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from kolibrie_tpu.core.triple import Triple
from kolibrie_tpu.rsp.r2s import Relation2StreamOperator, StreamOperator
from kolibrie_tpu.rsp.s2r import ContentContainer, ReportStrategy, Tick, WindowTriple
from kolibrie_tpu.rsp.window_runner import WindowRunner, WindowSpec


class TripleFilter:
    """One positional filter (query_builder.rs:75-82)."""

    EXACT = "exact"
    CONTAINS = "contains"
    STARTS_WITH = "starts_with"
    ENDS_WITH = "ends_with"

    def __init__(self, kind: str, value=None):
        self.kind = kind
        self.value = value

    def matches(self, s: str) -> bool:
        if self.kind == TripleFilter.EXACT:
            return s == self.value
        if self.kind == TripleFilter.CONTAINS:
            return self.value in s
        if self.kind == TripleFilter.STARTS_WITH:
            return s.startswith(self.value)
        if self.kind == TripleFilter.ENDS_WITH:
            return s.endswith(self.value)
        raise ValueError(f"unknown filter kind {self.kind!r}")


class JoinCondition:
    ON_SUBJECT = "subject"
    ON_PREDICATE = "predicate"
    ON_OBJECT = "object"


class QueryBuilder:
    """Chainable triple query; terminal methods return materialized results."""

    def __init__(self, db):
        self.db = db
        self._filters: Dict[str, Optional[TripleFilter]] = {
            "subject": None,
            "predicate": None,
            "object": None,
        }
        self._custom_filter: Optional[Callable[[Triple], bool]] = None
        self._join_db = None
        self._join_conditions: List = []
        self._distinct = False
        self._sort_key: Optional[Callable[[Triple], object]] = None
        self._sort_desc = False
        self._limit: Optional[int] = None
        self._offset: Optional[int] = None
        # Streaming state (query_builder.rs:624-751)
        self._window_spec: Optional[Tuple[int, int]] = None
        self._report_strategies: List[ReportStrategy] = []
        self._tick: str = Tick.TIME_DRIVEN
        self._stream_operator: Optional[str] = None
        self._r2s: Optional[Relation2StreamOperator] = None
        self._runner: Optional[WindowRunner] = None
        self._pending: List[ContentContainer] = []
        self._stream_results: List[List[Triple]] = []
        self._current_ts = 0
        self.streaming = False

    # ------------------------------------------------------------ filters

    def _set(self, pos: str, kind: str, value) -> "QueryBuilder":
        self._filters[pos] = TripleFilter(kind, value)
        return self

    def with_subject(self, subject: str) -> "QueryBuilder":
        return self._set("subject", TripleFilter.EXACT, subject)

    def with_subject_like(self, pattern: str) -> "QueryBuilder":
        return self._set("subject", TripleFilter.CONTAINS, pattern)

    def with_subject_starting(self, prefix: str) -> "QueryBuilder":
        return self._set("subject", TripleFilter.STARTS_WITH, prefix)

    def with_subject_ending(self, suffix: str) -> "QueryBuilder":
        return self._set("subject", TripleFilter.ENDS_WITH, suffix)

    def with_predicate(self, predicate: str) -> "QueryBuilder":
        return self._set("predicate", TripleFilter.EXACT, predicate)

    def with_predicate_like(self, pattern: str) -> "QueryBuilder":
        return self._set("predicate", TripleFilter.CONTAINS, pattern)

    def with_predicate_starting(self, prefix: str) -> "QueryBuilder":
        return self._set("predicate", TripleFilter.STARTS_WITH, prefix)

    def with_predicate_ending(self, suffix: str) -> "QueryBuilder":
        return self._set("predicate", TripleFilter.ENDS_WITH, suffix)

    def with_object(self, obj: str) -> "QueryBuilder":
        return self._set("object", TripleFilter.EXACT, obj)

    def with_object_like(self, pattern: str) -> "QueryBuilder":
        return self._set("object", TripleFilter.CONTAINS, pattern)

    def with_object_starting(self, prefix: str) -> "QueryBuilder":
        return self._set("object", TripleFilter.STARTS_WITH, prefix)

    def with_object_ending(self, suffix: str) -> "QueryBuilder":
        return self._set("object", TripleFilter.ENDS_WITH, suffix)

    def filter(self, predicate: Callable[[Triple], bool]) -> "QueryBuilder":
        self._custom_filter = predicate
        return self

    # -------------------------------------------------------------- joins

    def join(self, other) -> "QueryBuilder":
        self._join_db = other
        return self

    def join_on_subject(self) -> "QueryBuilder":
        self._join_conditions.append(JoinCondition.ON_SUBJECT)
        return self

    def join_on_predicate(self) -> "QueryBuilder":
        self._join_conditions.append(JoinCondition.ON_PREDICATE)
        return self

    def join_on_object(self) -> "QueryBuilder":
        self._join_conditions.append(JoinCondition.ON_OBJECT)
        return self

    def join_with(self, condition: Callable[[Triple, Triple], bool]) -> "QueryBuilder":
        self._join_conditions.append(condition)
        return self

    # ----------------------------------------------------------- modifiers

    def distinct(self) -> "QueryBuilder":
        self._distinct = True
        return self

    def order_by(self, key: Callable[[Triple], object]) -> "QueryBuilder":
        self._sort_key = key
        return self

    def desc(self) -> "QueryBuilder":
        self._sort_desc = True
        return self

    def asc(self) -> "QueryBuilder":
        self._sort_desc = False
        return self

    def limit(self, n: int) -> "QueryBuilder":
        self._limit = n
        return self

    def offset(self, n: int) -> "QueryBuilder":
        self._offset = n
        return self

    # ----------------------------------------------------------- execution

    def _column_mask(self, pos: str, ids: np.ndarray) -> Optional[np.ndarray]:
        """Mask for one positional filter over an ID column (vectorized)."""
        filt = self._filters[pos]
        if filt is None:
            return None
        if filt.kind == TripleFilter.EXACT:
            # Exact match never needs string decode: one lookup, one compare.
            tid = self.db.lookup_term_str(filt.value)
            if tid is None:
                return np.zeros(len(ids), dtype=bool)
            return ids == np.uint32(tid)
        uniq, inverse = np.unique(ids, return_inverse=True)
        keep = np.fromiter(
            (filt.matches(self.db.decode_term(int(u)) or "") for u in uniq),
            dtype=bool,
            count=len(uniq),
        )
        return keep[inverse]

    def _matching_triples(self) -> List[Triple]:
        s, p, o = self.db.store.columns()
        mask = np.ones(len(s), dtype=bool)
        for pos, col in (("subject", s), ("predicate", p), ("object", o)):
            m = self._column_mask(pos, col)
            if m is not None:
                mask &= m
        idx = np.nonzero(mask)[0]
        triples = [Triple(int(s[i]), int(p[i]), int(o[i])) for i in idx]
        if self._custom_filter is not None:
            triples = [t for t in triples if self._custom_filter(t)]
        return triples

    def _apply_join(self, left: List[Triple]) -> List[Triple]:
        """Hash-join against the second DB (reference semantics: the output
        triple mixes left/right fields per condition, query_builder.rs:562-618).

        If the two databases do not share a dictionary, the right side is
        re-encoded into the left dictionary first — raw IDs from different
        dictionaries are not comparable."""
        if self._join_db.dictionary is self.db.dictionary:
            right = list(self._join_db.store)
        else:
            enc = self.db.encode_term_str
            rdec = self._join_db.decode_term
            right = [
                Triple(
                    enc(rdec(t.subject) or ""),
                    enc(rdec(t.predicate) or ""),
                    enc(rdec(t.object) or ""),
                )
                for t in self._join_db.store
            ]
        out = set()
        for cond in self._join_conditions:
            if callable(cond):
                for lt in left:
                    for rt in right:
                        if cond(lt, rt):
                            out.add(Triple(lt.subject, rt.predicate, rt.object))
                continue
            table: Dict[int, List[Triple]] = {}
            keyget = {
                JoinCondition.ON_SUBJECT: lambda t: t.subject,
                JoinCondition.ON_PREDICATE: lambda t: t.predicate,
                JoinCondition.ON_OBJECT: lambda t: t.object,
            }[cond]
            for rt in right:
                table.setdefault(keyget(rt), []).append(rt)
            keep_left_pred = cond != JoinCondition.ON_OBJECT
            for lt in left:
                for rt in table.get(keyget(lt), ()):
                    pred = lt.predicate if keep_left_pred else rt.predicate
                    out.add(Triple(lt.subject, pred, rt.object))
        return sorted(out)

    def get_triples(self) -> List[Triple]:
        """Materialize: ordered unique triples (the reference's BTreeSet)."""
        if self.streaming:
            return []
        results = sorted(set(self._matching_triples()))
        if self._join_db is not None and self._join_conditions:
            results = self._apply_join(results)
        if self._sort_key is not None:
            results.sort(key=self._sort_key, reverse=self._sort_desc)
        if self._offset is not None or self._limit is not None:
            start = self._offset or 0
            end = start + self._limit if self._limit is not None else None
            results = results[start:end]
        return results

    def _decode(self, tid: int) -> str:
        return self.db.decode_term(tid) or ""

    def get_decoded_triples(self) -> List[Tuple[str, str, str]]:
        return [
            (self._decode(t.subject), self._decode(t.predicate), self._decode(t.object))
            for t in self.get_triples()
        ]

    def _get_position(self, getter) -> List[str]:
        vals = [self._decode(getter(t)) for t in self.get_triples()]
        if self._distinct:
            vals = sorted(set(vals))
        return vals

    def get_subjects(self) -> List[str]:
        return self._get_position(lambda t: t.subject)

    def get_predicates(self) -> List[str]:
        return self._get_position(lambda t: t.predicate)

    def get_objects(self) -> List[str]:
        return self._get_position(lambda t: t.object)

    def count(self) -> int:
        return len(self.get_triples())

    def group_by(self, key_fn: Callable[[Triple], object]) -> Dict[object, List[Triple]]:
        groups: Dict[object, List[Triple]] = {}
        for t in self.get_triples():
            groups.setdefault(key_fn(t), []).append(t)
        return dict(sorted(groups.items(), key=lambda kv: kv[0]))

    # ----------------------------------------------------------- streaming

    def window(self, width: int, slide: int) -> "QueryBuilder":
        self._window_spec = (width, slide)
        return self

    def with_report_strategy(self, strategy) -> "QueryBuilder":
        if isinstance(strategy, str):
            strategy = ReportStrategy.from_name(strategy)
        self._report_strategies.append(strategy)
        return self

    def with_tick_strategy(self, tick: str) -> "QueryBuilder":
        self._tick = tick
        return self

    def with_stream_operator(self, operator: str) -> "QueryBuilder":
        self._stream_operator = operator
        return self

    def as_stream(self) -> "QueryBuilder":
        if self._window_spec is not None:
            width, slide = self._window_spec
            spec = WindowSpec(
                window_iri="builder", stream_iri="builder", width=width, slide=slide,
                tick=self._tick,
            )
            self._runner = WindowRunner(spec)
            if self._report_strategies:
                report = self._runner.window.report
                report.strategies = list(self._report_strategies)
            self._runner.register_callback(self._pending.append)
        if self._stream_operator is not None:
            self._r2s = Relation2StreamOperator(self._stream_operator, self._current_ts)
        self.streaming = True
        return self

    def add_stream_triple(self, subject: str, predicate: str, obj: str, timestamp: int) -> None:
        if not self.streaming:
            raise RuntimeError("Query not in streaming mode. Call as_stream() first.")
        if self._runner is None:
            raise RuntimeError("No window configured for streaming.")
        self._runner.add_to_window(WindowTriple(subject, predicate, obj), timestamp)
        self._current_ts = timestamp

    @classmethod
    def _norm_term_text(cls, term: str) -> str:
        """Text-level counterpart of encode_term_str normalization: strip
        surrounding ``<...>`` and recursively normalize each component of
        ``<< s p o >>`` (so bracketed and bare spellings compare equal)."""
        from kolibrie_tpu.query.sparql_database import split_quoted_triple_content

        term = term.strip()
        if term.startswith("<<") and term.endswith(">>"):
            parts = split_quoted_triple_content(term[2:-2].strip())
            return "<< " + " ".join(cls._norm_term_text(p) for p in parts) + " >>"
        if term.startswith("<") and term.endswith(">"):
            return term[1:-1]
        return term

    def _execute_on_window_content(self, content: ContentContainer) -> List[Triple]:
        """Apply the configured s/p/o filters to the window's string triples
        and intern matches into the database dictionary (query_builder.rs:757+).

        Filters run BEFORE interning so rejected stream triples never grow
        the dictionary; exact filters compare normalized text so they agree
        with the static path's ID-based semantics."""
        out = []
        norm_exact = {
            pos: self._norm_term_text(f.value)
            for pos, f in self._filters.items()
            if f is not None and f.kind == TripleFilter.EXACT
        }
        enc = self.db.encode_term_str
        for wt in content:
            ok = True
            for pos, val in (("subject", wt.s), ("predicate", wt.p), ("object", wt.o)):
                filt = self._filters[pos]
                if filt is None:
                    continue
                if filt.kind == TripleFilter.EXACT:
                    if self._norm_term_text(val) != norm_exact[pos]:
                        ok = False
                        break
                elif not filt.matches(self._norm_term_text(val)):
                    ok = False
                    break
            if not ok:
                continue
            t = Triple(enc(wt.s), enc(wt.p), enc(wt.o))
            if self._custom_filter is None or self._custom_filter(t):
                out.append(t)
        return out

    def get_stream_results(self) -> List[List[Triple]]:
        if not self.streaming or self._runner is None:
            return []
        pending, self._pending = self._pending, []
        results = []
        for content in pending:
            window_results = self._execute_on_window_content(content)
            if self._r2s is not None:
                emitted = self._r2s.eval(window_results, self._current_ts)
                if emitted:
                    results.append(emitted)
            elif window_results:
                results.append(window_results)
        self._stream_results.extend(results)
        return results

    def get_all_stream_results(self) -> List[List[Triple]]:
        return list(self._stream_results)

    def clear_stream_results(self) -> None:
        self._stream_results.clear()

    def stop_stream(self) -> None:
        if self._runner is not None:
            self._runner.stop()
        self.streaming = False

    def is_streaming(self) -> bool:
        return self.streaming
