"""RDF data-format parsers (host-side): N-Triples(-star), Turtle(-star), N3
data, RDF/XML.

Parity: the reference's hand-rolled parsers in
``kolibrie/src/sparql_database.rs`` — ``parse_rdf`` (RDF/XML via quick-xml,
:401), ``parse_turtle`` (line-based with ``;``/``,`` shorthand + Turtle-star,
:729), ``parse_n3`` (:1015), ``parse_ntriples`` (-star, :1076-1141).

Terms are produced as strings and dictionary-encoded by the caller
(:class:`~kolibrie_tpu.query.sparql_database.SparqlDatabase`):

- IRIs are stored **expanded, without angle brackets**;
- literals keep their quoted lexical form incl. ``@lang`` / ``^^datatype``
  suffix (datatype IRI expanded, unbracketed), e.g. ``"30"`` or
  ``"5.2"^^http://www.w3.org/2001/XMLSchema#decimal``;
- blank nodes as ``_:label``;
- quoted triples as nested ``("qt", s, p, o)`` tuples (RDF-star).
"""

from __future__ import annotations

import re
import xml.etree.ElementTree as ET
from typing import Dict, Iterator, List, Optional, Tuple, Union

RDF_TYPE = "http://www.w3.org/1999/02/22-rdf-syntax-ns#type"
RDF_NS = "http://www.w3.org/1999/02/22-rdf-syntax-ns#"

# A parsed term: plain string, or ("qt", s, p, o) for a quoted triple.
ParsedTerm = Union[str, Tuple]
ParsedTriple = Tuple[ParsedTerm, ParsedTerm, ParsedTerm]


class RdfParseError(ValueError):
    def __init__(self, message: str, line: Optional[int] = None):
        super().__init__(f"line {line}: {message}" if line else message)
        self.line = line


# --------------------------------------------------------------------------
# Tokenizer shared by the Turtle-family parsers
# --------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
      (?P<comment>\#[^\n]*)
    | (?P<qt_open><<)
    | (?P<qt_close>>>)
    | (?P<iri><[^<>\s]*>)
    | (?P<literal>"(?:[^"\\]|\\.)*"(?:@[A-Za-z][A-Za-z0-9-]*|\^\^(?:<[^<>\s]*>|[A-Za-z_][\w.-]*:[\w.-]*))?)
    | (?P<sliteral>'(?:[^'\\]|\\.)*'(?:@[A-Za-z][A-Za-z0-9-]*|\^\^(?:<[^<>\s]*>|[A-Za-z_][\w.-]*:[\w.-]*))?)
    | (?P<blank>_:[\w-]+)
    | (?P<punct>[;,.\[\]()])
    | (?P<keyword>(?:@prefix|@base|[Pp][Rr][Ee][Ff][Ii][Xx]|[Bb][Aa][Ss][Ee])(?![\w:.-]))
    | (?P<num>[+-]?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
    | (?P<bool>(?:true|false)(?![\w:.-]))
    | (?P<pname>[A-Za-z_][\w.-]*?:[\w.%-]*|:[\w.%-]*|[A-Za-z_][\w-]*)
    """,
    re.VERBOSE,
)

XSD = "http://www.w3.org/2001/XMLSchema#"


def _tokenize(data: str) -> Iterator[Tuple[str, str, int]]:
    """Yield (kind, text, line_no)."""
    line = 1
    pos = 0
    n = len(data)
    while pos < n:
        ch = data[pos]
        if ch in " \t\r":
            pos += 1
            continue
        if ch == "\n":
            line += 1
            pos += 1
            continue
        m = _TOKEN_RE.match(data, pos)
        if m is None:
            raise RdfParseError(f"unexpected character {data[pos]!r}", line)
        kind = m.lastgroup
        text = m.group()
        pos = m.end()
        line += text.count("\n")
        if kind == "comment":
            continue
        yield kind, text, line  # type: ignore[misc]


_ESCAPES = {
    "t": "\t",
    "n": "\n",
    "r": "\r",
    '"': '"',
    "'": "'",
    "\\": "\\",
    "b": "\b",
    "f": "\f",
}


def _unescape(s: str) -> str:
    if "\\" not in s:
        return s
    out = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            if nxt in _ESCAPES:
                out.append(_ESCAPES[nxt])
                i += 2
                continue
            if nxt == "u" and i + 6 <= len(s):
                out.append(chr(int(s[i + 2 : i + 6], 16)))
                i += 6
                continue
            if nxt == "U" and i + 10 <= len(s):
                out.append(chr(int(s[i + 2 : i + 10], 16)))
                i += 10
                continue
        out.append(c)
        i += 1
    return "".join(out)


class _TurtleParser:
    """Recursive-descent Turtle(-star) parser producing ParsedTriples.

    Supports: @prefix/@base (and SPARQL-style PREFIX/BASE), prefixed names,
    IRIs, literals (lang tags, datatypes, numeric/boolean shorthand), ``a``,
    ``;`` / ``,`` predicate/object lists, blank nodes ``_:x`` and anonymous
    ``[]`` (incl. property lists), quoted triples ``<< s p o >>`` in subject
    or object position.
    """

    def __init__(self, data: str, prefixes: Optional[Dict[str, str]] = None):
        self.tokens = list(_tokenize(data))
        self.i = 0
        self.prefixes: Dict[str, str] = dict(prefixes or {})
        self.base = ""
        self.triples: List[ParsedTriple] = []
        self._bnode_counter = 0

    # --- token helpers

    def _peek(self):
        return self.tokens[self.i] if self.i < len(self.tokens) else (None, None, -1)

    def _next(self):
        tok = self._peek()
        self.i += 1
        return tok

    def _expect_punct(self, p: str):
        kind, text, line = self._next()
        if kind != "punct" or text != p:
            raise RdfParseError(f"expected {p!r}, got {text!r}", line)

    # --- term productions

    def _expand_iri(self, text: str) -> str:
        iri = text[1:-1]
        if self.base and not re.match(r"^[A-Za-z][\w+.-]*:", iri):
            return self.base + iri
        return iri

    def _expand_pname(self, text: str, line: int) -> str:
        if ":" in text:
            pfx, local = text.split(":", 1)
        else:
            raise RdfParseError(f"unknown keyword {text!r}", line)
        ns = self.prefixes.get(pfx)
        if ns is None:
            raise RdfParseError(f"undefined prefix {pfx + ':'!r}", line)
        return ns + local

    def _literal_value(self, text: str) -> str:
        quote = text[0]
        # find closing quote (respecting escapes)
        j = 1
        while j < len(text):
            if text[j] == "\\":
                j += 2
                continue
            if text[j] == quote:
                break
            j += 1
        lex = _unescape(text[1:j])
        suffix = text[j + 1 :]
        if suffix.startswith("^^"):
            dt = suffix[2:]
            if dt.startswith("<"):
                dt = self._expand_iri(dt)
            else:
                dt = self._expand_pname(dt, 0)
            return f'"{lex}"^^{dt}'
        if suffix.startswith("@"):
            return f'"{lex}"{suffix}'
        return f'"{lex}"'

    def _fresh_bnode(self) -> str:
        self._bnode_counter += 1
        return f"_:anon{self._bnode_counter}"

    def _parse_term(self, position: str) -> ParsedTerm:
        kind, text, line = self._next()
        if kind == "iri":
            return self._expand_iri(text)
        if kind in ("literal", "sliteral"):
            return self._literal_value(text)
        if kind == "blank":
            return text
        if kind == "num":
            dt = "integer" if re.fullmatch(r"[+-]?\d+", text) else "decimal"
            if "e" in text.lower():
                dt = "double"
            return f'"{text}"^^{XSD}{dt}'
        if kind == "bool":
            return f'"{text}"^^{XSD}boolean'
        if kind == "qt_open":
            s = self._parse_term("subject")
            p = self._parse_term("predicate")
            o = self._parse_term("object")
            k, t, l = self._next()
            if k != "qt_close":
                raise RdfParseError(f"expected '>>', got {t!r}", l)
            return ("qt", s, p, o)
        if kind == "punct" and text == "[":
            bnode = self._fresh_bnode()
            nk, nt, _ = self._peek()
            if nk == "punct" and nt == "]":
                self._next()
                return bnode
            self._parse_predicate_object_list(bnode)
            self._expect_punct("]")
            return bnode
        if kind == "pname":
            if text == "a" and position == "predicate":
                return RDF_TYPE
            return self._expand_pname(text, line)
        raise RdfParseError(f"unexpected token {text!r} in {position}", line)

    # --- statement productions

    def _parse_predicate_object_list(self, subject: ParsedTerm):
        while True:
            pred = self._parse_term("predicate")
            while True:
                obj = self._parse_term("object")
                self.triples.append((subject, pred, obj))
                k, t, _ = self._peek()
                if k == "punct" and t == ",":
                    self._next()
                    continue
                break
            k, t, _ = self._peek()
            if k == "punct" and t == ";":
                self._next()
                # allow trailing ';' before '.' or ']'
                k2, t2, _ = self._peek()
                if k2 == "punct" and t2 in (".", "]"):
                    break
                continue
            break

    def _parse_directive(self, keyword: str):
        kw = keyword.lower().lstrip("@")
        if kw == "prefix":
            k, t, line = self._next()
            if k != "pname" or not t.endswith(":"):
                # pname token may carry the local part; prefix decl needs "pfx:"
                if k == "pname" and ":" in t:
                    pass
                else:
                    raise RdfParseError(f"bad @prefix declaration near {t!r}", line)
            pfx = t[:-1] if t.endswith(":") else t.split(":", 1)[0]
            k2, iri, line2 = self._next()
            if k2 != "iri":
                raise RdfParseError(f"expected IRI in @prefix, got {iri!r}", line2)
            self.prefixes[pfx] = iri[1:-1]
        elif kw == "base":
            k2, iri, line2 = self._next()
            if k2 != "iri":
                raise RdfParseError(f"expected IRI in @base, got {iri!r}", line2)
            self.base = iri[1:-1]
        else:
            raise RdfParseError(f"unknown directive {keyword!r}")
        # optional trailing '.' (required for @prefix, absent for SPARQL PREFIX)
        k, t, _ = self._peek()
        if k == "punct" and t == ".":
            self._next()

    def parse(self) -> List[ParsedTriple]:
        while self.i < len(self.tokens):
            kind, text, line = self._peek()
            if kind == "keyword":
                self._next()
                self._parse_directive(text)
                continue
            subject = self._parse_term("subject")
            self._parse_predicate_object_list(subject)
            k, t, l = self._peek()
            if k == "punct" and t == ".":
                self._next()
            elif k is None:
                break
            else:
                raise RdfParseError(f"expected '.', got {t!r}", l)
        return self.triples


def parse_turtle(
    data: str, prefixes: Optional[Dict[str, str]] = None
) -> Tuple[List[ParsedTriple], Dict[str, str]]:
    """Parse Turtle(-star); returns (triples, prefix map)."""
    p = _TurtleParser(data, prefixes)
    triples = p.parse()
    return triples, p.prefixes


def parse_n3(
    data: str, prefixes: Optional[Dict[str, str]] = None
) -> Tuple[List[ParsedTriple], Dict[str, str]]:
    """Parse N3 *data* (the Turtle-compatible subset; rule blocks are handled
    by :mod:`kolibrie_tpu.reasoner.n3_parser`)."""
    return parse_turtle(data, prefixes)


def parse_ntriples(data: str) -> List[ParsedTriple]:
    """Parse N-Triples(-star).  Line-oriented; full-IRI terms only."""
    p = _TurtleParser(data)
    return p.parse()


# --------------------------------------------------------------------------
# RDF/XML
# --------------------------------------------------------------------------


def _split_qname(tag: str) -> Tuple[str, str]:
    if tag.startswith("{"):
        ns, local = tag[1:].split("}", 1)
        return ns, local
    return "", tag


def parse_rdf_xml(data: str) -> List[ParsedTriple]:
    """Parse RDF/XML (streamed).  Supports rdf:Description / typed node
    elements, rdf:about / rdf:ID / rdf:nodeID, property elements with
    rdf:resource, literal content (rdf:datatype, xml:lang), and nested node
    elements.  Parity: ``sparql_database.rs:401-571`` (quick-xml streaming).
    """
    triples: List[ParsedTriple] = []
    root = ET.fromstring(data)
    rns, rlocal = _split_qname(root.tag)
    counter = [0]

    def fresh() -> str:
        counter[0] += 1
        return f"_:xml{counter[0]}"

    def node_subject(el: ET.Element) -> str:
        about = el.get(f"{{{RDF_NS}}}about")
        if about is not None:
            return about
        rid = el.get(f"{{{RDF_NS}}}ID")
        if rid is not None:
            return "#" + rid
        nid = el.get(f"{{{RDF_NS}}}nodeID")
        if nid is not None:
            return "_:" + nid
        return fresh()

    def parse_node(el: ET.Element) -> str:
        subj = node_subject(el)
        ns, local = _split_qname(el.tag)
        if not (ns == RDF_NS and local == "Description"):
            triples.append((subj, RDF_TYPE, ns + local))
        # non-rdf attributes are literal properties
        for attr, val in el.attrib.items():
            ans, alocal = _split_qname(attr)
            if ans in (RDF_NS, "http://www.w3.org/XML/1998/namespace") or ans == "":
                continue
            triples.append((subj, ans + alocal, f'"{val}"'))
        for prop in el:
            pns, plocal = _split_qname(prop.tag)
            pred = pns + plocal
            res = prop.get(f"{{{RDF_NS}}}resource")
            nid = prop.get(f"{{{RDF_NS}}}nodeID")
            if res is not None:
                triples.append((subj, pred, res))
            elif nid is not None:
                triples.append((subj, pred, "_:" + nid))
            elif len(prop):
                for child in prop:
                    triples.append((subj, pred, parse_node(child)))
            else:
                text = (prop.text or "").strip()
                dt = prop.get(f"{{{RDF_NS}}}datatype")
                lang = prop.get("{http://www.w3.org/XML/1998/namespace}lang")
                if dt:
                    triples.append((subj, pred, f'"{text}"^^{dt}'))
                elif lang:
                    triples.append((subj, pred, f'"{text}"@{lang}'))
                else:
                    triples.append((subj, pred, f'"{text}"'))
        return subj

    if rns == RDF_NS and rlocal == "RDF":
        for el in root:
            parse_node(el)
    else:
        parse_node(root)
    return triples


# --------------------------------------------------------------------------
# Serialization (store -> text); parity: sparql_database.rs:277-400
# --------------------------------------------------------------------------


def _escape_lex(lex: str) -> str:
    """N-Triples/Turtle string escaping for a raw lexical form."""
    return (
        lex.replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
        .replace("\r", "\\r")
        .replace("\t", "\\t")
    )


def format_term_nt(term: str) -> str:
    """Render a stored term string in N-Triples syntax.

    Stored literal lexical forms are raw/unescaped (see module docstring),
    so they are re-escaped here — otherwise a literal containing a quote or
    newline produces output no Turtle parser accepts.  Quoted triples
    re-bracket recursively: the decoded form carries bare inner IRIs
    (``<< http://a http://p http://o >>``), the syntactic form needs
    ``<< <http://a> <http://p> <http://o> >>``.
    """
    if term.startswith("_:"):
        return term
    if term.startswith('"'):
        lex, dt, lang = _parse_stored_literal(term)
        esc = _escape_lex(lex)
        if dt:
            return f'"{esc}"^^<{dt}>'
        if lang:
            return f'"{esc}"@{lang}'
        return f'"{esc}"'
    if term.startswith("<<"):
        from kolibrie_tpu.query.sparql_database import split_quoted_triple_content

        parts = split_quoted_triple_content(term[2:-2].strip())
        if len(parts) == 3:
            return "<< " + " ".join(format_term_nt(p) for p in parts) + " >>"
        return term
    return f"<{term}>"


_LANG_TAG_RE = re.compile(r"^[A-Za-z]{1,8}(-[A-Za-z0-9]{1,8})*$")


def _parse_stored_literal(term: str) -> Tuple[str, Optional[str], Optional[str]]:
    """Split a stored literal ``"lex"``, ``"lex"^^dt`` or ``"lex"@lang`` into
    (lexical form, datatype IRI or None, language tag or None).

    The stored lexical form is raw/unescaped and may itself contain ``"``,
    ``@`` or ``^^`` — so suffixes are recognized only when anchored at the
    END of the term: a plain literal always ends with its closing quote, and
    a candidate datatype/lang suffix must itself be well-formed.
    """
    if term.endswith('"') and len(term) >= 2:
        return term[1:-1], None, None
    if '"^^' in term:
        lex, dt = term.rsplit('"^^', 1)
        if '"' not in dt and " " not in dt:
            return lex[1:], dt.strip("<>"), None
    if '"@' in term:
        lex, lang = term.rsplit('"@', 1)
        if _LANG_TAG_RE.match(lang):
            return lex[1:], None, lang
    return term[1:] if term.startswith('"') else term, None, None


_NCNAME_START_RE = re.compile(r"[A-Za-z_]")
# XML NCName (dots allowed anywhere after the first char)
_PN_LOCAL_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_.\-]*$")
# Turtle PN_LOCAL may not END with '.' (a trailing dot terminates the
# statement for conformant parsers)
_TTL_LOCAL_RE = re.compile(r"^[A-Za-z_]([A-Za-z0-9_.\-]*[A-Za-z0-9_\-])?$")


def _split_iri_qname(iri: str) -> Optional[Tuple[str, str]]:
    """Split an IRI into (namespace, NCName local part) for XML QName use.
    Prefers the fragment/last-slash boundary, then backs up until the local
    part starts with an NCName start char.  None if no valid split exists."""
    for sep in ("#", "/", ":"):
        idx = iri.rfind(sep)
        if idx < 0 or idx == len(iri) - 1:
            continue
        local = iri[idx + 1 :]
        if _PN_LOCAL_RE.match(local):
            return iri[: idx + 1], local
        # back up past leading non-NCName-start chars (e.g. digits)
        m = _NCNAME_START_RE.search(local)
        if m and _PN_LOCAL_RE.match(local[m.start() :]):
            cut = idx + 1 + m.start()
            return iri[:cut], iri[cut:]
    return None


def serialize_rdfxml(
    decoded_triples, prefixes: Optional[Dict[str, str]] = None
) -> str:
    """RDF/XML writer over decoded (s, p, o) term strings.

    Parity: ``kolibrie/src/sparql_database.rs:277-317`` ``generate_rdf_xml``
    — subject-grouped ``rdf:Description`` blocks with the database's prefix
    table as namespace declarations — but emits spec-valid XML the
    reference's string-template writer does not: predicate QName splitting
    with auto-declared namespaces, ``rdf:resource`` for IRI objects,
    ``rdf:nodeID`` for blank nodes, ``rdf:datatype``/``xml:lang`` literal
    attributes, and XML escaping.  Triples touching a quoted triple are
    skipped (RDF/XML has no RDF-star syntax; N-Triples/Turtle carry those).
    """
    from xml.sax.saxutils import escape, quoteattr

    ns_to_prefix: Dict[str, str] = {RDF_NS: "rdf"}
    iri_to_prefix = {v: k for k, v in (prefixes or {}).items() if k}
    auto = [0]

    def prefix_for(ns: str) -> str:
        pfx = ns_to_prefix.get(ns)
        if pfx is None:
            taken = set(ns_to_prefix.values())
            pfx = iri_to_prefix.get(ns)
            if pfx is not None and (pfx in taken or not _PN_LOCAL_RE.match(pfx)):
                pfx = None  # registered name unusable as an XML prefix here
            if pfx is None:
                # auto names must not collide with registered prefixes either
                while True:
                    auto[0] += 1
                    pfx = f"ns{auto[0]}"
                    if pfx not in taken and pfx not in iri_to_prefix.values():
                        break
            ns_to_prefix[ns] = pfx
        return pfx

    subjects: Dict[str, List[Tuple[str, str]]] = {}
    for s, p, o in decoded_triples:
        if "<<" in (s[:2], p[:2], o[:2]) or s.startswith('"'):
            continue  # not expressible in RDF/XML
        subjects.setdefault(s, []).append((p, o))

    body: List[str] = []
    for s in sorted(subjects):
        if s.startswith("_:"):
            body.append(f"  <rdf:Description rdf:nodeID={quoteattr(s[2:])}>")
        else:
            body.append(f"  <rdf:Description rdf:about={quoteattr(s)}>")
        for p, o in subjects[s]:
            split = _split_iri_qname(p)
            if split is None:
                # RDF/XML requires every predicate to be an XML QName; a
                # silent drop would lose data, so refuse (rdflib does too)
                raise ValueError(
                    f"predicate IRI not serializable as an XML QName: {p!r}"
                )
            ns, local = split
            qn = f"{prefix_for(ns)}:{local}"
            if o.startswith('"'):
                lex, dt, lang = _parse_stored_literal(o)
                attrs = ""
                if dt:
                    attrs = f" rdf:datatype={quoteattr(dt)}"
                elif lang:
                    attrs = f" xml:lang={quoteattr(lang)}"
                body.append(f"    <{qn}{attrs}>{escape(lex)}</{qn}>")
            elif o.startswith("_:"):
                body.append(f"    <{qn} rdf:nodeID={quoteattr(o[2:])}/>")
            else:
                body.append(f"    <{qn} rdf:resource={quoteattr(o)}/>")
        body.append("  </rdf:Description>")

    decls = [
        f"xmlns:{pfx}={quoteattr(ns)}"
        for ns, pfx in sorted(ns_to_prefix.items(), key=lambda kv: kv[1])
    ]
    head = "<rdf:RDF " + " ".join(decls) + ">"
    return "\n".join(['<?xml version="1.0" encoding="utf-8"?>', head, *body, "</rdf:RDF>"]) + "\n"


def serialize_turtle(
    decoded_triples, prefixes: Optional[Dict[str, str]] = None
) -> str:
    """Subject/predicate-grouped Turtle-star writer with prefix compaction
    and ``a`` for rdf:type.  Parity: ``sparql_database.rs:343-400``
    ``generate_turtle`` (BTreeMap grouping with ``;`` / ``,``)."""
    prefixes = prefixes or {}
    # longest-namespace-first so the most specific prefix wins
    by_len = sorted(
        ((v, k) for k, v in prefixes.items() if k), key=lambda kv: -len(kv[0])
    )

    def compact(term: str) -> str:
        if term.startswith('"') or term.startswith("_:") or term.startswith("<<"):
            return format_term_nt(term)
        for ns, pfx in by_len:
            if term.startswith(ns):
                local = term[len(ns):]
                if _TTL_LOCAL_RE.match(local):
                    return f"{pfx}:{local}"
        return f"<{term}>"

    subjects: Dict[str, Dict[str, List[str]]] = {}
    order: List[str] = []
    for s, p, o in decoded_triples:
        if s not in subjects:
            subjects[s] = {}
            order.append(s)
        subjects[s].setdefault(p, []).append(o)

    lines = [f"@prefix {k}: <{v}> ." for k, v in sorted(prefixes.items()) if k]
    if lines:
        lines.append("")
    for s in order:
        s_str = compact(s)
        parts = []
        for p, objs in subjects[s].items():
            p_str = "a" if p == RDF_TYPE else compact(p)
            o_str = " , ".join(compact(o) for o in objs)
            parts.append(f"{p_str} {o_str}")
        lines.append(f"{s_str} " + " ;\n    ".join(parts) + " .")
    return "\n".join(lines) + "\n"
