"""Exposition: Prometheus text format for ``GET /metrics`` and the
single source of truth behind ``GET /stats``.

Before this module existed the server had two stats code paths —
``TemplateBatcher.stats()`` poked the compile cache, plan cache and
breaker board with function-level imports on every poll, and
``_handle_stats`` assembled a second dict around it.  Both now render
here: :func:`store_stats` builds one store's block, :func:`build_stats`
the whole ``/stats`` payload, and the heavyweight imports run once at
module import instead of per scrape.

The JSON shapes are load-bearing (tests/test_plan_template.py and
tests/test_chaos.py assert on keys), so :func:`store_stats` preserves
them exactly.
"""

from __future__ import annotations

from typing import List

from kolibrie_tpu.obs import metrics

# rendering itself is stdlib-only and shared with the router's fleet
# aggregation — it lives in promtext; re-exported here because every
# existing caller imports it from this module
from kolibrie_tpu.obs.promtext import render_prometheus  # noqa: F401

# Satellite: module-scope imports — previously re-imported inside
# TemplateBatcher.stats() on every /stats poll.
from kolibrie_tpu.optimizer.device_engine import device_compile_stats
from kolibrie_tpu.query.executor import plan_cache_info
from kolibrie_tpu.resilience.breaker import breaker_board


# ----------------------------------------------------------------- /stats


def _pct(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


def store_stats(batcher) -> dict:
    """One store's ``/stats`` block (formerly ``TemplateBatcher.stats``).
    Key set is asserted by tests — extend, don't rename."""
    with batcher.lock:
        per = {
            fp: {
                "requests": rec["requests"],
                "dedup_hits": rec["dedup_hits"],
                "dispatches": len(rec["lat"]),
                "dispatch_ms_p50": _pct(rec["lat"], 0.50),
                "dispatch_ms_p95": _pct(rec["lat"], 0.95),
            }
            for fp, rec in batcher.templates.items()
        }
        depths = list(getattr(batcher, "depth_at_dispatch", ()))
        distinct = list(getattr(batcher, "distinct_per_dispatch", ()))
        out = {
            "requests": batcher.requests,
            "dispatches": batcher.dispatches,
            "dedup_hits": batcher.dedup_hits,
            "max_batch": batcher.max_batch,
            "shed_queue_full": batcher.shed_queue_full,
            "shed_deadline": batcher.shed_deadline,
            "queue_depth": len(batcher.pending),
            # dispatch-shape distribution (bounded recent window): how
            # deep the drained queue ran and how template-diverse each
            # dispatch was — distinct >= 2 is the population the MQO
            # shared-prefix layer can help (docs/MQO.md)
            "queue_depth_at_dispatch_p50": _pct(depths, 0.50),
            "queue_depth_at_dispatch_p95": _pct(depths, 0.95),
            "distinct_templates_p50": _pct(distinct, 0.50),
            "distinct_templates_p95": _pct(distinct, 0.95),
            "per_template": per,
        }
    with batcher.dispatch_lock:
        out["triples"] = len(batcher.db.store)
        out["plan_cache"] = plan_cache_info(batcher.db)
        out["breakers"] = breaker_board(batcher.db).snapshot()
        sharded = batcher.db.__dict__.get("_sharded_serving")
        if sharded is not None:
            # shard count, per-shard occupancy, imbalance, last cap hit —
            # the degraded-routing signals (docs/SHARDING.md)
            out["sharding"] = sharded.stats()
    out["device_compiles"] = device_compile_stats()
    from kolibrie_tpu.optimizer import mqo

    # shared-prefix registry for this store: mode, standing count, per-
    # prefix beneficiaries / shared evals / cache hits (docs/MQO.md)
    out["mqo"] = mqo.stats(batcher.db)
    return out


def build_stats(state) -> dict:
    """The whole ``GET /stats`` payload (formerly inline in
    ``_handle_stats``): per-store blocks plus RSP session and resilience
    counters.  ``state`` is the server's ``_ServerState``."""
    with state.lock:
        stores = dict(state.stores)
        sessions = dict(state.sessions)
    per_session = {}
    for sid, s in sessions.items():
        with s.lock:
            info = {
                "subscribers": len(s.subscribers),
                "dropped_subscribers": s.dropped_subscribers,
                "crash_recoveries": s.crash_recoveries,
                "recovered": getattr(s, "recovered", False),
            }
        rstats = getattr(s.engine, "resilience_stats", None)
        if rstats is not None:
            info["windows"] = rstats()
        mstats = getattr(s.engine, "mqo_stats", None)
        if mstats is not None:
            # fire-round prefix sharing across the session's standing
            # windows (docs/MQO.md): hits climb when same-content rounds
            # reuse the cached prefix table
            info["mqo"] = mstats()
        per_session[sid] = info
    resilience = {
        "admission": state.admission.snapshot(),
        "sessions": per_session,
    }
    durability = getattr(state, "durability", None)
    if durability is not None:
        resilience["durability"] = {
            "status": getattr(state, "status", "ready"),
            **durability.stats(),
        }
    # compile-tail block: persistent-cache hit/miss traffic + warmer
    # progress — the "is the restart tail actually dead" dashboard
    from kolibrie_tpu.query import compile_cache

    compile_tail: dict = {"cache": compile_cache.stats()}
    warmer = getattr(state, "prewarmer", None)
    if warmer is not None:
        compile_tail["prewarm"] = warmer.stats()
    # capacity-advisor block: per-template current caps / high-water mark /
    # retry counts (process-wide — the advisor spans stores and survives
    # base-version churn; "is steady state really zero-retry" dashboard)
    from kolibrie_tpu.optimizer.stats_advisor import stats_advisor
    from kolibrie_tpu.query.template import cap_advisor

    out = {
        "stores": {sid: store_stats(b) for sid, b in stores.items()},
        "rsp_sessions": len(sessions),
        "resilience": resilience,
        "compile_tail": compile_tail,
        "cap_advisor": cap_advisor.stats(),
        # feedback-optimizer block: per-template learned-key counts,
        # plan generation, replans and drift state (docs/OPTIMIZER.md)
        "stats_advisor": stats_advisor.stats(),
    }
    # replication block: ship/apply counters + watermark/lag on nodes
    # with a role in a fleet (primary ship server or follower); absent on
    # plain single-process servers
    replication = getattr(state, "replication", None)
    if replication is not None:
        out["replication"] = {
            "node_role": getattr(state, "role", "primary"),
            **replication.stats(),
        }
    return out


# ------------------------------------------------- scrape-time collectors

_compile_cache_gauge = metrics.gauge(
    "kolibrie_device_compile_cache_entries",
    "jit cache sizes per device entry point (a recompile adds an entry)",
    labels=("entry",),
)


def _collect_compile_cache() -> None:
    for name, size in device_compile_stats().items():
        _compile_cache_gauge.labels(name).set(size)


metrics.register_collector(_collect_compile_cache)

_queue_depth_gauge = metrics.gauge(
    "kolibrie_batcher_queue_depth",
    "requests pending in a store's batching window",
    labels=("store",),
)
_rsp_sessions_gauge = metrics.gauge(
    "kolibrie_rsp_sessions", "live RSP sessions"
)
_store_shards_gauge = metrics.gauge(
    "kolibrie_store_shards",
    "mesh shard count serving a store (0 rows absent = single-device)",
    labels=("store",),
)
_store_shard_imbalance_gauge = metrics.gauge(
    "kolibrie_store_shard_imbalance",
    "per-store max/mean shard row occupancy",
    labels=("store",),
)
_plan_cache_gauges = {
    "parse_entries": metrics.gauge(
        "kolibrie_plan_cache_parse_entries",
        "parse-level plan cache occupancy", labels=("store",),
    ),
    "templates": metrics.gauge(
        "kolibrie_plan_cache_templates",
        "template-level plan cache occupancy", labels=("store",),
    ),
}


def refresh_server_gauges(state) -> None:
    """Pull server-held state into gauges — called by the /metrics
    handler before rendering (the registry's own collectors cannot see
    the server state object)."""
    with state.lock:
        stores = dict(state.stores)
        n_sessions = len(state.sessions)
    _rsp_sessions_gauge.set(n_sessions)
    for sid, b in stores.items():
        with b.lock:
            _queue_depth_gauge.labels(sid).set(len(b.pending))
        info = plan_cache_info(b.db)
        for key, g in _plan_cache_gauges.items():
            g.labels(sid).set(info[key])
        sharded = b.db.__dict__.get("_sharded_serving")
        if sharded is not None:
            sh_stats = sharded.stats()
            _store_shards_gauge.labels(sid).set(sh_stats["shards"])
            if "imbalance" in sh_stats:
                _store_shard_imbalance_gauge.labels(sid).set(
                    sh_stats["imbalance"]
                )
    # follower watermark/lag SLO gauges refresh at scrape time so a
    # wedged poll loop cannot freeze the lag /metrics reports — the
    # follower owns the gauge families; primaries (ShipServer) have no
    # refresh hook and push their counters inline
    replication = getattr(state, "replication", None)
    refresh = getattr(replication, "refresh_gauges", None)
    if refresh is not None:
        refresh()
