"""kolibrie_tpu.obs — spans, metrics, and exposition.

Import discipline: :mod:`runtime`, :mod:`spans` and :mod:`metrics` are
stdlib-only and import nothing from the engine, so any layer (resilience
included) may instrument itself without cycles.  :mod:`export` imports
the engine (compile stats, plan cache, breakers) and is therefore NOT
imported here — only the HTTP frontend and tests pull it in.
"""

from kolibrie_tpu.obs.runtime import enabled, set_enabled  # noqa: F401
from kolibrie_tpu.obs import metrics, spans  # noqa: F401
