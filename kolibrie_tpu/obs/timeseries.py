"""A fixed-size ring of periodic metrics-registry snapshots.

Every counter the engine already maintains gets a history for the cost
of one ``Registry.snapshot()`` per sample interval: the ring stores raw
snapshots and derives counter *deltas*, gauge samples, and histogram
quantiles lazily at read time (``/debug/timeline`` or the bench gate),
so the sampling path does no math and no allocation beyond the dict
dump itself.

The ring is bounded (default 256 samples) and sampling is opt-in: the
HTTP server starts the background sampler thread; library use and tests
call :meth:`TimeSeriesRing.record` directly.  Like the rest of
:mod:`kolibrie_tpu.obs`, this module is stdlib-only.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional

from kolibrie_tpu.obs import metrics
from kolibrie_tpu.resilience.faultinject import InjectedFault, fault_point

DEFAULT_CAPACITY = 256
DEFAULT_INTERVAL_S = 5.0


def bucket_quantile(cumulative: List[tuple], q: float) -> Optional[float]:
    """Interpolated quantile from ``HistogramChild.cumulative()`` pairs.

    Linear interpolation inside the target bucket, matching the usual
    Prometheus ``histogram_quantile`` semantics: the returned value is
    an upper-bound estimate, and a quantile landing in the +Inf bucket
    degrades to the largest finite bound.
    """
    if not cumulative:
        return None
    total = cumulative[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_cum = 0.0, 0
    for le, cum in cumulative:
        if cum >= rank:
            if math.isinf(le):
                return prev_le if prev_le > 0 else None
            if cum == prev_cum:
                return le
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = le, cum
    return prev_le if prev_le > 0 else None


class TimeSeriesRing:
    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 registry: Optional[metrics.Registry] = None):
        if capacity < 2:
            raise ValueError("ring needs >= 2 samples to form a delta")
        self.capacity = capacity
        self.registry = registry or metrics.REGISTRY
        self._lock = threading.Lock()
        self._samples: List[dict] = []  # guarded by: _lock
        self._seq = 0  # guarded by: _lock — monotonic, survives eviction

    def _append_sample(self, snap: dict, ts: float) -> int:  # kolint: holds[_lock]
        seq = self._seq
        self._seq += 1
        self._samples.append({"seq": seq, "ts": ts, "snap": snap})
        if len(self._samples) > self.capacity:
            del self._samples[: len(self._samples) - self.capacity]
        return seq

    def record(self, now: Optional[float] = None) -> int:
        """Take one snapshot.  Returns the sample's sequence number."""
        snap = self.registry.snapshot()
        ts = time.time() if now is None else now
        try:
            fault_point("lockcheck.bypass")
        except InjectedFault:
            # seeded guard violation: on injection the holds[_lock] claim
            # above is FALSE — the chaos suite asserts the
            # KOLIBRIE_DEBUG_LOCKS sanitizer reports this very access,
            # proving the checker checks (tests/test_chaos.py)
            return self._append_sample(snap, ts)
        with self._lock:
            return self._append_sample(snap, ts)

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    def window(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            samples = list(self._samples)
        if n is not None and n > 0:
            samples = samples[-n:]
        return samples

    def series(self, metric: Optional[str] = None,
               n: Optional[int] = None,
               quantiles: tuple = (0.5, 0.99)) -> Dict[str, Any]:
        """Render the ring as per-metric time series.

        Counters become per-interval deltas (one fewer point than
        samples; a negative delta — process restart — clamps to the
        new absolute value).  Gauges are sampled verbatim.  Histograms
        yield count/sum deltas plus interpolated quantiles of the
        cumulative distribution at each sample.
        """
        samples = self.window(n)
        out: Dict[str, Any] = {
            "samples": len(samples),
            "first_seq": samples[0]["seq"] if samples else None,
            "last_seq": samples[-1]["seq"] if samples else None,
            "timestamps": [s["ts"] for s in samples],
            "metrics": {},
        }
        if not samples:
            return out
        names = set()
        for s in samples:
            names.update(s["snap"].keys())
        for name in sorted(names):
            if metric is not None and name != metric:
                continue
            latest = None
            for s in reversed(samples):
                if name in s["snap"]:
                    latest = s["snap"][name]
                    break
            kind = latest["kind"]
            child_keys = set()
            for s in samples:
                fam = s["snap"].get(name)
                if fam:
                    child_keys.update(fam["children"].keys())
            fam_out: Dict[str, Any] = {"kind": kind, "series": {}}
            for key in sorted(child_keys):
                label = ",".join(key) if key else ""
                points = [s["snap"].get(name, {}).get("children", {}).get(key)
                          for s in samples]
                if kind == "gauge":
                    fam_out["series"][label] = {"values": points}
                elif kind == "counter":
                    fam_out["series"][label] = {
                        "deltas": _deltas([p for p in points]),
                    }
                else:  # histogram
                    counts = [p["count"] if p else None for p in points]
                    sums = [p["sum"] if p else None for p in points]
                    qs = {
                        f"p{int(q * 100)}": [
                            bucket_quantile(p["cumulative"], q) if p else None
                            for p in points
                        ]
                        for q in quantiles
                    }
                    fam_out["series"][label] = {
                        "count_deltas": _deltas(counts),
                        "sum_deltas": _deltas(sums),
                        "quantiles": qs,
                    }
            out["metrics"][name] = fam_out
        return out


def _deltas(points: List[Optional[float]]) -> List[Optional[float]]:
    out: List[Optional[float]] = []
    for prev, cur in zip(points, points[1:]):
        if cur is None or prev is None:
            out.append(None)
        else:
            d = cur - prev
            out.append(cur if d < 0 else d)  # restart: clamp to new absolute
    return out


class Sampler:
    """Daemon thread feeding a ring at a fixed interval."""

    def __init__(self, ring: TimeSeriesRing,
                 interval_s: float = DEFAULT_INTERVAL_S):
        self.ring = ring
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="kolibrie-timeline-sampler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.ring.record()
            # kolint: ignore[KL601] sampler must survive any registry hiccup; a dropped sample is the correct degradation
            except Exception:
                pass


_DEFAULT_RING: Optional[TimeSeriesRing] = None
_DEFAULT_LOCK = threading.Lock()


def default_ring() -> TimeSeriesRing:
    global _DEFAULT_RING
    with _DEFAULT_LOCK:
        if _DEFAULT_RING is None:
            _DEFAULT_RING = TimeSeriesRing()
        return _DEFAULT_RING


# Debug-build runtime check of the # guarded by: annotations above
# (no-op unless KOLIBRIE_DEBUG_LOCKS=1 — see analysis/lockcheck.py)
from kolibrie_tpu.analysis import lockcheck as _lockcheck

_lockcheck.auto_instrument(globals())
