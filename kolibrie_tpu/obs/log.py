"""Structured JSON-lines logging for long-running components.

One record is one JSON object on one line: ``ts`` (unix seconds),
``level``, ``component``, ``msg``, the process-wide ``role`` (primary /
follower / router — set once at startup via :func:`set_role`), the
active ``trace_id`` auto-injected from the span context when one is
live, plus any caller-supplied fields.  A record therefore joins the
span ring on trace id — grep the log tail for a trace and you get the
narrative between its spans.

Two sinks, both cheap:

- an in-memory **tail ring** (bounded deque) that always records, so
  the flight recorder (:mod:`kolibrie_tpu.obs.flightrec`) can dump the
  last N records postmortem without any file I/O on the logging path;
- **stderr**, for operators, gated by :func:`set_quiet` /
  ``KOLIBRIE_LOG_QUIET=1`` — stdout stays reserved for user-facing CLI
  output and the bench's JSON block.

Like :mod:`kolibrie_tpu.obs.spans` this module is stdlib-only and
imports nothing from the engine, so any layer may log without cycles.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from kolibrie_tpu.obs import spans

DEFAULT_TAIL_CAPACITY = 1024

_LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40}

_lock = threading.Lock()
_tail: deque = deque(maxlen=DEFAULT_TAIL_CAPACITY)  # guarded by: _lock

_role: Optional[str] = None
_node: Optional[str] = None
_quiet: bool = os.environ.get("KOLIBRIE_LOG_QUIET") == "1"
_min_level: int = _LEVELS.get(
    os.environ.get("KOLIBRIE_LOG_LEVEL", "info"), _LEVELS["info"]
)

_loggers: Dict[str, "Logger"] = {}
_loggers_lock = threading.Lock()


def set_role(role: Optional[str]) -> None:
    """Install the process-wide node role stamped on every record."""
    global _role
    # kolint: ignore[KL311] process identity is set once at startup before serving threads exist; the rebind is an atomic str swap and readers tolerate either value
    _role = role


def get_role() -> Optional[str]:
    return _role


def set_identity(role: str, port: Optional[int] = None) -> None:
    """Role + port in one call: the ``role:port`` node identity is what
    fleet spans carry as their ``node`` attribute, so a stitched trace
    names which process each hop ran on."""
    global _node
    set_role(role)
    # kolint: ignore[KL311] same startup-once discipline as _role above; hot log paths read it lock-free by design
    _node = f"{role}:{port}" if port is not None else role


def node() -> Optional[str]:
    """The ``role:port`` identity set by :func:`set_identity`, or None
    on processes that never declared one (library use, tests)."""
    return _node


def set_quiet(value: bool) -> None:
    """Suppress (or restore) the stderr sink.  The tail ring always
    records regardless — quiet mode only silences the console."""
    global _quiet
    _quiet = bool(value)


def set_min_level(level: str) -> None:
    global _min_level
    _min_level = _LEVELS[level]


class Logger:
    """One component's handle.  Stateless beyond the component name, so
    handles are free to cache at module scope."""

    __slots__ = ("component",)

    def __init__(self, component: str):
        self.component = component

    def _emit(self, level: str, msg: str, fields: Dict[str, Any]) -> None:
        if _LEVELS[level] < _min_level:
            return
        rec: Dict[str, Any] = {
            "ts": round(time.time(), 6),
            "level": level,
            "component": self.component,
            "msg": msg,
        }
        if _role is not None:
            rec["role"] = _role
        trace_id = spans.current_trace_id()
        if trace_id is not None:
            rec["trace_id"] = trace_id
        for k, v in fields.items():
            if k not in rec:
                rec[k] = v
        with _lock:
            _tail.append(rec)
        if not _quiet:
            try:
                sys.stderr.write(
                    json.dumps(rec, sort_keys=True, default=str) + "\n"
                )
            except (OSError, ValueError):
                pass  # closed/broken stderr must never take the server down

    def debug(self, msg: str, **fields: Any) -> None:
        self._emit("debug", msg, fields)

    def info(self, msg: str, **fields: Any) -> None:
        self._emit("info", msg, fields)

    def warn(self, msg: str, **fields: Any) -> None:
        self._emit("warn", msg, fields)

    def error(self, msg: str, **fields: Any) -> None:
        self._emit("error", msg, fields)


def get_logger(component: str) -> Logger:
    with _loggers_lock:
        lg = _loggers.get(component)
        if lg is None:
            lg = _loggers[component] = Logger(component)
        return lg


# --------------------------------------------------------------- tail ring


def tail(
    n: Optional[int] = None,
    level: Optional[str] = None,
    component: Optional[str] = None,
) -> List[dict]:
    """The most recent records, oldest first, optionally filtered."""
    with _lock:
        recs = list(_tail)
    if level is not None:
        floor = _LEVELS[level]
        recs = [r for r in recs if _LEVELS[r["level"]] >= floor]
    if component is not None:
        recs = [r for r in recs if r["component"] == component]
    if n is not None:
        recs = recs[-int(n):]
    return recs


def export_jsonl(n: Optional[int] = None) -> str:
    """The tail ring, one JSON object per line — the flight recorder's
    log artifact."""
    return "\n".join(
        json.dumps(r, sort_keys=True, default=str) for r in tail(n)
    )


def set_tail_capacity(n: int) -> None:
    """Resize the tail ring (keeps the newest records).  Test hook."""
    global _tail
    with _lock:
        _tail = deque(_tail, maxlen=int(n))


def clear() -> None:
    with _lock:
        _tail.clear()
