"""EXPLAIN ANALYZE capture: a thread-local sink for per-operator
actuals harvested from a single dispatch.

The engine computes a device-resident stats pytree alongside every
result (see ``device_engine._plan_body``); fetching it costs a host
sync, so the engine only does that fetch when a capture is *active*.
This module is that switch plus the bucket the fetched numbers land in.

Stdlib-only by design (same import discipline as :mod:`spans` /
:mod:`metrics`): the engine imports us, never the reverse.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

_tls = threading.local()


class Capture:
    """Accumulates analyze records for one dispatch.

    ``records`` is a list of dicts, each tagged with a ``kind``:

    - ``device``:  specialized-path per-operator stats (key -> rows)
    - ``interp``:  interpreter per-op rows + opcode dispatch counts
    - ``sharded``: per-member, per-shard row/exchange totals
    """

    __slots__ = ("records",)

    def __init__(self) -> None:
        self.records: List[Dict[str, Any]] = []

    def record(self, kind: str, **payload: Any) -> None:
        entry: Dict[str, Any] = {"kind": kind}
        entry.update(payload)
        self.records.append(entry)

    def last(self, kind: Optional[str] = None) -> Optional[Dict[str, Any]]:
        for entry in reversed(self.records):
            if kind is None or entry["kind"] == kind:
                return entry
        return None


def active() -> Optional[Capture]:
    """The capture currently open on this thread, or None.

    Hot paths must treat None as "skip the stats fetch entirely" so an
    uninstrumented dispatch pays nothing beyond computing the (fused,
    already-resident) stats vector.
    """
    return getattr(_tls, "capture", None)


@contextmanager
def capture() -> Iterator[Capture]:
    """Open an analyze capture on this thread.  Nested captures see
    only their own records; the outer capture resumes on exit."""
    prev = getattr(_tls, "capture", None)
    cap = Capture()
    _tls.capture = cap
    try:
        yield cap
    finally:
        _tls.capture = prev


def record(kind: str, **payload: Any) -> None:
    """Record into the active capture, if any.  Cheap no-op otherwise."""
    cap = active()
    if cap is not None:
        cap.record(kind, **payload)
