"""Prometheus text exposition (v0.0.4): render and merge.

Extracted from :mod:`kolibrie_tpu.obs.export` so the router — which
deliberately imports no query-engine code — can render its own registry
and merge scraped fleet exposition without pulling in the engine.
:mod:`export` re-exports :func:`render_prometheus` unchanged.

:func:`merge_prometheus` is the ``GET /fleet/metrics`` core: it takes
one exposition text per node, stamps every sample with a ``node`` label,
and regroups families so each appears once with a single HELP/TYPE pair
even when families overlap across nodes or carry disjoint label sets.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List

from kolibrie_tpu.obs import metrics
from kolibrie_tpu.obs.metrics import Registry


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (
        s.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')
    )


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _labels_str(names, values, extra=()) -> str:
    pairs = [
        f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)
    ] + [f'{n}="{_escape_label(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render_prometheus(registry: Registry = metrics.REGISTRY) -> str:
    """The registry in Prometheus text exposition format v0.0.4.
    Runs registered collectors first so pull-style gauges are fresh."""
    registry.run_collectors()
    lines: List[str] = []
    for fam in registry.families():
        lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for values, child in fam.children():
            if fam.kind in ("counter", "gauge"):
                lines.append(
                    f"{fam.name}{_labels_str(fam.label_names, values)} "
                    f"{_fmt_value(child.value)}"
                )
            else:  # histogram
                for le, acc in child.cumulative():
                    ls = _labels_str(
                        fam.label_names, values, extra=[("le", _fmt_value(le))]
                    )
                    lines.append(f"{fam.name}_bucket{ls} {acc}")
                base = _labels_str(fam.label_names, values)
                with child._lock:
                    s, c = child.sum, child.count
                lines.append(f"{fam.name}_sum{base} {_fmt_value(s)}")
                lines.append(f"{fam.name}_count{base} {c}")
    return "\n".join(lines) + "\n"


# -------------------------------------------------------------- fleet merge

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)(\s+\d+)?$"
)
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _family_of(sample_name: str, typed: Dict[str, str]) -> str:
    """Histogram/summary series names carry suffixes; map them back to
    the family that HELP/TYPE described."""
    if sample_name in typed:
        return sample_name
    for suf in _HIST_SUFFIXES:
        if sample_name.endswith(suf) and sample_name[: -len(suf)] in typed:
            return sample_name[: -len(suf)]
    return sample_name


def merge_prometheus(per_node: Dict[str, str]) -> str:
    """Merge one exposition text per node into a single text, stamping
    every sample with ``node="<name>"``.

    Families present on several nodes collapse to one HELP/TYPE header
    (first node's wording wins); families unique to one node pass
    through; samples with disjoint label sets coexist because each line
    keeps its own label string — the ``node`` label is prepended, which
    also disambiguates identical series scraped from different nodes.
    Unparseable lines are dropped rather than corrupting the merge.
    """
    order: List[str] = []  # family emission order, first-seen
    helps: Dict[str, str] = {}
    types: Dict[str, str] = {}
    samples: Dict[str, List[str]] = {}
    for node in sorted(per_node):
        text = per_node[node]
        typed: Dict[str, str] = {}
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("# HELP "):
                rest = line[len("# HELP "):]
                name, _, help_text = rest.partition(" ")
                typed.setdefault(name, "")
                if name not in helps:
                    helps[name] = help_text
                continue
            if line.startswith("# TYPE "):
                rest = line[len("# TYPE "):]
                name, _, kind = rest.partition(" ")
                typed[name] = kind.strip()
                if name not in types:
                    types[name] = kind.strip()
                continue
            if not line or line.startswith("#"):
                continue
            m = _SAMPLE_RE.match(line)
            if m is None:
                continue
            sname, labels, value = m.group(1), m.group(2), m.group(3)
            fam = _family_of(sname, typed)
            node_pair = f'node="{_escape_label(node)}"'
            inner = labels[1:-1].strip() if labels else ""
            if inner:
                stamped = f"{sname}{{{node_pair},{inner}}} {value}"
            else:
                stamped = f"{sname}{{{node_pair}}} {value}"
            if fam not in samples:
                samples[fam] = []
                order.append(fam)
            samples[fam].append(stamped)
    lines: List[str] = []
    for fam in order:
        if fam in helps:
            lines.append(f"# HELP {fam} {helps[fam]}")
        if fam in types:
            lines.append(f"# TYPE {fam} {types[fam]}")
        lines.extend(samples[fam])
    return "\n".join(lines) + "\n"
