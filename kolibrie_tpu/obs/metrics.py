"""Process-wide metrics registry: counters, gauges, fixed-bucket
histograms.  No dependencies, no background threads, lock-cheap.

Design points, in order of importance:

- **Hot path cost**: one dict lookup + one small-lock increment.
  Families cache their label children (``labels()`` is get-or-create on
  a dict keyed by the label-value tuple), so steady-state instrumented
  code never allocates.  Histograms use fixed buckets chosen at
  creation — ``observe`` is a linear scan over ~14 floats, far cheaper
  than the device work it measures.
- **Cardinality discipline**: the only unbounded-ish label in the
  catalog is the template fingerprint, which is bounded by the plan
  template cache (~64 entries) upstream.  The registry enforces
  nothing; call sites must.
- **Collectors**: state that lives elsewhere (jit cache sizes, queue
  depth) is pulled at scrape time via ``register_collector`` callbacks
  rather than pushed on every mutation.

A module-level :data:`REGISTRY` is the default sink; the convenience
constructors (:func:`counter` …) are what instrumented code uses.
Tests that need isolation construct their own :class:`Registry`.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kolibrie_tpu.obs import runtime

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Latency buckets in seconds: 0.5 ms … 10 s.  Wide because the same
# shape serves both a sub-ms plan-cache hit and a multi-second compile.
DEFAULT_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# Count-shaped buckets (batch sizes, fixpoint rounds, delta facts).
DEFAULT_COUNT_BUCKETS = (
    1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
    1024.0, 4096.0, 16384.0, 65536.0,
)


class _Child:
    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = threading.Lock()


class CounterChild(_Child):
    __slots__ = ("value",)

    def __init__(self):
        super().__init__()
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not runtime.enabled():
            return
        with self._lock:
            self.value += amount


class GaugeChild(_Child):
    __slots__ = ("value",)

    def __init__(self):
        super().__init__()
        self.value = 0.0

    def set(self, value: float) -> None:
        if not runtime.enabled():
            return
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not runtime.enabled():
            return
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class HistogramChild(_Child):
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Tuple[float, ...]):
        super().__init__()
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # +1 for +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        if not runtime.enabled():
            return
        with self._lock:
            self.sum += value
            self.count += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def cumulative(self) -> List[Tuple[float, int]]:
        """(le, cumulative count) pairs ending with (+Inf, count)."""
        with self._lock:
            out, acc = [], 0
            for b, c in zip(self.buckets, self.counts):
                acc += c
                out.append((b, acc))
            out.append((math.inf, acc + self.counts[-1]))
            return out


_KINDS = {"counter": CounterChild, "gauge": GaugeChild, "histogram": HistogramChild}


class Family:
    """One named metric with a fixed label schema and per-label-value
    children."""

    def __init__(self, name: str, help: str, kind: str,
                 label_names: Tuple[str, ...],
                 buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.help = help
        self.kind = kind
        self.label_names = label_names
        self.buckets = buckets
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}  # guarded by: _lock
        if not label_names:
            self._default = self._make_child()
            self._children[()] = self._default
        else:
            self._default = None

    def _make_child(self) -> _Child:
        if self.kind == "histogram":
            return HistogramChild(self.buckets)
        return _KINDS[self.kind]()

    def labels(self, *values) -> _Child:
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {values!r}"
            )
        key = tuple(str(v) for v in values)
        # kolint: ignore[KL301] double-checked locking: the lock-free read is a fast path; a miss falls through to the locked re-check below
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._children[key] = self._make_child()
        return child

    # Label-less families proxy straight to the single child.
    def inc(self, amount: float = 1.0) -> None:
        self._default.inc(amount)

    def set(self, value: float) -> None:
        self._default.set(value)

    def dec(self, amount: float = 1.0) -> None:
        self._default.dec(amount)

    def observe(self, value: float) -> None:
        self._default.observe(value)

    def children(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        with self._lock:
            return sorted(self._children.items())


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, Family] = {}  # guarded by: _lock
        self._collectors: List[Callable[[], None]] = []  # guarded by: _lock

    def _get_or_create(self, name: str, help: str, kind: str,
                       labels: Sequence[str],
                       buckets: Optional[Sequence[float]] = None) -> Family:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name: {name!r}")
        for ln in labels:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name: {ln!r}")
        label_names = tuple(labels)
        bt = tuple(sorted(buckets)) if buckets is not None else None
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.label_names != label_names:
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        f"kind/labels ({fam.kind}{fam.label_names} vs "
                        f"{kind}{label_names})"
                    )
                return fam
            fam = Family(name, help, kind, label_names, bt)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "", labels: Sequence[str] = ()) -> Family:
        return self._get_or_create(name, help, "counter", labels)

    def gauge(self, name: str, help: str = "", labels: Sequence[str] = ()) -> Family:
        return self._get_or_create(name, help, "gauge", labels)

    def histogram(self, name: str, help: str = "", labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS) -> Family:
        return self._get_or_create(name, help, "histogram", labels, buckets)

    def register_collector(self, fn: Callable[[], None]) -> None:
        """``fn`` runs at each scrape, before rendering — use it to
        refresh gauges whose truth lives elsewhere.  Idempotent on the
        function object so module reloads don't stack duplicates."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            # kolint: ignore[KL601] a broken collector must never break the scrape, and counting it here would recurse into the registry being scraped
            except Exception:
                pass

    def families(self) -> List[Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def snapshot(self) -> Dict[str, dict]:
        """Point-in-time value dump for the timeline ring.

        Returns ``{name: {"kind", "label_names", "children": {labels:
        value}}}`` where a counter/gauge value is a float and a
        histogram value is ``{"count", "sum", "cumulative"}`` (the
        ``cumulative()`` (le, count) pairs).  Collectors are NOT run
        here — the ring samples raw state; scrape-time refresh belongs
        to the exporter.
        """
        out: Dict[str, dict] = {}
        for fam in self.families():
            kids: Dict[Tuple[str, ...], object] = {}
            for key, child in fam.children():
                if fam.kind == "histogram":
                    with child._lock:
                        cnt, tot = child.count, child.sum
                    kids[key] = {"count": cnt, "sum": tot,
                                 "cumulative": child.cumulative()}
                else:
                    kids[key] = child.value
            out[fam.name] = {"kind": fam.kind,
                             "label_names": fam.label_names,
                             "children": kids}
        return out

    def get(self, name: str) -> Optional[Family]:
        with self._lock:
            return self._families.get(name)


REGISTRY = Registry()


def counter(name: str, help: str = "", labels: Sequence[str] = ()) -> Family:
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Sequence[str] = ()) -> Family:
    return REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS) -> Family:
    return REGISTRY.histogram(name, help, labels, buckets)


def register_collector(fn: Callable[[], None]) -> None:
    REGISTRY.register_collector(fn)
