"""Crash flight recorder: postmortem bundles that survive the process.

Every diagnostic surface this package grew — the span ring, the metrics
timeline ring, the structured-log tail — is process memory, and a dead
process takes it to the grave.  The flight recorder writes those
surfaces to disk as a **postmortem bundle**: a directory under
``<data_dir>/postmortem/`` holding

- ``manifest.json`` — reason, wall time, pid, role, artifact list;
- ``spans.jsonl``   — the span ring, one span per line;
- ``timeline.json`` — the metrics timeline ring rendered as series;
- ``log_tail.jsonl``— the structured-log tail ring;
- ``stats.json``    — the server's ``/stats`` payload (best effort);
- ``config.json``   — argv, python version, and ``KOLIBRIE_*``/``JAX_*``
  environment.

Two write modes, both through :mod:`kolibrie_tpu.durability.fsio`:

- :func:`dump` publishes a uniquely-named bundle via temp-dir write +
  :func:`~kolibrie_tpu.durability.fsio.atomic_rename_dir` — a crash
  mid-dump leaves either no bundle or a complete one.  Used on SIGTERM
  (the graceful-shutdown path), fatal errors (:func:`install_excepthook`)
  and ``POST /debug/bundle``.
- :class:`FlightRecorder` keeps a rolling **blackbox** bundle fresh from
  a background thread, each artifact replaced individually with
  :func:`~kolibrie_tpu.durability.fsio.atomic_write_bytes`.  ``kill -9``
  cannot be caught, so the blackbox is how a hard-killed primary still
  leaves evidence — the chaos drill asserts exactly that.  Checkpoints
  skip the fsync (a SIGKILL loses process buffers, not the page cache);
  terminal dumps pay it.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from kolibrie_tpu.durability import fsio
from kolibrie_tpu.obs import log as obslog
from kolibrie_tpu.obs import metrics as obs_metrics
from kolibrie_tpu.obs import spans
from kolibrie_tpu.obs import timeseries

BLACKBOX_DIRNAME = "blackbox"
DEFAULT_CHECKPOINT_INTERVAL_S = 5.0

_log = obslog.get_logger("flightrec")

# reasons are a closed set (checkpoint/sigterm/fatal/manual) — bounded
# label cardinality per KL501
_BUNDLES = obs_metrics.counter(
    "kolibrie_postmortem_bundles_total",
    "postmortem bundles written, by trigger",
    labels=("reason",),
)


def postmortem_dir(data_dir: str) -> str:
    return os.path.join(data_dir, "postmortem")


def _config_snapshot() -> dict:
    env = {
        k: v
        for k, v in os.environ.items()
        if k.startswith(("KOLIBRIE_", "JAX_"))
    }
    return {
        "argv": list(sys.argv),
        "pid": os.getpid(),
        "python": sys.version.split()[0],
        "env": env,
    }


def _artifacts(
    stats_fn: Optional[Callable[[], dict]],
    ring: Optional[timeseries.TimeSeriesRing],
) -> Dict[str, bytes]:
    """Render every diagnostic surface to bytes.  Pure reads — safe to
    call from a signal-adjacent shutdown path or an excepthook."""
    stats: Any = None
    if stats_fn is not None:
        try:
            stats = stats_fn()
        # kolint: ignore[KL601] a broken stats path must not cost the bundle's other artifacts
        except Exception as exc:
            stats = {"error": repr(exc)}
    if ring is None:
        ring = timeseries.default_ring()
    try:
        timeline = ring.series()
    # kolint: ignore[KL601] same: timeline render failure degrades to an error marker, not a lost bundle
    except Exception as exc:
        timeline = {"error": repr(exc)}
    enc = lambda obj: json.dumps(  # noqa: E731
        obj, sort_keys=True, default=str
    ).encode()
    return {
        "spans.jsonl": (spans.export_jsonl() + "\n").encode(),
        "timeline.json": enc(timeline),
        "log_tail.jsonl": (obslog.export_jsonl() + "\n").encode(),
        "stats.json": enc(stats),
        "config.json": enc(_config_snapshot()),
    }


def _manifest(reason: str, names: List[str]) -> bytes:
    return json.dumps(
        {
            "reason": reason,
            "ts": round(time.time(), 6),
            "pid": os.getpid(),
            "role": obslog.get_role(),
            "artifacts": sorted(names),
        },
        sort_keys=True,
    ).encode()


def dump(
    data_dir: str,
    reason: str,
    stats_fn: Optional[Callable[[], dict]] = None,
    ring: Optional[timeseries.TimeSeriesRing] = None,
) -> str:
    """Write one uniquely-named bundle; returns its path.  The temp-dir
    write + atomic rename means a reader never sees a partial bundle."""
    root = postmortem_dir(data_dir)
    os.makedirs(root, exist_ok=True)
    name = f"pm-{int(time.time() * 1000)}-{os.getpid()}-{reason}"
    final = os.path.join(root, name)
    tmp = os.path.join(root, f".{name}.tmp")
    os.makedirs(tmp, exist_ok=True)
    files = _artifacts(stats_fn, ring)
    for fname, data in files.items():
        fsio.atomic_write_bytes(os.path.join(tmp, fname), data)
    fsio.atomic_write_bytes(
        os.path.join(tmp, "manifest.json"),
        _manifest(reason, list(files)),
    )
    fsio.atomic_rename_dir(tmp, final)
    _BUNDLES.labels(reason).inc()
    _log.info("postmortem bundle written", reason=reason, path=final)
    return final


def try_dump(data_dir: str, reason: str, **kw: Any) -> Optional[str]:
    """:func:`dump`, but a recorder failure on a dying process must not
    mask the original failure — log and return None instead."""
    try:
        return dump(data_dir, reason, **kw)
    # kolint: ignore[KL601] last-gasp path: any dump error is logged, never raised over the real crash
    except Exception as exc:
        _log.error("postmortem dump failed", reason=reason, error=repr(exc))
        return None


def install_excepthook(
    data_dir: str,
    stats_fn: Optional[Callable[[], dict]] = None,
) -> None:
    """Chain a bundle dump in front of the current ``sys.excepthook`` so
    an uncaught fatal error on the main thread leaves evidence."""
    prior = sys.excepthook

    def _hook(exc_type, exc, tb):
        try_dump(data_dir, "fatal", stats_fn=stats_fn)
        prior(exc_type, exc, tb)

    sys.excepthook = _hook


def read_bundle(path: str) -> dict:
    """Parse a bundle back into dicts/lists — the test-side consumer.
    Raises on malformed JSON: parseability IS the assertion."""
    out: Dict[str, Any] = {}
    with open(os.path.join(path, "manifest.json")) as fh:
        out["manifest"] = json.load(fh)
    for fname in out["manifest"]["artifacts"]:
        fpath = os.path.join(path, fname)
        with open(fpath) as fh:
            text = fh.read()
        key = fname.rsplit(".", 1)[0]
        if fname.endswith(".jsonl"):
            out[key] = [
                json.loads(line) for line in text.splitlines() if line.strip()
            ]
        else:
            out[key] = json.loads(text)
    return out


def list_bundles(data_dir: str) -> List[str]:
    """Bundle paths under ``data_dir``, oldest first (blackbox last)."""
    root = postmortem_dir(data_dir)
    if not os.path.isdir(root):
        return []
    names = [
        n
        for n in sorted(os.listdir(root))
        if not n.startswith(".")
        and os.path.isfile(os.path.join(root, n, "manifest.json"))
    ]
    names.sort(key=lambda n: n == BLACKBOX_DIRNAME)
    return [os.path.join(root, n) for n in names]


class FlightRecorder:
    """Rolling blackbox: a daemon thread refreshing one well-known
    bundle directory so even ``kill -9`` leaves a recent snapshot."""

    def __init__(
        self,
        data_dir: str,
        interval_s: float = DEFAULT_CHECKPOINT_INTERVAL_S,
        stats_fn: Optional[Callable[[], dict]] = None,
        ring: Optional[timeseries.TimeSeriesRing] = None,
    ):
        self.data_dir = data_dir
        self.interval_s = interval_s
        self.stats_fn = stats_fn
        self.ring = ring
        self._stats_lock = threading.Lock()
        self.checkpoints = 0  # guarded by: _stats_lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def blackbox_path(self) -> str:
        return os.path.join(postmortem_dir(self.data_dir), BLACKBOX_DIRNAME)

    def checkpoint(self) -> str:
        """Refresh the blackbox in place.  Each artifact is replaced
        atomically (fsync skipped — see module docstring), manifest
        last, so a concurrent reader always parses cleanly."""
        box = self.blackbox_path
        os.makedirs(box, exist_ok=True)
        files = _artifacts(self.stats_fn, self.ring)
        for fname, data in files.items():
            fsio.atomic_write_bytes(
                os.path.join(box, fname), data, fsync=False
            )
        fsio.atomic_write_bytes(
            os.path.join(box, "manifest.json"),
            _manifest("checkpoint", list(files)),
            fsync=False,
        )
        with self._stats_lock:
            self.checkpoints += 1
        _BUNDLES.labels("checkpoint").inc()
        return box

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="kolibrie-flightrec", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.checkpoint()
            # kolint: ignore[KL601] the recorder must outlive any single broken artifact render
            except Exception as exc:
                _log.error("blackbox checkpoint failed", error=repr(exc))

    def stats(self) -> dict:
        with self._stats_lock:
            done = self.checkpoints
        return {
            "interval_s": self.interval_s,
            "checkpoints": done,
            "blackbox": self.blackbox_path,
        }


# Debug-build runtime check of the # guarded by: annotations above
# (no-op unless KOLIBRIE_DEBUG_LOCKS=1 — see analysis/lockcheck.py)
from kolibrie_tpu.analysis import lockcheck as _lockcheck

_lockcheck.auto_instrument(globals())
