"""Observability kill switch shared by spans and metrics.

One process-wide flag, initialized from ``KOLIBRIE_OBS_DISABLED=1`` and
flippable at runtime (:func:`set_enabled`) so the bench can measure the
instrumented and uninstrumented executor in the SAME process.  Every
obs entry point checks :func:`enabled` first; disabled, the whole
subsystem costs one attribute read per call site.
"""

from __future__ import annotations

import os

_enabled: bool = os.environ.get("KOLIBRIE_OBS_DISABLED") != "1"


def enabled() -> bool:
    return _enabled


def set_enabled(value: bool) -> None:
    global _enabled
    _enabled = bool(value)
