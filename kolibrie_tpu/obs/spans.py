"""Lightweight span tracing with thread-local context propagation.

The model is deliberately small — a strict subset of OpenTelemetry's,
with zero dependencies and zero background threads:

- a **trace** is a string id (client-supplied via ``X-Kolibrie-Trace-Id``
  or a generated 128-bit hex string) carried in a thread-local;
- a **span** is a named timed section opened with the :func:`span`
  context manager; nesting builds the parent chain via the same
  thread-local stack :mod:`kolibrie_tpu.resilience.deadline` uses for
  deadlines;
- finished spans land in one process-wide bounded ring buffer
  (``collections.deque(maxlen=…)``) exportable as JSONL — there is no
  exporter pipeline, a scrape of ``GET /debug/traces`` IS the export;
- **baggage** is a tiny k→v dict riding along with the trace so the
  executor can tell the device engine which template fingerprint it is
  lowering without threading an argument through six call frames.

Threads do not inherit context automatically.  Code that hops threads
(the batcher leader dispatching for its followers) captures
:func:`current_trace_id` at submit time and re-enters it with
:func:`trace_scope` on the other side — exactly how the deadline is
propagated today.

Everything is a no-op when :func:`kolibrie_tpu.obs.runtime.enabled`
is False.
"""

from __future__ import annotations

import json
import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from kolibrie_tpu.obs import runtime

DEFAULT_RING_CAPACITY = 4096

_tls = threading.local()

# ids only need uniqueness, not unpredictability; getrandbits is ~10x
# cheaper than uuid4 and atomic under the GIL (C-implemented method on a
# shared Mersenne twister seeded from os.urandom)
_rand = random.Random()

_ring_lock = threading.Lock()
_ring: deque = deque(maxlen=DEFAULT_RING_CAPACITY)  # guarded by: _ring_lock


class Span:
    __slots__ = (
        "trace_id",
        "span_id",
        "parent_id",
        "name",
        "start_s",
        "_t0",
        "dur_ms",
        "attrs",
        "error",
    )

    def __init__(self, trace_id: str, parent_id: Optional[str], name: str,
                 attrs: Dict[str, Any]):
        self.trace_id = trace_id
        self.span_id = f"{_rand.getrandbits(64):016x}"
        self.parent_id = parent_id
        self.name = name
        self.start_s = time.time()
        self._t0 = time.perf_counter()
        self.dur_ms: float = 0.0
        self.attrs = attrs
        self.error: Optional[str] = None

    def finish(self) -> None:
        self.dur_ms = (time.perf_counter() - self._t0) * 1000.0

    def to_dict(self) -> dict:
        d = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "dur_ms": round(self.dur_ms, 4),
        }
        if self.attrs:
            d["attrs"] = self.attrs
        if self.error is not None:
            d["error"] = self.error
        return d


# ------------------------------------------------------------------ context


def _ctx():
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        ctx = _tls.ctx = {"trace_id": None, "stack": [], "baggage": {}}
    return ctx


def current_trace_id() -> Optional[str]:
    """The active trace id on this thread, or None."""
    return _ctx()["trace_id"]


def current_span_id() -> Optional[str]:
    stack = _ctx()["stack"]
    return stack[-1].span_id if stack else None


def new_trace_id() -> str:
    return f"{_rand.getrandbits(128):032x}"


@contextmanager
def trace_scope(trace_id: Optional[str] = None):
    """Install ``trace_id`` (or a fresh one) as this thread's active
    trace.  Saves and restores any enclosing context, including baggage,
    so scopes nest — the batcher leader can re-enter each follower's
    trace while holding its own."""
    ctx = _ctx()
    prior = (ctx["trace_id"], ctx["stack"], ctx["baggage"])
    ctx["trace_id"] = trace_id or new_trace_id()
    ctx["stack"] = []
    ctx["baggage"] = {}
    try:
        yield ctx["trace_id"]
    finally:
        ctx["trace_id"], ctx["stack"], ctx["baggage"] = prior


class _NoopScope:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP = _NoopScope()


class _SpanScope:
    """Hand-rolled context manager: the span enter/exit pair sits on the
    per-query hot path, where ``@contextmanager`` generator machinery is
    measurable (bench.py's obs overhead budget is 3%)."""

    __slots__ = ("name", "attrs", "ctx", "sp", "implicit")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs

    def __enter__(self) -> Span:
        ctx = self.ctx = _ctx()
        self.implicit = ctx["trace_id"] is None
        if self.implicit:
            # A span outside any trace_scope (library use, tests) still
            # gets recorded, under its own single-span trace.
            ctx["trace_id"] = new_trace_id()
        stack = ctx["stack"]
        parent = stack[-1].span_id if stack else None
        sp = self.sp = Span(ctx["trace_id"], parent, self.name, self.attrs)
        stack.append(sp)
        return sp

    def __exit__(self, exc_type, exc, tb):
        sp = self.sp
        if exc_type is not None:
            sp.error = f"{exc_type.__name__}: {exc}"
        sp.finish()
        ctx = self.ctx
        stack = ctx["stack"]
        if stack and stack[-1] is sp:
            stack.pop()
        if self.implicit:
            ctx["trace_id"] = None
            ctx["baggage"] = {}
        with _ring_lock:
            _ring.append(sp)
        return False


def span(name: str, **attrs):
    """Open a named timed section.  Records a finished span into the
    ring on exit; ``with span(...) as sp`` yields the :class:`Span` (or
    None when disabled) so callers can attach attrs discovered
    mid-flight."""
    if not runtime.enabled():
        return _NOOP
    return _SpanScope(name, attrs)


# ------------------------------------------------------------------ baggage


def set_baggage(key: str, value: Any) -> None:
    if runtime.enabled():
        _ctx()["baggage"][key] = value


def get_baggage(key: str, default: Any = None) -> Any:
    return _ctx()["baggage"].get(key, default)


# --------------------------------------------------------------------- ring


def set_ring_capacity(n: int) -> None:
    """Resize the span ring (drops existing spans).  Test hook."""
    global _ring
    with _ring_lock:
        _ring = deque(_ring, maxlen=int(n))


def clear() -> None:
    with _ring_lock:
        _ring.clear()


def spans_snapshot(trace_id: Optional[str] = None) -> List[dict]:
    with _ring_lock:
        spans = list(_ring)
    if trace_id is not None:
        spans = [s for s in spans if s.trace_id == trace_id]
    return [s.to_dict() for s in spans]


def export_jsonl(trace_id: Optional[str] = None) -> str:
    """The ring (optionally one trace), one JSON object per line."""
    return "\n".join(
        json.dumps(d, sort_keys=True) for d in spans_snapshot(trace_id)
    )
