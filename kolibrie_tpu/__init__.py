"""kolibrie_tpu — a TPU-native SPARQL/RDF + RSP streaming + probabilistic Datalog +
neurosymbolic ML framework.

A ground-up, TPU-first rebuild of the capabilities of StreamIntelligenceLab/Kolibrie
(Rust, single-node Rayon/SIMD).  Design stance (see SURVEY.md §7):

- Strings live on host; the device sees only dense u32/u64 ID columns.
- The triple store is columnar (SoA ``subj[]/pred[]/obj[]``) kept in sorted orders
  (SPO/POS/OSP) — the XLA-friendly equivalent of the reference's six-permutation
  HashMap index (``shared/src/index_manager.rs``).
- Joins are sort-merge / hash joins over ID columns executed through JAX/XLA
  (``kolibrie_tpu.ops``); filters/aggregates are vectorized VPU ops.
- Fixpoints (semi-naive, provenance) are host-driven loops over jitted bodies.
- Distribution shards triple columns across a ``jax.sharding.Mesh`` with
  all-to-all exchange over ICI (``kolibrie_tpu.parallel``).
"""

from kolibrie_tpu.core.dictionary import Dictionary
from kolibrie_tpu.core.triple import Triple
from kolibrie_tpu.core.terms import Term, TriplePattern
from kolibrie_tpu.core.rule import Rule, FilterCondition

__version__ = "0.1.0"

_LAZY = {
    "SparqlDatabase": ("kolibrie_tpu.query.sparql_database", "SparqlDatabase"),
    "execute_query": ("kolibrie_tpu.query.executor", "execute_query"),
    "execute_query_volcano": ("kolibrie_tpu.query.executor", "execute_query_volcano"),
    "Reasoner": ("kolibrie_tpu.reasoner.reasoner", "Reasoner"),
    "QueryBuilder": ("kolibrie_tpu.query.builder", "QueryBuilder"),
    "QueryEngine": ("kolibrie_tpu.query.engine", "QueryEngine"),
    "RSPBuilder": ("kolibrie_tpu.rsp.builder", "RSPBuilder"),
    "RSPEngine": ("kolibrie_tpu.rsp.engine", "RSPEngine"),
}


def __getattr__(name):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    try:
        mod = importlib.import_module(target[0])
    except ModuleNotFoundError as e:
        raise AttributeError(
            f"{name!r} is not available yet ({target[0]} missing)"
        ) from e
    val = getattr(mod, target[1])
    globals()[name] = val
    return val

__all__ = [
    "Dictionary",
    "Triple",
    "Term",
    "TriplePattern",
    "Rule",
    "FilterCondition",
    "SparqlDatabase",
    "Reasoner",
    "execute_query",
    "execute_query_volcano",
]
