"""Follower-side replication: :class:`ReplicationFollower`.

A follower owns its OWN durability directory, laid out identically to a
primary's (``wal/`` + ``snapshots/``), and keeps it a byte-faithful
mirror: snapshot generations and sealed WAL segments arrive whole,
CRC-verified, and land via the atomic temp-write → rename discipline
(:mod:`durability.fsio`).  That symmetry is the whole failover story —
a promoted follower's data dir IS a valid primary data dir, and a later
crash-recovery on it replays exactly like any other.

Lifecycle:

1. **bootstrap** — clean local debris (``.tmp-gen-*`` leftovers, torn
   tail segments: both are pre-crash junk, never replayed), fetch the
   primary's newest snapshot generation if it is ahead of ours, load it,
   then replay whatever locally-shipped segments continue it.
2. **poll loop** — ask the primary to seal + list new segments, fetch
   each in order, store durably, replay into the live stores under the
   serving layer's per-store dispatch locks.  Duplicated deliveries are
   skipped by the applied-segment watermark (and replay itself is
   idempotent — :func:`durability.manager.replay_records`); torn and
   dropped deliveries surface as :class:`ProtocolError`/timeouts and are
   simply re-requested, which is safe because sealed segments are
   immutable.
3. **promote** — stop polling, discard any local segment past the
   applied watermark (valid bytes that were never applied must not
   resurface as acknowledged state), open a fresh WAL segment, attach
   the stores.  From that point the node journals like any primary.

Staleness is bounded by ``poll_interval_s`` + the primary's seal
interval; the watermark (applied segment + per-store
``(base_version, delta_epoch)``) is exported for ``/healthz``, the
router's promotion decision, and read-your-writes tokens.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Callable, Dict, Optional

from kolibrie_tpu.durability.fsio import atomic_rename_dir, atomic_write_bytes
from kolibrie_tpu.durability.manager import (
    DurabilityManager,
    RecoveryResult,
    replay_records,
)
from kolibrie_tpu.durability.wal import (
    WalWriter,
    list_segments,
    scan_segment_file,
    segment_path,
)
from kolibrie_tpu.obs import log as obslog
from kolibrie_tpu.obs import metrics as obs_metrics
from kolibrie_tpu.obs import spans as obs_spans
from kolibrie_tpu.replication.protocol import (
    ProtocolError,
    ShipClient,
    file_crc,
)

_GEN_PREFIX = "gen-"
_GEN_TMP_PREFIX = ".tmp-gen-"

_SEGS_APPLIED = obs_metrics.counter(
    "kolibrie_repl_segments_applied_total", "shipped segments applied"
)
_RECORDS_APPLIED = obs_metrics.counter(
    "kolibrie_repl_records_applied_total", "WAL records replayed from ship"
)
_POLL_ERRORS = obs_metrics.counter(
    "kolibrie_repl_poll_errors_total",
    "poll-loop failures (timeouts, tears, desyncs) — each one reconnects",
)
_BOOTSTRAPS = obs_metrics.counter(
    "kolibrie_repl_bootstraps_total", "snapshot bootstraps (initial + re-)"
)
_LAG_SEGMENTS = obs_metrics.gauge(
    "kolibrie_repl_lag_segments",
    "sealed segments the follower has not applied yet",
)
_LAG_RECORDS = obs_metrics.gauge(
    "kolibrie_repl_lag_records",
    "primary-appended WAL records not yet applied here "
    "(same-epoch estimate, re-baselined at bootstrap)",
)
_APPLIED_SEGMENT = obs_metrics.gauge(
    "kolibrie_repl_applied_segment", "highest fully-applied segment index"
)
_APPLIED_RECORDS = obs_metrics.gauge(
    "kolibrie_repl_applied_records",
    "WAL records applied since the last bootstrap (watermark component)",
)
_APPLY_SECONDS = obs_metrics.histogram(
    "kolibrie_repl_apply_seconds",
    "per-segment replay (scan-to-applied) wall time",
)

_log = obslog.get_logger("replication.follower")


class ReplicationFollower:
    """Pulls a primary's durability state into ``data_dir`` and keeps
    live stores in sync.

    ``on_store_update(sid, db, created)`` is called (outside any lock)
    whenever a store object appears or is replaced — the serving layer
    registers/replaces its batcher there.  ``lock_for(sid)`` returns the
    lock to hold while records mutate that store (the batcher's dispatch
    lock), or None before the store is being served.
    """

    def __init__(
        self,
        data_dir: str,
        source_host: str,
        source_port: int,
        poll_interval_s: float = 0.15,
        timeout_s: float = 5.0,
        on_store_update: Optional[Callable] = None,
        lock_for: Optional[Callable] = None,
    ):
        self.data_dir = data_dir
        self.source_host = source_host
        self.source_port = source_port
        self.poll_interval_s = poll_interval_s
        self.on_store_update = on_store_update or (lambda sid, db, created: None)
        self.lock_for = lock_for or (lambda sid: None)
        # a never-started manager: supplies paths, generation loading,
        # and (after promotion) the WAL writer + attachments
        self.manager = DurabilityManager(data_dir)
        self.client = ShipClient(source_host, source_port, timeout_s=timeout_s)
        self.res = RecoveryResult()
        self.applied_segment = 0  # guarded by: _lock
        self.applied_records = 0  # guarded by: _lock
        # last seen (active_segment, offset)
        self.primary_pos = (0, 0)  # guarded by: _lock
        # primary's process-lifetime append count, and its value at our
        # last bootstrap: the difference minus our own applies is the
        # lag-in-records SLO estimate (clamped — the counters live in
        # different processes and reset on different events)
        self.primary_records = 0  # guarded by: _lock
        self.records_baseline = 0  # guarded by: _lock
        self.last_applied_unix = 0.0  # guarded by: _lock
        self.bootstrapped = False
        self.promoted = False
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stats_counters = {  # guarded by: _lock (rw)
            "polls": 0,
            "poll_errors": 0,
            "segments_applied": 0,
            "bootstraps": 0,
            "duplicate_segments_skipped": 0,
        }

    # ----------------------------------------------------------- local fs

    def _clean_local_debris(self) -> Dict[str, int]:
        """Remove what a crashed follower leaves behind: ``.tmp-gen-*``
        snapshot debris and torn-tail WAL segments.  Shipped segments
        land atomically, so ANY invalid local segment is pre-crash junk
        — deleted whole and re-fetched, never truncated-and-replayed."""
        removed = {"tmp_gens": 0, "bad_segments": 0}
        snap_dir = self.manager.snap_dir
        for name in os.listdir(snap_dir):
            if name.startswith(_GEN_TMP_PREFIX):
                shutil.rmtree(os.path.join(snap_dir, name), ignore_errors=True)
                removed["tmp_gens"] += 1
        for idx in list_segments(self.manager.wal_dir):
            path = segment_path(self.manager.wal_dir, idx)
            _records, _good, reason = scan_segment_file(path)
            if reason is not None:
                os.unlink(path)
                removed["bad_segments"] += 1
        return removed

    def _fetch_generation(self, gen: int, files) -> None:
        """Ship one snapshot generation into a ``.tmp-gen-*`` staging dir
        and publish it atomically — a crash mid-fetch leaves only debris
        that the next bootstrap cleans."""
        snap_dir = self.manager.snap_dir
        tmp = os.path.join(snap_dir, f"{_GEN_TMP_PREFIX}{gen:08d}")
        final = os.path.join(snap_dir, f"{_GEN_PREFIX}{gen:08d}")
        if os.path.isdir(final):
            return
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        for ent in files:
            name = ent["name"]
            meta, data = self.client.request(
                {"t": "file", "gen": gen, "name": name}
            )
            if meta.get("crc") != file_crc(data):
                raise ProtocolError(f"snapshot file {name} fails ship CRC")
            atomic_write_bytes(os.path.join(tmp, name), data)
        atomic_rename_dir(tmp, final)

    def _store_segment(self, idx: int, data: bytes) -> None:
        atomic_write_bytes(segment_path(self.manager.wal_dir, idx), data)

    # ------------------------------------------------------------ replay

    def _apply_records(self, records) -> None:
        """Replay records into the live result set, serialized against
        the serving layer per store.  Records are grouped into runs per
        store so a bulk segment doesn't take/drop a dispatch lock per
        record."""
        i, n = 0, len(records)
        while i < n:
            meta, _tail = records[i]
            sid = str(meta.get("st")) if meta.get("k") in ("mut", "store") else None
            j = i + 1
            while j < n:
                m2 = records[j][0]
                s2 = str(m2.get("st")) if m2.get("k") in ("mut", "store") else None
                if s2 != sid:
                    break
                j += 1
            run = records[i:j]
            known = sid is not None and sid in self.res.stores
            lock = self.lock_for(sid) if known else None
            if lock is not None:
                with lock:
                    replay_records(self.res, run)
            else:
                replay_records(self.res, run)
            if sid is not None:
                db = self.res.stores.get(sid)
                if db is not None:
                    self.on_store_update(sid, db, created=not known)
            i = j
        with self._lock:
            self.applied_records += len(records)
            total = self.applied_records
        _RECORDS_APPLIED.inc(len(records))
        _APPLIED_RECORDS.set(total)

    def _advance_from_local(self) -> None:
        """Replay locally-present segments that directly continue the
        applied watermark.  Valid-but-non-contiguous files stay on disk
        and apply once the gap fills."""
        while True:
            with self._lock:
                nxt = self.applied_segment + 1
            path = segment_path(self.manager.wal_dir, nxt)
            if not os.path.exists(path):
                return
            t0 = time.perf_counter()
            with obs_spans.span(
                "repl.apply_segment", segment=nxt, node=obslog.node()
            ) as sp:
                records, _good, reason = scan_segment_file(path)
                if reason is not None:
                    os.unlink(path)  # torn local copy: refetch whole
                    return
                if sp is not None:
                    sp.attrs["records"] = len(records)
                self._apply_records(records)
            _APPLY_SECONDS.observe(time.perf_counter() - t0)
            with self._lock:
                self.applied_segment = nxt
                self.last_applied_unix = time.time()
                self.stats_counters["segments_applied"] += 1
            _SEGS_APPLIED.inc()
            _APPLIED_SEGMENT.set(nxt)

    # --------------------------------------------------------- bootstrap

    def bootstrap(self) -> dict:
        """Initial (or re-) bootstrap from the primary's newest valid
        snapshot generation."""
        removed = self._clean_local_debris()
        manifest, _tail = self.client.request({"t": "manifest"})
        gen = int(manifest.get("gen", 0))
        wal_start = int(manifest.get("wal_start", 1))
        if gen > 0:
            self._fetch_generation(gen, manifest.get("files") or [])
            _gen_manifest, stores, sessions = self.manager.load_generation(gen)
            res = RecoveryResult()
            res.stores = stores
            res.sessions = sessions
            for sid, db in stores.items():
                res.modes[sid] = db.execution_mode
            wal_start = int(_gen_manifest.get("wal_start", wal_start))
        else:
            res = RecoveryResult()
        old = set(self.res.stores)
        # kolint: ignore[KL312] bootstrap publishes a fully-built RecoveryResult by one atomic rebind; replay is idempotent and concurrent readers tolerate either generation
        self.res = res
        self.manager.generation = max(self.manager.generation, gen)
        # segments below the generation's replay horizon are dead weight
        for idx in list_segments(self.manager.wal_dir):
            if idx < wal_start:
                os.unlink(segment_path(self.manager.wal_dir, idx))
        with self._lock:
            self.applied_segment = wal_start - 1
            self.applied_records = 0
            pos = manifest.get("pos") or [0, 0]
            self.primary_pos = (int(pos[0]), int(pos[1]))
            self.primary_records = int(manifest.get("records", 0))
            self.records_baseline = self.primary_records
        for sid, db in res.stores.items():
            self.on_store_update(sid, db, created=sid not in old)
        self._advance_from_local()
        with self._lock:
            self.bootstrapped = True
            self.stats_counters["bootstraps"] += 1
        _BOOTSTRAPS.inc()
        _log.info(
            "bootstrap complete",
            generation=gen,
            wal_start=wal_start,
            source=f"{self.source_host}:{self.source_port}",
            **removed,
        )
        return {"generation": gen, "wal_start": wal_start, **removed}

    # --------------------------------------------------------- poll loop

    def _fetch_segment(self, idx: int) -> bool:
        """Fetch + durably store + apply one sealed segment; False when
        the primary pruned it (snapshot passed us — re-bootstrap)."""
        meta, data = self.client.request({"t": "seg", "seg": idx})
        if meta.get("t") == "gone":
            return False
        if meta.get("crc") != file_crc(data):
            raise ProtocolError(f"segment {idx} fails ship CRC")
        self._store_segment(idx, data)
        self._advance_from_local()
        return True

    def poll_once(self) -> None:
        """One poll round: seal + list on the primary, then fetch/apply
        everything past our watermark in order."""
        with self._lock:
            after = self.applied_segment
        meta, _tail = self.client.request({"t": "poll", "after": after})
        pos = meta.get("pos") or [0, 0]
        with self._lock:
            self.primary_pos = (int(pos[0]), int(pos[1]))
            self.primary_records = int(meta.get("records", 0))
            self.stats_counters["polls"] += 1
        for idx in sorted(int(i) for i in meta.get("sealed") or ()):
            with self._lock:
                applied = self.applied_segment
            if idx <= applied:
                # duplicated delivery (injected or raced): watermark says
                # it is already applied — skip, don't re-replay
                with self._lock:
                    self.stats_counters["duplicate_segments_skipped"] += 1
                continue
            if idx != applied + 1 or not self._fetch_segment(idx):
                # gap (pruned by a snapshot) — start over from the
                # primary's current generation
                self.bootstrap()
                break
        _LAG_SEGMENTS.set(self.lag_segments())
        _LAG_RECORDS.set(self.lag_records())

    def _poll_loop(self) -> None:
        backoff = self.poll_interval_s
        while not self._stop.is_set():
            try:
                # each poll round is a root activity on this node: mint a
                # fresh trace so apply spans group per-round in the ring
                with obs_spans.trace_scope(None):
                    with self._lock:
                        booted = self.bootstrapped
                    if not booted:
                        self.bootstrap()
                    self.poll_once()
                backoff = self.poll_interval_s
            except (ProtocolError, OSError):
                with self._lock:
                    self.stats_counters["poll_errors"] += 1
                _POLL_ERRORS.inc()
                self.client.close()
                backoff = min(backoff * 2.0, 2.0)
            self._stop.wait(backoff)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._poll_loop, name="repl-follower", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self.client.close()

    # -------------------------------------------------------- promotion

    def promote(self) -> dict:
        """Become the primary: stop replicating, drop local segments past
        the applied watermark (never acknowledge bytes that were never
        applied), open a fresh WAL segment, attach the stores so new
        writes journal.  Returns the promotion watermark."""
        self.stop()
        with self._lock:
            applied = self.applied_segment
        for idx in list_segments(self.manager.wal_dir):
            if idx > applied:
                os.unlink(segment_path(self.manager.wal_dir, idx))
        self.manager.wal = WalWriter(
            self.manager.wal_dir,
            start_segment=applied + 1,
            fsync_policy=self.manager.fsync_policy,
            segment_bytes=self.manager.segment_bytes,
            group_interval_s=self.manager.group_interval_s,
        )
        for sid, db in self.res.stores.items():
            self.manager.attach(sid, db, log_create=False)
        self.promoted = True
        wm = self.watermark()
        _log.info(
            "promoted to primary",
            applied_segment=wm["applied_segment"],
            applied_records=wm["applied_records"],
        )
        return wm

    # ------------------------------------------------------------- state

    def lag_segments(self) -> int:
        with self._lock:
            active = self.primary_pos[0]
            # the newest sealed segment is active-1; clamp for a fresh
            # primary that has sealed nothing yet
            return max(0, (active - 1) - self.applied_segment)

    def lag_records(self) -> int:
        """Records the primary appended (in this epoch) that we have not
        applied.  An estimate: both counters are process-local, so the
        clamp absorbs restarts and snapshot re-baselines."""
        with self._lock:
            behind = (
                self.primary_records
                - self.records_baseline
                - self.applied_records
            )
            return max(0, behind)

    def refresh_gauges(self) -> None:
        """Pull the watermark/lag state into the SLO gauges — called by
        the exporter at scrape time so ``/metrics`` stays truthful even
        when the poll loop is wedged (exactly when lag matters)."""
        _LAG_SEGMENTS.set(self.lag_segments())
        _LAG_RECORDS.set(self.lag_records())
        with self._lock:
            _APPLIED_SEGMENT.set(self.applied_segment)
            _APPLIED_RECORDS.set(self.applied_records)

    def watermark(self) -> dict:
        with self._lock:
            wm = {
                "applied_segment": self.applied_segment,
                "applied_records": self.applied_records,
                "primary_position": list(self.primary_pos),
                "last_applied_unix": self.last_applied_unix,
            }
        wm["stores"] = {
            sid: list(db.store.version_key())
            for sid, db in self.res.stores.items()
        }
        return wm

    def stats(self) -> dict:
        lag_seg = self.lag_segments()
        lag_rec = self.lag_records()
        with self._lock:
            out = {
                "role": "primary" if self.promoted else "follower",
                "source": f"{self.source_host}:{self.source_port}",
                "bootstrapped": self.bootstrapped,
                "lag_segments": lag_seg,
                "lag_records": lag_rec,
                **self.stats_counters,
            }
        out["watermark"] = self.watermark()
        return out


# Debug-build runtime check of the # guarded by: annotations above
# (no-op unless KOLIBRIE_DEBUG_LOCKS=1 — see analysis/lockcheck.py)
from kolibrie_tpu.analysis import lockcheck as _lockcheck

_lockcheck.auto_instrument(globals())
