"""Template-affinity front router for a replica fleet.

A thin stdlib HTTP proxy that knows three things about the fleet:

- **who is healthy** — a probe thread polls every replica's ``/healthz``
  (role, status, replication watermark); ``evict_after`` consecutive
  failures evicts a replica from routing until a probe succeeds again.
- **where a template lives** — read queries are placed by rendezvous
  (highest-random-weight) hashing over a TEMPLATE key: the query text
  with literals/IRIs/numbers masked.  Two instantiations of the same
  template always land on the same replica, so that replica's plan
  cache, compile cache, and MQO shared-prefix registry stay hot for the
  template while other replicas never pay its warmup (docs/MQO.md,
  docs/COMPILE_CACHE.md).  Rendezvous hashing keeps the map stable under
  eviction: only the evicted replica's templates move.
- **who is primary** — writes forward to the primary; a follower
  answering 409 ``not_primary`` re-aims the request.  When the primary
  stays unprobeable the promotion supervisor picks the follower with the
  HIGHEST DURABLE WATERMARK ``(applied_segment, applied_records)`` and
  POSTs ``/admin/promote`` — highest watermark wins, because a follower
  can only apply whole sealed segments and the acked-write token for any
  acknowledged mutation is covered by some sealed segment.

Retries are deadline-aware: each request carries a budget
(``X-Kolibrie-Deadline-Ms`` or the router default) and failed attempts
back off exponentially but never past the remaining budget.
"""

from __future__ import annotations

import hashlib
import json
import re
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from kolibrie_tpu.obs import log as obslog
from kolibrie_tpu.obs import metrics as obs_metrics
from kolibrie_tpu.obs import promtext
from kolibrie_tpu.obs import spans as obs_spans

DEFAULT_BUDGET_MS = 10_000.0
MAX_BODY_BYTES = 64 * 1024 * 1024
DEFAULT_FLEET_CACHE_TTL_S = 1.0

_log = obslog.get_logger("router")

_ROUTER_REQS = obs_metrics.counter(
    "kolibrie_router_requests_total",
    "requests routed, by route and outcome",
    labels=("route", "outcome"),
)
_ROUTER_RETRIES = obs_metrics.counter(
    "kolibrie_router_retries_total", "upstream attempts beyond the first"
)
_ROUTER_EVICTIONS = obs_metrics.counter(
    "kolibrie_router_evictions_total", "replicas evicted by the prober"
)
_ROUTER_PROMOTIONS = obs_metrics.counter(
    "kolibrie_router_promotions_total", "follower promotions ordered"
)
_ROUTER_UPSTREAM_LAT = obs_metrics.histogram(
    "kolibrie_router_upstream_seconds",
    "upstream request wall time per replica",
    labels=("replica",),
)
_ROUTER_PROBE_FAILURES = obs_metrics.counter(
    "kolibrie_router_probe_failures_total",
    "health probes that failed (connect/parse), per replica",
    labels=("replica",),
)
_ROUTER_UPSTREAM_ERRORS = obs_metrics.counter(
    "kolibrie_router_upstream_errors_total",
    "forward attempts that failed at the transport layer, per replica",
    labels=("replica",),
)
_ROUTER_PROMOTE_FAILURES = obs_metrics.counter(
    "kolibrie_router_promote_failures_total",
    "promotion orders that failed (the supervisor retries next round)",
)
_ROUTER_FAILOVER_SECONDS = obs_metrics.histogram(
    "kolibrie_router_failover_seconds",
    "primary-unroutable to promotion-acknowledged wall time",
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
)
# per-replica health gauges: label cardinality is the configured fleet
# size, bounded at router construction (KL501)
_REPLICA_UP = obs_metrics.gauge(
    "kolibrie_router_replica_up",
    "1 when the replica is healthy and routable, else 0",
    labels=("replica",),
)
_REPLICA_APPLIED_SEGMENT = obs_metrics.gauge(
    "kolibrie_router_replica_applied_segment",
    "replica's applied-segment watermark as last probed",
    labels=("replica",),
)
_REPLICA_FAILURES = obs_metrics.gauge(
    "kolibrie_router_replica_consecutive_failures",
    "consecutive probe failures (evicts at the configured threshold)",
    labels=("replica",),
)

# bounded route-label set (route-clamp pattern — client typos must not
# mint unbounded label values)
_KNOWN_ROUTES = frozenset(
    {
        "/query",
        "/store/load",
        "/store/query",
        "/explain",
        "/rsp-query",
        "/rsp/register",
        "/rsp/push",
        "/rsp/checkpoint",
        "/rsp/restore",
        "/stats",
        "/metrics",
        "/healthz",
        "/admin/promote",
    }
)


def _route_label(path: str) -> str:
    p = path.partition("?")[0]
    return p if p in _KNOWN_ROUTES else "other"

# routes whose POST bodies are reads — affinity-balanced across the
# fleet; every other POST is a mutation and goes to the primary
READ_POST_ROUTES = frozenset(
    {"/store/query", "/query", "/explain", "/debug/explain"}
)

_MASK_RE = re.compile(
    r"""("(?:[^"\\]|\\.)*")|(<[^>\s]*>)|(\b\d+(?:\.\d+)?\b)""",
)


def template_affinity_key(text: str) -> str:
    """A cheap router-side approximation of the engine's template
    fingerprint: quoted literals, IRIs, and numbers mask to placeholders
    so instantiations of one template share a key.  It need not match
    the engine's fingerprint exactly — it only has to be STABLE, so a
    template's traffic keeps hitting the replica whose caches it
    already warmed."""
    masked = _MASK_RE.sub("?", text)
    return hashlib.sha1(" ".join(masked.split()).encode("utf-8")).hexdigest()


def _wm_segment(wm: Optional[dict]) -> int:
    """A node's durable segment position regardless of role: followers
    report ``applied_segment``, primaries report their open WAL position
    under ``durable_wal.segment`` (/healthz shape)."""
    wm = wm or {}
    if wm.get("applied_segment") is not None:
        return int(wm["applied_segment"])
    return int((wm.get("durable_wal") or {}).get("segment") or 0)


class Replica:
    """Probe-maintained view of one backend."""

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url.rstrip("/")
        self.role = "unknown"
        self.status = "unknown"
        self.healthy = False
        self.watermark: dict = {}
        self.consecutive_failures = 0
        self.evicted = False
        self.last_probe_unix = 0.0

    def snapshot(self) -> dict:
        return {
            "url": self.url,
            "role": self.role,
            "status": self.status,
            "healthy": self.healthy,
            "evicted": self.evicted,
            "consecutive_failures": self.consecutive_failures,
            "watermark": self.watermark,
            "last_probe_unix": self.last_probe_unix,
        }


class RouterCore:
    """Fleet state + placement + promotion.  Owns the probe thread; the
    HTTP handler class below is a thin shell over this."""

    def __init__(
        self,
        replicas: List[Tuple[str, str]],
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 2.0,
        evict_after: int = 3,
        promote_after: int = 3,
        promote_cooldown_s: float = 5.0,
        auto_promote: bool = True,
        fleet_cache_ttl_s: float = DEFAULT_FLEET_CACHE_TTL_S,
    ):
        self.replicas: Dict[str, Replica] = {
            name: Replica(name, url) for name, url in replicas
        }
        self.lock = threading.Lock()
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.evict_after = evict_after
        self.promote_after = promote_after
        self.promote_cooldown_s = promote_cooldown_s
        self.auto_promote = auto_promote
        self.fleet_cache_ttl_s = fleet_cache_ttl_s
        self.promotions = 0
        self.last_promotion_unix = 0.0
        self.node_id = "router"  # refined to router:<port> by make_router
        self.last_failover_ms = 0.0
        self._failover_started: Optional[float] = None  # guarded by: lock
        self._fleet_lock = threading.Lock()
        # TTL caches for the fleet aggregation endpoints: (monotonic, data)
        self._fleet_metrics_cache: Tuple[float, str] = (0.0, "")
        self._fleet_status_cache: Tuple[float, Optional[dict]] = (0.0, None)
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- probing

    def probe_once(self) -> None:
        # one trace id per probe round: every probed replica records the
        # same id, so a fleet-state transition reads as one stitched
        # trace across the router and all nodes.  A probe fired from
        # inside a request (the unroutable wait loop) keeps that
        # request's trace instead.
        with obs_spans.trace_scope(obs_spans.current_trace_id()) as tid:
            for rep in list(self.replicas.values()):
                with obs_spans.span(
                    "router.probe", replica=rep.name, node=self.node_id
                ):
                    self._probe_replica(rep, tid)
        if self.auto_promote:
            self._maybe_promote()

    def _probe_replica(self, rep: Replica, trace_id: Optional[str]) -> None:
        req = urllib.request.Request(rep.url + "/healthz")
        if trace_id:
            req.add_header("X-Kolibrie-Trace-Id", trace_id)
        try:
            with urllib.request.urlopen(
                req, timeout=self.probe_timeout_s
            ) as resp:
                body = json.loads(resp.read().decode("utf-8"))
            ok, code = True, resp.status
        except urllib.error.HTTPError as e:
            # 503 recovering still carries a parseable body — the
            # node is ALIVE but not ready; that is not an eviction
            try:
                body = json.loads(e.read().decode("utf-8"))
                ok, code = True, e.code
            except Exception:
                _ROUTER_PROBE_FAILURES.labels(rep.name).inc()
                body, ok, code = {}, False, e.code
        except Exception:
            # connect refused / timeout / reset — the probe's whole
            # job is turning these into liveness state below
            _ROUTER_PROBE_FAILURES.labels(rep.name).inc()
            body, ok, code = {}, False, 0
        with self.lock:
            rep.last_probe_unix = time.time()
            if ok:
                rep.consecutive_failures = 0
                if rep.evicted:
                    rep.evicted = False
                    _log.info("replica restored", replica=rep.name)
                rep.status = str(body.get("status", "unknown"))
                rep.role = str(body.get("role", rep.role))
                repl = body.get("replication") or {}
                rep.watermark = repl.get("watermark") or body.get(
                    "watermark"
                ) or {}
                rep.healthy = code == 200 and rep.status == "ready"
            else:
                rep.consecutive_failures += 1
                rep.healthy = False
                if (
                    not rep.evicted
                    and rep.consecutive_failures >= self.evict_after
                ):
                    rep.evicted = True
                    _ROUTER_EVICTIONS.inc()
                    _log.warn(
                        "replica evicted",
                        replica=rep.name,
                        consecutive_failures=rep.consecutive_failures,
                    )
            _REPLICA_UP.labels(rep.name).set(
                1 if (rep.healthy and not rep.evicted) else 0
            )
            _REPLICA_FAILURES.labels(rep.name).set(rep.consecutive_failures)
            _REPLICA_APPLIED_SEGMENT.labels(rep.name).set(
                _wm_segment(rep.watermark)
            )

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            self.probe_once()

    def start(self) -> None:
        self.probe_once()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="router-probe", daemon=True
        )
        self._probe_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
            self._probe_thread = None

    # ----------------------------------------------------------- placement

    def primary(self) -> Optional[Replica]:
        with self.lock:
            for rep in self.replicas.values():
                if rep.role == "primary" and not rep.evicted:
                    return rep
        return None

    def read_order(self, affinity_key: str) -> List[Replica]:
        """Healthy replicas in rendezvous order for this template key —
        element 0 is the home; the rest are the retry ladder."""
        with self.lock:
            live = [
                r
                for r in self.replicas.values()
                if r.healthy and not r.evicted
            ]
        return sorted(
            live,
            key=lambda r: hashlib.sha1(
                f"{affinity_key}|{r.name}".encode("utf-8")
            ).hexdigest(),
            reverse=True,
        )

    # ----------------------------------------------------------- promotion

    def _maybe_promote(self) -> None:
        with self.lock:
            primaries = [
                r for r in self.replicas.values() if r.role == "primary"
            ]
            dead_primary = primaries and all(
                r.consecutive_failures >= self.promote_after
                for r in primaries
            )
            no_primary = not primaries
            if not (dead_primary or no_primary):
                self._failover_started = None
                return
            # failover clock starts when the primary first becomes
            # unroutable, not when the order is finally sent — the SLO
            # covers the whole unavailability window
            if self._failover_started is None:
                self._failover_started = time.monotonic()
            if (
                time.time() - self.last_promotion_unix
                < self.promote_cooldown_s
            ):
                return
            candidates = [
                r
                for r in self.replicas.values()
                if r.role == "follower" and r.healthy and not r.evicted
            ]
        if not candidates:
            return
        self.promote(candidates)

    def promote(self, candidates: List[Replica]) -> Optional[Replica]:
        """Highest durable watermark wins: the most-caught-up follower
        holds a superset of every other follower's acknowledged state
        (all ship from one primary, whole sealed segments, in order)."""

        def key(r: Replica) -> Tuple[int, int]:
            wm = r.watermark or {}
            return (
                int(wm.get("applied_segment") or 0),
                int(wm.get("applied_records") or 0),
            )

        winner = max(candidates, key=key)
        with obs_spans.trace_scope(obs_spans.current_trace_id()) as tid, \
                obs_spans.span(
                    "router.promote", replica=winner.name, node=self.node_id
                ):
            try:
                req = urllib.request.Request(
                    winner.url + "/admin/promote",
                    data=b"{}",
                    headers={
                        "Content-Type": "application/json",
                        "X-Kolibrie-Trace-Id": tid,
                    },
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=30.0) as resp:
                    json.loads(resp.read().decode("utf-8"))
            except Exception as exc:
                # the candidate died between probe and order: counted, and
                # the supervisor re-runs on the next probe round
                _ROUTER_PROMOTE_FAILURES.inc()
                _log.error(
                    "promotion order failed",
                    replica=winner.name,
                    error=repr(exc),
                )
                return None
        with self.lock:
            for rep in self.replicas.values():
                if rep.role == "primary":
                    rep.role = "unknown"
            winner.role = "primary"
            self.promotions += 1
            self.last_promotion_unix = time.time()
            started = self._failover_started
            self._failover_started = None
        # failover duration: primary-unroutable → promotion acknowledged;
        # a manually-ordered promote (no outage observed) times only the
        # order round-trip and is recorded the same way
        if started is not None:
            elapsed = time.monotonic() - started
            _ROUTER_FAILOVER_SECONDS.observe(elapsed)
            with self.lock:
                self.last_failover_ms = elapsed * 1000.0
        _ROUTER_PROMOTIONS.inc()
        wm = winner.watermark or {}
        with self.lock:
            failover_ms = self.last_failover_ms
        _log.info(
            "follower promoted",
            replica=winner.name,
            applied_segment=wm.get("applied_segment"),
            applied_records=wm.get("applied_records"),
            failover_ms=round(failover_ms, 1),
        )
        return winner

    # ------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self.lock:
            return {
                "replicas": {
                    name: rep.snapshot()
                    for name, rep in self.replicas.items()
                },
                "promotions": self.promotions,
                "last_failover_ms": self.last_failover_ms,
            }

    # -------------------------------------------------- fleet aggregation

    def fleet_metrics(self) -> str:
        """Every healthy replica's ``/metrics`` plus the router's own
        registry, merged with a ``node`` label.  TTL-cached: a scrape
        storm costs one fleet sweep per TTL window."""
        with self._fleet_lock:
            ts, cached = self._fleet_metrics_cache
            if cached and time.monotonic() - ts < self.fleet_cache_ttl_s:
                return cached
        with obs_spans.trace_scope(obs_spans.current_trace_id()) as tid, \
                obs_spans.span("router.fleet_metrics", node=self.node_id):
            with self.lock:
                targets = [
                    (rep.name, rep.url)
                    for rep in self.replicas.values()
                    if rep.healthy and not rep.evicted
                ]
            per_node: Dict[str, str] = {
                self.node_id: promtext.render_prometheus()
            }
            for name, url in targets:
                req = urllib.request.Request(url + "/metrics")
                req.add_header("X-Kolibrie-Trace-Id", tid)
                try:
                    with urllib.request.urlopen(
                        req, timeout=self.probe_timeout_s
                    ) as resp:
                        per_node[name] = resp.read().decode("utf-8")
                except Exception:
                    # a replica dying mid-sweep is the prober's problem;
                    # the merge simply goes on without it
                    _ROUTER_UPSTREAM_ERRORS.labels(name).inc()
            merged = promtext.merge_prometheus(per_node)
        with self._fleet_lock:
            self._fleet_metrics_cache = (time.monotonic(), merged)
        return merged

    def fleet_status(self) -> dict:
        """Per-replica watermark / applied-lag / staleness, rendered
        from the prober's last ``/healthz`` view.  TTL-cached alongside
        :meth:`fleet_metrics`."""
        with self._fleet_lock:
            ts, cached = self._fleet_status_cache
            if cached is not None and (
                time.monotonic() - ts < self.fleet_cache_ttl_s
            ):
                return cached
        now = time.time()
        with self.lock:
            snaps = {
                name: rep.snapshot()
                for name, rep in self.replicas.items()
            }
            promotions = self.promotions
            last_failover_ms = self.last_failover_ms
        applied = [
            _wm_segment(s["watermark"]) for s in snaps.values()
        ]
        head = max(applied) if applied else 0
        nodes = {}
        for name, s in snaps.items():
            wm = s["watermark"] or {}
            seg = _wm_segment(wm)
            last_applied = float(wm.get("last_applied_unix") or 0.0)
            nodes[name] = {
                "url": s["url"],
                "role": s["role"],
                "status": s["status"],
                "healthy": s["healthy"],
                "evicted": s["evicted"],
                "applied_segment": seg,
                "applied_records": int(wm.get("applied_records") or 0),
                # lag vs the most-advanced node the prober can see —
                # the fleet-relative number an operator actually pages on
                "applied_lag_segments": max(0, head - seg),
                "staleness_s": (
                    round(now - last_applied, 3) if last_applied else None
                ),
                "probe_age_s": (
                    round(max(0.0, now - s["last_probe_unix"]), 3)
                    if s["last_probe_unix"]
                    else None
                ),
            }
        out = {
            "head_segment": head,
            "promotions": promotions,
            "last_failover_ms": last_failover_ms,
            "nodes": nodes,
        }
        with self._fleet_lock:
            self._fleet_status_cache = (time.monotonic(), out)
        return out


class RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    core: RouterCore = None  # bound by make_router
    quiet = False

    def log_message(self, fmt, *args):
        if not self.quiet:
            super().log_message(fmt, *args)

    # ------------------------------------------------------------ plumbing

    def _send_json(self, payload: dict, code: int = 200) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _forward_once(
        self, rep: Replica, method: str, path: str, body: Optional[bytes],
        timeout_s: float, attempt: int = 0,
    ) -> Tuple[int, bytes, str]:
        headers = {}
        for h in ("Content-Type", "X-Kolibrie-Deadline-Ms"):
            v = self.headers.get(h)
            if v:
                headers[h] = v
        # trace propagation: forward the client's id when present,
        # otherwise mint here — either way EVERY hop (first try and each
        # retry rung) carries the same id the router's own spans use
        trace_id = (
            self.headers.get("X-Kolibrie-Trace-Id")
            or obs_spans.current_trace_id()
            or obs_spans.new_trace_id()
        )
        headers["X-Kolibrie-Trace-Id"] = trace_id
        req = urllib.request.Request(
            rep.url + path, data=body, headers=headers, method=method
        )
        t0 = time.perf_counter()
        with obs_spans.span(
            "router.forward",
            replica=rep.name,
            path=path.partition("?")[0],
            attempt=attempt,
            node=self.core.node_id,
        ):
            try:
                with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                    data = resp.read()
                    ctype = resp.headers.get(
                        "Content-Type", "application/json"
                    )
                    return resp.status, data, ctype
            except urllib.error.HTTPError as e:
                data = e.read()
                ctype = e.headers.get("Content-Type", "application/json")
                return e.code, data, ctype
            finally:
                _ROUTER_UPSTREAM_LAT.labels(rep.name).observe(
                    time.perf_counter() - t0
                )

    def _budget_s(self) -> float:
        raw = self.headers.get("X-Kolibrie-Deadline-Ms")
        try:
            ms = float(raw) if raw is not None else DEFAULT_BUDGET_MS
        except ValueError:
            ms = DEFAULT_BUDGET_MS
        return ms / 1000.0 if ms > 0 else DEFAULT_BUDGET_MS / 1000.0

    def _route(self, method: str, path: str, body: Optional[bytes]) -> None:
        # the whole routing ladder runs under one trace scope (client-
        # supplied id or minted), so retries, probes fired from the wait
        # loop, and the forwarded request itself all stitch together
        with obs_spans.trace_scope(
            self.headers.get("X-Kolibrie-Trace-Id") or None
        ), obs_spans.span(
            "router.request",
            route=_route_label(path),
            method=method,
            node=self.core.node_id,
        ):
            self._route_traced(method, path, body)

    def _route_traced(
        self, method: str, path: str, body: Optional[bytes]
    ) -> None:
        core = self.core
        route = _route_label(path)
        is_read = method == "GET" or path.partition("?")[0] in READ_POST_ROUTES
        affinity = ""
        if method == "POST" and body and is_read:
            try:
                req = json.loads(body.decode("utf-8"))
                affinity = template_affinity_key(
                    str(req.get("sparql") or req.get("query") or "")
                )
            except (ValueError, AttributeError, TypeError):
                affinity = ""  # unparseable body: no affinity, still routable
        deadline = time.monotonic() + self._budget_s()
        attempt = 0
        last_err = "no live replica"
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                _ROUTER_REQS.labels(route, "deadline").inc()
                self._send_json(
                    {"error": last_err, "code": "deadline_exceeded"}, 504
                )
                return
            if is_read:
                order = core.read_order(affinity or path)
                # writes always belong on the primary; reads fall back to
                # it only through the rendezvous ladder
                target = order[attempt % len(order)] if order else None
            else:
                target = core.primary()
            if target is None:
                # nothing routable yet (startup, failover window): wait a
                # beat for the prober/supervisor to converge
                last_err = "no routable replica"
                core.probe_once()
                time.sleep(min(0.1, max(0.0, remaining)))
                attempt += 1
                if attempt > 200:
                    _ROUTER_REQS.labels(route, "unroutable").inc()
                    self._send_json(
                        {"error": last_err, "code": "unavailable"}, 503
                    )
                    return
                continue
            if attempt > 0:
                _ROUTER_RETRIES.inc()
            try:
                code, data, ctype = self._forward_once(
                    target, method, path, body,
                    timeout_s=max(0.05, min(remaining, 60.0)),
                    attempt=attempt,
                )
            except Exception as exc:  # connect refused / timeout / reset
                _ROUTER_UPSTREAM_ERRORS.labels(target.name).inc()
                last_err = f"{target.name}: {exc}"
                with core.lock:
                    target.consecutive_failures += 1
                    target.healthy = False
                attempt += 1
                backoff = min(0.05 * (2 ** min(attempt, 5)), 0.5)
                time.sleep(min(backoff, max(0.0, remaining)))
                continue
            if code == 409 or (code == 503 and not is_read):
                # not_primary (stale role map) or a primary mid-recovery:
                # re-probe and retry within budget
                last_err = f"{target.name}: upstream {code}"
                core.probe_once()
                attempt += 1
                time.sleep(min(0.05, max(0.0, remaining)))
                continue
            if code == 503 and is_read:
                # follower behind the requested watermark / recovering —
                # try the next rung of the ladder
                last_err = f"{target.name}: upstream 503"
                attempt += 1
                time.sleep(min(0.02, max(0.0, remaining)))
                continue
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.send_header("X-Kolibrie-Replica", target.name)
            # echo the trace id the forward carried so the client can
            # pull the stitched trace from any node's /debug/traces
            trace_id = obs_spans.current_trace_id()
            if trace_id:
                self.send_header("X-Kolibrie-Trace-Id", trace_id)
            self.end_headers()
            self.wfile.write(data)
            _ROUTER_REQS.labels(
                route, "ok" if code < 400 else "error"
            ).inc()
            return

    # -------------------------------------------------------------- verbs

    def do_GET(self):
        path = self.path.partition("?")[0]
        if path == "/router/stats":
            self._send_json(self.core.stats())
            return
        if path == "/router/healthz":
            stats = self.core.stats()
            any_ready = any(
                r["healthy"] for r in stats["replicas"].values()
            )
            self._send_json(stats, 200 if any_ready else 503)
            return
        if path == "/fleet/metrics":
            body = self.core.fleet_metrics().encode("utf-8")
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path == "/fleet/status":
            self._send_json(self.core.fleet_status())
            return
        self._route("GET", self.path, None)

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        if length > MAX_BODY_BYTES:
            self._send_json(
                {"error": "request too large", "code": "request_too_large"},
                413,
            )
            return
        body = self.rfile.read(length)
        self._route("POST", self.path, body)


def make_router(
    replicas: List[Tuple[str, str]],
    host: str = "127.0.0.1",
    port: int = 0,
    quiet: bool = False,
    **core_kwargs,
):
    """Build (httpd, core).  ``replicas`` is ``[(name, base_url), ...]``;
    roles are discovered by probing, not configured."""
    core = RouterCore(replicas, **core_kwargs)
    handler = type(
        "BoundRouterHandler", (RouterHandler,), {"core": core, "quiet": quiet}
    )
    httpd = ThreadingHTTPServer((host, port), handler)
    core.node_id = f"router:{httpd.server_address[1]}"
    core.start()
    return httpd, core


# Debug-build runtime check of the # guarded by: annotations above
# (no-op unless KOLIBRIE_DEBUG_LOCKS=1 — see analysis/lockcheck.py)
from kolibrie_tpu.analysis import lockcheck as _lockcheck

_lockcheck.auto_instrument(globals())
