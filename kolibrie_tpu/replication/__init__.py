"""WAL-shipping replication: a primary seals and streams WAL segments
to read-only followers; a thin router load-balances reads with
template affinity and promotes the most-caught-up follower when the
primary dies (docs/REPLICATION.md).

Layers:

- :mod:`protocol`  — length-prefixed checksummed messages over TCP,
  reusing the WAL frame format (``durability/wal.py``), with sequence
  ids so duplicated deliveries are detectable.
- :mod:`primary`   — ``ShipServer``: serves manifest / snapshot files /
  sealed segments off a live :class:`DurabilityManager`.
- :mod:`follower`  — ``ReplicationFollower``: bootstraps from the newest
  valid snapshot generation, replays shipped segments idempotently,
  tracks the ``(base_version, delta_epoch)`` watermark, and can be
  promoted to primary (fresh WAL segment, attach stores, accept writes).
- :mod:`router`    — ``AffinityRouter``: template-affinity read
  balancing, health probes, deadline-aware retry with backoff,
  dead-replica eviction, and the promotion supervisor.
"""

from kolibrie_tpu.replication.protocol import (  # noqa: F401
    ProtocolError,
    ShipClient,
    recv_msg,
    send_msg,
)
