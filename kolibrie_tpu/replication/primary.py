"""Primary-side segment shipping: :class:`ShipServer`.

A tiny TCP service bound to a live :class:`DurabilityManager`.  It
serves three things, all pull-driven by followers (the primary never
tracks follower state — a dead follower costs nothing):

- ``manifest`` — current snapshot generation + its file list, the sealed
  segment range, and the primary's durable WAL position.
- ``file``     — one snapshot-generation file, whole, CRC-stamped.
- ``seg``      — one SEALED WAL segment, whole, CRC-stamped.  Sealed
  segments are immutable (the writer only ever appends to the newest),
  which is what makes whole-file shipping + retry trivially idempotent.
- ``poll``     — seal the active segment if it holds records (rate
  limited by ``seal_interval_s`` so a chatty follower cannot force
  per-append rotation), then report sealed segments past the follower's
  watermark.

The poll-driven seal is the replication/durability contract in one
place: an acknowledged write sits in the active segment at position
``(seg, off)``; the next poll seals ``seg``; a follower that has applied
``seg`` therefore holds every acknowledged write up to that token —
the read-your-writes check in the HTTP layer is just
``applied_segment >= token.segment``.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Optional

from kolibrie_tpu.durability.wal import list_segments, segment_path
from kolibrie_tpu.obs import metrics as obs_metrics
from kolibrie_tpu.replication.protocol import (
    ProtocolError,
    file_crc,
    recv_msg,
    send_msg,
)

_SEGS_SHIPPED = obs_metrics.counter(
    "kolibrie_repl_segments_shipped_total", "sealed WAL segments shipped"
)
_SHIP_BYTES = obs_metrics.counter(
    "kolibrie_repl_ship_bytes_total", "bytes shipped (segments + snapshots)"
)
_SEALS = obs_metrics.counter(
    "kolibrie_repl_seals_total", "poll-driven seals of the active segment"
)
_POLLS = obs_metrics.counter(
    "kolibrie_repl_polls_total", "follower poll requests served"
)
_SNAP_FILES_SHIPPED = obs_metrics.counter(
    "kolibrie_repl_snapshot_files_shipped_total",
    "snapshot generation files shipped to bootstrapping followers",
)


class ShipServer:
    """Streams the durability directory to followers.  One listener
    thread + one thread per follower connection; all state it serves is
    the manager's on-disk state, so there is nothing to lock against the
    ingest path except the seal rate limiter."""

    def __init__(
        self,
        manager,
        host: str = "127.0.0.1",
        port: int = 0,
        seal_interval_s: float = 0.25,
    ):
        self.manager = manager
        self.seal_interval_s = seal_interval_s
        self._last_seal = 0.0
        self._seal_lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._threads = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="repl-ship-accept", daemon=True
        )
        self._accept_thread.start()

    # ------------------------------------------------------------- serving

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # listener closed
            t = threading.Thread(
                target=self._serve_conn,
                args=(conn,),
                name="repl-ship-conn",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.settimeout(30.0)
        rfile = conn.makefile("rb")
        try:
            while not self._stop.is_set():
                try:
                    got = recv_msg(rfile)
                except (ProtocolError, OSError):
                    return
                if got is None:
                    return
                meta, _tail = got
                try:
                    self._dispatch(conn, meta)
                except (ProtocolError, OSError):
                    return  # injected tear / peer gone: drop the conn
        finally:
            try:
                rfile.close()
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn: socket.socket, meta: dict) -> None:
        t = meta.get("t")
        q = meta.get("q")
        if t == "manifest":
            send_msg(conn, self._manifest_meta(q))
        elif t == "poll":
            _POLLS.inc()
            self._maybe_seal()
            send_msg(conn, self._poll_meta(q, int(meta.get("after", 0))))
        elif t == "file":
            self._send_snap_file(
                conn, q, int(meta.get("gen", 0)), str(meta.get("name", ""))
            )
        elif t == "seg":
            self._send_segment(conn, q, int(meta.get("seg", 0)))
        else:
            send_msg(conn, {"t": "err", "q": q, "reason": f"unknown type {t!r}"})

    # ------------------------------------------------------------- replies

    def _wal_state(self):
        """(sealed_segments, wal_start, position, records) — all from
        disk + the live writer, consistent enough for pull-style
        shipping.  ``records`` is the writer's process-lifetime append
        count: the follower differences it against its own apply count
        for the ``kolibrie_repl_lag_records`` SLO gauge."""
        wal = self.manager.wal
        segs = list_segments(self.manager.wal_dir)
        if wal is not None:
            active, off = wal.position()
            records = wal.appended_records
        else:
            active, off = (segs[-1] + 1) if segs else 1, 0
            records = 0
        sealed = [i for i in segs if i < active]
        wal_start = segs[0] if segs else active
        return sealed, wal_start, (active, off), records

    def _manifest_meta(self, q) -> dict:
        gen = self.manager.generation
        files = []
        if gen > 0:
            root = self.manager.generation_dir(gen)
            for name in sorted(os.listdir(root)):
                path = os.path.join(root, name)
                if os.path.isfile(path):
                    files.append({"name": name, "size": os.path.getsize(path)})
        sealed, wal_start, pos, records = self._wal_state()
        return {
            "t": "manifest",
            "q": q,
            "gen": gen,
            "files": files,
            "sealed": sealed,
            "wal_start": wal_start,
            "pos": list(pos),
            "records": records,
        }

    def _maybe_seal(self) -> None:
        wal = self.manager.wal
        if wal is None:
            return
        with self._seal_lock:
            now = time.monotonic()
            if now - self._last_seal < self.seal_interval_s:
                return
            self._last_seal = now
        if wal.seal_if_dirty() is not None:
            _SEALS.inc()

    def _poll_meta(self, q, after: int) -> dict:
        sealed, wal_start, pos, records = self._wal_state()
        return {
            "t": "poll",
            "q": q,
            "sealed": [i for i in sealed if i > after],
            "wal_start": wal_start,
            "gen": self.manager.generation,
            "pos": list(pos),
            "records": records,
            "now": time.time(),
        }

    def _send_snap_file(self, conn, q, gen: int, name: str) -> None:
        if gen <= 0 or not name or os.path.basename(name) != name:
            send_msg(conn, {"t": "err", "q": q, "reason": "bad file request"})
            return
        path = os.path.join(self.manager.generation_dir(gen), name)
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError as exc:
            send_msg(conn, {"t": "err", "q": q, "reason": repr(exc)})
            return
        _SNAP_FILES_SHIPPED.inc()
        _SHIP_BYTES.inc(len(data))
        send_msg(
            conn,
            {"t": "file", "q": q, "name": name, "crc": file_crc(data)},
            data,
        )

    def _send_segment(self, conn, q, seg: int) -> None:
        sealed, wal_start, _pos, _records = self._wal_state()
        if seg not in sealed:
            # pruned by a snapshot (bootstrap again) or not sealed yet
            send_msg(
                conn, {"t": "gone", "q": q, "seg": seg, "wal_start": wal_start}
            )
            return
        path = segment_path(self.manager.wal_dir, seg)
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError as exc:
            send_msg(conn, {"t": "err", "q": q, "reason": repr(exc)})
            return
        _SEGS_SHIPPED.inc()
        _SHIP_BYTES.inc(len(data))
        send_msg(
            conn,
            {"t": "seg", "q": q, "seg": seg, "crc": file_crc(data)},
            data,
        )

    # -------------------------------------------------------------- admin

    def stats(self) -> dict:
        sealed, wal_start, pos, _records = self._wal_state()
        return {
            "role": "primary",
            "addr": f"{self.host}:{self.port}",
            "sealed_segments": len(sealed),
            "wal_start": wal_start,
            "position": list(pos),
            "seal_interval_s": self.seal_interval_s,
        }

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
