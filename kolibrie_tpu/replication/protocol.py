"""Replication wire protocol: WAL frames over TCP.

A message is exactly one WAL record frame (``durability/wal.py``)::

    u32 payload_len | u32 crc32(payload) | u32 meta_len | meta JSON | tail

so the ship path reuses :func:`~kolibrie_tpu.durability.wal.encode_record`
/ :func:`~kolibrie_tpu.durability.wal.read_frame` verbatim — one frame
format on disk and on the wire, one CRC discipline, one torn-delivery
story.  ``meta`` is the message (``{"t": "...", "q": seq, ...}``); bulk
bytes (snapshot files, whole sealed segments) ride in the tail.

The protocol is strict request/response, but every request carries a
client-chosen sequence id ``q`` which the server echoes.  That makes the
three injected delivery faults (site ``repl.send``) detectable:

- **torn** — the sender transmits a prefix and drops the connection; the
  receiver's ``read_frame`` raises (short read / CRC) and the client
  reconnects and re-requests.
- **dropped** — the frame never leaves the sender; the receiver's socket
  timeout fires and the client reconnects and re-requests.
- **duplicated** — the frame arrives twice; the second copy's stale
  ``q`` identifies it and the receiver discards it (and the replication
  layer additionally skips already-applied segments by watermark, so
  even a re-APPLIED segment is a no-op).
"""

from __future__ import annotations

import socket
import threading
import zlib
from typing import Optional, Tuple

from kolibrie_tpu.durability.wal import encode_record, read_frame
from kolibrie_tpu.obs import metrics as obs_metrics
from kolibrie_tpu.resilience.errors import DurabilityError
from kolibrie_tpu.resilience.faultinject import (
    InjectedShipDrop,
    InjectedShipDuplicate,
    InjectedShipTorn,
    fault_point,
)

#: default per-request socket timeout — a dropped frame must turn into a
#: reconnect quickly enough that replication lag stays bounded
DEFAULT_TIMEOUT_S = 5.0

_SHIP_FAULTS = obs_metrics.counter(
    "kolibrie_repl_ship_faults_total",
    "injected/observed delivery faults at the ship layer",
    labels=("kind",),
)
_DUP_DISCARDS = obs_metrics.counter(
    "kolibrie_repl_duplicate_frames_discarded_total",
    "stale-sequence frames discarded by the ship client",
)


class ProtocolError(DurabilityError):
    """The ship stream desynchronised (torn frame, bad CRC, unexpected
    sequence id).  The remedy is always the same: drop the connection,
    reconnect, re-request — sealed segments are immutable so a retry is
    never wrong."""


def send_msg(sock: socket.socket, meta: dict, tail: bytes = b"") -> None:
    """Send one message; the ``repl.send`` fault site may tear, drop, or
    duplicate the delivery (chaos tests arm it)."""
    frame = encode_record(meta, tail)
    try:
        fault_point("repl.send")
    except InjectedShipTorn:
        _SHIP_FAULTS.labels("torn").inc()
        try:
            sock.sendall(frame[: max(1, len(frame) // 2)])
        finally:
            # the tear IS the connection dying mid-frame
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        raise ProtocolError("injected torn ship delivery")
    except InjectedShipDrop:
        _SHIP_FAULTS.labels("dropped").inc()
        return  # silently never sent; the peer's timeout handles it
    except InjectedShipDuplicate:
        _SHIP_FAULTS.labels("duplicated").inc()
        sock.sendall(frame)
        sock.sendall(frame)
        return
    sock.sendall(frame)


def recv_msg(rfile) -> Optional[Tuple[dict, bytes]]:
    """Read one message from a buffered socket file (``makefile("rb")``).
    Returns ``(meta, tail)`` or None on clean EOF; raises
    :class:`ProtocolError` on a torn/corrupt frame."""
    try:
        return read_frame(rfile)
    except DurabilityError as exc:
        raise ProtocolError(f"ship stream corrupt: {exc}") from exc


def file_crc(data: bytes) -> int:
    """Whole-payload CRC for shipped files/segments — checked end to end
    on top of the per-frame CRC (defence in depth: a duplicated or
    reordered delivery must not splice two valid frames into one bad
    file)."""
    return zlib.crc32(data) & 0xFFFFFFFF


class ShipClient:
    """Request/response client over one persistent connection, with
    sequence-id bookkeeping and reconnect-on-fault.  Thread-safe for one
    caller at a time (the follower's poll loop); a lock guards against
    accidental concurrent use."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout_s: float = DEFAULT_TIMEOUT_S,
    ):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._seq = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------- wiring

    def _connect(self) -> None:
        self.close()
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        )
        sock.settimeout(self.timeout_s)
        self._sock = sock
        self._rfile = sock.makefile("rb")

    def close(self) -> None:
        if self._rfile is not None:
            try:
                self._rfile.close()
            except OSError:
                pass
            self._rfile = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # ------------------------------------------------------------ request

    def request(self, meta: dict, tail: bytes = b"") -> Tuple[dict, bytes]:
        """Send ``meta`` (a fresh ``q`` is stamped in) and return the
        matching response.  Stale-``q`` frames (duplicated deliveries)
        are discarded; timeouts, tears, and desyncs raise
        :class:`ProtocolError` after tearing the connection down so the
        next call reconnects."""
        with self._lock:
            if self._sock is None:
                self._connect()
            self._seq += 1
            q = self._seq
            req = dict(meta)
            req["q"] = q
            try:
                send_msg(self._sock, req, tail)
                while True:
                    got = recv_msg(self._rfile)
                    if got is None:
                        raise ProtocolError("ship connection closed")
                    rmeta, rtail = got
                    rq = rmeta.get("q")
                    if rq == q:
                        if rmeta.get("t") == "err":
                            raise ProtocolError(
                                f"ship server error: {rmeta.get('reason')}"
                            )
                        return rmeta, rtail
                    if isinstance(rq, int) and rq < q:
                        # duplicated delivery of an earlier reply
                        _DUP_DISCARDS.inc()
                        continue
                    raise ProtocolError(
                        f"ship stream desync: expected q={q} got q={rq!r}"
                    )
            except (OSError, ProtocolError):
                self.close()
                raise
            except Exception:
                self.close()
                raise
