"""HTTP frontend: /query, /rsp-query, /rsp/register, /rsp/push, SSE events.

Parity: ``kolibrie-http-server/src/main.rs`` — routes (:593-624), request/
response JSON shapes (:55-158), results table with first-seen header order
(:189-213), persistent RSP sessions in a locked map with a monotone counter
(:32-40, :743-756), SSE result streaming (:306-307, :828-878), 64MB request
cap (:42-44), CORS headers, and the playground served at ``/``.

Rebuild notes: built on stdlib ``ThreadingHTTPServer`` (one thread per
connection, like the reference's thread-per-conn TCP loop); sessions hold an
``RSPEngine`` plus per-subscriber SSE queues.
"""

from __future__ import annotations

import itertools
import json
import os
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from kolibrie_tpu.frontends.rules import (
    apply_n3_logic,
    apply_sparql_rules,
    strip_hash_comments,
)

MAX_REQUEST_BYTES = 64 * 1024 * 1024  # main.rs:42-44
SSE_KEEPALIVE_SECONDS = 15.0

_PLAYGROUND_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "web",
    "playground.html",
)


def results_to_table(results: List[Tuple[Tuple[str, str], ...]]) -> List[List[str]]:
    """Binding rows → [header, row, row...] with first-seen var order
    (main.rs:189-213)."""
    if not results:
        return []
    headers: List[str] = []
    for row in results:
        for key, _ in row:
            if key not in headers:
                headers.append(key)
    table = [list(headers)]
    for row in results:
        m = dict(row)
        table.append([m.get(h, "") for h in headers])
    return table


def _parsed_term_to_str(term) -> str:
    """ParsedTerm → text form an RSP WindowTriple carries (<< >> for quoted)."""
    if isinstance(term, tuple):
        _, s, p, o = term
        return (
            f"<< {_parsed_term_to_str(s)} {_parsed_term_to_str(p)} "
            f"{_parsed_term_to_str(o)} >>"
        )
    return term


def _load_rdf_into(db, data: str, fmt: str) -> int:
    data = data or ""
    if not data.strip():
        return 0
    if fmt in ("ntriples", "turtle"):
        data = strip_hash_comments(data)
    if fmt == "ntriples":
        return db.parse_ntriples(data)
    if fmt == "turtle":
        return db.parse_turtle(data)
    if fmt == "n3":
        return db.parse_n3(data)
    return db.parse_rdf(data)


class EngineSession:
    """One persistent RSP session: engine + result log + SSE subscribers."""

    def __init__(self, engine, streams: List[str]):
        self.engine = engine
        self.streams = streams
        self.results: List[List[List[str]]] = []
        self.subscribers: List["queue.Queue[str]"] = []
        self.lock = threading.Lock()
        # serializes engine mutation: the RSP engine's single-thread drain
        # path is not safe under concurrent /rsp/push handler threads
        self.push_lock = threading.Lock()

    def emit(self, row: Tuple[Tuple[str, str], ...]) -> None:
        table = results_to_table([row])
        payload = json.dumps({"results": table})
        with self.lock:
            self.results.append(table)
            for q in self.subscribers:
                q.put(payload)

    def subscribe_with_backlog(self) -> Tuple["queue.Queue[str]", List[str]]:
        """Atomically add a subscriber and snapshot prior results — a row
        emitted between the two would otherwise be delivered twice."""
        q: "queue.Queue[str]" = queue.Queue()
        with self.lock:
            self.subscribers.append(q)
            backlog = [json.dumps({"results": t}) for t in self.results]
        return q, backlog

    def unsubscribe(self, q) -> None:
        with self.lock:
            if q in self.subscribers:
                self.subscribers.remove(q)


def _pct(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


class _BatchRequest:
    __slots__ = ("text", "done", "result", "error")

    def __init__(self, text: str):
        self.text = text
        self.done = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None


class TemplateBatcher:
    """Serving-side micro-batcher over one persistent store.

    Handler threads call :meth:`submit`; requests that land within the
    batching window ride one dispatch.  Inside a dispatch, identical
    query texts are deduplicated (one execution, shared result) and
    same-template queries are stacked into a single vmap program by
    ``execute_queries_batched`` — under load, N constant-variants of one
    query shape cost one device call, not N.

    The first waiter whose window expires claims ``dispatch_lock`` and
    drains the whole pending list (leader election); followers just wait
    on their request event.  All database access — dispatch, loads,
    stats — serializes on ``dispatch_lock``, so the engine itself never
    sees concurrency."""

    def __init__(self, db, window_ms: float = 5.0):
        self.db = db
        self.window = window_ms / 1000.0
        self.lock = threading.Lock()  # guards pending + counters
        self.dispatch_lock = threading.Lock()  # serializes db access
        self.pending: List[_BatchRequest] = []
        self.requests = 0
        self.dispatches = 0
        self.dedup_hits = 0
        self.max_batch = 0
        # fp -> {"requests", "dedup_hits", "lat": [dispatch ms, ...]}
        self.templates: Dict[str, dict] = {}

    # ------------------------------------------------------------- dispatch

    def submit(self, text: str):
        req = _BatchRequest(text)
        with self.lock:
            self.pending.append(req)
            self.requests += 1
        # collect followers for one window, then elect a dispatcher; loop
        # covers the race where a drain happened between append and wait
        while not req.done.wait(timeout=self.window):
            if self.dispatch_lock.acquire(blocking=False):
                try:
                    with self.lock:
                        batch, self.pending = self.pending, []
                    if batch:
                        self._run_batch(batch)
                finally:
                    self.dispatch_lock.release()
            if req.done.is_set():
                break
        if req.error is not None:
            raise req.error
        return req.result

    def _run_batch(self, batch: List[_BatchRequest]) -> None:
        from kolibrie_tpu.query.executor import (
            execute_queries_batched,
            execute_query_volcano,
        )

        texts = [r.text for r in batch]
        uniq = list(dict.fromkeys(texts))
        start = time.perf_counter()
        try:
            by_text = dict(zip(uniq, execute_queries_batched(self.db, uniq)))
        except Exception:
            # one bad member must not fail its batch-mates: solo retries
            for r in batch:
                try:
                    r.result = execute_query_volcano(r.text, self.db)
                except Exception as e:
                    r.error = e
                r.done.set()
            self._count(batch, texts, uniq, time.perf_counter() - start)
            return
        for r in batch:
            r.result = by_text[r.text]
            r.done.set()
        self._count(batch, texts, uniq, time.perf_counter() - start)

    def _count(self, batch, texts, uniq, elapsed: float) -> None:
        ms = elapsed * 1000.0
        parse_cache = self.db.__dict__.get("_plan_cache", {})
        by_fp: Dict[str, List[str]] = {}
        for text in uniq:
            ent = parse_cache.get(text)
            by_fp.setdefault((ent or {}).get("fp") or "unparsed", []).append(text)
        with self.lock:
            self.dispatches += 1
            self.dedup_hits += len(texts) - len(uniq)
            self.max_batch = max(self.max_batch, len(batch))
            for fp, members in by_fp.items():
                rec = self.templates.setdefault(
                    fp, {"requests": 0, "dedup_hits": 0, "lat": []}
                )
                for text in members:
                    rec["requests"] += texts.count(text)
                    rec["dedup_hits"] += texts.count(text) - 1
                rec["lat"].append(ms)
                del rec["lat"][:-256]  # bounded latency window

    # ---------------------------------------------------------------- stats

    def stats(self) -> dict:
        from kolibrie_tpu.optimizer.device_engine import device_compile_stats
        from kolibrie_tpu.query.executor import plan_cache_info

        with self.lock:
            per = {
                fp: {
                    "requests": rec["requests"],
                    "dedup_hits": rec["dedup_hits"],
                    "dispatches": len(rec["lat"]),
                    "dispatch_ms_p50": _pct(rec["lat"], 0.50),
                    "dispatch_ms_p95": _pct(rec["lat"], 0.95),
                }
                for fp, rec in self.templates.items()
            }
            out = {
                "requests": self.requests,
                "dispatches": self.dispatches,
                "dedup_hits": self.dedup_hits,
                "max_batch": self.max_batch,
                "per_template": per,
            }
        with self.dispatch_lock:
            out["triples"] = len(self.db.store)
            out["plan_cache"] = plan_cache_info(self.db)
        out["device_compiles"] = device_compile_stats()
        return out


class _ServerState:
    def __init__(self):
        self.sessions: Dict[str, EngineSession] = {}
        self.stores: Dict[str, TemplateBatcher] = {}
        self.lock = threading.Lock()
        self.counter = itertools.count(1)


def _build_rsp_engine(
    query: str,
    static_rdf: Optional[str],
    static_format: str,
    n3logic: Optional[str],
    sparql_rules: Optional[List[str]],
    consumer,
):
    """Build an RSPEngine for /rsp-query and /rsp/register (main.rs:648-756)."""
    from kolibrie_tpu.query.sparql_database import SparqlDatabase
    from kolibrie_tpu.rsp.builder import RSPBuilder
    from kolibrie_tpu.rsp.engine import OperationMode

    builder = (
        RSPBuilder(strip_hash_comments(query))
        .set_operation_mode(OperationMode.SINGLE_THREAD)
        .with_consumer(consumer)
    )
    if n3logic and n3logic.strip():
        builder.add_rules(strip_hash_comments(n3logic))
    engine = builder.build()
    if static_rdf and static_rdf.strip():
        if static_format == "turtle":
            engine.static_db.parse_turtle(strip_hash_comments(static_rdf))
        else:
            tmp = SparqlDatabase()
            _load_rdf_into(tmp, static_rdf, static_format)
            engine.static_db.parse_ntriples(tmp.to_ntriples())
    if sparql_rules:
        apply_sparql_rules(engine.static_db, sparql_rules)
    return engine


def _push_event(engine, stream: str, timestamp: int, ntriples: str) -> int:
    """Parse N-Triples and route each triple to the stream's windows."""
    from kolibrie_tpu.query.rdf_parsers import parse_ntriples
    from kolibrie_tpu.rsp.s2r import WindowTriple

    cleaned = strip_hash_comments(ntriples)
    if not cleaned.strip():
        return 0
    triples = parse_ntriples(cleaned)
    for s, p, o in triples:
        engine.add_to_stream(
            stream,
            WindowTriple(
                _parsed_term_to_str(s),
                _parsed_term_to_str(p),
                _parsed_term_to_str(o),
            ),
            timestamp,
        )
    engine.process_single_thread_window_results()
    return len(triples)


class KolibrieHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    state: _ServerState = None  # set by serve()
    quiet = False

    # ------------------------------------------------------------- plumbing

    def log_message(self, fmt, *args):
        if not self.quiet:
            super().log_message(fmt, *args)

    def _send(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("Access-Control-Allow-Origin", "*")
        self.send_header("Access-Control-Allow-Methods", "GET, POST, OPTIONS")
        self.send_header("Access-Control-Allow-Headers", "Content-Type")
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, payload, code: int = 200) -> None:
        self._send(code, json.dumps(payload).encode(), "application/json")

    def _send_error_json(self, message: str, code: int = 400) -> None:
        self._send_json({"error": message}, code)

    def _read_body(self) -> Optional[bytes]:
        length = int(self.headers.get("Content-Length", 0))
        if length > MAX_REQUEST_BYTES:
            self._send_error_json("request too large", 413)
            return None
        return self.rfile.read(length)

    def _read_json(self) -> Optional[dict]:
        body = self._read_body()
        if body is None:
            return None
        try:
            payload = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            self._send_error_json(f"Invalid JSON: {e}")
            return None
        if not isinstance(payload, dict):
            self._send_error_json("Invalid JSON: expected an object")
            return None
        return payload

    # --------------------------------------------------------------- routes

    def do_OPTIONS(self):
        self._send(204, b"", "text/plain")

    def do_GET(self):
        if self.path == "/" or self.path == "/playground":
            try:
                with open(_PLAYGROUND_PATH, "rb") as f:
                    self._send(200, f.read(), "text/html; charset=utf-8")
            except OSError:
                self._send_error_json("playground not available", 404)
            return
        if self.path.startswith("/rsp/events/"):
            self._handle_sse(self.path[len("/rsp/events/"):])
            return
        if self.path == "/stats":
            self._handle_stats()
            return
        self._send_error_json("not found", 404)

    def do_POST(self):
        if self.path == "/query":
            self._handle_query()
        elif self.path == "/store/load":
            self._handle_store_load()
        elif self.path == "/store/query":
            self._handle_store_query()
        elif self.path == "/explain":
            self._handle_explain()
        elif self.path == "/rsp-query":
            self._handle_rsp_query()
        elif self.path == "/rsp/register":
            self._handle_rsp_register()
        elif self.path == "/rsp/push":
            self._handle_rsp_push()
        elif self.path == "/rsp/checkpoint":
            self._handle_rsp_checkpoint()
        elif self.path == "/rsp/restore":
            self._handle_rsp_restore()
        else:
            self._send_error_json("not found", 404)

    # -------------------------------------------------------------- /explain

    def _handle_explain(self):
        """Device physical-plan EXPLAIN: {"sparql": ..., "rdf"?: ...,
        "format"?: ...} → {"plan": tree string} (scan orders, join keys +
        exact counts, or an honest 'host path: <reason>' line)."""
        from kolibrie_tpu.query.engine import QueryEngine
        from kolibrie_tpu.query.sparql_database import SparqlDatabase

        req = self._read_json()
        if req is None:
            return
        if not req.get("sparql"):
            self._send_error_json("No query provided")
            return
        db = SparqlDatabase()
        try:
            _load_rdf_into(db, req.get("rdf") or "", req.get("format", "rdfxml"))
        except Exception as e:
            self._send_error_json(f"RDF parse error: {e}")
            return
        try:
            plan = QueryEngine(db).explain_device(
                strip_hash_comments(req["sparql"])
            )
        except Exception as e:
            self._send_error_json(f"Explain failed: {e}")
            return
        self._send_json({"plan": plan})

    # ---------------------------------------------------------------- /query

    def _handle_query(self):
        from kolibrie_tpu.query.executor import (
            execute_query,
            execute_query_volcano,
        )
        from kolibrie_tpu.query.sparql_database import SparqlDatabase

        req = self._read_json()
        if req is None:
            return
        queries: List[str] = []
        if req.get("sparql"):
            queries.append(req["sparql"])
        queries.extend(req.get("queries") or [])
        if not queries:
            self._send_error_json("No queries provided")
            return
        rules: List[str] = []
        if req.get("rule"):
            rules.append(req["rule"])
        rules.extend(req.get("rules") or [])
        fmt = req.get("format", "rdfxml")

        db = SparqlDatabase()
        try:
            _load_rdf_into(db, req.get("rdf") or "", fmt)
        except Exception as e:
            self._send_error_json(f"RDF parse error: {e}")
            return

        n3logic = req.get("n3logic")
        if n3logic:
            try:
                apply_n3_logic(db, n3logic)
            except Exception as e:
                self._send_error_json(f"N3 rule error: {e}")
                return
        if rules:
            try:
                apply_sparql_rules(db, rules)
            except Exception as e:
                self._send_error_json(f"Rule error: {e}")
                return

        results = []
        # The reference routes only pre-indexed ntriples loads through the
        # Volcano optimizer (main.rs:941); here Volcano IS the default path
        # and {"legacy": true} opts into the sequential agreement path.
        run = execute_query if req.get("legacy") else execute_query_volcano
        for idx, q in enumerate(queries):
            start = time.perf_counter()
            try:
                rows = run(strip_hash_comments(q), db)
            except Exception as e:
                self._send_error_json(f"Query {idx} failed: {e}")
                return
            results.append(
                {
                    "query_index": idx,
                    "query": q,
                    "data": rows,
                    "execution_time_ms": (time.perf_counter() - start) * 1000.0,
                }
            )
        self._send_json({"results": results})

    # ----------------------------------------------------- persistent stores

    def _handle_store_load(self):
        """Create or extend a persistent store: {"store_id"?, "rdf",
        "format"?, "mode"?} → {"store_id", "loaded", "triples"}.  Unlike
        /query (fresh database per request), the store survives across
        requests so repeat queries hit the warm plan-template cache and
        concurrent same-template queries micro-batch."""
        from kolibrie_tpu.query.sparql_database import SparqlDatabase

        req = self._read_json()
        if req is None:
            return
        state = self.state
        sid = str(req.get("store_id") or "")
        with state.lock:
            if not sid:
                sid = f"store-{next(state.counter)}"
            batcher = state.stores.get(sid)
            if batcher is None:
                db = SparqlDatabase()
                db.execution_mode = req.get("mode") or "device"
                batcher = TemplateBatcher(db)
                state.stores[sid] = batcher
        try:
            with batcher.dispatch_lock:
                if req.get("mode"):
                    batcher.db.execution_mode = req["mode"]
                n = _load_rdf_into(
                    batcher.db, req.get("rdf") or "", req.get("format", "ntriples")
                )
        except Exception as e:
            self._send_error_json(f"RDF parse error: {e}")
            return
        self._send_json(
            {"store_id": sid, "loaded": n, "triples": len(batcher.db.store)}
        )

    def _handle_store_query(self):
        """Query a persistent store through the template batcher:
        {"store_id", "sparql"} → {"data", "execution_time_ms"}.  In-flight
        identical queries are answered by one execution; same-template
        variants within the batching window share one device dispatch."""
        req = self._read_json()
        if req is None:
            return
        if not req.get("sparql"):
            self._send_error_json("No query provided")
            return
        state = self.state
        with state.lock:
            batcher = state.stores.get(str(req.get("store_id") or ""))
        if batcher is None:
            self._send_error_json("store not found", 404)
            return
        start = time.perf_counter()
        try:
            rows = batcher.submit(strip_hash_comments(req["sparql"]))
        except Exception as e:
            self._send_error_json(f"Query failed: {e}")
            return
        self._send_json(
            {
                "data": rows,
                "execution_time_ms": (time.perf_counter() - start) * 1000.0,
            }
        )

    def _handle_stats(self):
        """Serving metrics per store: request/dedup/batch counters, per-
        template dispatch latency percentiles, the two-level plan-cache
        snapshot, and jit compile counts."""
        state = self.state
        with state.lock:
            stores = dict(state.stores)
            n_sessions = len(state.sessions)
        self._send_json(
            {
                "stores": {sid: b.stats() for sid, b in stores.items()},
                "rsp_sessions": n_sessions,
            }
        )

    # ------------------------------------------------------------ /rsp-query

    def _handle_rsp_query(self):
        req = self._read_json()
        if req is None:
            return
        if not req.get("query"):
            self._send_error_json("No query provided")
            return
        collected: List = []
        start = time.perf_counter()
        try:
            engine = _build_rsp_engine(
                req["query"],
                req.get("static_rdf"),
                req.get("static_format", "rdfxml"),
                None,
                None,
                collected.append,
            )
        except Exception as e:
            self._send_error_json(f"Failed to build RSP engine: {e}")
            return
        events = [e for e in (req.get("events") or []) if isinstance(e, dict)]
        events.sort(key=lambda e: e.get("timestamp", 0))
        try:
            for ev in events:
                _push_event(
                    engine,
                    ev.get("stream", ""),
                    int(ev.get("timestamp", 0)),
                    ev.get("ntriples", ""),
                )
        except Exception as e:
            self._send_error_json(f"Event error: {e}")
            return
        engine.stop()
        table = results_to_table(collected)
        self._send_json(
            {
                "data": table,
                "total_results": max(0, len(table) - 1),
                "execution_time_ms": (time.perf_counter() - start) * 1000.0,
            }
        )

    # --------------------------------------------------------- /rsp sessions

    def _create_session(self, reg: dict, restore_blob: Optional[bytes] = None):
        """Shared register/restore core: build the engine from its
        CONFIGURATION, optionally restore checkpointed state, register the
        session, and answer with its id.  (docs/PREEMPTION.md: a restore is
        a re-register plus state.)"""
        holder: List[EngineSession] = []

        def consumer(row):
            if holder:
                holder[0].emit(row)

        try:
            engine = _build_rsp_engine(
                reg["query"],
                reg.get("static_rdf"),
                reg.get("static_format") or "rdfxml",
                reg.get("n3logic"),
                reg.get("sparql_rules"),
                consumer,
            )
            if restore_blob is not None:
                engine.restore_state(restore_blob)
        except Exception as e:
            verb = "restore" if restore_blob is not None else "build"
            self._send_error_json(f"Failed to {verb} RSP engine: {e}")
            return
        streams = [cfg.stream_iri for cfg in engine.window_configs]
        session = EngineSession(engine, streams)
        # keep the CONFIGURATION so /rsp/checkpoint blobs are restorable
        session.register_request = {
            k: reg.get(k)
            for k in (
                "query",
                "static_rdf",
                "static_format",
                "n3logic",
                "sparql_rules",
            )
        }
        holder.append(session)
        state = self.state
        with state.lock:
            session_id = str(next(state.counter))
            state.sessions[session_id] = session
        self._send_json({"session_id": session_id, "streams": streams})

    def _handle_rsp_register(self):
        req = self._read_json()
        if req is None:
            return
        if not req.get("query"):
            self._send_error_json("No query provided")
            return
        self._create_session(req)

    def _handle_rsp_checkpoint(self):
        """Snapshot a live session: configuration (the original register
        request) + resumable engine state (base64 pickle blob).  POST the
        SAME payload to /rsp/restore to resume after a restart
        (docs/PREEMPTION.md)."""
        import base64

        req = self._read_json()
        if req is None:
            return
        state = self.state
        with state.lock:
            session = state.sessions.get(str(req.get("session_id")))
        if session is None:
            self._send_error_json("session not found", 404)
            return
        with session.push_lock:
            blob = session.engine.checkpoint_state()
        self._send_json(
            {
                "register": getattr(session, "register_request", {}),
                "state": base64.b64encode(blob).decode("ascii"),
            }
        )

    def _handle_rsp_restore(self):
        """Rebuild a session from a /rsp/checkpoint payload: re-register
        the configuration, then restore the engine state; returns a fresh
        session_id continuing the stream exactly where the snapshot was.
        The state blob is JSON (safe on untrusted input — see
        RSPEngine.checkpoint_state), never pickle."""
        import base64

        req = self._read_json()
        if req is None:
            return
        reg = req.get("register") or {}
        if not reg.get("query"):
            self._send_error_json("No query in register payload")
            return
        try:
            blob = base64.b64decode(req.get("state", ""), validate=True)
        except Exception:
            self._send_error_json("Invalid base64 state")
            return
        self._create_session(reg, restore_blob=blob)

    def _handle_rsp_push(self):
        req = self._read_json()
        if req is None:
            return
        state = self.state
        with state.lock:
            session = state.sessions.get(str(req.get("session_id")))
        if session is None:
            self._send_error_json("session not found", 404)
            return
        try:
            with session.push_lock:
                n = _push_event(
                    session.engine,
                    req.get("stream", ""),
                    int(req.get("timestamp", 0)),
                    req.get("ntriples", ""),
                )
        except Exception as e:
            self._send_error_json(f"Push error: {e}")
            return
        self._send_json({"ok": True, "triples": n})

    def _handle_sse(self, session_id: str):
        state = self.state
        with state.lock:
            session = state.sessions.get(session_id)
        if session is None:
            self._send_error_json("session not found", 404)
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Access-Control-Allow-Origin", "*")
        # SSE is an unbounded stream: no Content-Length, close to terminate.
        self.send_header("Connection", "close")
        self.end_headers()
        q, backlog = session.subscribe_with_backlog()
        try:
            # replay results that arrived before the client connected
            for payload in backlog:
                self.wfile.write(f"data: {payload}\n\n".encode())
            self.wfile.flush()
            while True:
                try:
                    payload = q.get(timeout=SSE_KEEPALIVE_SECONDS)
                    self.wfile.write(f"data: {payload}\n\n".encode())
                except queue.Empty:
                    self.wfile.write(b": keepalive\n\n")
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            session.unsubscribe(q)


def make_server(host: str = "127.0.0.1", port: int = 7878, quiet: bool = False):
    handler = type(
        "BoundHandler", (KolibrieHandler,), {"state": _ServerState(), "quiet": quiet}
    )
    return ThreadingHTTPServer((host, port), handler)


def serve(host: str = "127.0.0.1", port: int = 7878) -> None:
    httpd = make_server(host, port)
    print(f"kolibrie-tpu server listening on http://{host}:{port}")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        httpd.shutdown()


if __name__ == "__main__":
    import sys

    serve(
        sys.argv[1] if len(sys.argv) > 1 else "127.0.0.1",
        int(sys.argv[2]) if len(sys.argv) > 2 else 7878,
    )
