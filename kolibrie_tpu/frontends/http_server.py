"""HTTP frontend: /query, /rsp-query, /rsp/register, /rsp/push, SSE events.

Parity: ``kolibrie-http-server/src/main.rs`` — routes (:593-624), request/
response JSON shapes (:55-158), results table with first-seen header order
(:189-213), persistent RSP sessions in a locked map with a monotone counter
(:32-40, :743-756), SSE result streaming (:306-307, :828-878), 64MB request
cap (:42-44), CORS headers, and the playground served at ``/``.

Rebuild notes: built on stdlib ``ThreadingHTTPServer`` (one thread per
connection, like the reference's thread-per-conn TCP loop); sessions hold an
``RSPEngine`` plus per-subscriber SSE queues.
"""

from __future__ import annotations

import itertools
import json
import os
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from kolibrie_tpu.frontends.rules import (
    apply_n3_logic,
    apply_sparql_rules,
    strip_hash_comments,
)
from kolibrie_tpu.obs import export as obs_export
from kolibrie_tpu.obs import flightrec
from kolibrie_tpu.obs import log as obslog
from kolibrie_tpu.obs import metrics as obs_metrics
from kolibrie_tpu.obs.spans import (
    current_trace_id,
    export_jsonl,
    span,
    trace_scope,
)
from kolibrie_tpu.resilience.admission import AdmissionController
from kolibrie_tpu.resilience.deadline import (
    Deadline,
    check_deadline,
    current_deadline,
    deadline_scope,
)
from kolibrie_tpu.resilience.errors import (
    BadRequest,
    DeadlineExceeded,
    KolibrieError,
    NotFound,
    NotPrimary,
    Overloaded,
    QueryError,
    RequestTooLarge,
    Unavailable,
    WindowCrash,
    error_response,
)

MAX_REQUEST_BYTES = 64 * 1024 * 1024  # main.rs:42-44
SSE_KEEPALIVE_SECONDS = 15.0

# Resilience knobs (docs/RESILIENCE.md).  deadline <= 0 disables deadlines.
DEFAULT_DEADLINE_MS = float(os.environ.get("KOLIBRIE_DEADLINE_MS", "30000"))
MAX_INFLIGHT = int(os.environ.get("KOLIBRIE_MAX_INFLIGHT", "64"))
MAX_QUEUE_DEPTH = int(os.environ.get("KOLIBRIE_MAX_QUEUE_DEPTH", "256"))
SSE_SUBSCRIBER_QUEUE_MAX = int(
    os.environ.get("KOLIBRIE_SSE_QUEUE_MAX", "1024")
)
# Opt-in mesh serving (docs/SHARDING.md): persistent stores attach a
# ShardedDatabase so batched same-template groups run as one shard_map
# dispatch.  Requires a multi-device runtime; silently stays single-device
# otherwise (degraded path).
SHARDED_SERVING = os.environ.get("KOLIBRIE_SHARDED", "").strip().lower() not in (
    "", "0", "off", "false",
)

# ------------------------------------------------------- serving metrics
# (docs/OBSERVABILITY.md has the full catalog)

_HTTP_REQS = obs_metrics.counter(
    "kolibrie_http_requests_total",
    "HTTP responses by route and status code",
    labels=("route", "code"),
)
_HTTP_LAT = obs_metrics.histogram(
    "kolibrie_http_request_seconds",
    "request wall time by route",
    labels=("route",),
)
_BATCH_REQS = obs_metrics.counter(
    "kolibrie_batcher_requests_total", "queries submitted to a batcher"
)
_BATCH_DISPATCHES = obs_metrics.counter(
    "kolibrie_batcher_dispatches_total", "batch dispatches drained"
)
_BATCH_DEDUP = obs_metrics.counter(
    "kolibrie_batcher_dedup_hits_total",
    "in-flight identical-text queries answered by one execution",
)
_BATCH_SHED = obs_metrics.counter(
    "kolibrie_batcher_shed_total",
    "requests shed by the batcher",
    labels=("reason",),
)
_BATCH_SIZE = obs_metrics.histogram(
    "kolibrie_batcher_batch_size",
    "requests riding one dispatch",
    buckets=obs_metrics.DEFAULT_COUNT_BUCKETS,
)
_BATCH_QUEUE_AT_DISPATCH = obs_metrics.histogram(
    "kolibrie_batcher_queue_depth_at_dispatch",
    "pending-queue depth observed at the moment a leader drained it "
    "(distinct from the scrape-time kolibrie_batcher_queue_depth gauge: "
    "this one is sampled exactly when dispatch decisions are made, so "
    "its distribution shows what the MQO sharing layer actually sees)",
    buckets=obs_metrics.DEFAULT_COUNT_BUCKETS,
)
_BATCH_DISTINCT_TEMPLATES = obs_metrics.histogram(
    "kolibrie_batcher_distinct_templates_per_dispatch",
    "distinct template fingerprints riding one dispatch — values >= 2 "
    "are the mixed-template groups eligible for shared-prefix "
    "evaluation (docs/MQO.md)",
    buckets=obs_metrics.DEFAULT_COUNT_BUCKETS,
)
_BATCH_FALLBACKS = obs_metrics.counter(
    "kolibrie_batcher_fallback_total",
    "batched dispatches that failed and fell back to solo retries",
)
_SESSION_CKPT_FAILURES = obs_metrics.counter(
    "kolibrie_session_checkpoint_failures_total",
    "RSP session checkpoint/restore attempts that failed",
    labels=("op",),
)
_DURABILITY_ERRORS = obs_metrics.counter(
    "kolibrie_durability_errors_total",
    "background durability operations that failed (non-fatal: the WAL "
    "still covers the data; watch this climbing)",
    labels=("op",),
)
_BATCH_DISPATCH_LAT = obs_metrics.histogram(
    "kolibrie_batcher_dispatch_seconds",
    "batch dispatch wall time by template fingerprint",
    labels=("template",),
)
_SHARDED_ATTACH_ERRORS = obs_metrics.counter(
    "kolibrie_shard_attach_errors_total",
    "sharded-serving attach/refresh attempts that failed (store keeps "
    "serving single-device — the degraded path)",
)
_READS_SHED_CATCHING_UP = obs_metrics.counter(
    "kolibrie_reads_shed_catching_up_total",
    "reads refused because this follower was behind the client's "
    "read-your-writes watermark (the router retries the next replica) — "
    "a replication-SLO burn counter",
)
_PROMOTE_FINALIZE_SECONDS = obs_metrics.histogram(
    "kolibrie_promote_finalize_seconds",
    "follower-side promotion finalize (stop poll, truncate, reattach, "
    "rebuild sessions) wall time — the node-local share of failover",
)

_log = obslog.get_logger("http_server")

_PLAYGROUND_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "web",
    "playground.html",
)


def results_to_table(results: List[Tuple[Tuple[str, str], ...]]) -> List[List[str]]:
    """Binding rows → [header, row, row...] with first-seen var order
    (main.rs:189-213)."""
    if not results:
        return []
    headers: List[str] = []
    for row in results:
        for key, _ in row:
            if key not in headers:
                headers.append(key)
    table = [list(headers)]
    for row in results:
        m = dict(row)
        table.append([m.get(h, "") for h in headers])
    return table


def _parsed_term_to_str(term) -> str:
    """ParsedTerm → text form an RSP WindowTriple carries (<< >> for quoted)."""
    if isinstance(term, tuple):
        _, s, p, o = term
        return (
            f"<< {_parsed_term_to_str(s)} {_parsed_term_to_str(p)} "
            f"{_parsed_term_to_str(o)} >>"
        )
    return term


def _maybe_attach_sharded(db) -> None:
    """Attach (or refresh) the mesh serving layer for one store when
    KOLIBRIE_SHARDED is on.  Never fails the surrounding request: a
    single-device runtime, or an attach/refresh fault, leaves the store
    serving on the single-device path (that IS the degraded mode)."""
    if not SHARDED_SERVING:
        return
    try:
        from kolibrie_tpu.parallel.sharded_serving import attach_sharded

        sh = attach_sharded(db)
        if sh is not None:
            sh.refresh()
    except Exception:
        _SHARDED_ATTACH_ERRORS.inc()


def _load_rdf_into(db, data: str, fmt: str) -> int:
    data = data or ""
    if not data.strip():
        return 0
    if fmt in ("ntriples", "turtle"):
        data = strip_hash_comments(data)
    if fmt == "ntriples":
        return db.parse_ntriples(data)
    if fmt == "turtle":
        return db.parse_turtle(data)
    if fmt == "n3":
        return db.parse_n3(data)
    return db.parse_rdf(data)


class EngineSession:
    """One persistent RSP session: engine + result log + SSE subscribers."""

    def __init__(self, engine, streams: List[str]):
        self.engine = engine
        self.streams = streams
        self.results: List[List[List[str]]] = []  # guarded by: lock
        self.subscribers: List["queue.Queue[str]"] = []  # guarded by: lock
        self.lock = threading.Lock()
        # serializes engine mutation: the RSP engine's single-thread drain
        # path is not safe under concurrent /rsp/push handler threads
        self.push_lock = threading.Lock()
        self.dropped_subscribers = 0  # guarded by: lock
        self.crash_recoveries = 0  # guarded by: push_lock
        self.last_checkpoint: Optional[bytes] = None  # guarded by: push_lock
        # set by startup recovery: this session was rebuilt from its
        # logged CONFIGURATION + last durable checkpoint after a crash
        self.recovered = False

    def emit(self, row: Tuple[Tuple[str, str], ...]) -> None:
        table = results_to_table([row])
        payload = json.dumps({"results": table})
        with self.lock:
            self.results.append(table)
            dead = []
            for q in self.subscribers:
                try:
                    q.put_nowait(payload)
                except queue.Full:
                    # subscriber stopped draining — a broken pipe whose
                    # handler thread already died, or a stalled client.
                    # Prune it here; un-pruned it would pin its queue (and
                    # every future payload) forever.
                    dead.append(q)
            for q in dead:
                self.subscribers.remove(q)
                self.dropped_subscribers += 1

    def subscribe_with_backlog(self) -> Tuple["queue.Queue[str]", List[str]]:
        """Atomically add a subscriber and snapshot prior results — a row
        emitted between the two would otherwise be delivered twice."""
        q: "queue.Queue[str]" = queue.Queue(maxsize=SSE_SUBSCRIBER_QUEUE_MAX)
        with self.lock:
            self.subscribers.append(q)
            backlog = [json.dumps({"results": t}) for t in self.results]
        return q, backlog

    def unsubscribe(self, q) -> None:
        with self.lock:
            if q in self.subscribers:
                self.subscribers.remove(q)

    # --------------------------------------------------- crash recovery

    def maybe_checkpoint(self) -> None:  # kolint: holds[push_lock]
        """Snapshot engine state after a successful push (caller holds
        ``push_lock``).  Failures are non-fatal: a stale checkpoint only
        widens the at-least-once replay window on the next recovery."""
        try:
            self.last_checkpoint = self.engine.checkpoint_state()
        except Exception:
            # non-fatal, but never silent: an operator watching this
            # counter climb knows recovery will replay a widening window
            _SESSION_CKPT_FAILURES.labels("checkpoint").inc()

    def recover(self) -> bool:  # kolint: holds[push_lock]
        """Restore the engine from the last good checkpoint after a
        WindowCrash (caller holds ``push_lock``).  Returns whether the
        session is serving again."""
        if self.last_checkpoint is None:
            return False
        try:
            self.engine.restore_state(self.last_checkpoint)
        except Exception:
            _SESSION_CKPT_FAILURES.labels("restore").inc()
            return False
        self.crash_recoveries += 1
        return True


class _BatchRequest:
    __slots__ = ("text", "done", "result", "error", "deadline", "trace_id")

    def __init__(
        self,
        text: str,
        deadline: Optional[Deadline] = None,
        trace_id: Optional[str] = None,
    ):
        self.text = text
        self.done = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None
        # captured at submit time: the leader dispatches on ANOTHER
        # thread, where the submitter's thread-local scope is invisible
        self.deadline = deadline
        self.trace_id = trace_id


class TemplateBatcher:
    """Serving-side micro-batcher over one persistent store.

    Handler threads call :meth:`submit`; requests that land within the
    batching window ride one dispatch.  Inside a dispatch, identical
    query texts are deduplicated (one execution, shared result) and
    same-template queries are stacked into a single vmap program by
    ``execute_queries_batched`` — under load, N constant-variants of one
    query shape cost one device call, not N.

    The first waiter whose window expires claims ``dispatch_lock`` and
    drains the whole pending list (leader election); followers just wait
    on their request event.  All database access — dispatch, loads,
    stats — serializes on ``dispatch_lock``, so the engine itself never
    sees concurrency."""

    def __init__(
        self, db, window_ms: float = 5.0, max_queue_depth: int = MAX_QUEUE_DEPTH
    ):
        self.db = db
        self.window = window_ms / 1000.0
        self.max_queue_depth = max_queue_depth
        self.lock = threading.Lock()  # guards pending + counters
        self.dispatch_lock = threading.Lock()  # serializes db access
        self.pending: List[_BatchRequest] = []  # guarded by: lock
        self.requests = 0  # guarded by: lock
        self.dispatches = 0  # guarded by: lock
        self.dedup_hits = 0  # guarded by: lock
        self.max_batch = 0  # guarded by: lock
        self.shed_queue_full = 0  # guarded by: lock
        self.shed_deadline = 0  # guarded by: lock
        # fp -> {"requests", "dedup_hits", "lat": [dispatch ms, ...]}
        self.templates: Dict[str, dict] = {}  # guarded by: lock
        # bounded per-dispatch samples backing the /stats percentiles:
        # queue depth the leader drained, and how many distinct templates
        # rode the dispatch (>= 2 ⇒ MQO shared-prefix candidates)
        self.depth_at_dispatch: List[int] = []  # guarded by: lock
        self.distinct_per_dispatch: List[int] = []  # guarded by: lock

    # ------------------------------------------------------------- dispatch

    def submit(self, text: str):
        check_deadline("batcher.submit")
        req = _BatchRequest(
            text, deadline=current_deadline(), trace_id=current_trace_id()
        )
        with span("batcher.submit"):
            return self._submit(req)

    def _submit(self, req: _BatchRequest):
        with self.lock:
            if len(self.pending) >= self.max_queue_depth:
                # queue depth is the best single predictor of blowing the
                # deadline anyway: shed at the door, structured 429
                self.shed_queue_full += 1
                _BATCH_SHED.labels("queue_full").inc()
                raise Overloaded(
                    f"store queue full ({len(self.pending)} pending)",
                    retry_after_s=max(self.window * 4, 0.05),
                )
            self.pending.append(req)
            self.requests += 1
        _BATCH_REQS.inc()
        # collect followers for one window, then elect a dispatcher; loop
        # covers the race where a drain happened between append and wait
        while not req.done.wait(timeout=self.window):
            if req.deadline is not None and req.deadline.expired():
                # a waiter never blocks past its deadline: drop out even
                # if a leader is mid-dispatch (its result goes unread)
                with self.lock:
                    if req in self.pending:
                        self.pending.remove(req)
                    self.shed_deadline += 1
                _BATCH_SHED.labels("deadline").inc()
                raise DeadlineExceeded(
                    "deadline exceeded at batcher.queue", site="batcher.queue"
                )
            if self.dispatch_lock.acquire(blocking=False):
                try:
                    with self.lock:
                        batch, self.pending = self.pending, []
                    if batch:
                        self._run_batch(batch)
                finally:
                    self.dispatch_lock.release()
            if req.done.is_set():
                break
        if req.error is not None:
            raise req.error
        return req.result

    @staticmethod
    def _batch_deadline(batch: List[_BatchRequest]) -> Optional[Deadline]:
        """The LOOSEST member deadline (None if any member has none): one
        tight straggler must not kill the shared dispatch its batch-mates
        are riding.  The straggler itself sheds in its own wait loop."""
        loosest: Optional[Deadline] = None
        for r in batch:
            if r.deadline is None:
                return None
            if loosest is None or r.deadline.expires_at > loosest.expires_at:
                loosest = r.deadline
        return loosest

    def _run_batch(self, batch: List[_BatchRequest]) -> None:  # kolint: holds[dispatch_lock]
        from kolibrie_tpu.query.executor import (
            execute_queries_batched,
            execute_query_volcano,
        )

        texts = [r.text for r in batch]
        uniq = list(dict.fromkeys(texts))
        start = time.perf_counter()
        # the dispatch span lands in the LEADER's trace (followers' spans
        # would need span links, which this tracer doesn't model); solo
        # retries below re-enter each member's own captured trace
        with span("batcher.dispatch", batch=len(batch), uniq=len(uniq)):
            try:
                with deadline_scope(self._batch_deadline(batch)):
                    by_text = dict(
                        zip(uniq, execute_queries_batched(self.db, uniq))
                    )
            except Exception:
                # one bad member must not fail its batch-mates: solo
                # retries, each under its OWN deadline and trace (None
                # masks the leader's scope)
                _BATCH_FALLBACKS.inc()
                for r in batch:
                    try:
                        with trace_scope(r.trace_id), deadline_scope(
                            r.deadline
                        ), span("batcher.solo_retry"):
                            r.result = execute_query_volcano(r.text, self.db)
                    except Exception as e:
                        r.error = e
                    r.done.set()
                self._count(batch, texts, uniq, time.perf_counter() - start)
                return
        for r in batch:
            r.result = by_text[r.text]
            r.done.set()
        self._count(batch, texts, uniq, time.perf_counter() - start)

    def _count(self, batch, texts, uniq, elapsed: float) -> None:  # kolint: holds[dispatch_lock]
        ms = elapsed * 1000.0
        parse_cache = self.db.__dict__.get("_plan_cache", {})
        by_fp: Dict[str, List[str]] = {}
        for text in uniq:
            ent = parse_cache.get(text)
            by_fp.setdefault((ent or {}).get("fp") or "unparsed", []).append(text)
        with self.lock:
            self.dispatches += 1
            self.dedup_hits += len(texts) - len(uniq)
            self.max_batch = max(self.max_batch, len(batch))
            for fp, members in by_fp.items():
                rec = self.templates.setdefault(
                    fp, {"requests": 0, "dedup_hits": 0, "lat": []}
                )
                for text in members:
                    rec["requests"] += texts.count(text)
                    rec["dedup_hits"] += texts.count(text) - 1
                rec["lat"].append(ms)
                del rec["lat"][:-256]  # bounded latency window
            self.depth_at_dispatch.append(len(batch))
            del self.depth_at_dispatch[:-256]
            self.distinct_per_dispatch.append(len(by_fp))
            del self.distinct_per_dispatch[:-256]
        _BATCH_QUEUE_AT_DISPATCH.observe(len(batch))
        _BATCH_DISTINCT_TEMPLATES.observe(len(by_fp))
        _BATCH_DISPATCHES.inc()
        _BATCH_DEDUP.inc(len(texts) - len(uniq))
        _BATCH_SIZE.observe(len(batch))
        for fp in by_fp:
            _BATCH_DISPATCH_LAT.labels(fp).observe(elapsed)

    # ---------------------------------------------------------------- stats

    def stats(self) -> dict:
        """Single source of truth lives in obs.export (the /stats handler
        renders through the same function)."""
        return obs_export.store_stats(self)


class _ServerState:
    def __init__(
        self, data_dir: Optional[str] = None, role: str = "primary"
    ):
        self.sessions: Dict[str, EngineSession] = {}  # guarded by: lock
        self.stores: Dict[str, TemplateBatcher] = {}  # guarded by: lock
        self.lock = threading.Lock()
        self.counter = itertools.count(1)  # guarded by: lock
        self.admission = AdmissionController(max_inflight=MAX_INFLIGHT)
        # serving phase (guarded by: lock for writes; reads are racy-ok
        # single-word loads): "recovering" -> "ready" -> "draining"
        self.status = "ready"
        self.durability = None
        self.recovery_stats: dict = {}
        self.prewarmer = None  # set by make_server
        # replication role lifecycle: "primary" | "follower"; a follower
        # becomes primary via /admin/promote.  ``replication`` is the
        # ShipServer (primary) or ReplicationFollower (follower), or None
        # when this node is a plain single-process server.
        self.role = role
        self.replication = None
        self.primary_hint = ""  # follower: where writes should go
        self.repl_port: Optional[int] = None  # ship port (this or promoted)
        self.repl_seal_interval_s = 0.25
        self.data_dir = data_dir
        self.flightrec = None  # rolling blackbox recorder (durable nodes)
        self.http_port: Optional[int] = None  # bound port, for identity
        # the persistent compilation cache must be live BEFORE the first
        # lowering this process performs — including recovery's own WAL
        # replay dispatches, which should hit artifacts a previous
        # incarnation (or a fleet peer) compiled
        from kolibrie_tpu.query import compile_cache

        compile_cache.enable(data_dir=data_dir)
        if data_dir and role == "primary":
            from kolibrie_tpu.durability import DurabilityManager

            self.durability = DurabilityManager(data_dir)
            self.status = "recovering"
        elif role == "follower":
            # the follower's OWN DurabilityManager lives inside the
            # ReplicationFollower (it is never started — the follower
            # journals nothing until promotion); the gate stays closed
            # until the first bootstrap completes
            self.status = "recovering"


def _recover_server_state(state: _ServerState) -> None:
    """Startup recovery: latest valid snapshot + WAL replay → rebuild the
    persistent stores and /rsp sessions, then open the gate.  Runs on a
    background thread so the socket binds (and /healthz answers
    ``recovering``) while replay is in flight; mutating routes 503 with
    Retry-After until this flips status to ``ready``."""
    # fresh trace: recovery spans land in one queryable /debug/traces id
    # (thread-locals do not cross the make_server -> worker hop)
    with trace_scope(None):
        _recover_server_state_traced(state)


def _rebuild_sessions(
    state: _ServerState, sessions: Dict[str, dict]
) -> Tuple[Dict[str, str], int]:
    """Rebuild live /rsp sessions from recovered CONFIGURATION + state
    blobs (shared by startup recovery and follower promotion).  Returns
    (per-session failures, highest numeric session id seen)."""
    failures: Dict[str, str] = {}
    max_id = 0
    for sid, rec in sessions.items():
        reg = rec.get("register") or {}
        if not reg.get("query"):
            failures[sid] = "no CONFIGURATION logged (checkpoint only)"
            continue
        try:
            _, session, _ = _build_session(
                state, reg, restore_blob=rec.get("state"), session_id=sid
            )
            session.recovered = True
            session.last_checkpoint = rec.get("state")
        except Exception as e:
            failures[sid] = repr(e)
            continue
        if sid.isdigit():
            max_id = max(max_id, int(sid))
    return failures, max_id


def _recover_server_state_traced(state: _ServerState) -> None:
    import re

    failures: Dict[str, str] = {}
    max_id = 0
    try:
        # recovered stores come back mesh-attached: snapshot restore + WAL
        # replay rebuild the host store, then this hook rebuilds the
        # device-resident sharded mirrors before the store starts serving
        state.durability.on_store_recovered = (
            lambda _sid, db: _maybe_attach_sharded(db)
        )
        result = state.durability.recover()
        batchers: Dict[str, TemplateBatcher] = {}
        for sid, db in result.stores.items():
            # attach BEFORE serving: mutations from here on re-journal
            # (log_create=False — the store's existence is already durable)
            state.durability.attach(sid, db, log_create=False)
            batchers[sid] = TemplateBatcher(db)
            m = re.fullmatch(r"store-(\d+)", sid)
            if m:
                max_id = max(max_id, int(m.group(1)))
        with state.lock:
            state.stores.update(batchers)
        failures, max_sess = _rebuild_sessions(state, result.sessions)
        max_id = max(max_id, max_sess)
        stats = dict(result.stats)
    except Exception as e:
        # recovery must never wedge the server closed: serve empty, but
        # leave a loud trace in /healthz and /stats
        stats = {"error": repr(e)}
        try:
            state.durability.start()
        except Exception:
            _DURABILITY_ERRORS.labels("recovery_start").inc()
    if failures:
        stats["session_failures"] = failures
    with state.lock:
        # resume ids PAST everything recovered: a fresh register must
        # never collide with a recovered session or store id
        state.counter = itertools.count(max_id + 1)
        state.recovery_stats = stats
        state.status = "ready"


def _snapshot_now(state: _ServerState) -> int:
    """Commit a snapshot generation of every store and session.  Stores
    are captured under their dispatch_lock (per-store atomicity is
    sufficient: replay of overlapping WAL records is idempotent —
    see durability/manager.py); session blobs under their push_lock."""
    with state.lock:
        batchers = dict(state.stores)
        sessions = dict(state.sessions)
    sess_payload: Dict[str, dict] = {}
    for sid, session in sessions.items():
        with session.push_lock:
            blob = session.last_checkpoint
            try:
                blob = session.engine.checkpoint_state()
            except Exception:
                # stale blob is safe: recovery just replays a wider window
                _SESSION_CKPT_FAILURES.labels("checkpoint").inc()
        sess_payload[sid] = {
            "register": getattr(session, "register_request", {}) or {},
            "state": blob,
        }
    return state.durability.snapshot(
        {sid: b.db for sid, b in batchers.items()},
        sess_payload,
        locks={sid: b.dispatch_lock for sid, b in batchers.items()},
    )


def _maybe_snapshot(state: _ServerState) -> None:
    """Fold the WAL into a new generation when it has grown past the
    threshold (advisory check — cheap on every mutating request)."""
    if state.durability is None or not state.durability.should_snapshot():
        return
    try:
        _snapshot_now(state)
    except Exception:
        # a failed snapshot never fails the request that tripped it; the
        # WAL keeps growing and the next request retries
        _DURABILITY_ERRORS.labels("snapshot").inc()


def _make_follower(
    state: _ServerState,
    data_dir: str,
    source: str,
    poll_interval_s: float = 0.15,
):
    """Wire a :class:`ReplicationFollower` into the serving state: every
    store the replay surfaces gets a TemplateBatcher (or its db refreshed
    after a re-bootstrap), and replay serializes against the batcher's
    dispatch lock so reads never observe a half-applied segment."""
    from kolibrie_tpu.replication.follower import ReplicationFollower

    host, _, port = source.rpartition(":")

    def _lock_for(sid):
        with state.lock:
            b = state.stores.get(sid)
        return b.dispatch_lock if b is not None else None

    def _on_store_update(sid, db, created):
        with state.lock:
            b = state.stores.get(sid)
            if b is None:
                state.stores[sid] = TemplateBatcher(db)
                b = None
        if b is not None and b.db is not db:
            # re-bootstrap replaced the store object: swap it in under
            # the dispatch lock so in-flight queries finish on the old db
            with b.dispatch_lock:
                b.db = db
        _maybe_attach_sharded(db)

    follower = ReplicationFollower(
        data_dir,
        host or "127.0.0.1",
        int(port),
        poll_interval_s=poll_interval_s,
        on_store_update=_on_store_update,
        lock_for=_lock_for,
    )
    state.replication = follower
    state.primary_hint = source
    return follower


def _build_rsp_engine(
    query: str,
    static_rdf: Optional[str],
    static_format: str,
    n3logic: Optional[str],
    sparql_rules: Optional[List[str]],
    consumer,
):
    """Build an RSPEngine for /rsp-query and /rsp/register (main.rs:648-756)."""
    from kolibrie_tpu.query.sparql_database import SparqlDatabase
    from kolibrie_tpu.rsp.builder import RSPBuilder
    from kolibrie_tpu.rsp.engine import OperationMode

    builder = (
        RSPBuilder(strip_hash_comments(query))
        .set_operation_mode(OperationMode.SINGLE_THREAD)
        .with_consumer(consumer)
    )
    if n3logic and n3logic.strip():
        builder.add_rules(strip_hash_comments(n3logic))
    engine = builder.build()
    if static_rdf and static_rdf.strip():
        if static_format == "turtle":
            engine.static_db.parse_turtle(strip_hash_comments(static_rdf))
        else:
            tmp = SparqlDatabase()
            _load_rdf_into(tmp, static_rdf, static_format)
            engine.static_db.parse_ntriples(tmp.to_ntriples())
    if sparql_rules:
        apply_sparql_rules(engine.static_db, sparql_rules)
    return engine


def _build_session(
    state: _ServerState,
    reg: dict,
    restore_blob: Optional[bytes] = None,
    session_id: Optional[str] = None,
) -> Tuple[str, EngineSession, List[str]]:
    """Session factory shared by the /rsp handlers and startup recovery:
    build the engine from its CONFIGURATION, optionally restore
    checkpointed state, and register the session under ``session_id``
    (recovery preserves ids) or a fresh counter id."""
    holder: List[EngineSession] = []

    def consumer(row):
        if holder:
            holder[0].emit(row)

    engine = _build_rsp_engine(
        reg["query"],
        reg.get("static_rdf"),
        reg.get("static_format") or "rdfxml",
        reg.get("n3logic"),
        reg.get("sparql_rules"),
        consumer,
    )
    if restore_blob is not None:
        engine.restore_state(restore_blob)
    streams = [cfg.stream_iri for cfg in engine.window_configs]
    session = EngineSession(engine, streams)
    # keep the CONFIGURATION so /rsp/checkpoint blobs are restorable
    session.register_request = {
        k: reg.get(k)
        for k in (
            "query",
            "static_rdf",
            "static_format",
            "n3logic",
            "sparql_rules",
        )
    }
    holder.append(session)
    with state.lock:
        if session_id is None:
            session_id = str(next(state.counter))
        state.sessions[session_id] = session
    return session_id, session, streams


def _push_event(engine, stream: str, timestamp: int, ntriples: str) -> int:
    """Parse N-Triples and route each triple to the stream's windows."""
    from kolibrie_tpu.query.rdf_parsers import parse_ntriples
    from kolibrie_tpu.rsp.s2r import WindowTriple

    cleaned = strip_hash_comments(ntriples)
    if not cleaned.strip():
        return 0
    triples = parse_ntriples(cleaned)
    for s, p, o in triples:
        engine.add_to_stream(
            stream,
            WindowTriple(
                _parsed_term_to_str(s),
                _parsed_term_to_str(p),
                _parsed_term_to_str(o),
            ),
            timestamp,
        )
    engine.process_single_thread_window_results()
    return len(triples)


class KolibrieHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    state: _ServerState = None  # set by serve()
    quiet = False
    _trace_id: Optional[str] = None
    _route_label: Optional[str] = None
    _retry_after: Optional[float] = None

    # ------------------------------------------------------------- plumbing

    def log_message(self, fmt, *args):
        if not self.quiet:
            super().log_message(fmt, *args)

    def _send(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("Access-Control-Allow-Origin", "*")
        self.send_header("Access-Control-Allow-Methods", "GET, POST, OPTIONS")
        self.send_header(
            "Access-Control-Allow-Headers", "Content-Type, X-Kolibrie-Trace-Id"
        )
        if self._trace_id:
            self.send_header("X-Kolibrie-Trace-Id", self._trace_id)
        if self._retry_after is not None:
            # RFC 9110 delay-seconds is an integer; round UP so a client
            # honoring it never comes back early
            self.send_header(
                "Retry-After", str(max(1, int(-(-self._retry_after // 1))))
            )
            self._retry_after = None
        self.end_headers()
        self.wfile.write(body)
        if self._route_label is not None:
            _HTTP_REQS.labels(self._route_label, str(code)).inc()

    def _send_json(self, payload, code: int = 200) -> None:
        self._send(code, json.dumps(payload).encode(), "application/json")

    def _send_error_json(self, message: str, code: int = 400) -> None:
        self._send_json({"error": message}, code)

    def _send_failure(self, exc: Exception) -> None:
        """Map an exception through the shared taxonomy to a structured
        JSON response.  BaseExceptions outside Exception (KeyboardInterrupt,
        SystemExit) never reach here — the dispatch wrappers catch only
        ``Exception`` and :func:`error_response` re-raises them anyway."""
        status, payload = error_response(exc, context=self.path)
        if isinstance(payload, dict) and payload.get("retry_after_s"):
            self._retry_after = float(payload["retry_after_s"])
        self._send_json(payload, status)

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        if length > MAX_REQUEST_BYTES:
            raise RequestTooLarge("request too large")
        return self.rfile.read(length)

    def _read_json(self) -> dict:
        body = self._read_body()
        try:
            payload = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise BadRequest(f"Invalid JSON: {e}") from e
        if not isinstance(payload, dict):
            raise BadRequest("Invalid JSON: expected an object")
        return payload

    def _request_deadline(self, req: Optional[dict] = None) -> Optional[Deadline]:
        """The request's deadline budget: ``deadline_ms`` body field, then
        ``X-Kolibrie-Deadline-Ms`` header, then the server default.
        ``<= 0`` disables the deadline for this request."""
        raw = req.get("deadline_ms") if req else None
        if raw is None:
            raw = self.headers.get("X-Kolibrie-Deadline-Ms")
        if raw is None:
            raw = DEFAULT_DEADLINE_MS
        try:
            ms = float(raw)
        except (TypeError, ValueError):
            raise BadRequest(f"invalid deadline_ms: {raw!r}")
        return Deadline.from_ms(ms) if ms > 0 else None

    # --------------------------------------------------------------- routes

    def do_OPTIONS(self):
        self._route_label = "OPTIONS"
        self._send(204, b"", "text/plain")

    def do_GET(self):
        path, _, qs = self.path.partition("?")
        known = ("/", "/playground", "/stats", "/metrics", "/healthz",
                 "/debug/traces", "/debug/timeline")
        if path.startswith("/rsp/events/"):
            self._route_label = "/rsp/events"
        elif path.startswith("/rsp/results/"):
            self._route_label = "/rsp/results"
        else:
            self._route_label = path if path in known else "unknown"
        if path == "/" or path == "/playground":
            try:
                with open(_PLAYGROUND_PATH, "rb") as f:
                    self._send(200, f.read(), "text/html; charset=utf-8")
            except OSError:
                self._send_error_json("playground not available", 404)
            return
        if path.startswith("/rsp/events/"):
            # SSE is long-lived: no trace scope, no request span
            self._handle_sse(path[len("/rsp/events/"):])
            return
        routes = {
            "/stats": lambda: self._handle_stats(),
            "/metrics": lambda: self._handle_metrics(),
            "/healthz": lambda: self._handle_healthz(),
            "/debug/traces": lambda: self._handle_debug_traces(qs),
            "/debug/timeline": lambda: self._handle_debug_timeline(qs),
        }
        if path.startswith("/rsp/results/"):
            sid = path[len("/rsp/results/"):]
            routes[path] = lambda: self._handle_rsp_results(sid)
        with trace_scope(
            self.headers.get("X-Kolibrie-Trace-Id") or None
        ) as tid:
            self._trace_id = tid
            with span(
                "http.request", route=path, method="GET", node=obslog.node()
            ):
                try:
                    handler = routes.get(path)
                    if handler is None:
                        raise NotFound("not found")
                    handler()
                except Exception as e:
                    self._send_failure(e)

    _POST_ROUTES = {
        "/query": "_handle_query",
        "/store/load": "_handle_store_load",
        "/store/query": "_handle_store_query",
        "/explain": "_handle_explain",
        "/rsp-query": "_handle_rsp_query",
        "/rsp/register": "_handle_rsp_register",
        "/rsp/push": "_handle_rsp_push",
        "/rsp/checkpoint": "_handle_rsp_checkpoint",
        "/rsp/restore": "_handle_rsp_restore",
        "/admin/promote": "_handle_admin_promote",
        "/debug/profile": "_handle_debug_profile",
        "/debug/prewarm": "_handle_debug_prewarm",
        "/debug/explain": "_handle_debug_explain",
        "/debug/bundle": "_handle_debug_bundle",
    }

    # routes that must answer regardless of recovering/draining — the
    # flight recorder exists precisely for the moments the gate is shut
    _ALWAYS_OPEN_ROUTES = frozenset({"/debug/bundle"})

    # a follower serves reads at bounded staleness; writes belong on the
    # primary (409 not_primary re-aims the router's role map)
    _MUTATING_ROUTES = frozenset(
        {
            "/store/load",
            "/rsp-query",
            "/rsp/register",
            "/rsp/push",
            "/rsp/checkpoint",
            "/rsp/restore",
        }
    )

    def do_POST(self):
        path = self.path.partition("?")[0]
        name = self._POST_ROUTES.get(path)
        # unknown paths share one label: client typos must not mint
        # unbounded label values
        self._route_label = path if name else "unknown"
        start = time.perf_counter()
        # the client's trace id (or a fresh one) scopes the whole request;
        # _send echoes it back via X-Kolibrie-Trace-Id and error payloads
        # pick it up in errors.py
        with trace_scope(
            self.headers.get("X-Kolibrie-Trace-Id") or None
        ) as tid:
            self._trace_id = tid
            with span(
                "http.request", route=path, method="POST", node=obslog.node()
            ):
                try:
                    if name is None:
                        raise NotFound("not found")
                    # mutating routes wait out recovery (503 + Retry-After)
                    # and are refused outright during drain; observability
                    # GETs (/healthz, /stats, /metrics) stay open throughout
                    phase = self.state.status
                    if (
                        phase != "ready"
                        and path not in self._ALWAYS_OPEN_ROUTES
                    ):
                        raise Unavailable(phase=phase)
                    if (
                        self.state.role != "primary"
                        and path in self._MUTATING_ROUTES
                    ):
                        # follower (or mid-promotion candidate): writes
                        # re-aim at the primary via the router's role map
                        raise NotPrimary(
                            primary_hint=self.state.primary_hint
                        )
                    getattr(self, name)()
                except Exception as e:
                    # single choke point: handlers raise taxonomy errors
                    # (or plain exceptions, conservatively mapped);
                    # KeyboardInterrupt and SystemExit are BaseException
                    # and sail straight through
                    self._send_failure(e)
        _HTTP_LAT.labels(path if name else "unknown").observe(
            time.perf_counter() - start
        )

    # -------------------------------------------------------------- /explain

    def _handle_explain(self):
        """Device physical-plan EXPLAIN: {"sparql": ..., "rdf"?: ...,
        "format"?: ...} → {"plan": tree string} (scan orders, join keys +
        exact counts, or an honest 'host path: <reason>' line)."""
        from kolibrie_tpu.query.engine import QueryEngine
        from kolibrie_tpu.query.sparql_database import SparqlDatabase

        req = self._read_json()
        if not req.get("sparql"):
            raise BadRequest("No query provided")
        db = SparqlDatabase()
        try:
            _load_rdf_into(db, req.get("rdf") or "", req.get("format", "rdfxml"))
        except Exception as e:
            raise BadRequest(f"RDF parse error: {e}") from e
        with deadline_scope(self._request_deadline(req)):
            try:
                plan = QueryEngine(db).explain_device(
                    strip_hash_comments(req["sparql"])
                )
            except KolibrieError:
                raise
            except Exception as e:
                raise QueryError(f"Explain failed: {e}") from e
        self._send_json({"plan": plan})

    # ---------------------------------------------------------------- /query

    def _handle_query(self):
        from kolibrie_tpu.query.executor import (
            execute_query,
            execute_query_volcano,
        )
        from kolibrie_tpu.query.sparql_database import SparqlDatabase

        req = self._read_json()
        queries: List[str] = []
        if req.get("sparql"):
            queries.append(req["sparql"])
        queries.extend(req.get("queries") or [])
        if not queries:
            raise BadRequest("No queries provided")
        rules: List[str] = []
        if req.get("rule"):
            rules.append(req["rule"])
        rules.extend(req.get("rules") or [])
        fmt = req.get("format", "rdfxml")

        deadline = self._request_deadline(req)
        with self.state.admission.admitted_scope(), deadline_scope(deadline):
            db = SparqlDatabase()
            try:
                _load_rdf_into(db, req.get("rdf") or "", fmt)
            except Exception as e:
                raise BadRequest(f"RDF parse error: {e}") from e

            n3logic = req.get("n3logic")
            if n3logic:
                try:
                    apply_n3_logic(db, n3logic)
                except Exception as e:
                    raise BadRequest(f"N3 rule error: {e}") from e
            if rules:
                try:
                    apply_sparql_rules(db, rules)
                except Exception as e:
                    raise BadRequest(f"Rule error: {e}") from e

            results = []
            # The reference routes only pre-indexed ntriples loads through
            # the Volcano optimizer (main.rs:941); here Volcano IS the
            # default path and {"legacy": true} opts into the sequential
            # agreement path.
            run = execute_query if req.get("legacy") else execute_query_volcano
            for idx, q in enumerate(queries):
                start = time.perf_counter()
                try:
                    rows = run(strip_hash_comments(q), db)
                except KolibrieError:
                    raise
                except Exception as e:
                    raise QueryError(f"Query {idx} failed: {e}") from e
                results.append(
                    {
                        "query_index": idx,
                        "query": q,
                        "data": rows,
                        "execution_time_ms": (time.perf_counter() - start)
                        * 1000.0,
                    }
                )
        self._send_json({"results": results})

    # ----------------------------------------------------- persistent stores

    def _handle_store_load(self):
        """Create or extend a persistent store: {"store_id"?, "rdf",
        "format"?, "mode"?} → {"store_id", "loaded", "triples"}.  Unlike
        /query (fresh database per request), the store survives across
        requests so repeat queries hit the warm plan-template cache and
        concurrent same-template queries micro-batch."""
        from kolibrie_tpu.query.sparql_database import SparqlDatabase

        req = self._read_json()
        state = self.state
        sid = str(req.get("store_id") or "")
        with state.lock:
            if not sid:
                sid = f"store-{next(state.counter)}"
            batcher = state.stores.get(sid)
            if batcher is None:
                db = SparqlDatabase()
                db.execution_mode = req.get("mode") or "device"
                batcher = TemplateBatcher(db)
                state.stores[sid] = batcher
                if state.durability is not None:
                    # attach before the first mutation: every add/delete
                    # from here on lands in the WAL as a "mut" record
                    state.durability.attach(sid, db)
        try:
            with batcher.dispatch_lock:
                if req.get("mode"):
                    batcher.db.execution_mode = req["mode"]
                n = _load_rdf_into(
                    batcher.db, req.get("rdf") or "", req.get("format", "ntriples")
                )
                # eager mirror upload while we already hold the lock: the
                # first query after a load pays dispatch, not partitioning
                _maybe_attach_sharded(batcher.db)
        except Exception as e:
            raise BadRequest(f"RDF parse error: {e}") from e
        _maybe_snapshot(state)
        body = {
            "store_id": sid,
            "loaded": n,
            "triples": len(batcher.db.store),
        }
        if state.durability is not None and state.durability.wal is not None:
            # read-your-writes token: a follower that has applied this
            # segment holds this write (segments seal whole — see
            # replication/primary.py)
            seg, off = state.durability.wal.position()
            body["watermark"] = {"segment": seg, "offset": off}
        self._send_json(body)

    def _handle_store_query(self):
        """Query a persistent store through the template batcher:
        {"store_id", "sparql"} → {"data", "execution_time_ms"}.  In-flight
        identical queries are answered by one execution; same-template
        variants within the batching window share one device dispatch.

        ``?explain=analyze`` is the one-off debug variant: the query runs
        SOLO under the dispatch lock with an analyze capture active, and
        the response gains an ``"explain"`` key carrying the raw
        per-operator records (device / interp / sharded)."""
        from urllib.parse import parse_qs

        req = self._read_json()
        if not req.get("sparql"):
            raise BadRequest("No query provided")
        explain = (
            parse_qs(self.path.partition("?")[2]).get("explain") or [""]
        )[0]
        if explain not in ("", "analyze"):
            raise BadRequest(f"unknown explain mode: {explain!r}")
        state = self.state
        with state.lock:
            batcher = state.stores.get(str(req.get("store_id") or ""))
        if batcher is None:
            raise NotFound("store not found")
        self._check_min_watermark(req.get("min_watermark"))
        start = time.perf_counter()
        analysis = None
        with state.admission.admitted_scope(), deadline_scope(
            self._request_deadline(req)
        ):
            try:
                text = strip_hash_comments(req["sparql"])
                if explain == "analyze":
                    # the batch leader may be ANOTHER thread, and the
                    # analyze capture is thread-local — run solo so the
                    # records land here
                    from kolibrie_tpu.obs import analyze as obs_analyze
                    from kolibrie_tpu.query.executor import (
                        execute_queries_batched,
                    )

                    with batcher.dispatch_lock, obs_analyze.capture() as c:
                        rows = execute_queries_batched(batcher.db, [text])[0]
                    analysis = c.records
                else:
                    rows = batcher.submit(text)
            except KolibrieError:
                raise
            except Exception as e:
                raise QueryError(f"Query failed: {e}") from e
        body = {
            "data": rows,
            "execution_time_ms": (time.perf_counter() - start) * 1000.0,
        }
        if analysis is not None:
            body["explain"] = analysis
        self._send_json(body)

    def _handle_stats(self):
        """Serving metrics per store: request/dedup/batch counters, per-
        template dispatch latency percentiles, the two-level plan-cache
        snapshot, and jit compile counts.  Rendered by obs.export — the
        same source of truth as TemplateBatcher.stats()."""
        self._send_json(obs_export.build_stats(self.state))

    def _check_min_watermark(self, min_wm) -> None:
        """Read-your-writes: the client passes back the ``watermark``
        token a write returned; a follower that has not yet applied that
        segment answers 503 ``catching_up`` (+ jittered Retry-After) so
        the router tries the next replica instead of serving stale
        rows.  The primary trivially satisfies its own tokens."""
        if min_wm is None:
            return
        try:
            want = (
                int(min_wm.get("segment", 0))
                if isinstance(min_wm, dict)
                else int(min_wm)
            )
        except (TypeError, ValueError, AttributeError):
            raise BadRequest(f"invalid min_watermark: {min_wm!r}")
        state = self.state
        if state.role != "follower":
            return
        repl = state.replication
        applied = repl.applied_segment if repl is not None else -1
        if applied < want:
            _READS_SHED_CATCHING_UP.inc()
            raise Unavailable(
                "follower behind requested watermark "
                f"(applied={applied} < {want})",
                phase="catching_up",
            )

    def _handle_admin_promote(self):
        """Promote this follower to primary (the router's supervisor, or
        an operator, POSTs here after the old primary dies).  Highest
        durable watermark wins ACROSS candidates — that choice is the
        caller's; this node just finalizes: stop replicating, truncate
        unapplied local segments, open a fresh WAL segment, attach the
        stores, rebuild /rsp sessions, and (if configured) start shipping
        to the next generation of followers."""
        state = self.state
        with state.lock:
            repl = state.replication
            eligible = state.role == "follower" and repl is not None
            if eligible:
                # claim the transition under the lock: concurrent
                # /admin/promote posts must not double-finalize
                state.role = "candidate"
        if not eligible:
            self._send_json(
                {
                    "role": state.role,
                    "promoted": False,
                    "watermark": (
                        repl.watermark() if repl is not None else {}
                    ),
                }
            )
            return
        t0 = time.perf_counter()
        wm = repl.promote()
        state.durability = repl.manager
        failures, max_sess = _rebuild_sessions(state, repl.res.sessions)
        import re

        max_id = max_sess
        with state.lock:
            for sid in state.stores:
                m = re.fullmatch(r"store-(\d+)", sid)
                if m:
                    max_id = max(max_id, int(m.group(1)))
            state.counter = itertools.count(max_id + 1)
            state.role = "primary"
            state.primary_hint = ""
            if failures:
                state.recovery_stats = dict(
                    state.recovery_stats, session_failures=failures
                )
        if state.repl_port is not None:
            from kolibrie_tpu.replication.primary import ShipServer

            state.replication = ShipServer(
                state.durability,
                port=state.repl_port,
                seal_interval_s=state.repl_seal_interval_s,
            )
        else:
            state.replication = None
        elapsed = time.perf_counter() - t0
        _PROMOTE_FINALIZE_SECONDS.observe(elapsed)
        obslog.set_identity("primary", getattr(state, "http_port", None))
        _log.info(
            "promotion finalized",
            finalize_ms=round(elapsed * 1000.0, 1),
            applied_segment=wm.get("applied_segment"),
            applied_records=wm.get("applied_records"),
            session_failures=failures,
        )
        self._send_json(
            {"role": "primary", "promoted": True, "watermark": wm}
        )

    def _handle_healthz(self):
        """Readiness probe: 200 ``ready`` / 503 ``recovering``/``draining``
        (Docker HEALTHCHECK, the router's prober, and the chaos harness
        poll this).  Always carries the role and the store/WAL watermark —
        single-process servers included, so one curl answers 'what have
        you durably got' everywhere."""
        state = self.state
        body = {"status": state.status, "role": state.role}
        with state.lock:
            batchers = dict(state.stores)
        wm: dict = {
            "stores": {
                sid: list(b.db.store.version_key())
                for sid, b in sorted(batchers.items())
            }
        }
        if state.durability is not None:
            body["durability"] = state.durability.stats()
            body["recovery"] = state.recovery_stats
            if state.durability.wal is not None:
                seg, off = state.durability.wal.position()
                wm["durable_wal"] = {"segment": seg, "offset": off}
        body["watermark"] = wm
        if state.replication is not None:
            body["replication"] = state.replication.stats()
        self._send_json(body, 200 if state.status == "ready" else 503)

    def _handle_rsp_results(self, session_id: str):
        """The session's server-side result log (what SSE subscribers got),
        plus its recovery lineage — the chaos harness compares this against
        the oracle after a kill-restart."""
        with self.state.lock:
            session = self.state.sessions.get(session_id)
        if session is None:
            raise NotFound("session not found")
        with session.lock:
            results = list(session.results)
        self._send_json(
            {
                "results": results,
                "recovered": session.recovered,
                "crash_recoveries": session.crash_recoveries,
            }
        )

    def _handle_metrics(self):
        """Prometheus text exposition of the process-wide registry."""
        obs_export.refresh_server_gauges(self.state)
        self._send(
            200,
            obs_export.render_prometheus().encode("utf-8"),
            "text/plain; version=0.0.4; charset=utf-8",
        )

    def _handle_debug_traces(self, qs: str):
        """The span ring as JSONL; ``?trace_id=`` filters to one trace."""
        from urllib.parse import parse_qs

        trace_id = (parse_qs(qs).get("trace_id") or [None])[0]
        body = export_jsonl(trace_id)
        self._send(200, body.encode("utf-8"), "application/x-ndjson")

    def _handle_debug_timeline(self, qs: str):
        """``GET /debug/timeline``: the metrics time-series ring rendered
        as per-metric series — counter deltas, gauge samples, histogram
        count/sum deltas + interpolated quantiles.  ``?metric=`` narrows
        to one family, ``?n=`` to the trailing N samples."""
        from urllib.parse import parse_qs

        from kolibrie_tpu.obs import timeseries

        p = parse_qs(qs)
        metric = (p.get("metric") or [None])[0]
        try:
            n = int((p.get("n") or ["0"])[0]) or None
        except ValueError:
            raise BadRequest("invalid n")
        ring = timeseries.default_ring()
        body = ring.series(metric=metric, n=n)
        body["interval_s"] = timeseries.DEFAULT_INTERVAL_S
        body["capacity"] = ring.capacity
        self._send_json(body)

    def _handle_debug_bundle(self):
        """``POST /debug/bundle``: dump a postmortem bundle on demand —
        the operator's 'grab everything before I poke it' button.  Open
        even while recovering/draining (that is when it matters)."""
        state = self.state
        if state.data_dir is None:
            raise BadRequest("no data_dir: nowhere to write a bundle")
        path = flightrec.dump(
            state.data_dir,
            "manual",
            stats_fn=lambda: obs_export.build_stats(state),
        )
        self._send_json({"ok": True, "path": path})

    def _handle_debug_explain(self):
        """``POST /debug/explain``: EXPLAIN ANALYZE against a registered
        store ({"store_id", "sparql"}) or an inline dataset ({"sparql",
        "rdf"?, "format"?}) — the plan tree with per-operator actuals,
        occupancy and per-stage device time, as rendered by
        :meth:`QueryEngine.explain_device(analyze=True)`."""
        import contextlib

        from kolibrie_tpu.query.engine import QueryEngine
        from kolibrie_tpu.query.sparql_database import SparqlDatabase

        req = self._read_json()
        if not req.get("sparql"):
            raise BadRequest("No query provided")
        store_id = str(req.get("store_id") or "")
        if store_id:
            with self.state.lock:
                batcher = self.state.stores.get(store_id)
            if batcher is None:
                raise NotFound("store not found")
            db, lock = batcher.db, batcher.dispatch_lock
        else:
            db, lock = SparqlDatabase(), contextlib.nullcontext()
            try:
                _load_rdf_into(
                    db, req.get("rdf") or "", req.get("format", "rdfxml")
                )
            except Exception as e:
                raise BadRequest(f"RDF parse error: {e}") from e
        with deadline_scope(self._request_deadline(req)), lock:
            try:
                plan = QueryEngine(db).explain_device(
                    strip_hash_comments(req["sparql"]), analyze=True
                )
            except KolibrieError:
                raise
            except Exception as e:
                raise QueryError(f"Explain failed: {e}") from e
        self._send_json({"plan": plan})

    def _handle_debug_prewarm(self):
        """``POST /debug/prewarm``: one synchronous warm sweep — the
        manifest's top-N templates compiled (or disk-loaded) against
        every registered store, off the normal admission path.  Returns
        per-template compile wall-ms and the executable's source
        (``compiled`` = real XLA compile, ``disk`` = persistent-cache
        hit); operators call this after a deploy to pre-pay the tail."""
        from urllib.parse import parse_qs

        from kolibrie_tpu.query import compile_cache

        warmer = self.state.prewarmer
        if warmer is None:
            raise NotFound("prewarm not configured")
        qs = parse_qs(self.path.partition("?")[2])
        top_n = int((qs.get("top_n") or [0])[0]) or None
        results = warmer.run_once(top_n=top_n)
        self._send_json(
            {
                "warmed": results,
                "manifest": compile_cache.manifest_path(warmer.root),
                "compile_cache": compile_cache.stats(),
            }
        )

    def _handle_debug_profile(self):
        """``POST /debug/profile?seconds=N``: capture a jax.profiler trace
        for N wall seconds.  No-ops (``profiled: false``) on CPU backends
        so CI never pays for — or breaks on — the profiler; set
        ``KOLIBRIE_PROFILE_FORCE=1`` to capture anyway (the CPU trace is
        real and viewable, just not what the gate protects against)."""
        from urllib.parse import parse_qs

        import jax

        qs = parse_qs(self.path.partition("?")[2])
        try:
            seconds = float((qs.get("seconds") or ["1"])[0])
        except ValueError:
            raise BadRequest("invalid seconds")
        if not 0 < seconds <= 30:
            raise BadRequest("seconds must be in (0, 30]")
        backend = jax.default_backend()
        forced = os.environ.get("KOLIBRIE_PROFILE_FORCE", "") == "1"
        if backend not in ("tpu", "gpu") and not forced:
            self._send_json(
                {
                    "profiled": False,
                    "backend": backend,
                    "reason": "profiler capture is gated to accelerator "
                    "backends (CPU CI no-op); KOLIBRIE_PROFILE_FORCE=1 "
                    "overrides",
                }
            )
            return
        import tempfile

        out_dir = os.environ.get("KOLIBRIE_PROFILE_DIR") or tempfile.mkdtemp(
            prefix="kolibrie-profile-"
        )
        jax.profiler.start_trace(out_dir)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        n_files = sum(len(fs) for _, _, fs in os.walk(out_dir))
        self._send_json(
            {"profiled": True, "backend": backend, "forced": forced,
             "trace_dir": out_dir, "trace_files": n_files,
             "seconds": seconds}
        )

    # ------------------------------------------------------------ /rsp-query

    def _handle_rsp_query(self):
        req = self._read_json()
        if not req.get("query"):
            raise BadRequest("No query provided")
        collected: List = []
        start = time.perf_counter()
        try:
            engine = _build_rsp_engine(
                req["query"],
                req.get("static_rdf"),
                req.get("static_format", "rdfxml"),
                None,
                None,
                collected.append,
            )
        except Exception as e:
            raise BadRequest(f"Failed to build RSP engine: {e}") from e
        events = [e for e in (req.get("events") or []) if isinstance(e, dict)]
        events.sort(key=lambda e: e.get("timestamp", 0))
        try:
            for ev in events:
                _push_event(
                    engine,
                    ev.get("stream", ""),
                    int(ev.get("timestamp", 0)),
                    ev.get("ntriples", ""),
                )
        except KolibrieError:
            raise
        except Exception as e:
            raise QueryError(f"Event error: {e}") from e
        engine.stop()
        table = results_to_table(collected)
        self._send_json(
            {
                "data": table,
                "total_results": max(0, len(table) - 1),
                "execution_time_ms": (time.perf_counter() - start) * 1000.0,
            }
        )

    # --------------------------------------------------------- /rsp sessions

    def _create_session(self, reg: dict, restore_blob: Optional[bytes] = None):
        """Shared register/restore core: build the engine from its
        CONFIGURATION, optionally restore checkpointed state, register the
        session, and answer with its id.  (docs/PREEMPTION.md: a restore is
        a re-register plus state.)"""
        state = self.state
        try:
            session_id, session, streams = _build_session(
                state, reg, restore_blob=restore_blob
            )
        except Exception as e:
            verb = "restore" if restore_blob is not None else "build"
            raise BadRequest(f"Failed to {verb} RSP engine: {e}") from e
        if state.durability is not None:
            # CONFIGURATION first, then state: replay order mirrors this
            state.durability.log_session_register(
                session_id, session.register_request
            )
            if restore_blob is not None:
                state.durability.log_session_checkpoint(
                    session_id, restore_blob
                )
        self._send_json({"session_id": session_id, "streams": streams})

    def _handle_rsp_register(self):
        req = self._read_json()
        if not req.get("query"):
            raise BadRequest("No query provided")
        self._create_session(req)

    def _handle_rsp_checkpoint(self):
        """Snapshot a live session: configuration (the original register
        request) + resumable engine state (base64 pickle blob).  POST the
        SAME payload to /rsp/restore to resume after a restart
        (docs/PREEMPTION.md)."""
        import base64

        req = self._read_json()
        state = self.state
        with state.lock:
            session = state.sessions.get(str(req.get("session_id")))
        if session is None:
            raise NotFound("session not found")
        with session.push_lock:
            blob = session.engine.checkpoint_state()
        self._send_json(
            {
                "register": getattr(session, "register_request", {}),
                "state": base64.b64encode(blob).decode("ascii"),
            }
        )

    def _handle_rsp_restore(self):
        """Rebuild a session from a /rsp/checkpoint payload: re-register
        the configuration, then restore the engine state; returns a fresh
        session_id continuing the stream exactly where the snapshot was.
        The state blob is JSON (safe on untrusted input — see
        RSPEngine.checkpoint_state), never pickle."""
        import base64

        req = self._read_json()
        reg = req.get("register") or {}
        if not reg.get("query"):
            raise BadRequest("No query in register payload")
        try:
            blob = base64.b64decode(req.get("state", ""), validate=True)
        except Exception as e:
            raise BadRequest("Invalid base64 state") from e
        self._create_session(reg, restore_blob=blob)

    def _handle_rsp_push(self):
        req = self._read_json()
        state = self.state
        sid = str(req.get("session_id"))
        with state.lock:
            session = state.sessions.get(sid)
        if session is None:
            raise NotFound("session not found")
        with session.push_lock, deadline_scope(self._request_deadline(req)):
            try:
                prev_blob = session.last_checkpoint
                n = _push_event(
                    session.engine,
                    req.get("stream", ""),
                    int(req.get("timestamp", 0)),
                    req.get("ntriples", ""),
                )
                # checkpoint AFTER the event is fully processed: a crash
                # on a later push rolls back to this consistent state and
                # the client replays from here (at-least-once)
                session.maybe_checkpoint()
                if (
                    state.durability is not None
                    and session.last_checkpoint is not None
                    and session.last_checkpoint is not prev_blob
                ):
                    # the durable mirror of maybe_checkpoint: a kill -9
                    # resumes this session from exactly this blob
                    state.durability.log_session_checkpoint(
                        sid, session.last_checkpoint
                    )
            except WindowCrash as e:
                recovered = session.recover()
                payload = e.payload(context=self.path)
                payload["recovered"] = recovered
                payload["crash_recoveries"] = session.crash_recoveries
                self._send_json(payload, e.http_status)
                return
            except KolibrieError:
                raise
            except Exception as e:
                raise QueryError(f"Push error: {e}") from e
        _maybe_snapshot(state)
        self._send_json({"ok": True, "triples": n, "recovered": session.recovered})

    def _handle_sse(self, session_id: str):
        state = self.state
        if state.status != "ready":
            # a subscriber arriving mid-recovery would race session
            # rebuild — 503 with Retry-After like the mutating routes
            status, payload = error_response(
                Unavailable(phase=state.status), context=self.path
            )
            if payload.get("retry_after_s"):
                self._retry_after = float(payload["retry_after_s"])
            self._send_json(payload, status)
            return
        with state.lock:
            session = state.sessions.get(session_id)
        if session is None:
            self._send_error_json("session not found", 404)
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Access-Control-Allow-Origin", "*")
        # SSE is an unbounded stream: no Content-Length, close to terminate.
        self.send_header("Connection", "close")
        self.end_headers()
        q, backlog = session.subscribe_with_backlog()
        try:
            # replay results that arrived before the client connected
            for payload in backlog:
                self.wfile.write(f"data: {payload}\n\n".encode())
            self.wfile.flush()
            while True:
                try:
                    payload = q.get(timeout=SSE_KEEPALIVE_SECONDS)
                    self.wfile.write(f"data: {payload}\n\n".encode())
                except queue.Empty:
                    self.wfile.write(b": keepalive\n\n")
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError, ValueError):
            # ValueError covers "I/O operation on closed file", which is
            # not an OSError subclass.  A subscriber that dies WITHOUT
            # reaching this finally (killed daemon thread) is pruned by
            # EngineSession.emit when its bounded queue fills.
            pass
        finally:
            session.unsubscribe(q)


_TIMELINE_SAMPLER = None  # guarded by: _TIMELINE_LOCK
_TIMELINE_LOCK = threading.Lock()


def make_server(
    host: str = "127.0.0.1",
    port: int = 7878,
    quiet: bool = False,
    data_dir: Optional[str] = None,
    recover_async: bool = True,
    repl_port: Optional[int] = None,
    repl_source: Optional[str] = None,
    repl_poll_interval_s: float = 0.15,
    repl_seal_interval_s: float = 0.25,
):
    """Build the HTTP server.  With ``data_dir`` the server is durable:
    every store mutation batch and session checkpoint rides the WAL, and
    boot runs crash recovery (latest valid snapshot + WAL replay) before
    the gate opens — on a background thread by default so the socket
    binds immediately and serves 503 + Retry-After while replaying.

    Replication (docs/REPLICATION.md): ``repl_port`` starts a WAL-segment
    ship server on a durable primary (followers pull from it);
    ``repl_source`` ("host:port" of a primary's ship server) boots this
    node as a read-only follower of that primary instead — ``data_dir``
    is then the follower's own mirror directory."""
    role = "follower" if repl_source else "primary"
    state = _ServerState(data_dir=data_dir, role=role)
    state.repl_port = repl_port
    state.repl_seal_interval_s = repl_seal_interval_s
    handler = type(
        "BoundHandler", (KolibrieHandler,), {"state": state, "quiet": quiet}
    )
    httpd = ThreadingHTTPServer((host, port), handler)
    state.http_port = httpd.server_address[1]
    # node identity (role:port) stamps every span and log record so a
    # cross-process trace names which node each hop ran on
    obslog.set_identity(role, state.http_port)

    def _targets():
        with state.lock:
            batchers = dict(state.stores)
        return [
            (sid, b.db, b.dispatch_lock) for sid, b in sorted(batchers.items())
        ]

    from kolibrie_tpu.query.prewarm import PrewarmManager

    state.prewarmer = PrewarmManager(
        get_targets=_targets,
        is_idle=lambda: state.admission.inflight == 0,
        is_ready=lambda: state.status == "ready",
    )
    state.prewarmer.start()
    # /debug/timeline's data source: sample the metrics registry into the
    # default ring for the life of the process (daemon thread, started
    # once — test suites build many servers and must not stack samplers)
    from kolibrie_tpu.obs import timeseries

    global _TIMELINE_SAMPLER
    with _TIMELINE_LOCK:
        if _TIMELINE_SAMPLER is None:
            _TIMELINE_SAMPLER = timeseries.Sampler(timeseries.default_ring())
            _TIMELINE_SAMPLER.start()
    # rolling blackbox: durable nodes keep a recent postmortem bundle on
    # disk at all times, so even kill -9 leaves evidence (the SIGTERM and
    # fatal-error paths write a final, uniquely-named bundle on top)
    if data_dir and os.environ.get("KOLIBRIE_FLIGHTREC_DISABLED") != "1":
        state.flightrec = flightrec.FlightRecorder(
            data_dir,
            interval_s=float(
                os.environ.get("KOLIBRIE_FLIGHTREC_INTERVAL_S", "5.0")
            ),
            stats_fn=lambda: obs_export.build_stats(state),
        )
        state.flightrec.start()
    if state.durability is not None:
        if recover_async:
            threading.Thread(
                target=_recover_server_state,
                args=(state,),
                daemon=True,
                name="kolibrie-recovery",
            ).start()
        else:
            _recover_server_state(state)
        if repl_port is not None:
            # the ship server serves on-disk state only, so it can start
            # before recovery finishes — followers just see the segments
            # and generation the recovering primary already has
            from kolibrie_tpu.replication.primary import ShipServer

            state.replication = ShipServer(
                state.durability,
                port=repl_port,
                seal_interval_s=repl_seal_interval_s,
            )
    elif role == "follower":
        if not data_dir:
            raise ValueError("a follower needs data_dir (its mirror)")
        follower = _make_follower(
            state, data_dir, repl_source,
            poll_interval_s=repl_poll_interval_s,
        )

        def _follower_gate():
            # the poll loop runs bootstrap; the gate opens on the first
            # completed one and the server starts serving reads
            follower.start()
            while state.status == "recovering" and not follower.promoted:
                if follower.bootstrapped:
                    with state.lock:
                        if state.status == "recovering":
                            state.status = "ready"
                    return
                time.sleep(0.05)

        threading.Thread(
            target=_follower_gate, daemon=True, name="kolibrie-follower"
        ).start()
    return httpd


def shutdown_gracefully(httpd, timeout_s: float = 30.0) -> None:
    """SIGTERM path: gate admissions (``draining`` → new requests 503),
    wait for in-flight requests to finish, commit a final snapshot, flush
    and close the WAL, then stop the listener.  Safe to call on a
    non-durable server (drain + stop only)."""
    state = httpd.RequestHandlerClass.state
    with state.lock:
        state.status = "draining"
    _log.info("draining", timeout_s=timeout_s)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline and state.admission.inflight > 0:
        time.sleep(0.05)
    if state.flightrec is not None:
        # final bundle BEFORE teardown: it captures the still-live stats
        # surface; the rolling blackbox stays behind as well
        state.flightrec.stop()
        flightrec.try_dump(
            state.data_dir,
            "sigterm",
            stats_fn=lambda: obs_export.build_stats(state),
        )
    if state.prewarmer is not None:
        # stop the warmer before the final snapshot: it persists the
        # manifest so the NEXT incarnation knows this one's hot set
        state.prewarmer.stop()
    repl = state.replication
    if repl is not None:
        # follower: stop the poll loop; primary: close the ship listener
        closer = getattr(repl, "stop", None) or getattr(repl, "close")
        closer()
    if state.durability is not None:
        try:
            _snapshot_now(state)
        except Exception:
            # WAL replay covers everything the snapshot would have; close
            # still flushes + fsyncs the tail below
            _DURABILITY_ERRORS.labels("final_snapshot").inc()
        state.durability.close()
    httpd.shutdown()


def serve(host: str = "127.0.0.1", port: int = 7878) -> None:
    import signal

    data_dir = os.environ.get("KOLIBRIE_DATA_DIR") or None
    repl_port_raw = os.environ.get("KOLIBRIE_REPL_PORT") or ""
    repl_source = os.environ.get("KOLIBRIE_REPL_SOURCE") or None
    # chaos harnesses arm delivery faults in child processes via env
    # (KOLIBRIE_FAULT_PLAN JSON); a no-op in production where it is unset
    from kolibrie_tpu.resilience import faultinject

    plan = faultinject.plan_from_env()
    if plan is not None:
        faultinject.install(plan)
    httpd = make_server(
        host,
        port,
        data_dir=data_dir,
        repl_port=int(repl_port_raw) if repl_port_raw else None,
        repl_source=repl_source,
        repl_poll_interval_s=float(
            os.environ.get("KOLIBRIE_REPL_POLL_INTERVAL_S", "0.15")
        ),
        repl_seal_interval_s=float(
            os.environ.get("KOLIBRIE_REPL_SEAL_INTERVAL_S", "0.25")
        ),
    )

    def _on_sigterm(signum, frame):
        # drain on a worker thread: the handler itself must return fast,
        # and serve_forever unblocks when shutdown() is called
        threading.Thread(
            target=shutdown_gracefully, args=(httpd,), daemon=True
        ).start()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass  # not the main thread (embedded in tests)
    state = httpd.RequestHandlerClass.state
    if data_dir:
        # an uncaught fatal error on the serving process leaves a bundle
        flightrec.install_excepthook(
            data_dir, stats_fn=lambda: obs_export.build_stats(state)
        )
    _log.info("listening", host=host, port=port, url=f"http://{host}:{port}")
    if data_dir:
        _log.info("durable data dir", data_dir=data_dir)
    if repl_source:
        _log.info("replicating (read-only follower)", source=repl_source)
    elif state.replication is not None:
        _log.info("shipping WAL segments", port=state.replication.port)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        shutdown_gracefully(httpd)


if __name__ == "__main__":
    import sys

    serve(
        sys.argv[1] if len(sys.argv) > 1 else "127.0.0.1",
        int(sys.argv[2]) if len(sys.argv) > 2 else 7878,
    )
