"""Command-line interface: load RDF, run SPARQL, print a result table.

Parity: ``cli/src/main.rs:15-41`` (``--file RDF --query SPARQL``), extended
with format override, rule application (SPARQL RULE and N3 logic), and an
``--serve`` flag that starts the HTTP server.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List, Optional

from kolibrie_tpu.obs import log as obslog

_log = obslog.get_logger("cli")


def _read_arg(value: str) -> str:
    """Accept either inline text or a path to a file holding the text."""
    if os.path.exists(value):
        with open(value, "r", encoding="utf-8") as f:
            return f.read()
    return value


def _print_table(rows: List[List[str]], out) -> None:
    if not rows:
        print("(no results)", file=out)
        return
    widths = [
        max(len(str(r[i])) for r in rows if i < len(r))
        for i in range(max(len(r) for r in rows))
    ]
    for row in rows:
        print(
            "  ".join(str(v).ljust(w) for v, w in zip(row, widths)).rstrip(),
            file=out,
        )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="kolibrie-tpu",
        description="TPU-native SPARQL/RDF + RSP + probabilistic-Datalog engine",
    )
    ap.add_argument("--file", help="RDF data file (format by extension)")
    ap.add_argument("--format", help="override data format: turtle|ntriples|rdfxml|n3")
    ap.add_argument("--query", help="SPARQL query text or path to a .rq file")
    ap.add_argument(
        "--rule",
        action="append",
        default=[],
        help="SPARQL RULE definition (text or path); may repeat",
    )
    ap.add_argument("--n3logic", help="N3 logic rules (text or path)")
    ap.add_argument("--legacy", action="store_true", help="use the legacy join path")
    ap.add_argument(
        "--export",
        choices=["ntriples", "turtle", "rdfxml"],
        help="after loading (and applying rules), print the database in this "
        "format instead of running a query",
    )
    ap.add_argument("--time", action="store_true", help="print execution time")
    ap.add_argument(
        "--explain",
        action="store_true",
        help="print the device physical plan (scan orders, join keys +"
        " exact counts) for --query instead of executing it",
    )
    ap.add_argument("--serve", action="store_true", help="start the HTTP server")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7878)
    args = ap.parse_args(argv)

    if args.serve:
        from kolibrie_tpu.frontends.http_server import serve

        serve(args.host, args.port)
        return 0

    if not args.query and not args.export:
        ap.error("--query or --export is required (unless --serve)")

    from kolibrie_tpu.query.executor import execute_query, execute_query_volcano
    from kolibrie_tpu.query.sparql_database import SparqlDatabase

    db = SparqlDatabase()
    if args.file:
        db.load_file(args.file, args.format)

    if args.n3logic:
        from kolibrie_tpu.frontends.rules import apply_n3_logic

        inferred = apply_n3_logic(db, _read_arg(args.n3logic))
        _log.info("n3logic rules applied", inferred=inferred)

    for rule_text in args.rule:
        from kolibrie_tpu.frontends.rules import apply_sparql_rules

        inferred = apply_sparql_rules(db, [_read_arg(rule_text)])
        _log.info("sparql rule applied", inferred=inferred)

    if args.export:
        writer = {
            "ntriples": db.to_ntriples,
            "turtle": db.to_turtle,
            "rdfxml": db.to_rdfxml,
        }[args.export]
        sys.stdout.write(writer())
        return 0

    sparql = _read_arg(args.query)
    if args.explain:
        from kolibrie_tpu.query.engine import QueryEngine

        print(QueryEngine(db).explain_device(sparql), file=sys.stdout)
        return 0
    start = time.perf_counter()
    run = execute_query if args.legacy else execute_query_volcano
    rows = run(sparql, db)
    elapsed_ms = (time.perf_counter() - start) * 1000.0
    _print_table(rows, sys.stdout)
    if args.time:
        _log.info("query executed", rows=len(rows), elapsed_ms=round(elapsed_ms, 2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
