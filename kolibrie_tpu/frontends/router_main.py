"""Front-router entrypoint: ``python -m kolibrie_tpu.frontends.router_main``.

Boots the template-affinity router (:mod:`kolibrie_tpu.replication.router`)
in front of a fleet of replica HTTP servers.  The fleet is configured by
environment, matching the server-side convention in ``http_server.serve``:

- ``KOLIBRIE_REPLICAS``   — ``name=http://host:port,name=url,...`` (required)
- ``KOLIBRIE_ROUTER_PROBE_INTERVAL_S`` — health-probe cadence (default 0.5)
- ``KOLIBRIE_ROUTER_AUTO_PROMOTE``     — ``0`` disables the promotion
  supervisor (default on: a dead primary is replaced by the follower with
  the highest durable watermark)

This module deliberately imports no query-engine code: the router process
only speaks HTTP and JSON, so it boots in milliseconds and survives
engine-side crashes unaffected — which is the whole point of putting it
in front.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
from typing import List, Tuple

from kolibrie_tpu.obs import log as obslog
from kolibrie_tpu.replication.router import make_router

_log = obslog.get_logger("router_main")


def parse_replicas(spec: str) -> List[Tuple[str, str]]:
    """``"a=http://h:1,b=http://h:2"`` → ``[("a", "http://h:1"), ...]``.
    Raises ValueError on malformed entries — a router silently pointed at
    nothing would "work" while serving 503s forever."""
    out: List[Tuple[str, str]] = []
    for chunk in spec.split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        name, sep, url = chunk.partition("=")
        if not sep or not name.strip() or not url.strip().startswith("http"):
            raise ValueError(
                f"bad replica spec {chunk!r}; want name=http://host:port"
            )
        out.append((name.strip(), url.strip().rstrip("/")))
    if not out:
        raise ValueError("KOLIBRIE_REPLICAS is empty")
    return out


def serve(host: str = "127.0.0.1", port: int = 8090) -> None:
    spec = os.environ.get("KOLIBRIE_REPLICAS", "")
    replicas = parse_replicas(spec)
    probe_s = float(os.environ.get("KOLIBRIE_ROUTER_PROBE_INTERVAL_S", "0.5"))
    auto = os.environ.get("KOLIBRIE_ROUTER_AUTO_PROMOTE", "1") != "0"
    httpd, core = make_router(
        replicas,
        host=host,
        port=port,
        probe_interval_s=probe_s,
        auto_promote=auto,
    )
    bound = httpd.server_address
    obslog.set_identity("router", bound[1])
    _log.info(
        "router listening",
        url=f"http://{bound[0]}:{bound[1]}",
        replicas=len(replicas),
        auto_promote=auto,
    )
    stop = threading.Event()

    def _term(_sig, _frm):
        stop.set()
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    try:
        httpd.serve_forever(poll_interval=0.2)
    finally:
        core.stop()
        httpd.server_close()


if __name__ == "__main__":
    _host = sys.argv[1] if len(sys.argv) > 1 else "127.0.0.1"
    _port = int(sys.argv[2]) if len(sys.argv) > 2 else 8090
    serve(_host, _port)
