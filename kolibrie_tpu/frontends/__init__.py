"""User-facing frontends: command-line interface and HTTP server.

Parity: ``cli/src/main.rs`` (clap CLI) and
``kolibrie-http-server/src/main.rs`` (hand-rolled HTTP server with /query,
/rsp-query, /rsp/register, /rsp/push, SSE /rsp/events/<id>, playground).
"""


def cli_main(argv=None):
    """Lazy forward to :func:`kolibrie_tpu.frontends.cli.main` (keeps
    ``python -m kolibrie_tpu.frontends.cli`` free of double-import warnings)."""
    from kolibrie_tpu.frontends.cli import main

    return main(argv)
