"""Shared rule-application helpers for the CLI and HTTP frontends.

Parity: the /query handler's rule pipeline in
``kolibrie-http-server/src/main.rs`` — ``strip_hash_comments`` (:222),
``has_n3_rule_text`` (:216), N3-logic application via the Reasoner
(:985-1050), and SPARQL RULE processing via process_rule_definition
(:1053-1076).
"""

from __future__ import annotations

from typing import List

from kolibrie_tpu.core.triple import Triple


def strip_hash_comments(text: str) -> str:
    """Remove ``#`` comments without touching ``#`` inside IRIs or literals."""
    out: List[str] = []
    in_iri = False
    in_literal = False
    escaped = False
    skipping = False
    for ch in text:
        if skipping:
            if ch == "\n":
                skipping = False
                out.append(ch)
            continue
        if escaped:
            out.append(ch)
            escaped = False
            continue
        if ch == "\\" and in_literal:
            out.append(ch)
            escaped = True
            continue
        if ch == '"' and not in_iri:
            in_literal = not in_literal
        elif ch == "<" and not in_literal:
            in_iri = True
        elif ch == ">" and not in_literal:
            in_iri = False
        elif ch == "#" and not in_iri and not in_literal:
            skipping = True
            continue
        out.append(ch)
    return "".join(out)


def has_n3_rule_text(text: str) -> bool:
    return any(
        "=>" in line
        for line in text.splitlines()
        if not line.lstrip().startswith("#")
    )


def apply_n3_logic(db, n3_text: str) -> int:
    """Parse ``{ premise } => { conclusion }`` rules, run the semi-naive
    closure over the database's triples, and insert the inferred facts.

    Returns the number of newly inferred facts."""
    from kolibrie_tpu.reasoner.n3_parser import parse_n3_document
    from kolibrie_tpu.reasoner.rule_runtime import build_reasoner_from_db

    n3_text = strip_hash_comments(n3_text)
    if not has_n3_rule_text(n3_text):
        return 0
    kg = build_reasoner_from_db(db)
    for rule in parse_n3_document(n3_text, db.dictionary):
        kg.add_rule(rule)
    kg.infer_new_facts_semi_naive()
    new = kg.facts.triples_set() - db.store.triples_set()
    for key in new:
        db.store.add_triple(Triple(*key))
    return len(new)


def apply_sparql_rules(db, rule_texts: List[str]) -> int:
    """Process ``RULE :Name(...) :- ... => { ... }`` definitions (the full
    pipeline incl. TRAIN/ML.PREDICT, via rule_runtime)."""
    from kolibrie_tpu.query.parser import parse_combined_query
    from kolibrie_tpu.reasoner.rule_runtime import process_combined_rule

    total = 0
    for text in rule_texts:
        text = strip_hash_comments(text)
        if not text.strip():
            continue
        cq = parse_combined_query(text, db.prefixes)
        for rule in cq.rules:
            _, emitted = process_combined_rule(db, rule)
            total += len(emitted)
    return total
