"""``--explain KLxxx``: rule documentation at the terminal.

Every rule gets at least its registered one-liner plus the docstring of
the module that defines it (the rule families keep their design notes
there).  The rules people actually argue with — the dataflow taint and
race families — additionally carry a curated fixture and fix recipe, so
"why is kolint yelling" is answerable without opening docs/ANALYSIS.md.
"""

from __future__ import annotations

import sys
import textwrap
from typing import Dict, Optional

# rule id → (example fixture, fix recipe)
_CURATED: Dict[str, tuple] = {
    "KL111": (
        """\
        @jax.jit
        def hot(x):
            y = x * 2            # y derives from the traced param
            if y.sum() > 0:      # KL111: host `if` on a traced value
                return y
            return -y
        """,
        """\
        Branch on-device instead of on-host: jnp.where(cond, a, b) for
        element selection, lax.cond for whole-branch dispatch.  If the
        decision is genuinely host-side (config, capacity), hoist it out
        of the jit region and pass the result in as a static argument.
        """,
    ),
    "KL112": (
        """\
        def serve(rows):
            n = len(rows)             # per-call data…
            return kernel(rows, cap=n)  # KL112: …reaching static cap
        # (kernel declares cap in static_argnums)
        """,
        """\
        Every distinct static value compiles a new program.  Round the
        value through a capacity class first — cap=round_cap(len(rows))
        / pow2 bucket — so thousands of request sizes share a handful
        of compiled templates (the template-cap protocol).  Inside jit,
        use a traced operand's .shape: it is already a trace-time
        constant.
        """,
    ),
    "KL311": (
        """\
        class Sampler:
            def __init__(self):
                self.count = 0          # shared with the daemon below
            def _run(self):             # Thread(target=self._run)
                self.count += 1         # KL311: unguarded shared write
            def stats(self):
                return self.count
        """,
        """\
        Pick ONE named lock, hold it at every access, and annotate the
        field:  self.count = 0  # guarded by: _lock.  The annotation
        moves enforcement to KL301 (lexical) and the runtime sanitizer
        (KOLIBRIE_DEBUG_LOCKS=1).  += is a read-modify-write that drops
        increments under contention — GIL atomicity is not a contract.
        If the idiom is genuinely safe (startup-once publish, atomic
        rebind of an immutable snapshot), say WHY in a suppression:
        # kolint: ignore[KL311] <reason>.
        """,
    ),
    "KL312": (
        """\
        def promote(self):
            with self.lock:
                self.promotions += 1
            self.last_ms = elapsed      # KL312: slipped out of the lock
        """,
        """\
        Some accesses hold a lock, this one doesn't — usually a write
        that drifted out of its `with` block during a refactor, which
        makes the OTHER sites' locking theater.  Move the access under
        the same lock; when the lock-free read is intentional (snapshot
        idiom), suppress with the argument, or annotate the field
        `# guarded by: <lock>` and keep reads free (`writes` mode).
        """,
    ),
}


def explain(rule_id: str) -> Optional[str]:
    """Render the explanation text for ``rule_id``, or None if unknown."""
    from kolibrie_tpu.analysis.core import (
        META_PARSE,
        META_SUPPRESSION,
        RULES,
    )

    meta = {
        META_SUPPRESSION: "suppression directive malformed "
        "(missing reason / unknown rule id)",
        META_PARSE: "file does not parse",
    }
    if rule_id in meta:
        return f"{rule_id}: {meta[rule_id]}\n"
    if rule_id not in RULES:
        return None
    desc, fn = RULES[rule_id]
    out = [f"{rule_id}: {desc}", ""]
    curated = _CURATED.get(rule_id)
    if curated:
        fixture, recipe = curated
        out += [
            "Example:",
            textwrap.indent(textwrap.dedent(fixture).rstrip(), "    "),
            "",
            "Fix:",
            textwrap.indent(
                textwrap.fill(
                    " ".join(textwrap.dedent(recipe).split()), width=68
                ),
                "    ",
            ),
            "",
        ]
    mod = sys.modules.get(fn.__module__)
    doc = (mod.__doc__ or "").strip() if mod else ""
    if doc:
        out += [f"Family notes ({fn.__module__.rsplit('.', 1)[-1]}):", ""]
        out.append(textwrap.indent(doc, "    "))
        out.append("")
    return "\n".join(out)
