"""Runtime lock-discipline sanitizer — the dynamic half of KL301/KL31x.

The static rules prove lock discipline about the code they can SEE:
``# guarded by: <lock>`` annotations are enforced lexically (KL301) and
un-annotated shared state is race-checked against the thread model
(KL311/KL312).  Two escape hatches weaken those proofs on purpose —
``# kolint: holds[<lock>]`` (caller-holds contracts) and reasoned
suppressions.  This module turns the annotations into *checked claims*:
under ``KOLIBRIE_DEBUG_LOCKS=1`` every annotated attribute becomes a
data descriptor that asserts its declared lock is actually held at
access time, so a false ``holds[]`` claim or a refactor that moved an
access out of its ``with`` block shows up as a report in the chaos
suite instead of a heisenbug in production.

Zero-cost when off: :func:`auto_instrument` (called at the bottom of
modules that carry annotations) returns immediately unless the env var
is set, so production pays one dict lookup per import and nothing per
access.

Semantics:

- mode ``writes`` (annotation default): ``__set__``/``__delete__``
  check the lock; reads are free (the snapshot-read idiom).
- mode ``rw`` (``# guarded by: _lock (rw)``): reads check too — for
  state mutated in place through the reference (dicts of counters).
- ``__init__``-family frames are exempt: construction precedes sharing.
- Ownership test: ``RLock._is_owned()`` when available (exact), else
  ``Lock.locked()`` (held-by-someone — a thread-attribution false
  negative is possible, never a false positive report).
- Violations are RECORDED, not raised: :func:`reports` returns them and
  the chaos suite asserts emptiness (or, for the seeded
  ``lockcheck.bypass`` fault, non-emptiness).  Raising would change
  control flow and mask the very interleavings being hunted.

Caveat: instrumented attributes live in the instance ``__dict__`` under
a mangled slot, so code that inspects ``vars(obj)`` directly sees the
mangled names while the sanitizer is on.
"""

from __future__ import annotations

import os
import re
import sys
import threading
from typing import Any, Dict, List, Optional, Tuple, Type

from kolibrie_tpu.analysis.project import _GUARDED_RE

_EXEMPT_FRAMES = {
    "__init__", "__new__", "__post_init__", "__setstate__", "__getstate__",
}
_MAX_REPORTS = 200

_ASSIGN_RE = re.compile(r"^\s*self\.([A-Za-z_]\w*)\s*(?::[^=]*)?=[^=]")

_reports: List[dict] = []
_reports_lock = threading.Lock()


def enabled() -> bool:
    return os.environ.get("KOLIBRIE_DEBUG_LOCKS") == "1"


def reports() -> List[dict]:
    """Violations recorded so far (bounded at _MAX_REPORTS)."""
    with _reports_lock:
        return list(_reports)


def reset() -> None:
    with _reports_lock:
        _reports.clear()


def _held(lock: Any) -> Optional[bool]:
    """True/False when determinable, None when the primitive is opaque
    (duck-typed fakes in tests) or absent."""
    if lock is None:
        return None
    is_owned = getattr(lock, "_is_owned", None)
    if callable(is_owned):  # RLock: exact per-thread ownership
        try:
            return bool(is_owned())
        # kolint: ignore[KL601] a sanitizer probe must never take down the probed code; an un-probeable lock degrades to "unknown", not a report
        except Exception:
            return None
    locked = getattr(lock, "locked", None)
    if callable(locked):  # Lock: held-by-someone approximation
        try:
            return bool(locked())
        # kolint: ignore[KL601] same degrade-to-unknown contract as above
        except Exception:
            return None
    return None


def _record(cls_name: str, attr: str, event: str, lock_name: str, frame) -> None:
    ent = {
        "class": cls_name,
        "attr": attr,
        "event": event,
        "lock": lock_name,
        "where": f"{frame.f_code.co_filename}:{frame.f_lineno}",
        "func": frame.f_code.co_name,
        "thread": threading.current_thread().name,
    }
    with _reports_lock:
        if len(_reports) < _MAX_REPORTS:
            _reports.append(ent)


class GuardedAttribute:
    """Data descriptor asserting the declared lock is held at access."""

    def __init__(self, name: str, lock_name: str, mode: str, cls_name: str):
        self.name = name
        self.lock_name = lock_name.split(".")[-1]
        self.mode = mode
        self.cls_name = cls_name
        self.slot = f"_lockcheck_{name}"

    def _check(self, obj, event: str) -> None:
        frame = sys._getframe(2)
        if frame.f_code.co_name in _EXEMPT_FRAMES:
            return
        held = _held(obj.__dict__.get(self.lock_name))
        if held is False:
            _record(self.cls_name, self.name, event, self.lock_name, frame)

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        if self.mode == "rw":
            self._check(obj, "read")
        try:
            return obj.__dict__[self.slot]
        except KeyError:
            raise AttributeError(
                f"{type(obj).__name__!r} object has no attribute "
                f"{self.name!r}"
            ) from None

    def __set__(self, obj, value) -> None:
        self._check(obj, "write")
        obj.__dict__[self.slot] = value

    def __delete__(self, obj) -> None:
        self._check(obj, "write")
        try:
            del obj.__dict__[self.slot]
        except KeyError:
            raise AttributeError(self.name) from None


def _parse_guarded(src: str) -> Dict[str, Tuple[str, str]]:
    """attr → (lock, mode) from ``self.X = … # guarded by: L`` lines."""
    out: Dict[str, Tuple[str, str]] = {}
    for line in src.splitlines():
        m = _GUARDED_RE.search(line)
        if m is None:
            continue
        am = _ASSIGN_RE.match(line)
        if am is None:
            continue  # module-global or non-attribute annotation
        out[am.group(1)] = (m.group(1), m.group(2) or "writes")
    return out


def instrument_class(cls: Type, force: bool = False) -> Type:
    """Replace ``cls``'s annotated attributes with checking descriptors.
    No-op unless the env gate is set (or ``force``), and for classes
    whose source is unavailable (REPL, exec)."""
    if not (force or enabled()):
        return cls
    import inspect

    try:
        src = inspect.getsource(cls)
    except (OSError, TypeError):
        return cls
    for attr, (lock, mode) in _parse_guarded(src).items():
        if lock.split(".")[-1] == attr:
            continue  # a lock guarding itself is an annotation typo
        setattr(cls, attr, GuardedAttribute(attr, lock, mode, cls.__name__))
    return cls


def auto_instrument(namespace: Dict[str, Any]) -> None:
    """Instrument every class defined in ``namespace`` (a module's
    ``globals()``) that carries guard annotations.  Call at module
    bottom; free unless ``KOLIBRIE_DEBUG_LOCKS=1``."""
    if not enabled():
        return
    mod = namespace.get("__name__")
    for val in list(namespace.values()):
        if isinstance(val, type) and getattr(val, "__module__", None) == mod:
            instrument_class(val)


# ------------------------------------------------------------- selftest


class _Probe:
    """Fixture for :func:`selftest` — one field per mode."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0  # guarded by: _lock
        self.tracked = 0  # guarded by: _lock (rw)


def selftest() -> bool:
    """Prove the sanitizer is silent on disciplined accesses and
    reports an unlocked write AND an unlocked rw-read.  Instruments
    unconditionally (``force=True``) so lint.sh can run it without
    flipping the env for the whole process; probe reports are removed
    afterwards so they never pollute a real session's findings."""
    instrument_class(_Probe, force=True)
    start = len(reports())
    p = _Probe()
    with p._lock:
        p.value = 1  # kolint: ignore[KL301] selftest exercises the RUNTIME checker; the lock IS held here
        _ = p.tracked  # kolint: ignore[KL301] ditto — disciplined read under the lock
    quiet = len(reports()) == start
    p.value = 2  # kolint: ignore[KL301] deliberate violation the selftest asserts is caught
    _ = p.tracked  # kolint: ignore[KL301] deliberate rw-read violation
    mine = [r for r in reports()[start:] if r["class"] == "_Probe"]
    caught = {(r["attr"], r["event"]) for r in mine} >= {
        ("value", "write"),
        ("tracked", "read"),
    }
    with _reports_lock:
        _reports[:] = [r for r in _reports if r["class"] != "_Probe"]
    return quiet and caught
