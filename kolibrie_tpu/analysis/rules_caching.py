"""Rule family 9: cache-key versioning discipline.

The store mutates in place (delta appends, base rebuilds), so any result
cache keyed on store *identity* — ``id(db)``, ``id(store)``, or the db /
store object itself — silently serves stale rows the moment a mutation
lands.  The sanctioned idiom (docs/MQO.md) is to fold the store's
version coordinates into the key: both ``base_version`` and
``delta_epoch``, or equivalently one ``store.version_key()`` call (which
compacts first and returns exactly that pair).  PR 16's shared-prefix
cache was the motivating case; this rule keeps the next cache honest.

KL901  a cache/memo container subscript, ``.get`` or ``.setdefault``
       whose key expression carries store identity but neither both
       version components (``base_version`` AND ``delta_epoch``) nor a
       ``version_key()`` call.  Containers are recognized by name
       (``*cache*`` / ``*memo*``); identity is ``id(<db/store>)`` or a
       bare db/store object inside the key.  Keys that are plain
       strings/texts (no identity) are out of scope — identity-free
       keys cannot pin a stale store.

KL902  a learned-state ``*Advisor`` class keyed on the template
       fingerprint whose module defines an env-read mode flag
       (``*_mode()``) that participates in NO template fingerprint —
       not called inside any ``template_key``/``env_sig`` function and
       absent from every ``env_sig = (...)`` assignment.  A mode flag
       that gates *which plan a template gets* but stays out of the
       fingerprint lets an off-mode process replay a plan the advisor
       tuned (or vice versa) from a shared cache/manifest; the plan and
       the key disagree (docs/OPTIMIZER.md).  Advisors whose module has
       no mode function escape — state that is always-on (CapAdvisor's
       capacity high-water marks) cannot desync a fingerprint.
       Participation is checked across the analyzed file set, so run
       kolint over the package root, not a single file.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from kolibrie_tpu.analysis.core import Finding, rule
from kolibrie_tpu.analysis.project import Project, terminal_name

_CONTAINER_HINTS = ("cache", "memo")
_STORE_NAMES = ("db", "store")
_KEYED_METHODS = ("get", "setdefault", "pop")


def _container_name(node: ast.AST) -> str:
    """Terminal name of a subscripted/called container, lowercased."""
    name = terminal_name(node)
    return (name or "").lower()


def _is_store_ref(node: ast.AST) -> bool:
    """A db/store object reference: ``db``, ``self.db``, ``x.store``…"""
    name = terminal_name(node)
    return name in _STORE_NAMES


def _key_has_identity(key: ast.AST) -> bool:
    """Does the key expression carry store identity?  Only DIRECT object
    references count: ``id(db)`` or the db/store object itself as a key
    element.  ``db.expand_term(x)`` / ``store.base_version`` read an
    attribute OF the store — the key holds the attribute's value, not
    the object, so they are not identity."""
    derived = set()  # nodes whose value is derived from, not equal to, db
    for node in ast.walk(key):
        if isinstance(node, ast.Attribute):
            derived.add(id(node.value))
        elif isinstance(node, ast.Call):
            derived.add(id(node.func))
    for node in ast.walk(key):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "id"
            and node.args
            and _is_store_ref(node.args[0])
        ):
            return True
        if isinstance(node, (ast.Name, ast.Attribute)) and _is_store_ref(
            node
        ):
            # the object itself as a key element hashes by identity
            # unless it defines content-based __hash__ — none of ours do
            if id(node) not in derived:
                return True
    return False


def _key_is_versioned(key: ast.AST) -> bool:
    """Both version components present, or a version_key() call."""
    names = set()
    for node in ast.walk(key):
        if isinstance(node, ast.Call):
            if terminal_name(node.func) == "version_key":
                return True
        if isinstance(node, (ast.Name, ast.Attribute)):
            names.add(terminal_name(node))
    return "base_version" in names and "delta_epoch" in names


def _key_expr(node: ast.AST) -> Optional[ast.AST]:
    """The key expression of a cache access, or None when ``node`` is
    not a recognized cache access."""
    if isinstance(node, ast.Subscript):
        if any(h in _container_name(node.value) for h in _CONTAINER_HINTS):
            return node.slice
        return None
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in _KEYED_METHODS and node.args:
            if any(
                h in _container_name(node.func.value)
                for h in _CONTAINER_HINTS
            ):
                return node.args[0]
    return None


@rule(
    "KL901",
    "cache keyed on store identity without (base_version, delta_epoch) "
    "— serves stale rows after any mutation; fold store.version_key() "
    "into the key (docs/MQO.md)",
)
def unversioned_store_cache_key(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for f in project.files:
        if f.tree is None:
            continue
        for info in f.functions.values():
            # one level of local-binding resolution: `key = (id(db), fp)`
            # then `cache[key]` — the common shape.  Multiple assignments
            # to one name are merged conservatively (any unversioned
            # identity-carrying binding flags the access).
            bindings = {}
            for node in ast.walk(info.node):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            bindings.setdefault(tgt.id, []).append(
                                node.value
                            )
            for node in ast.walk(info.node):
                key = _key_expr(node)
                if key is None:
                    continue
                if isinstance(key, ast.Name) and key.id in bindings:
                    exprs = bindings[key.id]
                    if any(
                        _key_has_identity(e) and not _key_is_versioned(e)
                        for e in exprs
                    ):
                        key = next(
                            e for e in exprs if _key_has_identity(e)
                        )
                    else:
                        continue
                if not _key_has_identity(key):
                    continue
                if _key_is_versioned(key):
                    continue
                out.append(
                    Finding(
                        "KL901",
                        f.rel,
                        node.lineno,
                        "cache key carries store identity but no "
                        "(base_version, delta_epoch) — a mutation leaves "
                        "the entry live and stale; append "
                        "store.version_key() to the key",
                        scope=info.qualname,
                    )
                )
    return out


# --------------------------------------------------------------- KL902

_FP_PARAMS = ("fp", "fingerprint", "template_fp")


def _reads_env(fn_node: ast.AST) -> bool:
    """Does this function read process environment (``os.environ`` /
    ``getenv``)?  That is what makes a ``*_mode()`` a routing flag."""
    for node in ast.walk(fn_node):
        if isinstance(node, (ast.Name, ast.Attribute)):
            if terminal_name(node) == "environ":
                return True
        if isinstance(node, ast.Call):
            if terminal_name(node.func) == "getenv":
                return True
    return False


def _module_mode_functions(f) -> dict:
    """Module-level env-reading ``*_mode`` defs: name → lineno."""
    out = {}
    for qual, info in f.functions.items():
        if "." in qual or not qual.endswith("_mode"):
            continue
        if _reads_env(info.node):
            out[qual] = info.node.lineno
    return out


def _participating_names(project: Project) -> set:
    """Call names that ride a template fingerprint anywhere in the
    analyzed set: calls inside a ``template_key``/``env_sig`` function,
    or inside the value of an ``env_sig = (...)`` assignment."""
    names = set()

    def collect_calls(node: ast.AST) -> None:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                t = terminal_name(n.func)
                if t:
                    names.add(t)

    for f in project.files:
        if f.tree is None:
            continue
        for qual, info in f.functions.items():
            if qual.rsplit(".", 1)[-1] in ("template_key", "env_sig"):
                collect_calls(info.node)
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Assign):
                if any(
                    "env_sig" in (terminal_name(t) or "")
                    for t in node.targets
                ):
                    collect_calls(node.value)
    return names


def _fp_keyed_advisors(f) -> list:
    """ClassDefs named ``*Advisor*`` with a method taking a
    fingerprint-ish parameter: (name, lineno) pairs."""
    out = []
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.ClassDef) or "Advisor" not in node.name:
            continue
        keyed = any(
            info.class_name == node.name
            and any(p in _FP_PARAMS for p in info.params)
            for info in f.functions.values()
        )
        if keyed:
            out.append((node.name, node.lineno))
    return out


@rule(
    "KL902",
    "learned-state advisor keyed on template fingerprint whose mode "
    "flag is outside the fingerprint — an off-mode process replays "
    "tuned plans (or tuned processes replay static ones) from shared "
    "caches; call the *_mode() inside template_key / env_sig "
    "(docs/OPTIMIZER.md)",
)
def advisor_mode_outside_fingerprint(project: Project) -> List[Finding]:
    out: List[Finding] = []
    participating = _participating_names(project)
    for f in project.files:
        if f.tree is None:
            continue
        advisors = _fp_keyed_advisors(f)
        if not advisors:
            continue
        modes = _module_mode_functions(f)
        if not modes or any(name in participating for name in modes):
            continue
        mode_names = ", ".join(sorted(modes))
        for cls, lineno in advisors:
            out.append(
                Finding(
                    "KL902",
                    f.rel,
                    lineno,
                    f"{cls} keys learned state on the template "
                    f"fingerprint but its mode flag ({mode_names}) "
                    "participates in no fingerprint — fold the mode "
                    "into template_key/env_sig so off-mode processes "
                    "never replay tuned plans",
                    scope=cls,
                )
            )
    return out
