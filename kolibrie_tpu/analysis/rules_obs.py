"""Rule family 5: observability hygiene.

obs/metrics.py's registry enforces no cardinality bound — call sites
must (its own docstring says so).  The repo's conventions: route labels
are clamped to a known set before labeling, template fingerprints are
bounded by the plan cache upstream, and spans are only opened through
``with span(…)`` so every open has a scope exit.

KL501  metric label value not provably drawn from a bounded set
       (f-string / format / dict lookup / subscript as a label value)
KL502  span(…) opened without a `with` scope — the span never exits,
       never lands in the ring, and corrupts the parent stack
"""

from __future__ import annotations

import ast
from typing import List

from kolibrie_tpu.analysis.core import Finding, rule
from kolibrie_tpu.analysis.project import (
    Project,
    iter_own_nodes,
    terminal_name,
)

def _label_value_ok(expr: ast.AST) -> bool:
    """Conservatively bounded label expressions: literals, plain names/
    attributes (assumed clamped upstream — the rule targets *syntactic*
    unboundedness), str()/int() of those, `x or "fallback"`, and
    conditional picks between bounded branches."""
    if isinstance(expr, (ast.Constant, ast.Name, ast.Attribute)):
        return True
    if isinstance(expr, ast.IfExp):
        return _label_value_ok(expr.body) and _label_value_ok(expr.orelse)
    if isinstance(expr, ast.BoolOp):
        return all(_label_value_ok(v) for v in expr.values)
    if isinstance(expr, ast.Call):
        fn = terminal_name(expr.func)
        if fn in ("str", "int") and len(expr.args) == 1:
            return _label_value_ok(expr.args[0])
    return False


@rule(
    "KL501",
    "metric label value not provably bounded (f-string/format/"
    "subscript/dict-get as a .labels() argument mints unbounded series)",
)
def unbounded_label(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for f in project.files:
        if f.tree is None:
            continue
        for info in f.functions.values():
            for node in iter_own_nodes(info.node):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "labels"
                ):
                    continue
                for arg in node.args:
                    if not _label_value_ok(arg):
                        out.append(
                            Finding(
                                "KL501",
                                f.rel,
                                node.lineno,
                                "label value is a computed string "
                                "(f-string/format/lookup); clamp it to a "
                                "bounded set first (route-clamp pattern, "
                                "frontends/http_server.py do_POST)",
                                scope=info.qualname,
                            )
                        )
                        break
    return out


@rule(
    "KL502",
    "span(...) opened outside a `with` statement — no scope exit, the "
    "span never finishes and the parent stack leaks",
)
def span_without_scope(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for f in project.files:
        if f.tree is None:
            continue
        # `span` imported from obs.spans under any local alias
        # (executor uses `span as _obs_span`)
        span_aliases = {
            alias
            for alias, (mod, name) in f.imports.items()
            if name == "span" and "spans" in mod
        }
        if not span_aliases:
            continue
        for info in f.functions.values():
            parents = {}
            for node in iter_own_nodes(info.node):
                for child in ast.iter_child_nodes(node):
                    parents[child] = node
            for node in iter_own_nodes(info.node):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in span_aliases
                ):
                    continue
                p = parents.get(node)
                if isinstance(p, ast.withitem):
                    continue
                out.append(
                    Finding(
                        "KL502",
                        f.rel,
                        node.lineno,
                        f"{node.func.id}(…) called outside `with`; use "
                        "`with span(name):` so the scope always exits",
                        scope=info.qualname,
                    )
                )
    return out
