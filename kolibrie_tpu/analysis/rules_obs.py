"""Rule family 5: observability hygiene.

obs/metrics.py's registry enforces no cardinality bound — call sites
must (its own docstring says so).  The repo's conventions: route labels
are clamped to a known set before labeling, template fingerprints are
bounded by the plan cache upstream, and spans are only opened through
``with span(…)`` so every open has a scope exit.

KL501  metric label value not provably drawn from a bounded set
       (f-string / format / dict lookup / subscript as a label value)
KL502  span(…) opened without a `with` scope — the span never exits,
       never lands in the ring, and corrupts the parent stack
KL504  bare print() in library code — diagnostics belong in the
       structured logger (obs/log.py) where they carry level, component
       and trace id; user-facing output must name its stream with an
       explicit ``file=`` argument.  ``__main__.py`` modules, code under
       an ``if __name__ == "__main__"`` guard, and tests are exempt.
"""

from __future__ import annotations

import ast
import os
from typing import List

from kolibrie_tpu.analysis.core import Finding, rule
from kolibrie_tpu.analysis.project import (
    Project,
    iter_own_nodes,
    terminal_name,
)

def _label_value_ok(expr: ast.AST) -> bool:
    """Conservatively bounded label expressions: literals, plain names/
    attributes (assumed clamped upstream — the rule targets *syntactic*
    unboundedness), str()/int() of those, `x or "fallback"`, and
    conditional picks between bounded branches."""
    if isinstance(expr, (ast.Constant, ast.Name, ast.Attribute)):
        return True
    if isinstance(expr, ast.IfExp):
        return _label_value_ok(expr.body) and _label_value_ok(expr.orelse)
    if isinstance(expr, ast.BoolOp):
        return all(_label_value_ok(v) for v in expr.values)
    if isinstance(expr, ast.Call):
        fn = terminal_name(expr.func)
        if fn in ("str", "int") and len(expr.args) == 1:
            return _label_value_ok(expr.args[0])
    return False


@rule(
    "KL501",
    "metric label value not provably bounded (f-string/format/"
    "subscript/dict-get as a .labels() argument mints unbounded series)",
)
def unbounded_label(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for f in project.files:
        if f.tree is None:
            continue
        for info in f.functions.values():
            for node in iter_own_nodes(info.node):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "labels"
                ):
                    continue
                for arg in node.args:
                    if not _label_value_ok(arg):
                        out.append(
                            Finding(
                                "KL501",
                                f.rel,
                                node.lineno,
                                "label value is a computed string "
                                "(f-string/format/lookup); clamp it to a "
                                "bounded set first (route-clamp pattern, "
                                "frontends/http_server.py do_POST)",
                                scope=info.qualname,
                            )
                        )
                        break
    return out


@rule(
    "KL502",
    "span(...) opened outside a `with` statement — no scope exit, the "
    "span never finishes and the parent stack leaks",
)
def span_without_scope(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for f in project.files:
        if f.tree is None:
            continue
        # `span` imported from obs.spans under any local alias
        # (executor uses `span as _obs_span`)
        span_aliases = {
            alias
            for alias, (mod, name) in f.imports.items()
            if name == "span" and "spans" in mod
        }
        if not span_aliases:
            continue
        for info in f.functions.values():
            parents = {}
            for node in iter_own_nodes(info.node):
                for child in ast.iter_child_nodes(node):
                    parents[child] = node
            for node in iter_own_nodes(info.node):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in span_aliases
                ):
                    continue
                p = parents.get(node)
                if isinstance(p, ast.withitem):
                    continue
                out.append(
                    Finding(
                        "KL502",
                        f.rel,
                        node.lineno,
                        f"{node.func.id}(…) called outside `with`; use "
                        "`with span(name):` so the scope always exits",
                        scope=info.qualname,
                    )
                )
    return out


# ------------------------------------------------------------------ KL503

_METRIC_CTORS = {"counter", "gauge", "histogram"}
_METRIC_METHODS = {"inc", "dec", "set", "observe", "labels"}


def _metric_family_names(f) -> set:
    """Module-level names bound to obs.metrics family constructors:
    ``_LAT = metrics.histogram(…)`` / ``_REQS = counter(…)`` (under any
    import alias of the metrics module or its constructors)."""
    if f.tree is None:
        return set()
    metric_mods = {
        alias
        for alias, mod in f.module_aliases.items()
        if mod.endswith("obs.metrics")
    } | {
        alias
        for alias, (mod, name) in f.imports.items()
        if name == "metrics" and "obs" in mod
    }
    fams = set()
    for node in f.tree.body:
        if not (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
        ):
            continue
        fn = node.value.func
        ok = False
        if isinstance(fn, ast.Name):
            mod, orig = f.imports.get(fn.id, (None, None))
            ok = orig in _METRIC_CTORS and "metrics" in (mod or "")
        elif isinstance(fn, ast.Attribute) and isinstance(
            fn.value, ast.Name
        ):
            ok = fn.attr in _METRIC_CTORS and fn.value.id in metric_mods
        if ok:
            fams.update(
                t.id for t in node.targets if isinstance(t, ast.Name)
            )
    return fams


def _chain_base_name(expr: ast.AST):
    """``FAM.labels(x).inc`` → "FAM": peel attribute/call chains down to
    the root name."""
    while True:
        if isinstance(expr, ast.Call):
            expr = expr.func
        elif isinstance(expr, ast.Attribute):
            expr = expr.value
        elif isinstance(expr, ast.Name):
            return expr.id
        else:
            return None


# ------------------------------------------------------------------ KL504


def _main_guard_ranges(tree: ast.Module) -> List[tuple]:
    """Line spans of ``if __name__ == "__main__":`` blocks — script
    bodies are CLI territory, prints there are the interface."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.If):
            continue
        t = node.test
        if (
            isinstance(t, ast.Compare)
            and isinstance(t.left, ast.Name)
            and t.left.id == "__name__"
        ):
            out.append((node.lineno, node.end_lineno or node.lineno))
    return out


def _kl504_exempt_file(rel: str) -> bool:
    base = os.path.basename(rel)
    if base == "__main__.py":  # CLI entry point by definition
        return True
    if base.startswith("test_") or base == "conftest.py":
        return True
    parts = rel.replace(os.sep, "/").split("/")
    return "tests" in parts


@rule(
    "KL504",
    "bare print() in library code — use the structured logger "
    "(obs/log.py) for diagnostics, or pass an explicit file= stream "
    "for user-facing output",
)
def bare_print(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for f in project.files:
        if f.tree is None or _kl504_exempt_file(f.rel):
            continue
        guards = _main_guard_ranges(f.tree)
        # innermost-enclosing-function index for the baseline scope key
        spans = sorted(
            (
                (info.node.lineno, info.node.end_lineno or info.node.lineno,
                 info.qualname)
                for info in f.functions.values()
                if hasattr(info.node, "lineno")
            ),
            key=lambda s: s[1] - s[0],
        )
        for node in ast.walk(f.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                continue
            if any(kw.arg == "file" for kw in node.keywords):
                continue  # stream named explicitly → intentional output
            if any(lo <= node.lineno <= hi for lo, hi in guards):
                continue
            scope = next(
                (q for lo, hi, q in spans if lo <= node.lineno <= hi), ""
            )
            out.append(
                Finding(
                    "KL504",
                    f.rel,
                    node.lineno,
                    "bare print() in library code — diagnostics go through "
                    "obs.log.get_logger(component) (level + trace id + "
                    "tail ring); user-facing output must name its stream "
                    "(print(..., file=sys.stdout))",
                    scope=scope,
                )
            )
    return out


@rule(
    "KL503",
    "obs.metrics / obs.spans call inside jit-reachable code — it fires "
    "once at TRACE time, then never again for the cached executable",
)
def obs_call_in_jit(project: Project) -> List[Finding]:
    out: List[Finding] = []
    seen_files = {}
    for info in project.functions.values():
        if not info.jit_reachable:
            continue
        f = info.module
        if f.tree is None:
            continue
        if f.rel not in seen_files:
            span_aliases = {
                alias
                for alias, (mod, name) in f.imports.items()
                if name == "span" and "spans" in mod
            }
            seen_files[f.rel] = (span_aliases, _metric_family_names(f))
        span_aliases, fams = seen_files[f.rel]
        for node in iter_own_nodes(info.node):
            if not isinstance(node, ast.Call):
                continue
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in span_aliases
            ):
                out.append(
                    Finding(
                        "KL503",
                        f.rel,
                        node.lineno,
                        f"{node.func.id}(…) opens a span inside "
                        "jit-reachable code: it times the TRACE, not the "
                        "dispatch, and vanishes once the executable "
                        "caches — span outside the jit boundary",
                        scope=info.qualname,
                    )
                )
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_METHODS
                and _chain_base_name(node.func.value) in fams
            ):
                out.append(
                    Finding(
                        "KL503",
                        f.rel,
                        node.lineno,
                        f".{node.func.attr}() on metric family "
                        f"{_chain_base_name(node.func.value)!r} inside "
                        "jit-reachable code counts traces, not calls — "
                        "record the value outside the jit boundary (the "
                        "stats-vector pattern, optimizer/device_engine)",
                        scope=info.qualname,
                    )
                )
    return out
