"""Rule family 6: error taxonomy discipline.

resilience/errors.py defines the KolibrieError taxonomy and PR 3's
convention: failures are either re-raised as taxonomy errors, converted
to an error response, or at minimum counted in the metrics registry.
A broad ``except Exception`` that does none of those erases the failure
— the query "succeeds", the operator sees nothing, and the degraded
mode never trips.

KL601  `except Exception:` / bare `except:` whose body neither
       re-raises, raises a taxonomy error, records an obs metric,
       logs, nor routes to an error response.
"""

from __future__ import annotations

import ast
from typing import List

from kolibrie_tpu.analysis.core import Finding, rule
from kolibrie_tpu.analysis.project import Project, iter_own_nodes, terminal_name

# Call names that count as "the failure was surfaced somewhere".
_SURFACING_CALLS = {
    # obs metrics
    "inc", "observe", "set",
    # logging
    "debug", "info", "warning", "error", "exception", "critical", "log",
    "print",
    # http/error plumbing in frontends
    "error_response", "send_error", "_send_failure", "_fail", "record_error",
}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    if isinstance(t, (ast.Name, ast.Attribute)):
        return terminal_name(t) in ("Exception", "BaseException")
    if isinstance(t, ast.Tuple):
        return any(
            terminal_name(e) in ("Exception", "BaseException")
            for e in t.elts
        )
    return False


def _body_surfaces(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Return) and _mentions_exc(node, handler):
            return True
        if isinstance(node, ast.Assign) and _mentions_exc(node.value, handler):
            # `r.error = e`: stored for re-raise on another thread —
            # the async propagation pattern, not a swallow
            return True
        if isinstance(node, ast.Call):
            name = terminal_name(node.func)
            if name in _SURFACING_CALLS:
                return True
    return False


def _mentions_exc(node: ast.AST, handler: ast.ExceptHandler) -> bool:
    """``return error_payload(e)``-style returns surface the error."""
    if not handler.name:
        return False
    return any(
        isinstance(n, ast.Name) and n.id == handler.name
        for n in ast.walk(node)
    )


@rule(
    "KL601",
    "broad `except Exception` swallows the failure: no raise, no metric, "
    "no log, no error response — the taxonomy (resilience/errors.py) "
    "never sees it",
)
def swallowed_exception(project: Project) -> List[Finding]:
    out: List[Finding] = []

    def check(nodes, rel: str, scope: str) -> None:
        for node in nodes:
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node) or _body_surfaces(node):
                continue
            out.append(
                Finding(
                    "KL601",
                    rel,
                    node.lineno,
                    "broad except swallows the error; re-raise a "
                    "KolibrieError, count it (obs counter), or log it "
                    "— silent pass hides real failures",
                    scope=scope,
                )
            )

    for f in project.files:
        if f.tree is None:
            continue
        for info in f.functions.values():
            check(ast.walk(info.node), f.rel, info.qualname)
        # module-level handlers (import guards etc.) — iter_own_nodes on
        # the Module skips function/class bodies already covered above
        check(iter_own_nodes(f.tree), f.rel, "<module>")
    return out
