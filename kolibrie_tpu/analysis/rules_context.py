"""Rule family 4: trace/deadline context propagation across thread hops.

Thread-locals do not cross ``threading.Thread`` / executor ``submit``
boundaries.  The repo's pattern (PR 2/3, documented in obs/spans.py) is
capture-at-submit (``current_trace_id()`` / ``current_deadline()``) and
re-enter-on-dispatch (``trace_scope`` / ``deadline_scope``).

KL401  a Thread/submit target transitively calls span- or
       deadline-aware code, and NEITHER the submitting function captures
       context NOR the target's reachable code re-enters a scope —
       spans land in orphan traces and deadlines silently stop applying.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from kolibrie_tpu.analysis.core import Finding, rule
from kolibrie_tpu.analysis.project import (
    FuncInfo,
    Project,
    iter_own_nodes,
    terminal_name,
)

# (imported-from module, name) pairs; matched on the local alias too.
_AWARE = {"span", "_obs_span", "check_deadline", "current_deadline",
          "remaining_s"}
_REENTER = {"trace_scope", "deadline_scope"}
_CAPTURE = {"current_trace_id", "current_deadline"} | _REENTER


def _called_names(info: FuncInfo) -> Set[str]:
    out: Set[str] = set()
    for node in iter_own_nodes(info.node):
        if isinstance(node, ast.Call):
            t = terminal_name(node.func)
            if t:
                out.add(t)
    return out


@rule(
    "KL401",
    "Thread/executor target transitively calls span- or deadline-aware "
    "code without the capture-at-submit / re-enter-on-dispatch pattern",
)
def context_across_threads(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for f in project.files:
        if f.tree is None:
            continue
        for info in f.functions.values():
            for node in iter_own_nodes(info.node):
                if not isinstance(node, ast.Call):
                    continue
                target = _submission_target(project, f, info, node)
                if target is None:
                    continue
                reach = project.reachable_from(target)
                called = set()
                for r in reach:
                    called |= _called_names(r)
                if not (called & _AWARE):
                    continue  # target never touches span/deadline code
                if called & _REENTER:
                    continue  # re-enter-on-dispatch present
                if _called_names(info) & _CAPTURE:
                    continue  # capture-at-submit present
                out.append(
                    Finding(
                        "KL401",
                        f.rel,
                        node.lineno,
                        f"thread target {target.qualname}() reaches span/"
                        "deadline-aware code but no trace_scope/"
                        "deadline_scope is re-entered and the submitter "
                        "captures no context; capture current_trace_id()/"
                        "current_deadline() at submit and re-enter on the "
                        "worker (see obs/spans.py)",
                        scope=info.qualname,
                    )
                )
    return out


def _submission_target(
    project: Project, f, info: FuncInfo, call: ast.Call
) -> Optional[FuncInfo]:
    """Resolve Thread(target=X) / pool.submit(X, …) to a FuncInfo."""
    name = terminal_name(call.func)
    if name == "Thread":
        for kw in call.keywords:
            if kw.arg == "target":
                return project._resolve_callee(f, info, kw.value)
        return None
    if name == "submit" and isinstance(call.func, ast.Attribute):
        if call.args:
            return project._resolve_callee(f, info, call.args[0])
    return None
