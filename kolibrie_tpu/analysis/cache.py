"""kolint result cache + parallel rule execution.

kolint's engine is INTERPROCEDURAL — thread roots, call-graph
reachability, and taint summaries cross file boundaries — so a
classic per-file cache (reuse file X's findings because X didn't
change) is unsound: adding one ``Thread(target=…)`` in module A can
create race findings in module B.  The cache is therefore keyed on the
*project signature* (the multiset of every linted file's content hash
plus a hash of the analysis engine itself) with one entry per RULE:

    .kolint_cache/<sig>/<rule>.json

Any edit anywhere moves the signature and cold-starts every rule —
correct by construction.  What the layout buys:

- repeated runs over an unchanged tree are near-free (lint.sh runs
  kolint three times: the main gate plus two standalone rule-family
  passes; passes two and three hit the entries pass one wrote);
- ``--changed-only`` diffs the per-file digest manifest
  (``.kolint_cache/files.json``) from the previous run and reports
  only findings anchored in files that changed — the ANALYSIS still
  covers the whole project (soundness), only the report is focused.

Parallelism: rules are pure functions of the :class:`Project`, so cold
rules fan out over a fork-based process pool.  Workers inherit the
parsed project copy-on-write (the pool is created AFTER parsing), and
rules that share memoized project state (taint summaries for KL11x,
the thread model and race sites for KL31x) are bucketed into the same
worker so the shared work is done once per family, not once per rule.
Platforms without ``fork`` fall back to in-process execution.
"""

from __future__ import annotations

import hashlib
import json
import multiprocessing
import os
import shutil
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

CACHE_DIRNAME = ".kolint_cache"
_MANIFEST = "files.json"
_KEEP_SIGNATURES = 4  # GC horizon: current + a few recent branches

_engine_hash: Optional[str] = None


def cache_root(repo_root: str) -> str:
    return os.path.join(repo_root, CACHE_DIRNAME)


def engine_hash() -> str:
    """Hash of the analysis package's own sources — a rule edit must
    invalidate results computed by the old rule."""
    global _engine_hash
    if _engine_hash is None:
        pkg = os.path.dirname(os.path.abspath(__file__))
        h = hashlib.sha256()
        for name in sorted(os.listdir(pkg)):
            if not name.endswith(".py"):
                continue
            with open(os.path.join(pkg, name), "rb") as fh:
                h.update(name.encode())
                h.update(fh.read())
        _engine_hash = h.hexdigest()
    return _engine_hash


def file_digests(files) -> Dict[str, str]:
    """rel path → content sha256 for loaded :class:`SourceFile`\\ s."""
    return {
        f.rel: hashlib.sha256(f.text.encode("utf-8")).hexdigest()
        for f in files
    }


def project_signature(files) -> str:
    h = hashlib.sha256(engine_hash().encode())
    for rel, dig in sorted(file_digests(files).items()):
        h.update(rel.encode())
        h.update(dig.encode())
    return h.hexdigest()[:24]


# ------------------------------------------------------------ rule entries


def _rule_path(repo_root: str, sig: str, rule_id: str) -> str:
    return os.path.join(cache_root(repo_root), sig, f"{rule_id}.json")


def get_rule(repo_root: str, sig: str, rule_id: str) -> Optional[List[dict]]:
    path = _rule_path(repo_root, sig, rule_id)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return json.load(fh)["findings"]
    except (OSError, ValueError, KeyError):
        return None


def put_rule(
    repo_root: str, sig: str, rule_id: str, findings: List[dict]
) -> None:
    path = _rule_path(repo_root, sig, rule_id)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"findings": findings}, fh)
        os.replace(tmp, path)
    except OSError:
        pass  # a cache that can't write is just a slow cache


def gc(repo_root: str, keep_sig: str) -> None:
    """Drop signature dirs beyond the newest few — every edit mints a
    new signature, so the cache would otherwise grow per keystroke."""
    root = cache_root(repo_root)
    try:
        dirs = [
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)) and d != keep_sig
        ]
    except OSError:
        return
    dirs.sort(
        key=lambda d: os.path.getmtime(os.path.join(root, d)), reverse=True
    )
    for d in dirs[_KEEP_SIGNATURES - 1:]:
        shutil.rmtree(os.path.join(root, d), ignore_errors=True)


# --------------------------------------------------------- change tracking


def load_manifest(repo_root: str) -> Dict[str, str]:
    try:
        with open(
            os.path.join(cache_root(repo_root), _MANIFEST),
            "r", encoding="utf-8",
        ) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return {}


def write_manifest(repo_root: str, digests: Dict[str, str]) -> None:
    root = cache_root(repo_root)
    try:
        os.makedirs(root, exist_ok=True)
        tmp = os.path.join(root, _MANIFEST + ".tmp")
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(digests, fh, indent=0, sort_keys=True)
        os.replace(tmp, os.path.join(root, _MANIFEST))
    except OSError:
        pass


def changed_files(repo_root: str, files) -> Set[str]:
    """Files whose content differs from the previous run's manifest
    (new files count as changed; with no manifest, everything does)."""
    prev = load_manifest(repo_root)
    return {
        rel for rel, dig in file_digests(files).items()
        if prev.get(rel) != dig
    }


# ------------------------------------------------------- parallel execution

# Fork-inherited project for pool workers; set immediately before the
# pool is created so children see the fully-parsed state copy-on-write.
_WORKER_PROJECT = None


def _run_bucket(rule_ids: Sequence[str]) -> List[Tuple[str, List[dict]]]:
    from kolibrie_tpu.analysis.core import RULES

    out: List[Tuple[str, List[dict]]] = []
    for rid in rule_ids:
        _, fn = RULES[rid]
        out.append((rid, [f.to_dict() for f in fn(_WORKER_PROJECT)]))
    return out


def bucket_rules(rule_ids: Iterable[str]) -> List[List[str]]:
    """Group rules so families that share memoized project state land
    in one worker (KL111+KL112 share taint summaries, KL311+KL312 the
    thread model and race sites)."""
    fams: Dict[str, List[str]] = {}
    for rid in sorted(rule_ids):
        fams.setdefault(rid[:4], []).append(rid)
    return [fams[k] for k in sorted(fams)]


def run_rules(
    project, rule_ids: Sequence[str], jobs: int = 1
) -> Dict[str, List[dict]]:
    """Run ``rule_ids`` against ``project``, fanning family buckets over
    ``jobs`` fork-pool workers when possible.  → rule id → finding
    dicts (same shape as ``Finding.to_dict``)."""
    global _WORKER_PROJECT
    buckets = bucket_rules(rule_ids)
    use_pool = (
        jobs > 1
        and len(buckets) > 1
        and "fork" in multiprocessing.get_all_start_methods()
    )
    out: Dict[str, List[dict]] = {}
    _WORKER_PROJECT = project
    if not use_pool:
        try:
            for bucket in buckets:
                for rid, dicts in _run_bucket(bucket):
                    out[rid] = dicts
        finally:
            _WORKER_PROJECT = None
        return out
    try:
        ctx = multiprocessing.get_context("fork")
        with ctx.Pool(processes=min(jobs, len(buckets))) as pool:
            for res in pool.map(_run_bucket, buckets):
                for rid, dicts in res:
                    out[rid] = dicts
    finally:
        _WORKER_PROJECT = None
    return out
