"""Project model for kolint: parsed files, comment directives, the
function index, and the cross-module call graph with jit-site
reachability.

The call graph is deliberately conservative-but-name-based: a call edge
exists when the callee NAME resolves to a function definition in the
analyzed file set (same module top-level def, ``self.``-method of the
enclosing class, or an imported name whose source module is also being
analyzed).  Function names passed as ARGUMENTS (``lax.scan(body, …)``,
``partial(fn, …)``) also create edges — jitted code reaches its loop
bodies through exactly that shape.  Names that do not resolve (stdlib,
jax internals, dynamic dispatch) simply contribute no edge; rules that
consume reachability are written so a missing edge means a missed
finding, never a false one.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_SUPPRESS_RE = re.compile(r"#\s*kolint:\s*ignore\[([^\]]*)\]\s*(.*)")
_HOLDS_RE = re.compile(r"#\s*kolint:\s*holds\[([^\]]+)\]")
_GUARDED_RE = re.compile(
    r"#\s*guarded by:\s*([A-Za-z_][\w.]*)(?:\s*\((writes|rw)\))?"
)

# Decorator / callee names that create a jit compilation boundary.
JIT_WRAPPER_NAMES = {"jit", "pjit", "shard_map", "_shard_map", "pmap"}


@dataclass
class Suppression:
    line: int  # line the directive APPLIES to (comment-only lines bind down)
    rules: Tuple[str, ...]
    reason: str
    raw_line: int  # line the comment physically sits on
    used: bool = False


@dataclass
class GuardedState:
    """One ``# guarded by: <lock>`` annotation on mutable state.

    ``mode`` tunes the RUNTIME sanitizer (:mod:`analysis.lockcheck`)
    only — the static rules treat every mode identically:

    - ``"writes"`` (default): rebinding writes must hold the lock;
      reads may be lock-free (the snapshot-read idiom).
    - ``"rw"``: reads must hold it too — use for state mutated in
      place (``list.append``/dict writes), which a descriptor can only
      see as a read of the container.
    """

    attr: str  # attribute or module-global name
    lock: str  # annotation text, e.g. "self.lock" / "_ring_lock"
    class_name: Optional[str]  # None → module-level global
    line: int
    mode: str = "writes"


@dataclass
class FuncInfo:
    module: "SourceFile"
    qualname: str  # "Class.method" or "fn" (module-relative)
    node: ast.AST  # FunctionDef / AsyncFunctionDef / Lambda
    class_name: Optional[str]
    params: Tuple[str, ...] = ()
    static_params: Tuple[str, ...] = ()  # from jit static_argnames/nums
    is_jit_root: bool = False
    jit_reachable: bool = False
    callees: Set[str] = field(default_factory=set)  # global func keys
    holds_locks: Tuple[str, ...] = ()  # kolint: holds[lock] on the def

    @property
    def key(self) -> str:
        return f"{self.module.rel}::{self.qualname}"


class SourceFile:
    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[str] = None
        self.comments: Dict[int, str] = {}
        self.suppressions: List[Suppression] = []
        self.guarded: List[GuardedState] = []
        self.imports: Dict[str, Tuple[str, str]] = {}  # alias → (module, name)
        self.module_aliases: Dict[str, str] = {}  # alias → module path
        self.functions: Dict[str, FuncInfo] = {}  # qualname → info
        try:
            self.tree = ast.parse(text)
        except SyntaxError as e:
            self.parse_error = f"{e.msg} (line {e.lineno})"
            return
        self._collect_comments()
        self._collect_imports()

    # ------------------------------------------------------------ comments

    def _collect_comments(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.text).readline)
            for tok in toks:
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError):
            return
        lines = self.text.splitlines()
        for lineno, comment in self.comments.items():
            m = _SUPPRESS_RE.search(comment)
            if m:
                rules = tuple(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )
                code = lines[lineno - 1][: lines[lineno - 1].index("#")]
                applies = lineno + 1 if not code.strip() else lineno
                self.suppressions.append(
                    Suppression(applies, rules, m.group(2).strip(), lineno)
                )
            m = _GUARDED_RE.search(comment)
            if m:
                # attached to guarded state by _index_functions below
                self._pending_guard = getattr(self, "_pending_guard", {})
                self._pending_guard[lineno] = (m.group(1), m.group(2) or "writes")

    def holds_for_line(self, lineno: int) -> Tuple[str, ...]:
        """``# kolint: holds[lock]`` directives on a def's line."""
        m = _HOLDS_RE.search(self.comments.get(lineno, ""))
        if not m:
            return ()
        return tuple(s.strip() for s in m.group(1).split(",") if s.strip())

    # ------------------------------------------------------------- imports

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (
                        node.module,
                        alias.name,
                    )
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    self.module_aliases[
                        alias.asname or alias.name.split(".")[0]
                    ] = alias.name


def terminal_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` → ``c``; ``name`` → ``name``; else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` → "a.b.c" when the chain is pure names, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _static_names_from_jit_call(call: ast.Call, params: Tuple[str, ...]) -> Tuple[str, ...]:
    """static_argnames / static_argnums keywords of a jit/partial call →
    parameter names."""
    out: List[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            vals = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in vals:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.append(e.value)
        elif kw.arg == "static_argnums":
            v = kw.value
            vals = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in vals:
                if (
                    isinstance(e, ast.Constant)
                    and isinstance(e.value, int)
                    and e.value < len(params)
                ):
                    out.append(params[e.value])
    return tuple(out)


def is_jit_wrapper_call(call: ast.Call) -> bool:
    """Is this call ``jax.jit(…)`` / ``shard_map(…)`` / a partner?"""
    name = terminal_name(call.func)
    return name in JIT_WRAPPER_NAMES


def partial_bound_params(call: ast.Call, params: Tuple[str, ...]) -> Tuple[str, ...]:
    """Parameters of the target bound by ``partial(fn, a, kw=b)`` — those
    are trace-time constants (closure-captured), not traced arguments."""
    out: List[str] = list(params[: max(0, len(call.args) - 1)])
    for kw in call.keywords:
        if kw.arg and kw.arg in params:
            out.append(kw.arg)
    return tuple(out)


class Project:
    """All analyzed files + the derived function index and call graph."""

    def __init__(self, files: List[SourceFile]):
        self.files = files
        self.functions: Dict[str, FuncInfo] = {}
        # module import path guess → SourceFile (for cross-module edges)
        self.by_modpath: Dict[str, SourceFile] = {}
        for f in files:
            self.by_modpath[_modpath_of(f.rel)] = f
        for f in files:
            if f.tree is not None:
                self._index_functions(f)
        for f in files:
            if f.tree is not None:
                self._collect_edges_and_roots(f)
        self._propagate_reachability()

    # ------------------------------------------------------------ indexing

    def _index_functions(self, f: SourceFile) -> None:
        pending_guard: Dict[int, Tuple[str, str]] = getattr(
            f, "_pending_guard", {}
        )

        def visit(node: ast.AST, class_name: Optional[str], prefix: str):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name, f"{prefix}{child.name}.")
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    qual = f"{prefix}{child.name}"
                    a = child.args
                    params = tuple(
                        p.arg
                        for p in (
                            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                        )
                    )
                    holds = f.holds_for_line(child.lineno)
                    for deco in child.decorator_list:
                        holds = holds or f.holds_for_line(deco.lineno)
                    info = FuncInfo(
                        f, qual, child, class_name, params=params,
                        holds_locks=holds,
                    )
                    f.functions[qual] = info
                    self.functions[info.key] = info
                    visit(child, class_name, f"{qual}.")
                else:
                    # guarded-state annotations live on assignments
                    if isinstance(child, (ast.Assign, ast.AnnAssign)):
                        guard = pending_guard.get(child.lineno)
                        if guard:
                            lock, mode = guard
                            targets = (
                                child.targets
                                if isinstance(child, ast.Assign)
                                else [child.target]
                            )
                            for t in targets:
                                attr = terminal_name(t)
                                if attr:
                                    f.guarded.append(
                                        GuardedState(
                                            attr, lock, class_name,
                                            child.lineno, mode=mode,
                                        )
                                    )
                    visit(child, class_name, prefix)

        visit(f.tree, None, "")

    # ----------------------------------------------------- edges and roots

    def _resolve_callee(
        self, f: SourceFile, func: FuncInfo, node: ast.AST
    ) -> Optional[FuncInfo]:
        """Resolve a referenced callable to a FuncInfo in the project."""
        if isinstance(node, ast.Name):
            name = node.id
            # same-module: top-level def, or sibling nested def
            if name in f.functions:
                return f.functions[name]
            if func.class_name and f"{func.class_name}.{name}" in f.functions:
                pass  # bare name never resolves to a method
            nested = f"{func.qualname}.{name}"
            if nested in f.functions:
                return f.functions[nested]
            if name in f.imports:
                mod, orig = f.imports[name]
                src = self.by_modpath.get(mod)
                if src and orig in src.functions:
                    return src.functions[orig]
            return None
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name):
                base = node.value.id
                if base in ("self", "cls") and func.class_name:
                    qual = f"{func.class_name}.{node.attr}"
                    if qual in f.functions:
                        return f.functions[qual]
                # module alias:  import kolibrie_tpu.x as y ; y.fn()
                mod = f.module_aliases.get(base)
                if mod is None and base in f.imports:
                    im_mod, im_name = f.imports[base]
                    mod = f"{im_mod}.{im_name}"
                if mod:
                    src = self.by_modpath.get(mod)
                    if src and node.attr in src.functions:
                        return src.functions[node.attr]
        return None

    def _collect_edges_and_roots(self, f: SourceFile) -> None:
        # Pre-pass: decorated jit roots.
        for info in f.functions.values():
            node = info.node
            for deco in getattr(node, "decorator_list", ()):
                dname = terminal_name(deco if not isinstance(deco, ast.Call) else deco.func)
                if dname in JIT_WRAPPER_NAMES:
                    info.is_jit_root = True
                elif isinstance(deco, ast.Call) and dname == "partial":
                    inner = deco.args[0] if deco.args else None
                    if inner is not None and terminal_name(inner) in JIT_WRAPPER_NAMES:
                        info.is_jit_root = True
                        info.static_params = _static_names_from_jit_call(
                            deco, info.params
                        )

        # Per-function: call edges; jit roots via jax.jit(fn) forms.
        for info in f.functions.values():
            own = list(iter_own_nodes(info.node))
            has_jit_call = any(
                isinstance(n, ast.Call) and is_jit_wrapper_call(n) for n in own
            )
            # local `body = partial(fn, …)` aliases: jitted code reaches
            # its round/scan bodies through exactly this indirection
            partial_targets: List[Tuple[FuncInfo, ast.Call]] = []
            for node in own:
                if (
                    isinstance(node, ast.Call)
                    and terminal_name(node.func) == "partial"
                    and node.args
                ):
                    t = self._resolve_callee(f, info, node.args[0])
                    if t is not None:
                        partial_targets.append((t, node))
            for node in own:
                if not isinstance(node, ast.Call):
                    continue
                callee = self._resolve_callee(f, info, node.func)
                if callee is not None:
                    info.callees.add(callee.key)
                in_jit = is_jit_wrapper_call(node)
                # callables passed as arguments (scan/cond bodies,
                # partial(fn, …), Thread targets) are edges too
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    target = self._resolve_callee(f, info, arg)
                    bound: Tuple[str, ...] = ()
                    if target is None and isinstance(arg, ast.Call):
                        # partial(fn, …) → fn; bound args are constants
                        if terminal_name(arg.func) == "partial" and arg.args:
                            target = self._resolve_callee(f, info, arg.args[0])
                            if target is not None:
                                bound = partial_bound_params(
                                    arg, target.params
                                )
                    if target is not None:
                        if in_jit:
                            target.is_jit_root = True
                            target.static_params = tuple(
                                dict.fromkeys(
                                    target.static_params
                                    + _static_names_from_jit_call(
                                        node, target.params
                                    )
                                    + bound
                                )
                            )
                        else:
                            info.callees.add(target.key)
            if has_jit_call:
                # a function that builds a jit wrapper: every partial-
                # wrapped local function is (conservatively) a jit root,
                # with the partial-bound parameters as constants
                for t, pcall in partial_targets:
                    t.is_jit_root = True
                    t.static_params = tuple(
                        dict.fromkeys(
                            t.static_params
                            + partial_bound_params(pcall, t.params)
                        )
                    )

        # Lexically nested defs compile with (are reachable from) their
        # parent: closures appear without a resolvable call edge.
        for info in f.functions.values():
            parent_key = (
                info.qualname.rsplit(".", 1)[0]
                if "." in info.qualname else None
            )
            parent = f.functions.get(parent_key) if parent_key else None
            if parent is not None and parent.node is not info.node:
                parent.callees.add(info.key)

    def _propagate_reachability(self) -> None:
        work = [i for i in self.functions.values() if i.is_jit_root]
        seen: Set[str] = set()
        while work:
            info = work.pop()
            if info.key in seen:
                continue
            seen.add(info.key)
            info.jit_reachable = True
            for k in info.callees:
                nxt = self.functions.get(k)
                if nxt is not None and k not in seen:
                    work.append(nxt)

    # ------------------------------------------------------- reachability

    def reachable_from(self, root: FuncInfo) -> List[FuncInfo]:
        out: List[FuncInfo] = []
        seen: Set[str] = set()
        work = [root]
        while work:
            info = work.pop()
            if info.key in seen:
                continue
            seen.add(info.key)
            out.append(info)
            for k in info.callees:
                nxt = self.functions.get(k)
                if nxt is not None and k not in seen:
                    work.append(nxt)
        return out


def iter_own_nodes(func_node: ast.AST):
    """Every AST node lexically inside ``func_node``'s body, excluding
    nested function/class bodies (indexed as their own FuncInfos) and
    the function's own signature/decorators.

    The walk is memoized on the node (every rule family re-walks every
    function; one shared list per function is the difference between a
    seconds-scale and a minutes-scale repo run)."""
    cached = getattr(func_node, "_kolint_own_nodes", None)
    if cached is not None:
        return cached
    out: List[ast.AST] = []
    work = list(getattr(func_node, "body", []))
    while work:
        node = work.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        out.append(node)
        if isinstance(node, ast.Lambda):
            work.append(node.body)
            continue
        work.extend(ast.iter_child_nodes(node))
    try:
        func_node._kolint_own_nodes = out
    except (AttributeError, TypeError):
        pass
    return out


def _modpath_of(rel: str) -> str:
    """'kolibrie_tpu/obs/spans.py' → 'kolibrie_tpu.obs.spans'."""
    p = rel[:-3] if rel.endswith(".py") else rel
    p = p.replace(os.sep, ".").replace("/", ".")
    if p.endswith(".__init__"):
        p = p[: -len(".__init__")]
    return p


def load_files(paths: List[str], root: str) -> List[SourceFile]:
    out: List[SourceFile] = []
    seen: Set[str] = set()
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                ]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        full = os.path.join(dirpath, fn)
                        if full not in seen:
                            seen.add(full)
                            out.append(_load_one(full, root))
        elif ap.endswith(".py") and ap not in seen:
            seen.add(ap)
            out.append(_load_one(ap, root))
    return out


def _load_one(path: str, root: str) -> SourceFile:
    try:
        rel = os.path.relpath(path, root)
        if rel.startswith(".."):
            rel = path
    except ValueError:
        rel = path
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read()
    return SourceFile(path, rel.replace(os.sep, "/"), text)
