"""kolint CLI.

    python -m kolibrie_tpu.analysis [paths...]        lint (against baseline)
    python -m kolibrie_tpu.analysis --json            machine-readable output
    python -m kolibrie_tpu.analysis --no-baseline     raw findings
    python -m kolibrie_tpu.analysis --write-baseline  regenerate baseline
    python -m kolibrie_tpu.analysis --list-rules      rule catalog

Exit status: 0 when no non-baselined findings remain, 1 otherwise,
2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from kolibrie_tpu.analysis import core


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kolibrie_tpu.analysis",
        description="kolint: repo-native static analysis for tracing, "
        "recompile, lock-discipline, and observability invariants.",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files or directories to lint (default: the kolibrie_tpu "
        "package)",
    )
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument(
        "--baseline",
        metavar="PATH",
        default=None,
        help="baseline file (default: <repo>/kolint_baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report all findings, ignoring the baseline",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current (post-suppression) findings as the baseline",
    )
    ap.add_argument(
        "--rules",
        metavar="IDS",
        default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = ap.parse_args(argv)

    # import for registration before --list-rules
    from kolibrie_tpu.analysis import (  # noqa: F401
        rules_caching,
        rules_context,
        rules_errors,
        rules_locks,
        rules_obs,
        rules_tracing,
    )

    if args.list_rules:
        for rid in sorted(core.RULES):
            desc, _ = core.RULES[rid]
            print(f"{rid}  {desc}")
        print(f"{core.META_SUPPRESSION}  suppression directive malformed "
              "(no reason / unknown rule)")
        print(f"{core.META_PARSE}  file does not parse")
        return 0

    paths = args.paths or [
        os.path.join(core.repo_root(), "kolibrie_tpu")
    ]
    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rule_ids if r not in core.RULES]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)}", file=sys.stderr)
            return 2

    baseline_path = args.baseline or core.default_baseline_path()
    result = core.run(
        paths,
        baseline_path=baseline_path,
        use_baseline=not (args.no_baseline or args.write_baseline),
        rules=rule_ids,
    )

    if args.write_baseline:
        core.write_baseline(baseline_path, result.findings)
        print(
            f"wrote {len(result.findings)} finding(s) to {baseline_path}"
        )
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in result.findings],
                    "suppressed": len(result.suppressed),
                    "baselined": len(result.baselined),
                    "ok": result.ok,
                },
                indent=2,
            )
        )
    else:
        for f in result.findings:
            print(f.render())
        tail = (
            f"{len(result.findings)} finding(s), "
            f"{len(result.suppressed)} suppressed, "
            f"{len(result.baselined)} baselined"
        )
        print(tail)
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
