"""kolint CLI.

    python -m kolibrie_tpu.analysis [paths...]        lint (against baseline)
    python -m kolibrie_tpu.analysis --json            machine-readable output
    python -m kolibrie_tpu.analysis --no-baseline     raw findings
    python -m kolibrie_tpu.analysis --write-baseline  regenerate baseline
    python -m kolibrie_tpu.analysis --list-rules      rule catalog
    python -m kolibrie_tpu.analysis --explain KL311   rule doc + fix recipe
    python -m kolibrie_tpu.analysis --changed-only    report only edited files

Performance: results are cached per (project signature, rule) under
.kolint_cache/ and cold rules fan out over a small process pool —
``--no-cache`` / ``--jobs N`` (default ``KOLINT_JOBS`` or cpu-derived)
control both.  Every run prints ``kolint_runtime_s=…``; ``--max-seconds``
turns that number into a gate so lint stays fast enough to run on every
commit.

Exit status: 0 when no non-baselined findings remain, 1 otherwise (or
when --max-seconds is exceeded), 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from kolibrie_tpu.analysis import core


def _default_jobs() -> int:
    env = os.environ.get("KOLINT_JOBS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    # rules bucket into ~10 families; more workers than that is churn
    return min(4, os.cpu_count() or 1)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kolibrie_tpu.analysis",
        description="kolint: repo-native static analysis for tracing, "
        "recompile, lock-discipline, and observability invariants.",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files or directories to lint (default: the kolibrie_tpu "
        "package)",
    )
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument(
        "--baseline",
        metavar="PATH",
        default=None,
        help="baseline file (default: <repo>/kolint_baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report all findings, ignoring the baseline",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current (post-suppression) findings as the baseline",
    )
    ap.add_argument(
        "--rules",
        metavar="IDS",
        default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    ap.add_argument(
        "--explain",
        metavar="RULE",
        default=None,
        help="print one rule's documentation, example, and fix recipe",
    )
    ap.add_argument(
        "--no-cache",
        action="store_true",
        help="skip the .kolint_cache result cache (always re-analyze)",
    )
    ap.add_argument(
        "--jobs",
        type=int,
        default=None,
        metavar="N",
        help="process-pool workers for rule execution "
        "(default: $KOLINT_JOBS or cpu-derived; 1 = in-process)",
    )
    ap.add_argument(
        "--changed-only",
        action="store_true",
        help="report only findings in files changed since the last full "
        "run (analysis still covers the whole project)",
    )
    ap.add_argument(
        "--max-seconds",
        type=float,
        default=None,
        metavar="S",
        help="fail (exit 1) if the lint run takes longer than S seconds",
    )
    args = ap.parse_args(argv)

    # import for registration before --list-rules / --explain
    from kolibrie_tpu.analysis import (  # noqa: F401
        rules_caching,
        rules_context,
        rules_durability,
        rules_errors,
        rules_locks,
        rules_obs,
        rules_pallas,
        rules_races,
        rules_taint,
        rules_tracing,
    )

    if args.explain:
        from kolibrie_tpu.analysis.explain import explain

        text = explain(args.explain.strip().upper())
        if text is None:
            print(f"unknown rule id: {args.explain}", file=sys.stderr)
            return 2
        print(text)
        return 0

    if args.list_rules:
        for rid in sorted(core.RULES):
            desc, _ = core.RULES[rid]
            print(f"{rid}  {desc}")
        print(f"{core.META_SUPPRESSION}  suppression directive malformed "
              "(no reason / unknown rule)")
        print(f"{core.META_PARSE}  file does not parse")
        return 0

    paths = args.paths or [
        os.path.join(core.repo_root(), "kolibrie_tpu")
    ]
    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rule_ids if r not in core.RULES]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)}", file=sys.stderr)
            return 2

    baseline_path = args.baseline or core.default_baseline_path()
    t0 = time.perf_counter()
    result = core.run(
        paths,
        baseline_path=baseline_path,
        use_baseline=not (args.no_baseline or args.write_baseline),
        rules=rule_ids,
        use_cache=not args.no_cache,
        jobs=args.jobs if args.jobs is not None else _default_jobs(),
        changed_only=args.changed_only,
    )
    runtime_s = time.perf_counter() - t0
    too_slow = (
        args.max_seconds is not None and runtime_s > args.max_seconds
    )

    if args.write_baseline:
        core.write_baseline(baseline_path, result.findings)
        print(
            f"wrote {len(result.findings)} finding(s) to {baseline_path}"
        )
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in result.findings],
                    "suppressed": len(result.suppressed),
                    "baselined": len(result.baselined),
                    "runtime_s": round(runtime_s, 2),
                    "ok": result.ok and not too_slow,
                },
                indent=2,
            )
        )
    else:
        for f in result.findings:
            print(f.render())
        tail = (
            f"{len(result.findings)} finding(s), "
            f"{len(result.suppressed)} suppressed, "
            f"{len(result.baselined)} baselined"
        )
        print(tail)
        print(f"kolint_runtime_s={runtime_s:.2f}")
    if too_slow:
        print(
            f"kolint exceeded --max-seconds {args.max_seconds:g} "
            f"(took {runtime_s:.2f}s)",
            file=sys.stderr,
        )
        return 1
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
